"""Object-store read plane: coalesced parallel range reads (ROADMAP item 3).

A remote row-group read is not one I/O — it is a *set of byte ranges* (one
per column chunk) whose layout the Parquet footer already describes exactly.
The serial path pays one store round-trip per chunk; ``pre_buffer`` lets
pyarrow coalesce internally but hides the request plan from the resilience
layer, so a hedge or retry re-reads the *whole row group*. This module makes
the plan explicit:

- :class:`RangePlanner` turns ``(footer metadata, row group, columns)`` into
  the exact ``(offset, length)`` byte ranges of the needed column chunks,
  merges ranges whose gap is below ``gap_bytes`` (two adjacent 100 KB chunks
  separated by 4 KB are one GET, not two — the wasted gap bytes are cheaper
  than a second round trip) and splits ranges above ``max_range_bytes`` so a
  giant chunk still parallelizes.
- :class:`ParallelRangeReader` issues the planned ranges concurrently
  (bounded in-flight fetch threads, each range through its own store
  handle), with the per-**range** retry/hedge discipline of
  :class:`petastorm_tpu.resilience.ResilientIO` — one straggling range is
  hedged alone instead of re-reading the row group — and assembles the
  fetched segments into a random-access buffer that ``pq.ParquetFile``
  decodes from memory. Bytes the plan did not cover (page indexes, an
  unexpectedly long footer) fall back to an inline ranged read, counted as
  ``io_range_fallbacks`` — never an error.

Workers select the path with the ``remote_read`` factory knob
(``'ranged' | 'prebuffer' | 'serial'``; default auto = ``prebuffer`` for
remote protocols, ``serial`` for local — the pre-knob behavior). See
``docs/object_store.md`` for the planning math and the measured numbers.
"""

from __future__ import annotations

import io
import struct
import threading
import time
from bisect import bisect_right, insort
from typing import Callable, Dict, List, Optional, Tuple

from petastorm_tpu.latency import bucket_index

#: Merge two planned ranges when the gap between them is at most this many
#: bytes: one round trip costs more than re-downloading a small gap.
DEFAULT_GAP_BYTES = 64 * 1024

#: Split a merged range above this size so one giant column chunk still
#: spreads across the in-flight fetch slots.
DEFAULT_MAX_RANGE_BYTES = 8 * 1024 * 1024

#: Bound on concurrently in-flight range fetches per read.
DEFAULT_MAX_IN_FLIGHT = 8

#: First footer fetch size: one tail read this long resolves the footer for
#: almost every real file (a longer footer costs exactly one more fetch).
DEFAULT_FOOTER_BYTES = 64 * 1024

#: Valid ``remote_read`` factory knob values (``None`` = auto).
REMOTE_READ_MODES = ('ranged', 'prebuffer', 'serial')

_PARQUET_MAGIC = b'PAR1'
_FOOTER_LEN = struct.Struct('<I')


def resolve_remote_read(remote_read) -> Optional[str]:
    """Normalize the factory ``remote_read=`` knob: ``None``/``'auto'`` →
    ``None`` (the worker picks per filesystem protocol), otherwise one of
    :data:`REMOTE_READ_MODES`. A typo fails the factory, not the worker."""
    if remote_read is None or remote_read == 'auto':
        return None
    if remote_read in REMOTE_READ_MODES:
        return remote_read
    raise ValueError("remote_read must be one of {} or None/'auto', got "
                     '{!r}'.format(list(REMOTE_READ_MODES), remote_read))


class RangePlanner:
    """Plan a row-group read as explicit byte ranges from footer metadata.

    Pure computation — no I/O: the planner sees only the
    ``pq.FileMetaData`` the reader already holds, so planning is free to
    run per read.
    """

    def __init__(self, gap_bytes: int = DEFAULT_GAP_BYTES,
                 max_range_bytes: int = DEFAULT_MAX_RANGE_BYTES):
        if gap_bytes < 0:
            raise ValueError('gap_bytes must be >= 0, got '
                             '{}'.format(gap_bytes))
        if max_range_bytes < 1:
            raise ValueError('max_range_bytes must be >= 1, got '
                             '{}'.format(max_range_bytes))
        self.gap_bytes = gap_bytes
        self.max_range_bytes = max_range_bytes

    @staticmethod
    def column_chunk_ranges(metadata, row_group: int,
                            columns: Optional[List[str]] = None
                            ) -> List[Tuple[int, int]]:
        """``(offset, length)`` of every needed column chunk of one row
        group. A chunk starts at its dictionary page when one precedes the
        data pages (the same rule pyarrow's own ``pre_buffer`` coalescing
        applies) and spans ``total_compressed_size``. ``columns`` selects by
        top-level name (nested paths like ``a.list.item`` belong to ``a``);
        ``None`` takes every chunk."""
        wanted = None if columns is None else {c.split('.')[0]
                                               for c in columns}
        rg = metadata.row_group(row_group)
        ranges = []
        for i in range(rg.num_columns):
            chunk = rg.column(i)
            if wanted is not None \
                    and chunk.path_in_schema.split('.')[0] not in wanted:
                continue
            start = chunk.data_page_offset
            dict_off = chunk.dictionary_page_offset
            if dict_off is not None and 0 < dict_off < start:
                start = dict_off
            length = chunk.total_compressed_size
            if length > 0:
                ranges.append((int(start), int(length)))
        return sorted(ranges)

    def merge(self, ranges: List[Tuple[int, int]]) -> List[Tuple[int, int]]:
        """Coalesce sorted ``(offset, length)`` ranges whose gap is at most
        ``gap_bytes``, then split results above ``max_range_bytes``."""
        merged: List[List[int]] = []
        for offset, length in sorted(ranges):
            if merged and offset - (merged[-1][0] + merged[-1][1]) \
                    <= self.gap_bytes:
                end = max(merged[-1][0] + merged[-1][1], offset + length)
                merged[-1][1] = end - merged[-1][0]
            else:
                merged.append([offset, length])
        out: List[Tuple[int, int]] = []
        for offset, length in merged:
            while length > self.max_range_bytes:
                out.append((offset, self.max_range_bytes))
                offset += self.max_range_bytes
                length -= self.max_range_bytes
            out.append((offset, length))
        return out

    def plan(self, metadata, row_group: int,
             columns: Optional[List[str]] = None) -> List[Tuple[int, int]]:
        """The merged fetch plan for one row-group read."""
        return self.merge(self.column_chunk_ranges(metadata, row_group,
                                                   columns))

    @staticmethod
    def wasted_bytes(chunks: List[Tuple[int, int]],
                     plan: List[Tuple[int, int]]) -> int:
        """Gap bytes the merged ``plan`` fetches beyond the raw ``chunks``
        (the documented price of coalescing, reported per read)."""
        return (sum(n for _, n in plan) - sum(n for _, n in chunks))


class RangeBuffer:
    """Random-access read-only file over fetched ``(offset, bytes)``
    segments, with an inline fetch fallback for uncovered bytes.

    The fetch threads :meth:`insert` concurrently while pyarrow reads are
    not yet running; once :class:`ParallelRangeReader` hands the buffer to
    ``pq.ParquetFile`` only the reading thread touches it (the lock is kept
    because a fallback fetch mid-read also inserts). Uncovered reads call
    ``fetch_fn(offset, length)`` — the same resilient ranged read the
    planned segments used — and are tallied via ``on_fallback``.
    """

    def __init__(self, size: int,
                 fetch_fn: Callable[[int, int], bytes],
                 on_fallback: Optional[Callable[[int], None]] = None):
        self._size = int(size)
        self._fetch = fetch_fn
        self._on_fallback = on_fallback
        self._mutex = threading.Lock()
        self._starts: List[int] = []
        self._segments: Dict[int, bytes] = {}
        self._pos = 0
        self._closed = False

    # -- segment bookkeeping ---------------------------------------------------

    def insert(self, offset: int, data: bytes) -> None:
        with self._mutex:
            if offset in self._segments:
                if len(data) > len(self._segments[offset]):
                    self._segments[offset] = data
                return
            insort(self._starts, offset)
            self._segments[offset] = data

    def _covering_locked(self, offset: int) -> Optional[Tuple[int, bytes]]:
        """The segment containing ``offset``, or ``None``."""
        i = bisect_right(self._starts, offset) - 1
        if i < 0:
            return None
        start = self._starts[i]
        data = self._segments[start]
        if offset < start + len(data):
            return start, data
        return None

    def _next_start_locked(self, offset: int) -> int:
        i = bisect_right(self._starts, offset)
        return self._starts[i] if i < len(self._starts) else self._size

    # -- file protocol ---------------------------------------------------------

    def read(self, nbytes: int = -1) -> bytes:
        if nbytes is None or nbytes < 0:
            nbytes = self._size - self._pos
        nbytes = max(0, min(nbytes, self._size - self._pos))
        parts = []
        pos = self._pos
        remaining = nbytes
        while remaining > 0:
            with self._mutex:
                hit = self._covering_locked(pos)
                gap_end = (self._next_start_locked(pos) if hit is None
                           else None)
            if hit is not None:
                start, data = hit
                lo = pos - start
                take = min(remaining, len(data) - lo)
                parts.append(data[lo:lo + take])
            else:
                # uncovered bytes: fetch exactly the missing sub-range (to
                # the next known segment) through the resilient range read
                take = min(remaining, gap_end - pos)
                data = self._fetch(pos, take)
                if self._on_fallback is not None:
                    self._on_fallback(take)
                self.insert(pos, data)
                parts.append(data[:take])
            pos += take
            remaining -= take
        self._pos = pos
        return b''.join(parts)

    def seek(self, offset: int, whence: int = 0) -> int:
        if whence == 0:
            self._pos = offset
        elif whence == 1:
            self._pos += offset
        elif whence == 2:
            self._pos = self._size + offset
        else:
            raise ValueError('invalid whence {!r}'.format(whence))
        self._pos = max(0, min(self._pos, self._size))
        return self._pos

    def tell(self) -> int:
        return self._pos

    def size(self) -> int:
        return self._size

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return True

    def writable(self) -> bool:
        return False

    @property
    def closed(self) -> bool:
        return self._closed

    def close(self) -> None:
        self._closed = True


class ParallelRangeReader:
    """Coalesced parallel row-group reads over one (possibly fault-wrapped)
    filesystem.

    One instance per worker, shared by the worker thread and its readahead
    thread (all mutable state — the footer cache and the event tallies — is
    lock-protected; every read call builds its own :class:`RangeBuffer` and
    ``pq.ParquetFile``, and every range fetch opens its own store handle,
    so no file handle ever serves two concurrent reads).

    :param filesystem: fsspec-like filesystem (``open``/``size``); chaos and
        trace-replay wrappers apply per range because every range goes
        through ``filesystem.open``.
    :param resilience: optional
        :class:`petastorm_tpu.resilience.ResilientIO`; when set, EVERY range
        fetch runs under its retry (outer) and hedge (inner) layers — the
        per-request discipline that makes hedging cheap (a straggler range
        is duplicated alone, not the whole row group).
    :param max_in_flight: concurrent range fetches per row-group read.
    :param observe_spans: record one ``range_fetch`` span tuple per
        :meth:`fetch_range` (retry count annotated; the hedge layer's
        per-attempt spans come from ``ResilientIO.take_spans``). Off by
        default — the pod-observability plane opts in at construction
        (``docs/pod_observability.md``).
    :param observe_latency: feed each :meth:`fetch_range` duration into an
        internal ``io_range`` latency delta, drained by
        :meth:`take_latency` (the ``LatencyDeltas.drain`` shape).
    """

    #: Bound on undrained ``range_fetch`` spans (a construction that never
    #: drains must not grow without limit).
    MAX_PENDING_SPANS = 2048

    def __init__(self, filesystem, resilience=None,
                 gap_bytes: int = DEFAULT_GAP_BYTES,
                 max_range_bytes: int = DEFAULT_MAX_RANGE_BYTES,
                 max_in_flight: int = DEFAULT_MAX_IN_FLIGHT,
                 footer_bytes: int = DEFAULT_FOOTER_BYTES,
                 observe_spans: bool = False,
                 observe_latency: bool = False):
        if max_in_flight < 1:
            raise ValueError('max_in_flight must be >= 1, got '
                             '{}'.format(max_in_flight))
        self._fs = filesystem
        self._resilience = resilience
        self._planner = RangePlanner(gap_bytes=gap_bytes,
                                     max_range_bytes=max_range_bytes)
        self._max_in_flight = max_in_flight
        self._footer_bytes = max(16, footer_bytes)
        self._mutex = threading.Lock()
        # path -> (file size, FileMetaData, footer tail (offset, bytes))
        self._footers: Dict[str, Tuple[int, object, Tuple[int, bytes]]] = {}
        self._events: Dict[str, int] = {}
        self._observe_spans = bool(observe_spans)
        self._observe_latency = bool(observe_latency)
        # (name, cat, start_s, dur_s, args) tuples; accumulated under the
        # mutex because fetch_range runs on the worker thread, the
        # readahead thread AND the per-call pump threads
        self._spans: list = []
        # {'io_range': {'buckets': {index: n}, 'sum': s, 'count': n}} — the
        # LatencyDeltas entry shape, mergeable by bucket addition
        self._latency: Dict[str, dict] = {}

    # -- events ----------------------------------------------------------------

    def _count(self, name: str, n: int = 1) -> None:
        with self._mutex:
            self._events[name] = self._events.get(name, 0) + n

    def take_events(self) -> Dict[str, int]:
        """Drain accumulated ``io_range_*`` counter deltas (worker thread
        only — the same discipline as ``ResilientIO.take_events``)."""
        with self._mutex:
            events, self._events = self._events, {}
        return events

    def _observe_fetch(self, offset: int, length: int, start_s: float,
                       retries: int, error: Optional[str]) -> None:
        """Record one finished :meth:`fetch_range` into the span/latency
        accumulators (mutex-guarded: callers include pump threads)."""
        dur_s = time.perf_counter() - start_s
        span = None
        if self._observe_spans:
            args: dict = {'offset': offset, 'length': length}
            if retries:
                args['retries'] = retries
            if error is not None:
                args['error'] = error
            span = ('range_fetch', 'io', start_s, dur_s, args)
        with self._mutex:
            if span is not None:
                self._spans.append(span)
                if len(self._spans) > self.MAX_PENDING_SPANS:
                    del self._spans[:len(self._spans)
                                    - self.MAX_PENDING_SPANS]
            if self._observe_latency:
                entry = self._latency.get('io_range')
                if entry is None:
                    entry = self._latency['io_range'] = {
                        'buckets': {}, 'sum': 0.0, 'count': 0}
                index = bucket_index(dur_s)
                entry['buckets'][index] = entry['buckets'].get(index, 0) + 1
                entry['sum'] += dur_s
                entry['count'] += 1

    def take_spans(self) -> list:
        """Drain accumulated ``range_fetch`` span tuples (worker thread
        only; empty unless ``observe_spans=True``)."""
        with self._mutex:
            spans, self._spans = self._spans, []
        return spans

    def take_latency(self) -> Optional[Dict[str, dict]]:
        """Drain the accumulated ``io_range`` latency deltas (worker thread
        only; ``None`` unless ``observe_latency=True`` and data exists).
        Shape matches ``LatencyDeltas.drain`` — absorb with
        ``LatencyDeltas.absorb`` or ``PipelineLatency.merge_deltas``."""
        with self._mutex:
            if not self._latency:
                return None
            latency, self._latency = self._latency, {}
        return latency

    # -- range fetch -----------------------------------------------------------

    def _fetch_once(self, path: str, offset: int, length: int) -> bytes:
        """One ranged GET through a fresh store handle (short reads are
        drained — fsspec files may return less than asked)."""
        with self._fs.open(path, 'rb') as f:
            f.seek(offset)
            parts = []
            remaining = length
            while remaining > 0:
                chunk = f.read(remaining)
                if not chunk:
                    break
                parts.append(chunk)
                remaining -= len(chunk)
        return b''.join(parts)

    def fetch_range(self, path: str, offset: int, length: int) -> bytes:
        """One resilient ranged read: retry + hedge apply to THIS range.
        With the observe flags set, the whole resilient call (hedges and
        retries included — the latency the pipeline actually saw) lands as
        one ``range_fetch`` span / ``io_range`` latency observation."""
        def fetch():
            return self._fetch_once(path, offset, length)
        self._count('io_range_requests')
        self._count('io_range_bytes', length)
        observing = self._observe_spans or self._observe_latency
        if not observing:
            if self._resilience is not None and self._resilience.enabled:
                return self._resilience.read(
                    fetch, description='range_read({}@{}+{})'.format(
                        path, offset, length))
            return fetch()
        retries = [0]
        start_s = time.perf_counter()
        try:
            if self._resilience is not None and self._resilience.enabled:
                def on_retry(exc, attempt):
                    retries[0] += 1
                result = self._resilience.read(
                    fetch, on_retry=on_retry,
                    description='range_read({}@{}+{})'.format(
                        path, offset, length))
            else:
                result = fetch()
        except Exception as e:
            self._observe_fetch(offset, length, start_s, retries[0],
                                type(e).__name__)
            raise
        self._observe_fetch(offset, length, start_s, retries[0], None)
        return result

    # -- footer / metadata -----------------------------------------------------

    def _file_size(self, path: str) -> int:
        size = getattr(self._fs, 'size', None)
        if callable(size):
            got = size(path)
            if got is not None:
                return int(got)
        return int(self._fs.info(path)['size'])

    def file_metadata(self, path: str):
        """``(size, pq.FileMetaData, (tail_offset, tail_bytes))`` for
        ``path``, resolved once per file from at most two tail fetches and
        cached (the object-store footer-cache idiom)."""
        with self._mutex:
            cached = self._footers.get(path)
        if cached is not None:
            return cached
        import pyarrow.parquet as pq
        size = self._file_size(path)
        tail_len = min(size, self._footer_bytes)
        tail = self.fetch_range(path, size - tail_len, tail_len)
        if len(tail) < 8 or tail[-4:] != _PARQUET_MAGIC:
            raise IOError('not a parquet file (bad trailing magic): '
                          '{}'.format(path))
        footer_len = _FOOTER_LEN.unpack(tail[-8:-4])[0] + 8
        if footer_len > tail_len:
            # rare long footer: one more exact fetch
            tail_len = min(size, footer_len)
            tail = self.fetch_range(path, size - tail_len, tail_len)
        metadata = pq.read_metadata(io.BytesIO(tail))
        entry = (size, metadata, (size - tail_len, tail))
        with self._mutex:
            self._footers.setdefault(path, entry)
        return entry

    # -- the read --------------------------------------------------------------

    def _fetch_into(self, path: str, plan: List[Tuple[int, int]],
                    buffer: RangeBuffer) -> None:
        """Fetch every planned range into ``buffer``, ``max_in_flight`` at a
        time. Fetch threads are per-call and joined before return — no
        persistent pool, nothing to leak at worker shutdown (the hedge
        layer's own race threads are drained by ``ResilientIO.drain``)."""
        if len(plan) == 1 or self._max_in_flight == 1:
            for offset, length in plan:
                buffer.insert(offset, self.fetch_range(path, offset, length))
            return
        work = list(plan)
        errors: List[BaseException] = []

        def pump():
            while True:
                with self._mutex:
                    if not work or errors:
                        return
                    offset, length = work.pop()
                try:
                    buffer.insert(offset,
                                  self.fetch_range(path, offset, length))
                except BaseException as e:  # noqa: BLE001 - re-raised below
                    with self._mutex:
                        errors.append(e)
                    return

        threads = [threading.Thread(
            target=pump, daemon=True,
            name='petastorm-tpu-rangeio-{}'.format(i))
            for i in range(min(self._max_in_flight, len(plan)))]
        for thread in threads:
            thread.start()
        for thread in threads:
            thread.join()
        if errors:
            raise errors[0]

    def fetch_row_group_bytes(self, path: str, row_group: int,
                              columns: Optional[List[str]] = None) -> int:
        """Fetch (and discard) the planned ranges for one row group; returns
        the planned byte count. This is the raw-ingest probe the profiler
        and the object-store benchmark time: parallel range throughput with
        no parquet assembly — the ceiling ranged row-group reads run
        under."""
        size, metadata, _tail = self.file_metadata(path)
        plan = self._planner.plan(metadata, row_group, columns)
        buffer = RangeBuffer(size,
                             lambda off, n: self.fetch_range(path, off, n))
        self._fetch_into(path, plan, buffer)
        return sum(length for _, length in plan)

    def read_row_group(self, path: str, row_group: int,
                       columns: Optional[List[str]] = None):
        """Read one row group as a ``pa.Table`` via planned parallel range
        fetches. ``columns=None`` reads every column."""
        import pyarrow.parquet as pq
        size, metadata, (tail_offset, tail) = self.file_metadata(path)
        chunks = self._planner.column_chunk_ranges(metadata, row_group,
                                                  columns)
        plan = self._planner.merge(chunks)
        buffer = RangeBuffer(
            size, lambda off, n: self.fetch_range(path, off, n),
            on_fallback=lambda n: self._count('io_range_fallbacks'))
        # the cached footer tail serves pyarrow's own footer reads for free
        buffer.insert(tail_offset, tail)
        self._fetch_into(path, plan, buffer)
        self._count('io_ranged_reads')
        wasted = self._planner.wasted_bytes(chunks, plan)
        if wasted:
            self._count('io_range_wasted_bytes', wasted)
        try:
            pf = pq.ParquetFile(buffer, metadata=metadata)
        except TypeError:   # pyarrow predating the metadata kwarg
            pf = pq.ParquetFile(buffer)
        table = pf.read_row_group(row_group, columns=columns)
        return table
