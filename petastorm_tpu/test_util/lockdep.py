"""lockdep-lite: a test-mode lock wrapper that turns lock-order inversions
and blocking-while-locked into test failures.

The static half of the concurrency gate (``ci/analysis``, rule R3) checks
what is *lexically* inside a ``with lock:`` body; this module is the runtime
half, modeled on the Linux kernel's lockdep: it learns the **lock
acquisition graph** from real executions and fails the moment the graph
grows a cycle — so an A→B / B→A inversion is caught the *first* time both
orders are ever observed, on any threads, without needing the actual
deadlock interleaving to strike in CI.

How it works:

- :class:`TrackedLock` / :class:`TrackedRLock` wrap the stdlib primitives.
  Each thread keeps a stack of tracked locks it holds; acquiring lock ``B``
  while holding ``A`` records the directed edge ``A → B`` (with the
  acquisition site). If a path ``B → ... → A`` already exists, that is a
  lock-order inversion: a :class:`LockOrderInversionError` is raised at the
  acquisition site *and* recorded on the registry (worker funnels may
  swallow the raise — see :meth:`LockdepRegistry.assert_clean`).
- :func:`lockdep_enabled` patches the ``threading`` (and ``time``) module
  attributes *of the target petastorm_tpu modules* with thin proxies, so
  every ``threading.Lock()`` those modules construct while the harness is
  active is tracked — without touching the interpreter-global ``threading``
  module (pytest's own locks stay untracked). ``time.sleep`` in the target
  modules becomes a **blocking-call guard**: sleeping while holding a
  tracked lock raises :class:`BlockingCallWhileLockedError` (the runtime
  twin of petalint R3).

Opt-in via the ``PETASTORM_TPU_LOCKDEP=1`` env var and the autouse fixture
in ``tests/conftest.py`` (applied to the ``test_sharedcache`` /
``test_health`` / ``test_workers_pool`` lanes; ``ci/run_tests.sh`` runs
them with the harness on). See ``docs/static_analysis.md``.
"""

from __future__ import annotations

import importlib
import threading
import time
import traceback
from contextlib import contextmanager
from typing import Dict, List, Optional, Set, Tuple

#: Env var gating the conftest fixture (default off: the harness costs a
#: dict lookup per acquire and is a diagnostic, not a production layer).
LOCKDEP_ENV_VAR = 'PETASTORM_TPU_LOCKDEP'

#: Modules whose ``threading.Lock``/``RLock`` constructions (and
#: ``time.sleep`` calls) are tracked while the harness is active — the
#: concurrency-critical set from ``mypy.ini``/petalint R2's scope.
DEFAULT_TARGET_MODULES = (
    'petastorm_tpu.sharedcache',
    'petastorm_tpu.health',
    'petastorm_tpu.tracing',
    'petastorm_tpu.lineage',
    'petastorm_tpu.latency',
    'petastorm_tpu.autotune',
    'petastorm_tpu.resilience',
    'petastorm_tpu.faultfs',
    'petastorm_tpu.workers.thread_pool',
    'petastorm_tpu.workers.stats',
    'petastorm_tpu.workers.ventilator',
    'petastorm_tpu.readers.readahead',
    'petastorm_tpu.readers.piece_worker',
    'petastorm_tpu.ops.decode',
    'petastorm_tpu.objectstore',
    'petastorm_tpu.podobs',
    'petastorm_tpu.goodput',
)


class LockdepError(AssertionError):
    """Base class; an AssertionError so pytest renders it as a failure."""


class LockOrderInversionError(LockdepError):
    """Acquiring this lock would close a cycle in the acquisition graph."""


class BlockingCallWhileLockedError(LockdepError):
    """A blocking call (``time.sleep``) ran while holding a tracked lock."""


class SelfDeadlockError(LockdepError):
    """A thread blocked on a non-reentrant lock it already holds."""


def _site(skip: int = 2) -> str:
    """A short 'file:line in func' acquisition-site string."""
    for frame in reversed(traceback.extract_stack()[:-skip]):
        if 'lockdep' not in frame.filename:
            return '{}:{} in {}'.format(frame.filename, frame.lineno,
                                        frame.name)
    return '<unknown>'


class LockdepRegistry:
    """The global acquisition graph plus per-thread held stacks.

    Violations are both raised at the offending call site and appended to
    :attr:`violations`, because the raise may happen on a worker thread
    whose exception funnel ships it somewhere a test never looks —
    :meth:`assert_clean` at fixture teardown is the backstop.
    """

    def __init__(self):
        # internal mutex is a RAW lock: the registry must never trip itself
        self._mu = threading.Lock()
        self._edges: Dict[int, Set[int]] = {}
        self._edge_sites: Dict[Tuple[int, int], str] = {}
        self._names: Dict[int, str] = {}
        self._tls = threading.local()
        self.violations: List[LockdepError] = []
        self.locks_created = 0
        # strong refs to every tracked lock: graph edges key on id(lock),
        # and a GC'd lock's recycled id would inherit stale edges (phantom
        # cycles = flaky false inversions). Registries are per-test, so the
        # retention is bounded by the test's lock population.
        self._retained: List['TrackedLock'] = []

    def retain(self, lock: 'TrackedLock') -> None:
        with self._mu:
            self._retained.append(lock)
            self.locks_created += 1

    # -- per-thread held stack -------------------------------------------------

    def _held(self) -> List['TrackedLock']:
        held = getattr(self._tls, 'held', None)
        if held is None:
            held = self._tls.held = []
        return held

    def held_names(self) -> List[str]:
        return [lock.name for lock in self._held()]

    # -- graph -----------------------------------------------------------------

    def _path_exists(self, src: int, dst: int) -> Optional[List[int]]:
        """DFS: a path ``src -> ... -> dst`` in the edge set, as node ids."""
        stack = [(src, [src])]
        seen = set()
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            if node in seen:
                continue
            seen.add(node)
            for nxt in self._edges.get(node, ()):
                stack.append((nxt, path + [nxt]))
        return None

    def note_acquire(self, lock: 'TrackedLock') -> None:
        """Called BEFORE the real acquire: record edges held → lock and
        fail on a cycle."""
        held = self._held()
        if any(h is lock for h in held):
            if lock.reentrant:
                return      # RLock re-acquire: no self edges
            # a plain Lock re-acquired by its holder blocks FOREVER — turn
            # the silent hang into an immediate, named failure
            error = SelfDeadlockError(
                'self-deadlock: thread already holds non-reentrant lock '
                '{!r} and is blocking on it again at {}'.format(
                    lock.name, _site()))
            with self._mu:
                self.violations.append(error)
            raise error
        site = _site()
        for h in held:
            a, b = id(h), id(lock)
            with self._mu:
                self._names[a] = h.name
                self._names[b] = lock.name
                known = b in self._edges.get(a, ())
                cycle = None if known else self._path_exists(b, a)
                if cycle is None:
                    self._edges.setdefault(a, set()).add(b)
                    self._edge_sites.setdefault((a, b), site)
                    continue
                names = ' -> '.join(self._names.get(n, '?')
                                    for n in cycle + [b])
                forward = self._edge_sites.get((cycle[0], cycle[1]),
                                               '<unknown>')
                error = LockOrderInversionError(
                    'lock-order inversion: acquiring {!r} while holding '
                    '{!r} at {}, but the opposite order {} was taken at {} '
                    '— two threads interleaving these paths deadlock'
                    .format(lock.name, h.name, site, names, forward))
                self.violations.append(error)
            raise error

    def push(self, lock: 'TrackedLock') -> None:
        self._held().append(lock)

    def pop(self, lock: 'TrackedLock') -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                return

    # -- blocking guard --------------------------------------------------------

    def check_blocking(self, what: str) -> None:
        held = self.held_names()
        if not held:
            return
        error = BlockingCallWhileLockedError(
            '{} while holding tracked lock(s) {} at {} — blocking work '
            'under a lock wedges every other acquirer (petalint R3, '
            'enforced at runtime)'.format(what, held, _site()))
        with self._mu:
            self.violations.append(error)
        raise error

    # -- teardown --------------------------------------------------------------

    def assert_clean(self) -> None:
        """Raise the first recorded violation (worker funnels may have
        swallowed the in-thread raise)."""
        if self.violations:
            raise self.violations[0]


class TrackedLock:
    """``threading.Lock`` with acquisition-graph bookkeeping."""

    _factory = staticmethod(threading.Lock)
    reentrant = False

    def __init__(self, registry: LockdepRegistry,
                 name: Optional[str] = None):
        self._registry = registry
        self._inner = self._factory()
        self.name = name or '{}@{}'.format(type(self).__name__,
                                           hex(id(self)))
        registry.retain(self)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if blocking:
            # a non-blocking try-acquire cannot deadlock; only blocking
            # acquisition orders enter the graph
            self._registry.note_acquire(self)
        got = (self._inner.acquire(blocking, timeout) if timeout != -1
               else self._inner.acquire(blocking))
        if got:
            self._registry.push(self)
        return got

    def release(self) -> None:
        self._inner.release()
        self._registry.pop(self)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class TrackedRLock(TrackedLock):
    """``threading.RLock`` variant: reentrant acquires push/pop pairwise,
    and :meth:`LockdepRegistry.note_acquire` skips self-edges."""

    _factory = staticmethod(threading.RLock)
    reentrant = True


class _ThreadingProxy:
    """Stands in for a module's ``threading`` attribute: ``Lock``/``RLock``
    become tracked constructors, everything else delegates."""

    def __init__(self, registry: LockdepRegistry, modname: str):
        self._registry = registry
        self._modname = modname

    def Lock(self):  # noqa: N802 - stdlib API shape
        return TrackedLock(self._registry, name='Lock({})'.format(
            self._modname))

    def RLock(self):  # noqa: N802 - stdlib API shape
        return TrackedRLock(self._registry, name='RLock({})'.format(
            self._modname))

    def __getattr__(self, name):
        return getattr(threading, name)


class _TimeProxy:
    """Stands in for a module's ``time`` attribute: ``sleep`` checks the
    blocking guard first, everything else delegates."""

    def __init__(self, registry: LockdepRegistry):
        self._registry = registry

    def sleep(self, seconds):
        self._registry.check_blocking('time.sleep({})'.format(seconds))
        return time.sleep(seconds)

    def __getattr__(self, name):
        return getattr(time, name)


@contextmanager
def lockdep_enabled(modules=DEFAULT_TARGET_MODULES):
    """Patch the target modules' ``threading``/``time`` attributes with
    tracking proxies for the duration of the block; yields the
    :class:`LockdepRegistry`. Locks created by those modules while active
    are tracked; pre-existing locks are not (session-scoped fixtures stay
    untouched). Restores the real modules on exit — the caller decides
    whether to :meth:`~LockdepRegistry.assert_clean`."""
    registry = LockdepRegistry()
    patched = []
    for modname in modules:
        try:
            mod = importlib.import_module(modname)
        except ImportError:
            continue
        if getattr(mod, 'threading', None) is threading:
            mod.threading = _ThreadingProxy(registry, modname)
            patched.append((mod, 'threading', threading))
        if getattr(mod, 'time', None) is time:
            mod.time = _TimeProxy(registry)
            patched.append((mod, 'time', time))
    try:
        yield registry
    finally:
        for mod, attr, original in patched:
            setattr(mod, attr, original)
