"""Thread-leak probing shared by tests and the conftest teardown fixture.

Every long-lived pipeline thread is named ``petastorm-tpu-*`` (enforced
statically by petalint rule R5), which makes "did this reader tear down
cleanly" a one-liner: enumerate live threads with the prefix. Promoted here
from the ad-hoc helper in ``tests/test_tracing.py`` so the shutdown
contract is checkable from any test lane (see the
``no_dangling_petastorm_threads`` fixture in ``tests/conftest.py``).
"""

from __future__ import annotations

import threading
import time
from typing import List, Sequence

#: The thread-name prefix of every first-party pipeline thread.
THREAD_NAME_PREFIX = 'petastorm-tpu-'


def petastorm_threads() -> List[str]:
    """Sorted names of live ``petastorm-tpu-*`` threads in this process."""
    return sorted(t.name for t in threading.enumerate()
                  if t.is_alive() and t.name.startswith(THREAD_NAME_PREFIX))


def wait_for_no_new_threads(before: Sequence[str],
                            timeout_s: float = 5.0) -> List[str]:
    """Names of ``petastorm-tpu-*`` threads alive past ``timeout_s`` that
    were not in ``before`` (multiset-aware: a pre-existing leak from an
    earlier test is not re-billed to this one). Empty list = clean."""
    deadline = time.monotonic() + timeout_s
    while True:
        budget = list(before)
        leaked = []
        for name in petastorm_threads():
            if name in budget:
                budget.remove(name)
            else:
                leaked.append(name)
        if not leaked or time.monotonic() >= deadline:
            return leaked
        # daemons signalled by an earlier stop() may still be mid-exit
        time.sleep(0.05)
