"""Test utilities: synthetic dataset generators and a no-I/O reader mock
(reference ``petastorm/test_util/``)."""
