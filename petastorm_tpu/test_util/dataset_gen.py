"""Synthetic dataset generators for tests and benchmarks.

Reference parity: ``petastorm/tests/test_common.py`` (``TestSchema`` :39-57,
``create_test_dataset`` :98-297) — but written with the pyarrow-native
``materialize_dataset`` instead of a local Spark session (SURVEY.md §4).

Generators return the expected decoded rows so tests can do value-exact
round-trip asserts.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from petastorm_tpu.codecs import (CompressedImageCodec, CompressedNdarrayCodec, NdarrayCodec,
                                  ScalarCodec)
from petastorm_tpu.etl.dataset_metadata import materialize_dataset
from petastorm_tpu.fs import get_filesystem_and_path_or_paths
from petastorm_tpu.unischema import Unischema, UnischemaField

TestSchema = Unischema('TestSchema', [
    UnischemaField('id', np.int64, (), ScalarCodec(), False),
    UnischemaField('id2', np.int32, (), ScalarCodec(), False),
    UnischemaField('id_float', np.float64, (), ScalarCodec(), False),
    UnischemaField('id_odd', np.bool_, (), ScalarCodec(), False),
    UnischemaField('partition_key', str, (), ScalarCodec(), False),
    UnischemaField('python_primitive_uint8', np.uint8, (), ScalarCodec(), False),
    UnischemaField('image_png', np.uint8, (16, 8, 3), CompressedImageCodec('png'), False),
    UnischemaField('matrix', np.float32, (8, 4, 3), NdarrayCodec(), False),
    UnischemaField('matrix_uint16', np.uint16, (2, 3), CompressedNdarrayCodec(), False),
    UnischemaField('matrix_nullable', np.int32, (None,), NdarrayCodec(), True),
    UnischemaField('sensor_name', str, (1,), NdarrayCodec(), False),
    UnischemaField('string_array_nullable', str, (None,), NdarrayCodec(), True),
])


def _row_for_id(i: int) -> Dict:
    """Deterministic row content for a given id (seeded per-row)."""
    rng = np.random.default_rng(i)
    return {
        'id': np.int64(i),
        'id2': np.int32(i % 5),
        'id_float': np.float64(i),
        'id_odd': np.bool_(i % 2),
        'partition_key': 'p_{}'.format(i % 10),
        'python_primitive_uint8': np.uint8(i % 255),
        'image_png': rng.integers(0, 255, (16, 8, 3), dtype=np.uint8),
        'matrix': rng.standard_normal((8, 4, 3)).astype(np.float32),
        'matrix_uint16': rng.integers(0, 2 ** 16, (2, 3), dtype='uint16').astype(np.uint16),
        'matrix_nullable': (rng.integers(0, 100, (4,), dtype='int64').astype(np.int32)
                            if i % 3 else None),
        'sensor_name': np.asarray(['sensor_{}'.format(i)]),
        'string_array_nullable': (np.asarray([str(i), 'abc']) if i % 4 else None),
    }


def create_test_dataset(url: str, ids, num_files: int = 4,
                        row_group_size_mb: float = 0.002) -> List[Dict]:
    """Materialize the full-featured ``TestSchema`` dataset; returns expected rows."""
    ids = list(ids)
    rows = [_row_for_id(i) for i in ids]
    rows_per_file = max(1, (len(rows) + num_files - 1) // num_files)
    with materialize_dataset(url, TestSchema, row_group_size_mb=row_group_size_mb,
                             rows_per_file=rows_per_file) as writer:
        writer.write_rows(rows)
    return rows


def create_test_scalar_dataset(url: str, num_rows: int, num_files: int = 2,
                               partition_by=None) -> List[Dict]:
    """Scalars-only petastorm_tpu dataset (reference ``create_test_scalar_dataset``)."""
    schema = Unischema('ScalarSchema', [
        UnischemaField('id', np.int64, (), ScalarCodec(), False),
        UnischemaField('int_fixed_size_list', np.int32, (), ScalarCodec(), False),
        UnischemaField('float64', np.float64, (), ScalarCodec(), True),
        UnischemaField('string', str, (), ScalarCodec(), True),
    ])
    rows = [{'id': np.int64(i),
             'int_fixed_size_list': np.int32(i * 2),
             'float64': np.float64(i) / 3 if i % 7 else None,
             'string': 'hello_{}'.format(i)} for i in range(num_rows)]
    rows_per_file = max(1, (num_rows + num_files - 1) // num_files)
    with materialize_dataset(url, schema, rows_per_file=rows_per_file) as writer:
        writer.write_rows(rows)
    return rows


def create_partitioned_dataset(url: str, num_rows: int, num_partitions: int = 3) -> List[Dict]:
    """Hive-partitioned plain parquet store: ``part=p_K/part_*.parquet``."""
    fs, path, _ = get_filesystem_and_path_or_paths(url)
    rows = [{'id': i, 'value': float(i), 'part': 'p_{}'.format(i % num_partitions)}
            for i in range(num_rows)]
    for k in range(num_partitions):
        part_dir = '{}/part=p_{}'.format(path, k)
        fs.makedirs(part_dir, exist_ok=True)
        chunk = [{'id': r['id'], 'value': r['value']} for r in rows
                 if r['part'] == 'p_{}'.format(k)]
        table = pa.Table.from_pylist(chunk)
        with fs.open(part_dir + '/part_00000.parquet', 'wb') as f:
            pq.write_table(table, f, row_group_size=max(1, len(chunk) // 2))
    return rows


def create_non_petastorm_dataset(url: str, num_rows: int, num_files: int = 2) -> List[Dict]:
    """A plain parquet store (no ``_common_metadata``) for ``make_batch_reader`` tests."""
    fs, path, _ = get_filesystem_and_path_or_paths(url)
    fs.makedirs(path, exist_ok=True)
    rows = [{'id': i, 'value': float(i) * 1.5, 'name': 'row_{}'.format(i)}
            for i in range(num_rows)]
    per_file = max(1, (num_rows + num_files - 1) // num_files)
    for part, start in enumerate(range(0, num_rows, per_file)):
        chunk = rows[start:start + per_file]
        table = pa.Table.from_pylist(chunk)
        with fs.open('{}/part_{:05d}.parquet'.format(path, part), 'wb') as f:
            # Two row groups per file so row-group-granular features are exercised.
            pq.write_table(table, f, row_group_size=max(1, len(chunk) // 2))
    return rows
