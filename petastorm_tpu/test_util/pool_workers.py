"""Trivial workers used by pool tests (importable from spawned worker
interpreters, unlike classes defined inside test modules)."""

import numpy as np

from petastorm_tpu.workers.worker_base import WorkerBase


class SquareWorker(WorkerBase):
    """Publishes x*x for each ventilated x."""

    def process(self, x):
        self.publish_func(x * x)


class MultiEmitWorker(WorkerBase):
    """Publishes `count` copies of x (tests 0..n results per item)."""

    def process(self, x, count):
        for _ in range(count):
            self.publish_func(x)


class FailingWorker(WorkerBase):
    """Raises on items equal to the poison value."""

    def process(self, x):
        if x == self.args['poison']:
            raise ValueError('poisoned item {}'.format(x))
        self.publish_func(x)


class ArrayWorker(WorkerBase):
    """Publishes a numpy array; exercises non-trivial payloads over zmq."""

    def process(self, n):
        self.publish_func(np.full((n,), n, dtype=np.int64))
