"""Trivial workers used by pool tests (importable from spawned worker
interpreters, unlike classes defined inside test modules)."""

import os
import time

import numpy as np

from petastorm_tpu.workers.worker_base import WorkerBase


class SquareWorker(WorkerBase):
    """Publishes x*x for each ventilated x."""

    def process(self, x):
        self.publish_func(x * x)


class MultiEmitWorker(WorkerBase):
    """Publishes `count` copies of x (tests 0..n results per item)."""

    def process(self, x, count):
        for _ in range(count):
            self.publish_func(x)


class FailingWorker(WorkerBase):
    """Raises on items equal to the poison value."""

    def process(self, x):
        if x == self.args['poison']:
            raise ValueError('poisoned item {}'.format(x))
        self.publish_func(x)


class ArrayWorker(WorkerBase):
    """Publishes a numpy array; exercises non-trivial payloads over zmq."""

    def process(self, n):
        self.publish_func(np.full((n,), n, dtype=np.int64))


class WedgeWorker(WorkerBase):
    """Wedges mid-item on the designated poison value — the stall-injection
    fixture for watchdog/flight-recorder tests.

    The wedge beats ``decode`` and then blocks on an event gate until
    released: ``args['wedge_event']`` (a ``threading.Event``, in-process
    pools) or — the cross-process form of the same gate — the appearance of
    ``args['release_file']`` on disk (process pools; polled every 10 ms).
    ``args['max_wait_s']`` (default 60) bounds the wedge so a broken test
    can never hang CI. Non-poison items publish straight through.
    """

    def process(self, x):
        if x == self.args['wedge_on']:
            self.beat('decode')
            event = self.args.get('wedge_event')
            release_file = self.args.get('release_file')
            deadline = time.monotonic() + self.args.get('max_wait_s', 60)
            while time.monotonic() < deadline:
                if event is not None and event.wait(timeout=0.01):
                    break
                if release_file is not None and os.path.exists(release_file):
                    break
                if event is None and release_file is None:
                    raise ValueError('WedgeWorker needs wedge_event or '
                                     'release_file')
                if event is not None:
                    continue
                time.sleep(0.01)
            else:
                raise RuntimeError('WedgeWorker was never released within '
                                   '{}s'.format(self.args.get('max_wait_s', 60)))
        self.publish_func(x)
