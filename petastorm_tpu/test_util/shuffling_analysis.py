"""Shuffle-quality analysis: quantify how decorrelated a shuffled id stream is
from the unshuffled read order.

Reference parity: ``petastorm/test_util/shuffling_analysis.py:30-85`` — the
reference correlates shuffled vs unshuffled id streams over multiple reads;
``compute_correlation_distance`` here is the same statistic usable in tests:
values near 0 mean well shuffled, near 1 mean order preserved.
"""

from __future__ import annotations

import numpy as np


def compute_correlation_distance(shuffled_ids, unshuffled_ids) -> float:
    """|Pearson correlation| between the positions of each id in the two
    streams (0 = fully decorrelated order, 1 = identical/reversed order)."""
    shuffled_ids = np.asarray(shuffled_ids)
    unshuffled_ids = np.asarray(unshuffled_ids)
    if sorted(shuffled_ids.tolist()) != sorted(unshuffled_ids.tolist()):
        raise ValueError('Streams must contain the same multiset of ids')
    pos_in_shuffled = {v: i for i, v in enumerate(shuffled_ids.tolist())}
    positions = np.array([pos_in_shuffled[v] for v in unshuffled_ids.tolist()])
    baseline = np.arange(len(positions))
    if len(positions) < 2:
        return 1.0
    corr = np.corrcoef(positions, baseline)[0, 1]
    return float(abs(corr))


def analyze_shuffling_quality(reader_factory, num_reads: int = 3) -> float:
    """Open the reader ``num_reads + 1`` times: the first unshuffled pass is
    the baseline; returns the mean correlation distance of subsequent passes
    (reference ``analyze_shuffling_quality``)."""
    with reader_factory(shuffle=False) as reader:
        baseline = [row.id for row in reader]
    distances = []
    for _ in range(num_reads):
        with reader_factory(shuffle=True) as reader:
            shuffled = [row.id for row in reader]
        distances.append(compute_correlation_distance(shuffled, baseline))
    return float(np.mean(distances))
