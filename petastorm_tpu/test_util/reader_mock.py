"""Schema-driven fake reader: yields synthetic schema-compliant rows with zero
I/O — for testing adapters/loaders without a dataset on disk.

Reference parity: ``petastorm/test_util/reader_mock.py:19-82``.
"""

from __future__ import annotations

import numpy as np

from petastorm_tpu.unischema import Unischema


def schema_data_generator_example(schema: Unischema, rng=None):
    """Generate one schema-compliant row dict with random values."""
    rng = rng or np.random.default_rng()
    row = {}
    for field in schema.fields.values():
        shape = tuple(s if s is not None else rng.integers(1, 4)
                      for s in (field.shape or ()))
        dtype = field.numpy_dtype
        if dtype in (str, np.str_):
            value = ('mock_' + str(rng.integers(0, 1000)) if not shape
                     else np.array(['mock'] * int(np.prod(shape))).reshape(shape))
        elif dtype in (bytes, np.bytes_):
            value = b'mock'
        else:
            dt = np.dtype(dtype)
            if dt.kind in 'iu':
                value = np.asarray(rng.integers(0, 100, size=shape)).astype(dt)
            elif dt.kind == 'b':
                value = np.asarray(rng.integers(0, 2, size=shape) > 0)
            else:
                value = np.asarray(rng.random(size=shape)).astype(dt)
            if not shape:
                value = dt.type(value.item())
        row[field.name] = value
    return row


class ReaderMock(object):
    """Duck-types the Reader iteration surface (schema, batched_output, ngram,
    __iter__/__next__, reset/stop/join) over a row generator function."""

    def __init__(self, schema: Unischema, schema_data_generator=None,
                 num_rows: int = 1000, seed: int = 0):
        self.schema = schema
        self.ngram = None
        self.batched_output = False
        self.last_row_consumed = False
        self._generator = schema_data_generator or schema_data_generator_example
        self._num_rows = num_rows
        self._seed = seed
        self._produced = 0

    def __iter__(self):
        return self

    def __next__(self):
        if self._produced >= self._num_rows:
            self.last_row_consumed = True
            raise StopIteration
        rng = np.random.default_rng(self._seed + self._produced)
        self._produced += 1
        row = self._generator(self.schema, rng)
        return self.schema.make_namedtuple(**row)

    next = __next__

    def reset(self):
        self._produced = 0
        self.last_row_consumed = False

    def stop(self):
        pass

    def join(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        pass
