"""Host-side document packing: variable-length token sequences → fixed-shape
``(tokens, segment_ids, positions)`` batches for packed-attention training.

This bridges the data layer (NGram/token pipelines emit variable-length
documents; XLA wants static shapes) and the attention kernels'
``segment_ids`` support (``ops/attention.py``): several documents share one
sequence row, cross-document attention is masked, and positions restart per
document so rotary embeddings see each document at offset 0.

The reference has no packing (its TF/torch consumers tolerate ragged
batches); this is TPU-native capability: pad-to-bucket wastes
``(bucket − len)`` of every row, packing wastes only the final-row tail.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence

import numpy as np

import jax.numpy as jnp


class PackedBatch(NamedTuple):
    """``tokens`` (B, L); ``segment_ids`` (B, L) int32 — 0 marks padding,
    documents count from 1 per row; ``positions`` (B, L) int32 — restart at 0
    on every document boundary."""
    tokens: jnp.ndarray
    segment_ids: jnp.ndarray
    positions: jnp.ndarray


def pack_documents(docs: Sequence[Sequence[int]], seq_len: int, *,
                   pad_token: int = 0, dtype=np.int32,
                   num_rows: 'Optional[int]' = None) -> PackedBatch:
    """Greedy first-fit packing (documents in order, each placed into the
    first row with room — deterministic, so resumable pipelines re-produce
    identical batches).

    Every document must fit a row: ``len(doc) <= seq_len`` (split longer
    documents upstream — the NGram window assembler already bounds window
    length).

    ``num_rows`` pins the batch dimension for jitted consumers: the output
    is padded with all-padding rows up to ``num_rows`` (and packing raises
    if the documents need more). Without it the row count is data-dependent
    — fine eagerly, but every distinct count retraces a jitted train step,
    so streaming pipelines should always pass it.
    """
    rows: List[List[Sequence[int]]] = []
    space: List[int] = []
    for doc in docs:
        n = len(doc)
        if n == 0:
            raise ValueError('cannot pack an empty document')
        if n > seq_len:
            raise ValueError('document of length %d exceeds seq_len=%d; '
                             'split it upstream' % (n, seq_len))
        for i, free in enumerate(space):
            if free >= n:
                rows[i].append(doc)
                space[i] -= n
                break
        else:
            rows.append([doc])
            space.append(seq_len - n)

    if num_rows is not None:
        if len(rows) > num_rows:
            raise ValueError(
                'documents need %d rows but num_rows=%d; feed fewer '
                'documents per batch' % (len(rows), num_rows))
        rows.extend([[] for _ in range(num_rows - len(rows))])
    b = len(rows)
    tokens = np.full((b, seq_len), pad_token, dtype=dtype)
    segment_ids = np.zeros((b, seq_len), dtype=np.int32)
    positions = np.zeros((b, seq_len), dtype=np.int32)
    for i, row_docs in enumerate(rows):
        cursor = 0
        for seg, doc in enumerate(row_docs, start=1):
            n = len(doc)
            tokens[i, cursor:cursor + n] = np.asarray(doc, dtype=dtype)
            segment_ids[i, cursor:cursor + n] = seg
            positions[i, cursor:cursor + n] = np.arange(n)
            cursor += n
    return PackedBatch(jnp.asarray(tokens), jnp.asarray(segment_ids),
                       jnp.asarray(positions))


def packed_lm_targets(tokens, segment_ids):
    """Next-token targets and loss weights for a packed batch: weight 1 where
    the current AND next slot belong to the same (nonzero) document — the
    last token of each document and all padding get weight 0, so no document
    is trained to predict its neighbor's first token."""
    targets = jnp.concatenate(
        [tokens[:, 1:], jnp.zeros_like(tokens[:, :1])], axis=1)
    next_seg = jnp.concatenate(
        [segment_ids[:, 1:], jnp.zeros_like(segment_ids[:, :1])], axis=1)
    weights = ((segment_ids > 0)
               & (segment_ids == next_seg)).astype(jnp.float32)
    return targets, weights
