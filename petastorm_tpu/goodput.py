"""Training goodput plane: per-step data-stall attribution (docs/goodput.md).

Every sensor before this one watches the host pipeline; the
:class:`GoodputMonitor` answers the question the framework exists to
answer — *is the accelerator actually fed?* It decomposes every training
step the consumer loop takes into

    total_s = infeed_wait_s + train_wall_s
    infeed_wait_s = stall_s + h2d_stage_s          (data-path cost)
    train_wall_s  = device_step_s + host_overhead_s (with the opt-in fence)

using the timing sites the loader already owns (``JaxLoaderBase.__iter__``
times the blocking fetch; ``stage_to_global``/``prefetch_to_device``
report their staging seconds via :meth:`GoodputMonitor.note_stage`) plus
an opt-in ``block_until_ready`` step fence (:meth:`GoodputMonitor.fence`).
Without the fence the device/host split inside the train wall is unknown
and the whole wall is attributed to ``device_step`` (recorded as
unfenced, so verdicts stay honest about what was measured).

The monitor is **threadless** and records three ways, all mergeable:

- a bounded per-step ring (:meth:`steps`) for :meth:`explain_step`;
- the shared latency plane (new ``device_step`` / ``host_overhead``
  stages — the loader itself already records ``infeed_wait`` and
  ``train_step``), whose log-bucketed histograms merge bit-identically
  across hosts (docs/latency.md);
- summed-seconds counters in ``ReaderStats`` (``goodput_total_s`` et al.)
  from which the derived ``goodput_fraction`` / ``data_stall_fraction``
  are computed — pod aggregation sums the seconds and re-derives the
  fraction, never averages fractions (docs/pod_observability.md).

Default-on behind the structural ``PETASTORM_TPU_GOODPUT=0`` kill switch:
off means no monitor object, no ring, no new latency stages recorded, no
``/goodput`` route — not a no-op shim.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = ['GOODPUT_ENV_VAR', 'GoodputMonitor', 'goodput_enabled',
           'classify_step', 'DATA_STALL', 'COMPUTE_BOUND', 'HOST_OVERHEAD',
           'BALANCED']

#: Kill switch (default ON, the observability-plane convention): ``0`` /
#: ``false`` / ``off`` yields loaders with no monitor, no recorded
#: stages and no ``/goodput`` route.
GOODPUT_ENV_VAR = 'PETASTORM_TPU_GOODPUT'

#: Verdict vocabulary (docs/goodput.md): the dominant component of a
#: step's wall time, when it dominates at all.
DATA_STALL = 'data-stall'
COMPUTE_BOUND = 'compute-bound'
HOST_OVERHEAD = 'host-overhead'
BALANCED = 'balanced'

#: A component must carry at least this fraction of the step wall to be
#: named the verdict; below it no single stage dominates.
DOMINANCE_THRESHOLD = 0.4

#: Per-step ring bound: explain_step() reaches this far back.
DEFAULT_STEP_RING = 512

#: Rolling goodput window (steps) for :meth:`GoodputMonitor.summary`.
DEFAULT_WINDOW_STEPS = 32


def goodput_enabled() -> bool:
    """The goodput plane's kill switch (default on)."""
    return os.environ.get(GOODPUT_ENV_VAR, '1').lower() not in (
        '0', 'false', 'off')


def classify_step(entry: dict) -> str:
    """The verdict for one ring entry: the dominant wall-time component
    (data stall / device compute / host overhead) when one carries at
    least :data:`DOMINANCE_THRESHOLD` of the step, else ``balanced``."""
    total = entry.get('total_s') or 0.0
    if total <= 0.0:
        return BALANCED
    stall_f = (entry.get('stall_s', 0.0) + entry.get('h2d_stage_s', 0.0)) / total
    device_f = entry.get('device_step_s', 0.0) / total
    host_f = entry.get('host_overhead_s', 0.0) / total
    best, verdict = stall_f, DATA_STALL
    if device_f > best:
        best, verdict = device_f, COMPUTE_BOUND
    if host_f > best:
        best, verdict = host_f, HOST_OVERHEAD
    return verdict if best >= DOMINANCE_THRESHOLD else BALANCED


class GoodputMonitor:
    """Per-step goodput accounting for one consumer loop.

    Constructed by ``JaxLoaderBase`` when :func:`goodput_enabled`; the
    loader drives :meth:`note_fetch` / :meth:`finish_step` from its
    ``__iter__`` and the staging sites drive :meth:`note_stage` (possibly
    from the prefetch producer thread — the pending accumulators are
    lock-protected; the monitor itself never starts a thread).

    ``stats`` / ``tracer`` are the reader's planes (any may be ``None``):
    summed seconds land in ``ReaderStats`` counters, per-step
    ``device_step``/``host_overhead`` observations in the latency
    histograms, and one ``'step'`` span per step (cat ``'goodput'``,
    args carrying the verdict + stall ms) in the tracer — complete spans,
    so ``stitch_pod_trace`` aligns step boundaries across hosts unchanged.
    """

    def __init__(self, stats=None, tracer=None, latency=None,
                 ring_size: int = DEFAULT_STEP_RING,
                 window_steps: int = DEFAULT_WINDOW_STEPS,
                 host: Optional[str] = None):
        self._stats = stats
        self._tracer = tracer
        # a standalone monitor (benchmarks, pod fixtures) can record into
        # a latency plane directly; a loader-attached one routes through
        # stats.record_latency so histograms live with the reader's plane
        self._latency = latency
        self._host = host
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=ring_size)
        self._window_steps = max(1, int(window_steps))
        self._steps = 0
        self._fenced_steps = 0
        # summed-seconds totals (the mergeable pod export)
        self._total_s = 0.0
        self._stall_s = 0.0
        self._h2d_s = 0.0
        self._device_s = 0.0
        self._host_s = 0.0
        # pending state for the step in flight
        self._pending_infeed_s = 0.0
        self._pending_h2d_s = 0.0
        self._pending_fence_s = 0.0
        self._pending_fenced = False
        self._pending_provenance = None
        self._step_open = False

    # -- hot-path hooks (loader / staging sites) -------------------------------

    def note_fetch(self, infeed_wait_s: float, batch=None) -> None:
        """The loader fetched a batch after blocking ``infeed_wait_s``
        seconds; opens the step the consumer is about to run."""
        provenance = None
        if isinstance(batch, dict):
            provenance = batch.get('_provenance')
        with self._lock:
            self._pending_infeed_s = max(0.0, float(infeed_wait_s))
            self._pending_provenance = provenance
            self._pending_fence_s = 0.0
            self._pending_fenced = False
            self._step_open = True

    def note_stage(self, elapsed_s: float) -> None:
        """``elapsed_s`` seconds of host→device staging happened; it is
        attributed to the next step to finish (staging may run ahead on
        the prefetch producer thread — attribution, not measurement)."""
        with self._lock:
            self._pending_h2d_s += max(0.0, float(elapsed_s))

    def fence(self, outputs):
        """Opt-in step fence: ``jax.block_until_ready(outputs)`` timed, so
        the train wall splits into device time (the wait here) and host
        overhead (the rest). Call it on the step's outputs inside the
        training loop; returns ``outputs``. Without it the whole train
        wall counts as device time and the step records ``fenced=False``."""
        import jax
        start = time.perf_counter()
        outputs = jax.block_until_ready(outputs)
        elapsed = time.perf_counter() - start
        with self._lock:
            self._pending_fence_s += elapsed
            self._pending_fenced = True
        return outputs

    def finish_step(self, train_wall_s: float) -> Optional[dict]:
        """Close the step the consumer just ran (``train_wall_s`` is the
        yield-to-next-fetch wall the loader measured). Returns the ring
        entry, or ``None`` when no step was open."""
        train_wall_s = max(0.0, float(train_wall_s))
        with self._lock:
            if not self._step_open:
                return None
            infeed = self._pending_infeed_s
            h2d = self._pending_h2d_s
            fence_s = self._pending_fence_s
            fenced = self._pending_fenced
            provenance = self._pending_provenance
            self._pending_infeed_s = 0.0
            self._pending_h2d_s = 0.0
            self._pending_fence_s = 0.0
            self._pending_fenced = False
            self._pending_provenance = None
            self._step_open = False
            step = self._steps
            self._steps += 1
            # the h2d seconds on the critical path are at most the time
            # the consumer actually waited; the rest overlapped compute
            h2d_attrib = min(h2d, infeed)
            stall = infeed - h2d_attrib
            if fenced:
                device = min(fence_s, train_wall_s)
                host = train_wall_s - device
                self._fenced_steps += 1
            else:
                device = train_wall_s
                host = 0.0
            total = infeed + train_wall_s
            entry = {
                'step': step,
                'total_s': total,
                'infeed_wait_s': infeed,
                'stall_s': stall,
                'h2d_stage_s': h2d_attrib,
                'device_step_s': device,
                'host_overhead_s': host,
                'fenced': fenced,
                'provenance': provenance,
            }
            self._ring.append(entry)
            self._total_s += total
            self._stall_s += stall
            self._h2d_s += h2d_attrib
            self._device_s += device
            self._host_s += host
        self._record(entry)
        return entry

    def _record(self, entry: dict) -> None:
        """Export one closed step to the shared planes (outside the lock:
        stats/tracer take their own locks)."""
        stats = self._stats
        if stats is not None:
            stats.add_time('goodput_total_s', entry['total_s'])
            stats.add_time('goodput_stall_s', entry['stall_s'])
            stats.add_time('goodput_h2d_s', entry['h2d_stage_s'])
            stats.add_time('goodput_device_s', entry['device_step_s'])
            stats.add_time('goodput_host_s', entry['host_overhead_s'])
            stats.record_latency('device_step', entry['device_step_s'])
            if entry['fenced']:
                stats.record_latency('host_overhead', entry['host_overhead_s'])
        elif self._latency is not None:
            self._latency.record('device_step', entry['device_step_s'])
            if entry['fenced']:
                self._latency.record('host_overhead',
                                     entry['host_overhead_s'])
        tracer = self._tracer
        if tracer is not None:
            now = time.perf_counter()
            stall_ms = (entry['stall_s'] + entry['h2d_stage_s']) * 1000.0
            tracer.add_span('step', 'goodput', now - entry['total_s'],
                            entry['total_s'],
                            args={'step': entry['step'],
                                  'verdict': classify_step(entry),
                                  'stall_ms': round(stall_ms, 3),
                                  'fenced': entry['fenced']})

    # -- read side -------------------------------------------------------------

    def steps(self) -> List[dict]:
        """The bounded per-step ring, oldest first (copies)."""
        with self._lock:
            return [dict(e) for e in self._ring]

    def step(self, n: int) -> Optional[dict]:
        """Ring entry for step ``n`` (``None`` when evicted/unknown)."""
        with self._lock:
            for entry in reversed(self._ring):
                if entry['step'] == n:
                    return dict(entry)
        return None

    def state(self) -> dict:
        """The mergeable pod export: summed seconds + step counts. Pod
        aggregation adds these and re-derives fractions — fractions are
        never averaged across hosts (``_NON_ADDITIVE_SUFFIXES``)."""
        with self._lock:
            return {
                'steps': self._steps,
                'fenced_steps': self._fenced_steps,
                'total_s': self._total_s,
                'stall_s': self._stall_s,
                'h2d_s': self._h2d_s,
                'device_s': self._device_s,
                'host_s': self._host_s,
            }

    def window(self, steps: Optional[int] = None) -> dict:
        """Rolling goodput over the last ``steps`` ring entries."""
        limit = steps or self._window_steps
        with self._lock:
            tail = list(self._ring)[-limit:]
        total = sum(e['total_s'] for e in tail)
        if not tail or total <= 0.0:
            return {'steps': len(tail), 'goodput_fraction': None,
                    'data_stall_fraction': None}
        stall = sum(e['stall_s'] + e['h2d_stage_s'] for e in tail)
        device = sum(e['device_step_s'] for e in tail)
        return {
            'steps': len(tail),
            'goodput_fraction': round(device / total, 4),
            'data_stall_fraction': round(stall / total, 4),
        }

    def summary(self) -> dict:
        """Cumulative + rolling-window goodput; what ``/goodput`` serves."""
        state = self.state()
        total = state['total_s']
        out = {
            'enabled': True,
            'steps': state['steps'],
            'fenced_steps': state['fenced_steps'],
            'goodput_fraction': (round(state['device_s'] / total, 4)
                                 if total > 0 else None),
            'data_stall_fraction': (
                round((state['stall_s'] + state['h2d_s']) / total, 4)
                if total > 0 else None),
            'window': self.window(),
            'state': state,
        }
        if self._host is not None:
            out['host'] = self._host
        return out

    def flight_summary(self) -> dict:
        """The flight-record section: summary + the last few ring entries
        (JSON-able — provenance objects summarized)."""
        tail = self.steps()[-8:]
        for entry in tail:
            entry['provenance'] = _provenance_summary(entry.get('provenance'))
            entry['verdict'] = classify_step(entry)
        return dict(self.summary(), recent_steps=tail)

    def explain_step(self, n: Optional[int] = None, snapshot: Optional[dict] = None,
                     heartbeats=None) -> dict:
        """The per-step verdict, joined with the pipeline's own evidence:
        which component dominated step ``n`` (latest when ``None``), and —
        when it was a data stall — the culprit stage chain walked from
        ``bottleneck_signals`` over ``snapshot`` ("step 412 stalled 38ms on
        infeed_wait → queue_wait p99 tail → io_range"), prefetch-buffer
        occupancy at the snapshot, and the batch's ``_provenance`` naming
        the source row groups. ``heartbeats`` is accepted for parity with
        the health surfaces (reserved for stalled-entity naming)."""
        if n is None:
            entries = self.steps()
            entry = entries[-1] if entries else None
        else:
            entry = self.step(n)
        if entry is None:
            return {'enabled': True, 'step': n, 'verdict': None,
                    'explanation': 'no such step in the ring '
                                   '(evicted or never recorded)'}
        verdict = classify_step(entry)
        total = entry['total_s'] or 0.0
        stall_s = entry['stall_s'] + entry['h2d_stage_s']
        chain: List[str] = []
        if verdict == DATA_STALL:
            chain.append('h2d_stage' if entry['h2d_stage_s'] > entry['stall_s']
                         else 'infeed_wait')
            signals = None
            if snapshot:
                from petastorm_tpu.health import bottleneck_signals
                signals = bottleneck_signals(snapshot)
                if signals.get('tail_stall'):
                    chain.append('queue_wait p99 tail')
                if signals.get('slow_object_store'):
                    chain.append('io_range')
                elif signals.get('slow_peer_cache'):
                    chain.append('peer_fetch')
                elif not signals.get('tail_stall'):
                    bottleneck = signals.get('bottleneck')
                    if bottleneck and bottleneck != 'none':
                        chain.append(bottleneck)
        elif verdict == HOST_OVERHEAD:
            chain.append('host_overhead')
        elif verdict == COMPUTE_BOUND:
            chain.append('device_step')
        provenance = _provenance_summary(entry.get('provenance'))
        if chain and provenance and provenance.get('sources'):
            source = provenance['sources'][0]
            where = source.get('path')
            if where:
                suffix = where.rsplit('/', 1)[-1]
                chain[-1] = '{} ({} rg{})'.format(
                    chain[-1], suffix, source.get('row_group'))
        occupancy = None
        if snapshot:
            occupancy = snapshot.get('prefetch_occupancy')
        if verdict == DATA_STALL:
            explanation = 'step {} stalled {:.0f}ms on {}'.format(
                entry['step'], stall_s * 1000.0,
                ' → '.join(chain) if chain else 'infeed_wait')
        elif verdict == COMPUTE_BOUND:
            explanation = ('step {} spent {:.0f}ms of {:.0f}ms in device '
                           'compute — the input pipeline kept up'.format(
                               entry['step'],
                               entry['device_step_s'] * 1000.0,
                               total * 1000.0))
        elif verdict == HOST_OVERHEAD:
            explanation = ('step {} spent {:.0f}ms in host-side work '
                           'between fetch and device completion'.format(
                               entry['step'],
                               entry['host_overhead_s'] * 1000.0))
        else:
            explanation = ('step {} is balanced: no component carries '
                           '{:.0%} of the wall'.format(
                               entry['step'], DOMINANCE_THRESHOLD))
        out: Dict[str, Any] = {
            'enabled': True,
            'step': entry['step'],
            'verdict': verdict,
            'explanation': explanation,
            'chain': chain,
            'stall_ms': round(stall_s * 1000.0, 3),
            'decomposition': {
                'total_s': entry['total_s'],
                'infeed_wait_s': entry['infeed_wait_s'],
                'stall_s': entry['stall_s'],
                'h2d_stage_s': entry['h2d_stage_s'],
                'device_step_s': entry['device_step_s'],
                'host_overhead_s': entry['host_overhead_s'],
                'fenced': entry['fenced'],
            },
        }
        if occupancy is not None:
            out['prefetch_occupancy'] = occupancy
        if provenance is not None:
            out['provenance'] = provenance
        if self._host is not None:
            out['host'] = self._host
        return out


def _provenance_summary(provenance) -> Optional[dict]:
    """A JSON-able view of a ring entry's provenance (a
    ``BatchProvenance``, a ``Provenance`` record, or ``None``)."""
    if provenance is None:
        return None
    summary = getattr(provenance, 'summary', None)
    if callable(summary):
        try:
            return summary()
        except Exception:  # a foreign object with a summary() of its own
            return None
    asdict = getattr(provenance, '_asdict', None)
    if callable(asdict):
        record = asdict()
        record.pop('selection', None)
        return {'rows': record.get('rows'), 'sources': [record]}
    if isinstance(provenance, dict):
        return provenance
    return None
