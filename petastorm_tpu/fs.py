"""Filesystem resolution: URL -> (fsspec filesystem, path), picklable factories.

Reference parity: ``petastorm/fs_utils.py`` — ``FilesystemResolver`` (:42-166),
``get_filesystem_and_path_or_paths`` (:202-232), ``normalize_dir_url`` (:235).

TPU-first deviation: instead of hand-rolled per-scheme adapters (HDFS namenode
parsing, ``GCSFSWrapper``), resolution delegates to **fsspec**, whose
implementations (``gcsfs``, ``s3fs``, ``adlfs``, builtin ``file``/``memory``)
are what pyarrow's dataset API consumes directly. GCS is the first-class remote
for TPU pods. The reference's HDFS HA failover logic (``hdfs/namenode.py``) is
subsumed by fsspec's hdfs implementation; a retry wrapper is provided here for
parity with ``HAHdfsClient``-style robustness.
"""

from __future__ import annotations

import functools
import logging
from typing import Dict, Optional, Tuple
from urllib.parse import urlparse

logger = logging.getLogger(__name__)

#: Schemes normalized onto a canonical fsspec protocol.
_SCHEME_ALIASES = {
    '': 'file',
    'file': 'file',
    'hdfs': 'hdfs',
    's3': 's3', 's3a': 's3', 's3n': 's3',
    'gs': 'gcs', 'gcs': 'gcs',
    'memory': 'memory',
}


def normalize_dir_url(dataset_url: str) -> str:
    """Strip trailing slashes from a dataset directory URL
    (reference ``fs_utils.py:235-241``)."""
    if not isinstance(dataset_url, str):
        raise ValueError('dataset_url must be a string, got {!r}'.format(dataset_url))
    return dataset_url.rstrip('/')


def normalize_dataset_url_or_urls(dataset_url_or_urls):
    """Accept a single URL or a non-empty list of URLs
    (reference ``reader.py:52-58``)."""
    if isinstance(dataset_url_or_urls, (list, tuple)):
        if not dataset_url_or_urls:
            raise ValueError('dataset url list must be non-empty')
        return [normalize_dir_url(u) for u in dataset_url_or_urls]
    return normalize_dir_url(dataset_url_or_urls)


class FilesystemFactory:
    """Picklable callable producing a fresh fsspec filesystem — usable in spawned
    worker processes (reference ``filesystem_factory`` concept, ``fs_utils.py:170-199``).

    For HDFS HA name services (resolved from Hadoop XML configs) the factory
    returns an :class:`petastorm_tpu.hdfs.namenode.HAHdfsClient` that retries
    calls across namenodes (reference ``hdfs/namenode.py:241-319``)."""

    def __init__(self, protocol: str, storage_options: Optional[Dict] = None,
                 hdfs_namenodes: Optional[list] = None):
        self._protocol = protocol
        self._storage_options = dict(storage_options or {})
        self._hdfs_namenodes = hdfs_namenodes

    def __call__(self):
        if self._hdfs_namenodes:
            from petastorm_tpu.hdfs.namenode import HdfsConnector
            return HdfsConnector.connect_to_either_namenode(self._hdfs_namenodes)
        import fsspec
        return fsspec.filesystem(self._protocol, **self._storage_options)

    def __repr__(self):
        return 'FilesystemFactory({!r})'.format(self._protocol)


def _resolve_hdfs_namenodes(url: str) -> Optional[list]:
    """Namenode list when the url's authority is a configured HA name service
    (requires HADOOP_HOME-style configs); None otherwise."""
    netloc = urlparse(url).netloc
    if not netloc or ':' in netloc:
        return None   # explicit host:port — not a name service
    try:
        from petastorm_tpu.hdfs.namenode import HdfsNamenodeResolver
        return HdfsNamenodeResolver().resolve_hdfs_name_service(netloc)
    except Exception:
        logger.debug('HDFS name service resolution failed for %s', url,
                     exc_info=True)
        return None


def _parse_url(url: str) -> Tuple[str, str]:
    """URL -> (fsspec protocol, path). Scheme-less URLs are treated as local
    paths (deviation: the reference refuses them, ``fs_utils.py:74-79``; a local
    path default is friendlier and unambiguous on a TPU VM)."""
    parsed = urlparse(url)
    scheme = parsed.scheme.lower()
    if scheme not in _SCHEME_ALIASES:
        raise ValueError('Unsupported url scheme {!r} in {!r}. Supported: {}'.format(
            scheme, url, sorted(s for s in _SCHEME_ALIASES if s)))
    protocol = _SCHEME_ALIASES[scheme]
    if protocol == 'file':
        # RFC 8089 allows 'file://localhost/abs/path'; any other authority means
        # the user typed 'file://tmp/x' expecting /tmp/x — catch that typo.
        if parsed.netloc and parsed.netloc != 'localhost':
            raise ValueError(
                'file:// URLs must use three slashes (file:///abs/path); got {!r} whose '
                'authority component {!r} would be dropped'.format(url, parsed.netloc))
        path = parsed.path if scheme else url
    elif protocol in ('s3', 'gcs'):
        path = parsed.netloc + parsed.path
    elif protocol == 'memory':
        # fsspec memory paths are rooted: memory://a/b -> /a/b
        path = '/' + parsed.netloc + parsed.path if parsed.netloc else parsed.path
    else:  # hdfs and friends keep the authority in the filesystem, path only
        path = parsed.path
    return protocol, path


def get_filesystem_and_path_or_paths(url_or_urls, storage_options: Optional[Dict] = None):
    """Resolve URL(s) to ``(filesystem, path_or_paths, filesystem_factory)``.

    All URLs in a list must live on the same filesystem
    (reference ``fs_utils.py:202-232``).
    """
    import fsspec
    urls = url_or_urls if isinstance(url_or_urls, list) else [url_or_urls]
    parsed = [_parse_url(u) for u in urls]
    protocols = {p for p, _ in parsed}
    if len(protocols) > 1:
        raise ValueError('All urls must be on the same filesystem, got {}'.format(protocols))
    protocol = parsed[0][0]
    paths = [path for _, path in parsed]
    hdfs_namenodes = _resolve_hdfs_namenodes(urls[0]) if protocol == 'hdfs' else None
    factory = FilesystemFactory(protocol, storage_options,
                                hdfs_namenodes=hdfs_namenodes)
    fs = factory() if hdfs_namenodes else fsspec.filesystem(
        protocol, **(storage_options or {}))
    path_or_paths = paths if isinstance(url_or_urls, list) else paths[0]
    return fs, path_or_paths, factory


def get_dataset_path(url: str) -> str:
    """URL -> bare path on its filesystem (reference ``fs_utils.py:26-36``)."""
    return _parse_url(url)[1]


def retry_filesystem_call(func=None, *, attempts: int = 3,
                          initial_delay_s: float = 0.1,
                          total_budget_s: Optional[float] = 30.0):
    """Retry transient filesystem errors through the shared
    :class:`petastorm_tpu.resilience.RetryPolicy`.

    TPU-native stand-in for the reference's HDFS namenode failover decorator
    (``hdfs/namenode.py:146-186``): remote object stores (GCS/S3) fail
    transiently rather than failing over, so retry-with-backoff is the
    equivalent robustness mechanism.

    Two behaviors the old ad-hoc loop lacked (see ``docs/robustness.md``):
    **permanent errors fail in one attempt** — a ``FileNotFoundError`` /
    ``PermissionError`` / ``IsADirectoryError`` describes the request, not
    the store, and used to burn 3 attempts with delays on a typo'd path —
    and backoff is **full-jitter** with a total-wall cap, so many readers
    hitting one flaky store cannot synchronize into retry storms.
    """
    from petastorm_tpu.resilience import RetryPolicy
    policy = RetryPolicy(attempts=attempts, initial_backoff_s=initial_delay_s,
                         total_budget_s=total_budget_s)

    def decorate(f):
        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            return policy.call(f, *args, description=f.__name__, **kwargs)
        return wrapper
    return decorate(func) if func is not None else decorate
