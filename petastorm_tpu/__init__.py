"""petastorm_tpu: a TPU-native (JAX/XLA) data access framework with the
capabilities of petastorm (reference ``petastorm/__init__.py:15-17``).

Public API: :func:`make_reader`, :func:`make_batch_reader`,
:class:`TransformSpec`, :class:`NoDataAvailableError`.
"""

__version__ = '0.1.0'

from petastorm_tpu.errors import NoDataAvailableError  # noqa: F401
from petastorm_tpu.transform import TransformSpec  # noqa: F401

__all__ = ['make_reader', 'make_batch_reader', 'make_columnar_reader',
           'make_indexed_loader', 'make_indexed_ngram_loader',
           'WeightedIndexedMixture',
           'TransformSpec', 'NoDataAvailableError',
           'make_jax_loader', 'make_dataset_converter', 'materialize_dataset',
           'CoverageAuditor', 'Provenance', 'SharedRowGroupCache',
           'LatencyHistogram', 'SLOMonitor',
           'PipelineController', 'PodObserver',
           'RetryPolicy', 'HedgedRead', 'FaultInjector',
           'ElasticPodSim', 'PodMembership', 'LeasePlan',
           '__version__']


def __getattr__(name):
    # Lazy imports keep `import petastorm_tpu` light and avoid import cycles.
    if name in ('make_reader', 'make_batch_reader', 'make_columnar_reader'):
        from petastorm_tpu import reader
        return getattr(reader, name)
    if name == 'make_indexed_loader':
        from petastorm_tpu.indexed import make_indexed_loader
        return make_indexed_loader
    if name == 'make_indexed_ngram_loader':
        from petastorm_tpu.indexed_ngram import make_indexed_ngram_loader
        return make_indexed_ngram_loader
    if name == 'WeightedIndexedMixture':
        from petastorm_tpu.indexed_mixture import WeightedIndexedMixture
        return WeightedIndexedMixture
    if name == 'make_jax_loader':
        from petastorm_tpu.jax_utils import make_jax_loader
        return make_jax_loader
    if name == 'make_dataset_converter':
        from petastorm_tpu.converter import make_dataset_converter
        return make_dataset_converter
    if name == 'materialize_dataset':
        from petastorm_tpu.etl.dataset_metadata import materialize_dataset
        return materialize_dataset
    if name in ('CoverageAuditor', 'Provenance'):
        from petastorm_tpu import lineage
        return getattr(lineage, name)
    if name == 'SharedRowGroupCache':
        from petastorm_tpu.sharedcache import SharedRowGroupCache
        return SharedRowGroupCache
    if name in ('LatencyHistogram', 'SLOMonitor'):
        from petastorm_tpu import latency
        return getattr(latency, name)
    if name == 'PipelineController':
        from petastorm_tpu.autotune import PipelineController
        return PipelineController
    if name == 'PodObserver':
        from petastorm_tpu.podobs import PodObserver
        return PodObserver
    if name in ('RetryPolicy', 'HedgedRead'):
        from petastorm_tpu import resilience
        return getattr(resilience, name)
    if name == 'FaultInjector':
        from petastorm_tpu.faultfs import FaultInjector
        return FaultInjector
    if name in ('ElasticPodSim', 'PodMembership', 'LeasePlan'):
        from petastorm_tpu import podelastic
        return getattr(podelastic, name)
    raise AttributeError('module {!r} has no attribute {!r}'.format(__name__, name))
