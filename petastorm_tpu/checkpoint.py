"""Checkpointable iteration state for training loops.

The reference has no resume support: its unit of progress is the whole epoch
(``reader.py:468-492``; SURVEY §5.4). This module closes that gap on top of the
deterministic foundations this framework ships (seeded ventilator shuffle,
seeded shuffling buffers, deterministic piece ordering):

- :class:`CheckpointableLoader` wraps a loader *factory* and tracks
  ``(epoch, step)``. ``state_dict()`` is a tiny JSON-able dict that can ride
  inside any model checkpoint (orbax/flax/torch). ``load_state_dict()`` +
  iteration fast-forwards a freshly built loader to the saved position.

Exact resume requires the batch stream to be reproducible: pass a ``seed`` to
the reader (shuffle order) and loader (buffer RNG), and use a deterministic
results order (``reader_pool_type='dummy'`` or ``workers_count=1``). With a
nondeterministic pool the resume is best-effort: epoch boundaries are exact,
the intra-epoch position is approximate.

For **O(1) exact resume with any worker count** use
:mod:`petastorm_tpu.indexed` (``make_indexed_loader``; batches addressed by
(seed, epoch, index)) or, for NGram window pipelines,
:mod:`petastorm_tpu.indexed_ngram` (``make_indexed_ngram_loader``; windows
addressed the same way). Their cursors restore instantly and byte-exactly —
no replay. Ragged fields join in via ``make_indexed_loader(...,
pad_spec=...)``; predicates and TransformSpecs are supported on both (r05);
weighted mixes via :class:`petastorm_tpu.indexed_mixture.WeightedIndexedMixture`
(counter-keyed source draws, so the mixture cursor is O(1) too). This module
remains the replay fallback only for queue-based STREAMING pipelines that
cannot move to the indexed loaders (e.g. live worker-side predicate pushdown
over a streaming pool, or infinite ``num_epochs=None`` streams).
"""

from __future__ import annotations

import logging
from typing import Callable, Dict

logger = logging.getLogger(__name__)


class CheckpointableLoader(object):
    """Iterate ``loader_factory()`` epochs while tracking a resumable cursor.

    :param loader_factory: zero-arg callable returning a fresh single-epoch
        iterable of batches (e.g. a lambda building ``make_reader`` +
        ``JaxDataLoader`` with fixed seeds). A new loader is built per epoch so
        epoch boundaries stay clean after restore.

    Usage::

        ckpt_loader = CheckpointableLoader(make_loader)
        for batch in ckpt_loader.epochs(num_epochs=10):
            train_step(batch)
            if should_save():
                save(model_state, data_state=ckpt_loader.state_dict())

        # later, in a new process
        ckpt_loader = CheckpointableLoader(make_loader)
        ckpt_loader.load_state_dict(saved['data_state'])
        for batch in ckpt_loader.epochs(num_epochs=10):   # resumes mid-epoch
            ...
    """

    def __init__(self, loader_factory: Callable[[], object]):
        self._factory = loader_factory
        self.epoch = 0
        self.step = 0          # batches yielded in the current epoch
        self._skip = 0         # pending fast-forward after load_state_dict

    # -- state ----------------------------------------------------------------

    def state_dict(self) -> Dict[str, int]:
        # A restore that has not started iterating yet still owes `_skip`
        # batches; report it so save-before-resume does not regress the cursor.
        return {'epoch': self.epoch, 'step': max(self.step, self._skip),
                'version': 1}

    def load_state_dict(self, state: Dict[str, int]) -> None:
        """Restore the cursor. Rejects a state dict with a missing or
        unknown schema ``version``, or missing cursor keys, **loudly** —
        silently fast-forwarding from garbage (a truncated checkpoint, a
        key renamed by some serializer) would resume training at the wrong
        position without any symptom."""
        if not isinstance(state, dict):
            raise ValueError('checkpoint state must be a dict, got '
                             '{!r}'.format(type(state).__name__))
        if 'version' not in state:
            raise ValueError("checkpoint state has no 'version' key — it "
                             'was not produced by state_dict() (keys: '
                             '{})'.format(sorted(state)))
        if state['version'] != 1:
            raise ValueError('Unknown checkpoint state version {!r} '
                             '(this build reads version 1)'.format(
                                 state['version']))
        missing = [k for k in ('epoch', 'step') if k not in state]
        if missing:
            raise ValueError('checkpoint state is missing key(s) {} '
                             '(keys present: {})'.format(
                                 missing, sorted(state)))
        self.epoch = int(state['epoch'])
        self.step = 0
        self._skip = int(state['step'])

    # -- iteration ------------------------------------------------------------

    def epochs(self, num_epochs: int):
        """Yield batches of epochs ``[self.epoch, num_epochs)``, fast-forwarding
        ``step`` batches into the first epoch after a restore."""
        while self.epoch < num_epochs:
            yield from self._one_epoch()
            self.epoch += 1
            self.step = 0

    def _one_epoch(self):
        loader = self._factory()
        skip = self._skip
        self._skip = 0
        if skip:
            logger.info('Fast-forwarding %d batches into epoch %d', skip,
                        self.epoch)
        self.step = 0   # absolute batch index within the epoch, incl. skipped
        try:
            for batch in iter(loader):
                self.step += 1
                if self.step <= skip:
                    continue
                yield batch
            if self.step < skip:
                # The epoch was shorter than the saved cursor (dataset shrank
                # or nondeterministic stream); surface it rather than silently
                # yielding a truncated next epoch.
                logger.warning('Checkpoint cursor %d exceeds epoch length %d',
                               skip, self.step)
        finally:
            # Loaders own reader worker pools; release them per epoch.
            for method in ('stop', 'join'):
                fn = getattr(loader, method, None) or getattr(
                    getattr(loader, 'reader', None), method, None)
                if fn is not None:
                    try:
                        fn()
                    except Exception:  # cleanup must not mask iteration errors
                        logger.exception('Loader %s() failed', method)
