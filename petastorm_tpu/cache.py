"""Row-group caches.

Reference parity: ``petastorm/cache.py:20-39`` (``CacheBase``/``NullCache``),
``local_disk_cache.py:22-63`` (``LocalDiskCache``). The reference delegates to
the ``diskcache`` package; this is a self-contained file-based implementation
with approximate-LRU size-bounded eviction and atomic writes, safe for
concurrent worker threads/processes on one host.
"""

from __future__ import annotations

import hashlib
import logging
import os
import pickle
import tempfile
from abc import ABC, abstractmethod

logger = logging.getLogger(__name__)

#: Re-seed the :class:`LocalDiskCache` running byte total from a directory
#: scan every N stores: the total is per-process, so with several worker
#: processes writing to one cache directory each process only sees its own
#: stores and the counter drifts from reality. A periodic scan (plus an
#: immediate one whenever the counter goes negative — proof of staleness)
#: bounds the drift without paying O(entries) syscalls per store.
RESEED_SCAN_EVERY = 256


class CacheBase(ABC):
    @abstractmethod
    def get(self, key: str, fill_cache_func):
        """Return the cached value for ``key``; on miss call ``fill_cache_func()``,
        store and return its result."""

    def cleanup(self):
        """Remove on-disk state, if any."""


class NullCache(CacheBase):
    """Pass-through cache: always calls the fill function."""

    def get(self, key, fill_cache_func):
        return fill_cache_func()


class LocalDiskCache(CacheBase):
    """Pickle-on-disk cache with a size limit and mtime-LRU eviction.

    :param path: cache directory (created if missing).
    :param size_limit_bytes: approximate cap on total cached bytes.
    :param expected_row_size_bytes: advisory, kept for reference API parity.
    :param shards: fan-out subdirectories to keep directory listings short
        (reference shard sanity check, ``local_disk_cache.py:46-51``).
    """

    def __init__(self, path: str, size_limit_bytes: int,
                 expected_row_size_bytes: int = 0, shards: int = 6, cleanup: bool = False):
        self._path = path
        self._size_limit = size_limit_bytes
        self._shards = shards
        self._cleanup_on_exit = cleanup
        self._approx_total = None  # running byte total, re-seeded by scans
        self._stores_since_scan = 0
        for shard in range(shards):
            os.makedirs(os.path.join(path, 'shard_{:02d}'.format(shard)), exist_ok=True)

    def _key_path(self, key: str) -> str:
        digest = hashlib.md5(key.encode('utf-8')).hexdigest()
        shard = int(digest[:4], 16) % self._shards
        return os.path.join(self._path, 'shard_{:02d}'.format(shard), digest + '.pkl')

    def get(self, key, fill_cache_func):
        path = self._key_path(key)
        try:
            with open(path, 'rb') as f:
                value = pickle.load(f)
            # touch for LRU ordering
            os.utime(path, None)
            return value
        except (OSError, pickle.UnpicklingError, EOFError):
            pass
        value = fill_cache_func()
        try:
            self._store(path, value)
        except OSError as e:  # cache failures must never fail the read path
            logger.warning('Failed to store cache entry: %s', e)
        return value

    def _store(self, path: str, value) -> None:
        payload = pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)
        # Overwrites replace the old entry's bytes: release them from the
        # running total up front (passing a delta into _evict_if_needed would
        # double-subtract if its rescan both lists the old file and applies
        # the delta). The rescan still sees the not-yet-replaced file — a
        # transient overcount that evicts conservatively and self-corrects.
        try:
            old_size = os.stat(path).st_size
        except OSError:
            old_size = 0
        if old_size and self._approx_total is not None:
            self._approx_total -= old_size
        self._evict_if_needed(len(payload))
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
        try:
            with os.fdopen(fd, 'wb') as f:
                f.write(payload)
            os.replace(tmp, path)  # atomic on POSIX
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise

    def _entries(self):
        for shard in range(self._shards):
            shard_dir = os.path.join(self._path, 'shard_{:02d}'.format(shard))
            try:
                names = os.listdir(shard_dir)
            except OSError:
                continue
            for name in names:
                full = os.path.join(shard_dir, name)
                try:
                    st = os.stat(full)
                except OSError:
                    continue
                yield full, st.st_size, st.st_mtime

    def _evict_if_needed(self, incoming_bytes: int) -> None:
        # A full directory scan per store is O(cached entries) in syscalls;
        # keep a running total (seeded by one scan) and only rescan when the
        # counter crosses the limit. The counter drifts under concurrent
        # multi-process writers (each process only observes its own stores):
        # re-seed whenever it goes negative — proof of staleness — and every
        # RESEED_SCAN_EVERY stores so drift stays bounded either way.
        self._stores_since_scan += 1
        if (self._approx_total is None or self._approx_total < 0
                or self._stores_since_scan >= RESEED_SCAN_EVERY):
            self._approx_total = sum(size for _, size, _ in self._entries())
            self._stores_since_scan = 0
        self._approx_total += incoming_bytes
        if self._approx_total <= self._size_limit:
            return
        entries = list(self._entries())
        total = sum(size for _, size, _ in entries) + incoming_bytes
        for full, size, _ in sorted(entries, key=lambda e: e[2]):  # oldest first
            if total <= self._size_limit:
                break
            try:
                os.remove(full)
                total -= size
            except OSError:
                pass
        self._approx_total = total

    def size_bytes(self) -> int:
        return sum(size for _, size, _ in self._entries())

    def cleanup(self):
        if not self._cleanup_on_exit:
            return
        import shutil
        # Remove each shard dir ATOMICALLY (rename-then-rmtree): a
        # concurrent reader sees either the complete shard or none of it —
        # never a half-deleted tree whose surviving entries would be served
        # while their neighbors vanish mid-listing.
        for shard in range(self._shards):
            shard_dir = os.path.join(self._path, 'shard_{:02d}'.format(shard))
            doomed = '{}.removing.{}'.format(shard_dir, os.getpid())
            try:
                os.rename(shard_dir, doomed)
            except OSError:
                continue
            shutil.rmtree(doomed, ignore_errors=True)
        shutil.rmtree(self._path, ignore_errors=True)
