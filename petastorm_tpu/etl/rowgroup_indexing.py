"""Build and load secondary row-group indexes.

Reference parity: ``petastorm/etl/rowgroup_indexing.py`` —
``build_rowgroup_index`` (:37-80) and ``get_row_group_indexes`` (:136-158).
The reference distributes index building over a Spark job; here a host thread
pool scans the row groups (pyarrow reads release the GIL), and the result is
JSON in ``_common_metadata`` instead of a pickle.
"""

from __future__ import annotations

import json
import logging
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional

import pyarrow.parquet as pq

from petastorm_tpu.errors import PetastormMetadataError
from petastorm_tpu.etl.dataset_metadata import (ROWGROUPS_INDEX_KEY, add_to_common_metadata,
                                                get_schema, load_row_groups,
                                                read_common_metadata)
from petastorm_tpu.etl.rowgroup_indexers import RowGroupIndexerBase
from petastorm_tpu.fs import get_filesystem_and_path_or_paths, normalize_dir_url
from petastorm_tpu.unischema import decode_row

logger = logging.getLogger(__name__)


def build_rowgroup_index(dataset_url: str, indexers: List[RowGroupIndexerBase],
                         storage_options: Optional[Dict] = None,
                         num_workers: int = 8) -> None:
    """Scan every row group, feed the indexers, and persist the combined index
    into ``_common_metadata`` under ``ROWGROUPS_INDEX_KEY``."""
    dataset_url = normalize_dir_url(dataset_url)
    fs, path, _ = get_filesystem_and_path_or_paths(dataset_url, storage_options)
    schema = get_schema(fs, path)
    pieces = load_row_groups(fs, path)
    if not pieces:
        raise PetastormMetadataError('No row groups found at {}'.format(dataset_url))

    columns = sorted({c for indexer in indexers for c in indexer.column_names})
    unknown = set(columns) - set(schema.fields.keys())
    if unknown:
        raise ValueError('Indexed fields not in schema: {}'.format(sorted(unknown)))

    def scan(piece_with_index):
        piece_index, piece = piece_with_index
        with fs.open(piece.path, 'rb') as f:
            table = pq.ParquetFile(f).read_row_group(piece.row_group, columns=columns)
        rows = [decode_row(r, schema) for r in table.to_pylist()]
        return piece_index, rows

    with ThreadPoolExecutor(max_workers=num_workers) as executor:
        for piece_index, rows in executor.map(scan, enumerate(pieces)):
            for indexer in indexers:
                indexer.build_index(rows, piece_index)

    payload = json.dumps({ix.index_name: ix.to_json_dict() for ix in indexers})
    add_to_common_metadata(fs, path, ROWGROUPS_INDEX_KEY, payload.encode('utf-8'))
    logger.info('Built %d indexes over %d row groups', len(indexers), len(pieces))


def get_row_group_indexes(filesystem, dataset_path: str) -> Dict[str, RowGroupIndexerBase]:
    """Load the stored indexes, keyed by index name."""
    metadata = read_common_metadata(filesystem, dataset_path)
    if not metadata or ROWGROUPS_INDEX_KEY not in metadata:
        raise PetastormMetadataError(
            'Dataset at {} has no row-group index. Build one with '
            'petastorm_tpu.etl.rowgroup_indexing.build_rowgroup_index'.format(dataset_path))
    raw = json.loads(metadata[ROWGROUPS_INDEX_KEY].decode('utf-8'))
    return {name: RowGroupIndexerBase.from_json_dict(d) for name, d in raw.items()}
