"""Inspect a dataset's schema and indexes from the command line.

Reference parity: ``petastorm/etl/metadata_util.py``.

Usage::

    python -m petastorm_tpu.etl.metadata_util file:///tmp/dataset \
        [--schema] [--index] [--row-groups]
"""

from __future__ import annotations

import argparse

from petastorm_tpu.fs import get_filesystem_and_path_or_paths, normalize_dir_url


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description='Inspect petastorm_tpu metadata')
    parser.add_argument('dataset_url')
    parser.add_argument('--schema', action='store_true', help='Print the unischema')
    parser.add_argument('--index', action='store_true', help='Print rowgroup indexes')
    parser.add_argument('--row-groups', action='store_true',
                        help='Print row-group pieces')
    parser.add_argument('--skip-index', nargs='+', default=[],
                        help='Index names to skip when printing')
    args = parser.parse_args(argv)

    url = normalize_dir_url(args.dataset_url)
    fs, path, _ = get_filesystem_and_path_or_paths(url)

    if args.schema:
        from petastorm_tpu.etl.dataset_metadata import infer_or_load_unischema
        schema, stored = infer_or_load_unischema(fs, path)
        print('Schema ({}):'.format('stored' if stored else 'inferred'))
        for field in schema.fields.values():
            print('  {}'.format(field))

    if args.index:
        from petastorm_tpu.etl.rowgroup_indexing import get_row_group_indexes
        indexes = get_row_group_indexes(fs, path)
        if not indexes:
            print('No indexes found')
        for name, indexer in indexes.items():
            if name in args.skip_index:
                continue
            print('Index {}:'.format(name))
            print('  column: {}'.format(getattr(indexer, 'column_name', '?')))
            values = indexer.indexed_values
            print('  {} indexed values, e.g. {}'.format(
                len(values), list(values)[:5]))

    if args.row_groups:
        from petastorm_tpu.etl.dataset_metadata import load_row_groups
        pieces = load_row_groups(fs, path)
        print('{} row groups:'.format(len(pieces)))
        for p in pieces:
            print('  {}#{} rows={} partitions={}'.format(
                p.path, p.row_group, p.num_rows, dict(p.partition_dict)))
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
