"""Dataset materialization and metadata: write tensor datasets to Parquet and
discover their row groups.

Reference parity: ``petastorm/etl/dataset_metadata.py`` —
``materialize_dataset`` (:52-132), ``load_row_groups`` (:244-290),
``get_schema`` (:356-385), ``infer_or_load_unischema`` (:410-418).

TPU-first deviations:
 - The writer is **pyarrow-native** (no Spark/JVM). ``materialize_dataset``
   yields a :class:`DatasetWriter` that encodes rows with the schema's codecs
   and writes parquet files with controlled row-group sizes.
 - Metadata is **JSON inside the ``_common_metadata`` schema metadata**, not
   pickled python objects (the reference admits the pickle trap at
   ``etl/dataset_metadata.py:202``).
 - Row-group pieces are plain picklable dataclasses; discovery order is sorted
   by path then row-group index, which makes epoch shuffles seedable and
   iterator state checkpointable (reference notes this at ``:274-278``).
"""

from __future__ import annotations

import json
import logging
import posixpath
from concurrent.futures import ThreadPoolExecutor
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import pyarrow as pa
import pyarrow.parquet as pq

from petastorm_tpu.errors import PetastormMetadataError, PetastormMetadataGenerationError
from petastorm_tpu.fs import get_filesystem_and_path_or_paths, normalize_dir_url
from petastorm_tpu.unischema import Unischema, encode_row

logger = logging.getLogger(__name__)

#: Schema-metadata keys inside ``_common_metadata`` (reference keys at
#: ``etl/dataset_metadata.py:34-35``; ours carry JSON payloads).
UNISCHEMA_KEY = b'petastorm_tpu.unischema.v1'
ROW_GROUPS_PER_FILE_KEY = b'petastorm_tpu.num_row_groups_per_file.v1'
ROWGROUPS_INDEX_KEY = b'petastorm_tpu.rowgroup_index.v1'

_COMMON_METADATA = '_common_metadata'
_DEFAULT_ROW_GROUP_SIZE_MB = 32


def _is_data_file(path: str) -> bool:
    base = posixpath.basename(path)
    return (not base.startswith('_') and not base.startswith('.')
            and base.endswith('.parquet'))


def _partition_values_from_relpath(relpath: str) -> Dict[str, str]:
    """Parse hive-style ``key=value`` directory components into a dict."""
    values = {}
    for component in posixpath.dirname(relpath).split('/'):
        if '=' in component:
            key, _, value = component.partition('=')
            values[key] = value
    return values


@dataclass(frozen=True)
class RowGroupPiece:
    """One unit of ventilation: a single row group of a single parquet file.

    Replaces the reference's ``ParquetDatasetPiece`` (pyarrow-legacy API). The
    piece is picklable and carries everything a worker needs to read it.
    """
    path: str                      # absolute path on the dataset filesystem
    row_group: int                 # ordinal within the file
    num_rows: int = -1             # -1 when unknown (metadata-less discovery)
    partition_values: Tuple[Tuple[str, str], ...] = field(default=())

    @property
    def partition_dict(self) -> Dict[str, str]:
        return dict(self.partition_values)


class DatasetWriter:
    """Codec-encoding parquet writer with row-group size control.

    Produced by :func:`materialize_dataset`. Rows are buffered and flushed into
    ``part_NNNNN.parquet`` files; row-group row counts are derived from the
    ``row_group_size_mb`` target the same way the reference pushes
    ``parquet.block.size`` into hadoop conf (``etl/dataset_metadata.py:147-178``).
    """

    def __init__(self, filesystem, dataset_path: str, schema: Unischema,
                 row_group_size_mb: float = _DEFAULT_ROW_GROUP_SIZE_MB,
                 rows_per_file: int = 100000, file_size_mb: float = 256,
                 compression: str = 'snappy'):
        self._fs = filesystem
        self._path = dataset_path
        self._schema = schema
        self._row_group_bytes = int(row_group_size_mb * (1 << 20))
        self._rows_per_file = rows_per_file
        self._file_size_bytes = int(file_size_mb * (1 << 20))
        self._compression = compression
        self._buffer: List[Dict] = []
        self._buffer_bytes = 0
        self._part = 0
        self._files_written: List[str] = []
        # filename -> list of per-row-group row counts
        self._row_groups_per_file: Dict[str, List[int]] = {}
        self._fs.makedirs(dataset_path, exist_ok=True)

    @property
    def schema(self) -> Unischema:
        return self._schema

    def write_row(self, row_dict: Dict) -> None:
        encoded = encode_row(self._schema, row_dict)
        self._buffer.append(encoded)
        # Track approximate buffered bytes so huge rows can't accumulate into an
        # OOM before the count-based flush triggers.
        self._buffer_bytes += sum(
            len(v) if isinstance(v, (bytes, str)) else 8
            for v in encoded.values() if v is not None)
        if len(self._buffer) >= self._rows_per_file or self._buffer_bytes >= self._file_size_bytes:
            self._flush()

    def write_rows(self, rows) -> None:
        for row in rows:
            self.write_row(row)

    def write_encoded_table(self, table: pa.Table) -> None:
        """Write an already-encoded arrow table as one parquet file."""
        self._flush()
        self._write_table(table)

    def _flush(self) -> None:
        if not self._buffer:
            return
        table = pa.Table.from_pylist(self._buffer, schema=self._schema.as_arrow_schema())
        self._buffer = []
        self._buffer_bytes = 0
        self._write_table(table)

    def _write_table(self, table: pa.Table) -> None:
        filename = 'part_{:05d}.parquet'.format(self._part)
        self._part += 1
        full_path = posixpath.join(self._path, filename)
        nbytes = max(table.nbytes, 1)
        rows_per_group = max(1, int(table.num_rows * self._row_group_bytes / nbytes))
        with self._fs.open(full_path, 'wb') as f:
            pq.write_table(table, f, row_group_size=rows_per_group,
                           compression=self._compression)
        self._files_written.append(filename)
        num_groups = -(-table.num_rows // rows_per_group)
        counts = [rows_per_group] * (num_groups - 1)
        counts.append(table.num_rows - rows_per_group * (num_groups - 1))
        self._row_groups_per_file[filename] = counts

    def close(self) -> Dict[str, List[int]]:
        self._flush()
        return dict(self._row_groups_per_file)


def _write_common_metadata(filesystem, dataset_path: str, schema: Unischema,
                           row_groups_per_file: Optional[Dict[str, int]] = None,
                           extra_metadata: Optional[Dict[bytes, bytes]] = None) -> None:
    metadata = {UNISCHEMA_KEY: schema.to_json().encode('utf-8')}
    if row_groups_per_file is not None:
        metadata[ROW_GROUPS_PER_FILE_KEY] = json.dumps(row_groups_per_file).encode('utf-8')
    if extra_metadata:
        metadata.update(extra_metadata)
    arrow_schema = schema.as_arrow_schema().with_metadata(metadata)
    meta_path = posixpath.join(dataset_path, _COMMON_METADATA)
    with filesystem.open(meta_path, 'wb') as f:
        pq.write_metadata(arrow_schema, f)


def read_common_metadata(filesystem, dataset_path) -> Optional[Dict[bytes, bytes]]:
    """Return the ``_common_metadata`` schema metadata dict, or None if absent.
    A list of file paths (make_batch_reader url-list mode) never carries
    dataset-level metadata."""
    if isinstance(dataset_path, list):
        return None
    meta_path = posixpath.join(dataset_path, _COMMON_METADATA)
    if not filesystem.exists(meta_path):
        return None
    with filesystem.open(meta_path, 'rb') as f:
        arrow_schema = pq.read_schema(f)
    return dict(arrow_schema.metadata or {})


def add_to_common_metadata(filesystem, dataset_path: str, key: bytes, value: bytes) -> None:
    """Merge one key into ``_common_metadata``, preserving existing keys
    (reference ``utils.py:88-132`` ``add_to_dataset_metadata``)."""
    existing = read_common_metadata(filesystem, dataset_path) or {}
    existing[key] = value
    if UNISCHEMA_KEY not in existing:
        raise PetastormMetadataError(
            'Cannot add metadata to {}: no unischema present'.format(dataset_path))
    schema = Unischema.from_json(existing[UNISCHEMA_KEY].decode('utf-8'))
    arrow_schema = schema.as_arrow_schema().with_metadata(existing)
    meta_path = posixpath.join(dataset_path, _COMMON_METADATA)
    with filesystem.open(meta_path, 'wb') as f:
        pq.write_metadata(arrow_schema, f)


@contextmanager
def materialize_dataset(dataset_url: str, schema: Unischema,
                        row_group_size_mb: float = _DEFAULT_ROW_GROUP_SIZE_MB,
                        rows_per_file: int = 100000,
                        file_size_mb: float = 256,
                        compression: str = 'snappy',
                        overwrite: bool = False,
                        storage_options: Optional[Dict] = None):
    """Context manager for writing a petastorm_tpu dataset.

    Yields a :class:`DatasetWriter`; on exit writes ``_common_metadata`` (schema
    JSON + per-file row-group counts) and validates it can be re-loaded —
    mirroring the reference's post-write metadata generation + validation
    (``etl/dataset_metadata.py:52-132``).

    Usage::

        with materialize_dataset(url, MySchema, row_group_size_mb=32) as writer:
            writer.write_rows(dict_rows)
    """
    dataset_url = normalize_dir_url(dataset_url)
    fs, path, _ = get_filesystem_and_path_or_paths(dataset_url, storage_options)
    if fs.exists(path):
        existing = _list_data_files(fs, path)
        if existing:
            if not overwrite:
                raise ValueError(
                    '{} already contains {} data files; pass overwrite=True to replace '
                    'them (stale files would otherwise survive with new metadata '
                    'excluding them)'.format(dataset_url, len(existing)))
            for f in existing:
                fs.rm(f)
        # Stale metadata must die with the data files it described, so a failure
        # mid-write cannot leave metadata pointing at a deleted layout.
        meta_path = posixpath.join(path, _COMMON_METADATA)
        if fs.exists(meta_path):
            fs.rm(meta_path)
    writer = DatasetWriter(fs, path, schema, row_group_size_mb=row_group_size_mb,
                           rows_per_file=rows_per_file, file_size_mb=file_size_mb,
                           compression=compression)
    yield writer
    row_groups_per_file = writer.close()
    _write_common_metadata(fs, path, schema, row_groups_per_file)
    # Validation: fail fast if the metadata we just wrote cannot drive a reader.
    try:
        pieces = load_row_groups(fs, path)
    except Exception as e:
        raise PetastormMetadataGenerationError(
            'Could not load row groups from freshly written metadata at {}'.format(
                dataset_url)) from e
    if not pieces and row_groups_per_file:
        raise PetastormMetadataGenerationError(
            'Metadata was generated but no row groups discovered at {}'.format(dataset_url))


def _list_data_files(filesystem, dataset_path) -> List[str]:
    """Data files of a dataset directory, or the explicit file list in the
    caller's order (make_batch_reader accepts a list of parquet file urls,
    reference ``reader.py:52-58``; the user's ordering is part of the API)."""
    if isinstance(dataset_path, list):
        return list(dataset_path)
    files = [f for f in filesystem.find(dataset_path) if _is_data_file(f)]
    return sorted(files)


def load_row_groups(filesystem, dataset_path: str,
                    num_discovery_workers: int = 8,
                    footer_cache: Optional[Dict] = None) -> List[RowGroupPiece]:
    """Discover all row groups of a dataset as a deterministic piece list:
    sorted by (path, row_group) for directory datasets, caller's order for
    explicit file lists.

    Two strategies (reference's three at ``etl/dataset_metadata.py:244-290``;
    the ``_metadata`` summary-file path collapses into the JSON-key path here):

    1. ``_common_metadata`` carries per-file row-group counts → build pieces
       with no footer reads.
    2. Otherwise read every file footer concurrently
       (``_split_row_groups_from_footers`` equivalent, ``:340-353``).
    """
    metadata = read_common_metadata(filesystem, dataset_path)
    if metadata and ROW_GROUPS_PER_FILE_KEY in metadata:
        counts = json.loads(metadata[ROW_GROUPS_PER_FILE_KEY].decode('utf-8'))
        pieces = []
        for relpath in sorted(counts.keys()):
            full = posixpath.join(dataset_path, relpath)
            parts = tuple(sorted(_partition_values_from_relpath(relpath).items()))
            per_group_rows = counts[relpath]
            for rg, n in enumerate(per_group_rows):
                pieces.append(RowGroupPiece(path=full, row_group=rg, num_rows=n,
                                            partition_values=parts))
        return pieces

    files = _list_data_files(filesystem, dataset_path)

    def footer_row_groups(f: str) -> Tuple[str, int, List[int]]:
        with filesystem.open(f, 'rb') as fh:
            md = pq.ParquetFile(fh).metadata
            if footer_cache is not None:
                # callers (stats-based filter pruning) reuse the parsed
                # footers instead of paying a second round-trip per file
                footer_cache[f] = md
            return f, md.num_row_groups, [md.row_group(i).num_rows
                                          for i in range(md.num_row_groups)]

    pieces: List[RowGroupPiece] = []
    if not files:
        return pieces
    is_file_list = isinstance(dataset_path, list)
    with ThreadPoolExecutor(max_workers=num_discovery_workers) as executor:
        for f, n, num_rows in executor.map(footer_row_groups, files):
            if is_file_list:
                parts = ()   # explicit file lists carry no hive partition info
            else:
                rel = posixpath.relpath(f, dataset_path)
                parts = tuple(sorted(_partition_values_from_relpath(rel).items()))
            for rg in range(n):
                pieces.append(RowGroupPiece(path=f, row_group=rg, num_rows=num_rows[rg],
                                            partition_values=parts))
    if not is_file_list:
        # Deterministic global ordering for directory datasets; explicit file
        # lists keep the caller's order (executor.map preserves input order).
        pieces.sort(key=lambda p: (p.path, p.row_group))
    return pieces


def get_schema(filesystem, dataset_path: str) -> Unischema:
    """Load the Unischema stored in ``_common_metadata``
    (reference ``etl/dataset_metadata.py:356-385``)."""
    metadata = read_common_metadata(filesystem, dataset_path)
    if metadata is None:
        raise PetastormMetadataError(
            'Could not find _common_metadata file at {}. Run '
            '`python -m petastorm_tpu.etl.generate_metadata <url>` to add metadata to '
            'an existing store, or read it with make_batch_reader.'.format(dataset_path))
    if UNISCHEMA_KEY not in metadata:
        from petastorm_tpu.compat import (PETASTORM_UNISCHEMA_KEY,
                                          unischema_from_petastorm_pickle)
        if PETASTORM_UNISCHEMA_KEY in metadata:
            # Dataset written by original petastorm: decode its pickled schema
            # through the restricted compat unpickler.
            return unischema_from_petastorm_pickle(
                metadata[PETASTORM_UNISCHEMA_KEY])
        raise PetastormMetadataError(
            '_common_metadata at {} does not carry a unischema (key {}). Was this '
            'dataset written by petastorm_tpu.materialize_dataset?'.format(
                dataset_path, UNISCHEMA_KEY))
    return Unischema.from_json(metadata[UNISCHEMA_KEY].decode('utf-8'))


def get_schema_from_dataset_url(dataset_url: str,
                                storage_options: Optional[Dict] = None) -> Unischema:
    """URL-level convenience wrapper (reference ``etl/dataset_metadata.py:388-407``)."""
    fs, path, _ = get_filesystem_and_path_or_paths(normalize_dir_url(dataset_url),
                                                   storage_options)
    return get_schema(fs, path)


def read_dataset_arrow_schema(filesystem, dataset_path: str) -> pa.Schema:
    """Physical arrow schema of the store, from the first data file's footer."""
    files = _list_data_files(filesystem, dataset_path)
    if not files:
        raise PetastormMetadataError('No parquet files found at {}'.format(dataset_path))
    with filesystem.open(files[0], 'rb') as f:
        return pq.read_schema(f)


def infer_or_load_unischema(filesystem, dataset_path) -> Tuple[Unischema, bool]:
    """Load the stored Unischema, or infer one from the physical arrow schema
    (foreign parquet stores or explicit file lists). Returns ``(schema,
    was_stored)`` (reference ``etl/dataset_metadata.py:410-418``)."""
    try:
        return get_schema(filesystem, dataset_path), True
    except PetastormMetadataError:
        arrow_schema = read_dataset_arrow_schema(filesystem, dataset_path)
        schema = Unischema.from_arrow_schema(arrow_schema)
        # Hive partition columns live in directory names, not file schemas.
        partition_keys: Dict[str, None] = {}
        if not isinstance(dataset_path, list):
            for f in _list_data_files(filesystem, dataset_path):
                rel = posixpath.relpath(f, dataset_path)
                for key in _partition_values_from_relpath(rel):
                    partition_keys[key] = None
        if partition_keys:
            from petastorm_tpu.unischema import UnischemaField
            extra = [UnischemaField(k, str, (), None, False) for k in partition_keys
                     if k not in schema.fields]
            schema = Unischema('inferred_schema', list(schema.fields.values()) + extra)
        return schema, False
