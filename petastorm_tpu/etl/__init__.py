"""ETL: dataset materialization, metadata, row-group discovery and indexing.

Reference parity: ``petastorm/etl/`` — but Spark-free: writes go through
pyarrow directly (``etl/dataset_metadata.py`` in the reference drives a JVM
parquet writer via Spark; see SURVEY.md §7 step 2).
"""

from petastorm_tpu.etl.dataset_metadata import (  # noqa: F401
    materialize_dataset, load_row_groups, get_schema, get_schema_from_dataset_url,
    infer_or_load_unischema, RowGroupPiece)
