"""Row-group indexers: map field values to the set of row groups containing them.

Reference parity: ``petastorm/etl/rowgroup_indexers.py`` —
``SingleFieldIndexer`` (:21-75), ``FieldNotNullIndexer`` (:78-124); ABC at
``etl/__init__.py:21-50``. Indexes serialize to JSON (values stringified),
not pickle.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Dict, List, Set


class RowGroupIndexerBase(ABC):
    """Base class for indexers building secondary indexes over row groups."""

    def __init__(self, index_name: str, index_field: str):
        self._index_name = index_name
        self._index_field = index_field
        self._index: Dict[str, Set[int]] = {}

    @property
    def index_name(self) -> str:
        return self._index_name

    @property
    def column_names(self) -> List[str]:
        return [self._index_field]

    @property
    def indexed_values(self) -> List[str]:
        return sorted(self._index.keys())

    @abstractmethod
    def build_index(self, decoded_rows: List[dict], piece_index: int):
        """Accumulate index entries from one row group's decoded rows."""

    def get_row_group_indexes(self, value) -> Set[int]:
        return self._index.get(self._value_key(value), set())

    @staticmethod
    def _value_key(value) -> str:
        if isinstance(value, bytes):
            return value.decode('utf-8', 'replace')
        return str(value)

    # -- JSON (de)serialization ------------------------------------------------

    def to_json_dict(self) -> dict:
        return {
            'type': self.indexer_type,
            'index_name': self._index_name,
            'index_field': self._index_field,
            'values': {k: sorted(v) for k, v in self._index.items()},
        }

    @classmethod
    def from_json_dict(cls, d: dict) -> 'RowGroupIndexerBase':
        indexer_cls = _INDEXER_TYPES[d['type']]
        indexer = indexer_cls(d['index_name'], d['index_field'])
        indexer._index = {k: set(v) for k, v in d['values'].items()}
        return indexer


class SingleFieldIndexer(RowGroupIndexerBase):
    """value -> {row-group indexes containing a row with that value}."""

    indexer_type = 'single_field'

    def build_index(self, decoded_rows, piece_index):
        for row in decoded_rows:
            value = row.get(self._index_field)
            if value is None:
                continue
            self._index.setdefault(self._value_key(value), set()).add(piece_index)


class FieldNotNullIndexer(RowGroupIndexerBase):
    """Single bucket of row groups having at least one non-null value."""

    indexer_type = 'not_null'
    _NOT_NULL_KEY = '__not_null__'

    def build_index(self, decoded_rows, piece_index):
        for row in decoded_rows:
            if row.get(self._index_field) is not None:
                self._index.setdefault(self._NOT_NULL_KEY, set()).add(piece_index)
                return

    def get_row_group_indexes(self, value=None) -> set:
        return self._index.get(self._NOT_NULL_KEY, set())


_INDEXER_TYPES = {
    SingleFieldIndexer.indexer_type: SingleFieldIndexer,
    FieldNotNullIndexer.indexer_type: FieldNotNullIndexer,
}
