"""ETL-time repack: rewrite a store's compressed ndarray columns as plain
``NdarrayCodec`` so they become device-decode eligible.

``CompressedNdarrayCodec`` (zlib) has no device decode path — inflate is a
host algorithm — so a bytes-through reader permanently declines those
columns to the host matrix (``docs/decode.md``). The trade is storage
bytes for decode CPU; on an accelerator host whose ingest link is the
intended ceiling (PAPER §5.8), the right place to pay zlib is ONCE at ETL
time, not per epoch per worker. This module is that one-time payment:
stream-decode the source store and materialize a copy whose compressed
ndarray fields carry :class:`~petastorm_tpu.codecs.NdarrayCodec` — the
strict ``np.save`` v1 layout ``ops.decode`` can plan against. Parquet-level
compression (snappy by default) still applies on top, so the size
regression is bounded while the decode stays a header-strip + bitcast.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

from petastorm_tpu.codecs import CompressedNdarrayCodec, NdarrayCodec
from petastorm_tpu.unischema import Unischema, UnischemaField

logger = logging.getLogger(__name__)


def still_ineligible_after_repack(schema: Unischema,
                                  repacked: List[str]) -> Dict[str, str]:
    """``{name: reason}`` for repacked fields that STILL decline device
    decode after the codec swap — static per-field decliners the repack
    cannot fix (``nullable=True``, wildcard shapes, non-numeric or
    big-endian dtypes). Such a field decodes on the host batched path
    either way; the repack buys it nothing."""
    out: Dict[str, str] = {}
    for name in repacked:
        field = schema.fields[name]
        reason = field.codec.device_decode_unsupported_reason(field)
        if reason:
            out[name] = reason
    return out


def repack_schema(schema: Unischema,
                  fields: Optional[List[str]] = None
                  ) -> Tuple[Unischema, List[str]]:
    """``(post_repack_schema, repacked_names)``: every
    :class:`~petastorm_tpu.codecs.CompressedNdarrayCodec` field (or just
    the named ``fields``) re-declared with
    :class:`~petastorm_tpu.codecs.NdarrayCodec`; everything else verbatim.
    Raises ``ValueError`` when ``fields`` names a column that is not
    compressed-ndarray encoded (a silent no-op would hide a typo)."""
    wanted = set(fields) if fields is not None else None
    unknown = (wanted or set()) - set(schema.fields)
    if unknown:
        raise ValueError('repack fields name unknown columns: {}'.format(
            sorted(unknown)))
    out_fields = []
    repacked = []
    for name, field in schema.fields.items():
        eligible = isinstance(field.codec, CompressedNdarrayCodec)
        if wanted is not None and name in wanted and not eligible:
            raise ValueError(
                'field {!r} is not CompressedNdarrayCodec-encoded ({}); '
                'only zlib ndarray columns repack'.format(
                    name, type(field.codec).__name__))
        if eligible and (wanted is None or name in wanted):
            out_fields.append(UnischemaField(name, field.numpy_dtype,
                                             field.shape, NdarrayCodec(),
                                             field.nullable))
            repacked.append(name)
        else:
            out_fields.append(field)
    out_schema = Unischema(schema._name + '_repacked', out_fields)
    for name, reason in still_ineligible_after_repack(out_schema,
                                                      repacked).items():
        logger.warning(
            'repack_schema: field %r stays device-INELIGIBLE after the '
            'codec swap (%s); the repack pays zlib up front but the column '
            'still decodes on the host matrix', name, reason)
    return out_schema, repacked


def repack_to_ndarray_codec(source_url: str, output_url: str,
                            fields: Optional[List[str]] = None,
                            row_group_size_mb: float = 4.0,
                            compression: str = 'snappy',
                            overwrite: bool = False) -> Dict:
    """Materialize a device-decode-eligible copy of ``source_url`` at
    ``output_url``: compressed ndarray columns inflate once here and store
    as raw ``np.save`` payloads. Returns a summary dict
    (``rows``, ``repacked_fields``, ``output_url``, plus
    ``still_ineligible`` — repacked fields that remain device-ineligible
    for reasons the codec swap cannot fix, e.g. ``nullable=True``).

    The copy streams through a columnar reader (decode happens on the
    reader's host matrix — this tool never needs an accelerator), so
    arbitrarily large stores repack in bounded memory, one row group at a
    time."""
    from petastorm_tpu.etl.dataset_metadata import (get_schema_from_dataset_url,
                                                    materialize_dataset)
    from petastorm_tpu.reader import make_columnar_reader

    schema = get_schema_from_dataset_url(source_url)
    out_schema, repacked = repack_schema(schema, fields)
    rows = 0
    with materialize_dataset(output_url, out_schema,
                             row_group_size_mb=row_group_size_mb,
                             compression=compression,
                             overwrite=overwrite) as writer:
        with make_columnar_reader(source_url, num_epochs=1,
                                  shuffle_row_groups=False) as reader:
            names = list(out_schema.fields)
            for batch in reader:
                columns = {name: getattr(batch, name) for name in names}
                n = len(next(iter(columns.values()))) if columns else 0
                for i in range(n):
                    writer.write_row({name: col[i]
                                      for name, col in columns.items()})
                rows += n
    return {'rows': rows, 'repacked_fields': repacked,
            'output_url': output_url,
            'still_ineligible': still_ineligible_after_repack(out_schema,
                                                              repacked)}
