"""Add (or regenerate) petastorm_tpu metadata on an existing parquet store.

Reference parity: ``petastorm/etl/petastorm_generate_metadata.py`` —
``generate_petastorm_metadata`` (:47-111), CLI (:114-161). Our version scans
file footers with a thread pool instead of launching a Spark job, and stores
JSON rather than pickles. Existing rowgroup-index keys are preserved
(reference :102-111).
"""

from __future__ import annotations

import argparse
import logging
import posixpath
from typing import Dict, Optional

from petastorm_tpu.errors import PetastormMetadataError
from petastorm_tpu.etl.dataset_metadata import (ROWGROUPS_INDEX_KEY, _list_data_files,
                                                _write_common_metadata,
                                                load_row_groups, read_common_metadata)
from petastorm_tpu.fs import get_filesystem_and_path_or_paths, normalize_dir_url
from petastorm_tpu.unischema import Unischema

logger = logging.getLogger(__name__)


def _import_unischema(full_name: str) -> Unischema:
    """Load a Unischema instance from a ``package.module.attribute`` path."""
    import importlib
    module_name, _, attr = full_name.rpartition('.')
    if not module_name:
        raise ValueError('--unischema-class must be a full module path, got {!r}'
                         .format(full_name))
    schema = getattr(importlib.import_module(module_name), attr)
    if not isinstance(schema, Unischema):
        raise ValueError('{} is not a Unischema instance'.format(full_name))
    return schema


def generate_metadata(dataset_url: str, unischema: Optional[Unischema] = None,
                      storage_options: Optional[Dict] = None) -> None:
    """Write ``_common_metadata`` (schema + per-file row-group row counts) for a
    store that lacks it, preserving any existing index keys."""
    dataset_url = normalize_dir_url(dataset_url)
    fs, path, _ = get_filesystem_and_path_or_paths(dataset_url, storage_options)
    existing = read_common_metadata(fs, path) or {}

    if unischema is None:
        # infer_or_load_unischema handles both the stored-schema case and
        # inference (incl. hive partition columns) for foreign stores.
        from petastorm_tpu.etl.dataset_metadata import infer_or_load_unischema
        unischema, was_stored = infer_or_load_unischema(fs, path)
        if not was_stored:
            logger.info('No stored unischema; inferred one from the arrow schema')

    # Footer scan (concurrent) for accurate per-row-group row counts.
    import json
    from concurrent.futures import ThreadPoolExecutor
    import pyarrow.parquet as pq

    files = _list_data_files(fs, path)
    if not files:
        raise PetastormMetadataError('No parquet files found at {}'.format(dataset_url))

    def scan(f):
        with fs.open(f, 'rb') as fh:
            md = pq.ParquetFile(fh).metadata
            return f, [md.row_group(i).num_rows for i in range(md.num_row_groups)]

    counts = {}
    with ThreadPoolExecutor(max_workers=8) as ex:
        for f, per_group in ex.map(scan, files):
            counts[posixpath.relpath(f, path)] = per_group

    extra = {}
    if ROWGROUPS_INDEX_KEY in existing:
        extra[ROWGROUPS_INDEX_KEY] = existing[ROWGROUPS_INDEX_KEY]
    _write_common_metadata(fs, path, unischema, counts, extra_metadata=extra)
    # Validate: discovery must work from the new metadata.
    pieces = load_row_groups(fs, path)
    logger.info('Wrote metadata for %d row groups across %d files', len(pieces), len(files))


def main(argv=None):
    parser = argparse.ArgumentParser(
        description='Add petastorm_tpu metadata to an existing parquet store')
    parser.add_argument('dataset_url', help='e.g. file:///tmp/ds, gs://bucket/ds')
    parser.add_argument('--unischema-class', default=None,
                        help='Full path to a Unischema instance, e.g. mypkg.schemas.MySchema; '
                             'if omitted, the schema is loaded from existing metadata or '
                             'inferred from the parquet files')
    args = parser.parse_args(argv)
    schema = _import_unischema(args.unischema_class) if args.unischema_class else None
    logging.basicConfig(level=logging.INFO)
    generate_metadata(args.dataset_url, schema)


if __name__ == '__main__':
    main()
