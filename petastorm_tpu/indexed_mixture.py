"""Deterministic weighted mixture of indexed loaders with O(1) exact resume.

The streaming :class:`~petastorm_tpu.weighted_sampling_reader.WeightedSamplingReader`
(reference ``petastorm/weighted_sampling_reader.py:90-95``) draws from live
queue-backed readers, so a mid-stream checkpoint can only be approximated by
replay (``checkpoint.py``'s documented fallback). This module closes that
last replay-fallback frontier the same way the indexed loaders did for rows
and NGram windows: make the ENTIRE mixed stream a pure function of
``(sources, probabilities, seed, step)``.

- the source chosen at step ``k`` is ``choice(seed, k)`` — a counter-keyed
  draw, independent of consumption history;
- each source is an :class:`~petastorm_tpu.indexed.IndexedBatchLoader`-family
  loader whose own stream is already a pure function of its cursor;
- therefore ``state_dict()`` is just ``{'step': k, 'sources': [sub-cursors]}``
  and a restored mixture reproduces the remaining stream byte-for-byte,
  with any worker counts.

Iteration stops when the chosen source is exhausted (reference mixture
semantics: the first exhausted pick ends the stream).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np


class WeightedIndexedMixture:
    """Mix the batch streams of several indexed loaders by probability.

    :param loaders: indexed-family loaders (row ``IndexedBatchLoader`` /
        ``IndexedNGramLoader`` / their sharded variants). They need not
        share a schema — the caller mixes what it can consume — but they
        must all be batch-granular (each pick yields one batch).
    :param probabilities: per-loader sampling weights (normalized).
    :param seed: the mixture's OWN seed; the draw at step ``k`` depends only
        on ``(seed, k)``, so the choice sequence survives checkpoint/resume
        without recording it.
    """

    def __init__(self, loaders: Sequence, probabilities: Sequence[float],
                 seed: int = 0):
        from petastorm_tpu.weighted_sampling_reader import normalize_cumulative
        if len(loaders) != len(probabilities):
            raise ValueError('loaders and probabilities must have equal length')
        if not loaders:
            raise ValueError('At least one loader is required')
        for loader in loaders:
            # duck-typed indexed-family check: the O(1) cursor pair PLUS the
            # iteration/lifecycle surface this class drives. (A replay-based
            # checkpointable that happened to grow all four would still be
            # wrong here — the byte-exact guarantee needs cursor-addressed
            # streams — but it cannot be detected structurally; the docstring
            # states the contract.)
            missing = [attr for attr in ('state_dict', 'load_state_dict',
                                         '__iter__', 'close')
                       if not hasattr(loader, attr)]
            if missing:
                raise ValueError(
                    'WeightedIndexedMixture needs indexed-family loaders '
                    '(cursor state_dict/load_state_dict + __iter__/close); '
                    '{!r} lacks {}. Use WeightedSamplingReader for '
                    'streaming readers.'.format(type(loader).__name__,
                                                missing))
        self._loaders = list(loaders)
        self._cumulative = normalize_cumulative(probabilities)
        self.seed = seed
        self.step = 0

    # -- deterministic addressing ---------------------------------------------

    def _choice(self, step: int) -> int:
        """Source drawn at global step ``step`` — pure function of
        (seed, step), NOT of any consumption history."""
        from petastorm_tpu.weighted_sampling_reader import draw_index
        return draw_index(self._cumulative,
                          np.random.default_rng((self.seed, step)).random())

    # -- checkpoint state ------------------------------------------------------

    def state_dict(self) -> Dict:
        """O(1): the mixture step plus each source's own O(1) cursor."""
        return {'step': self.step,
                'sources': [ld.state_dict() for ld in self._loaders],
                'version': 1}

    def load_state_dict(self, state: Dict) -> None:
        if state.get('version', 1) != 1:
            raise ValueError('Unknown state version {}'.format(
                state.get('version')))
        if len(state['sources']) != len(self._loaders):
            raise ValueError('state has {} sources, mixture has {}'.format(
                len(state['sources']), len(self._loaders)))
        self.step = int(state['step'])
        for loader, sub in zip(self._loaders, state['sources']):
            loader.load_state_dict(sub)

    # -- iteration -------------------------------------------------------------

    def __iter__(self):
        iterators: List[Optional[object]] = [None] * len(self._loaders)
        try:
            while True:
                pick = self._choice(self.step)
                if iterators[pick] is None:
                    iterators[pick] = iter(self._loaders[pick])
                batch = next(iterators[pick], None)
                if batch is None:
                    return          # chosen source exhausted: stream ends
                self.step += 1
                yield batch
        finally:
            first_error = None
            for it in iterators:
                if it is None:
                    continue
                try:
                    it.close()
                except Exception as e:  # noqa: BLE001 - close the REST first
                    # one source's teardown failure must not leak the other
                    # sources' worker pools and parquet fds
                    if first_error is None:
                        first_error = e
            if first_error is not None:
                raise first_error

    def close(self):
        for loader in self._loaders:
            loader.close()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.close()
