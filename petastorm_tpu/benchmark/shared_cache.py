"""Shared-cache acceptance benchmark: K concurrent readers, one dataset,
every row group decoded ONCE per host (ROADMAP item 4 / BENCH_r11).

Protocol (see ``docs/cache.md``):

1. **Roofline pass.** One serial reader (dummy pool, no cache) over the
   whole store measures the raw I/O+decode cost; its samples/sec is the
   ceiling any *non-cached* reader can reach, and the denominator every
   cached claim is judged against (the VERDICT.md deliverable: cached lines
   must be compared to a *measured* ceiling, not to vibes).
2. **Shared pass.** K reader processes over the SAME dataset with
   ``cache_type='shared'`` pointing at one host-wide cache root (distinct
   shuffle seeds so the fleet fills different row groups concurrently;
   single-flight fills mean a group in flight in one process is awaited,
   not re-decoded, by the others). Aggregate samples/sec = total samples /
   fleet wall time.
3. **Decode-once assertion.** The cache's cross-process counter files must
   show ``fills == row_groups`` and ``hits == K*row_groups - row_groups``:
   the host decoded each group exactly once, every other consumption
   attached to the decoded segment.
4. **Baseline pass.** The same K processes with four *independent*
   ``local-disk`` caches (today's per-reader story): every process decodes
   everything. The headline claim is shared aggregate >= 2x this baseline.
5. **Warm pass.** One more shared reader after the fleet: 100% hits, no
   storage reads — its samples/sec vs the roofline shows the cache
   returning more than I/O+decode can possibly deliver.

The decode cost is real PNG codec work (``CompressedImageCodec``), the
workload class the ROADMAP calls decode-bound.

CLI::

    python -m petastorm_tpu.benchmark.shared_cache [--quick] [--json]
"""

from __future__ import annotations

import argparse
import json
import multiprocessing
import os
import shutil
import tempfile
import time

import numpy as np

_MB = 1024.0 * 1024.0


def generate_shared_cache_dataset(url: str, rows: int,
                                  rows_per_group: int = 16,
                                  image_hw: int = 48):
    """PNG-image petastorm store: decode-bound by construction."""
    from petastorm_tpu.codecs import CompressedImageCodec, ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import materialize_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField

    schema = Unischema('SharedCacheBench', [
        UnischemaField('idx', np.int64, (), ScalarCodec(), False),
        UnischemaField('image', np.uint8, (image_hw, image_hw, 3),
                       CompressedImageCodec('png'), False),
    ])
    rng = np.random.default_rng(0)
    # photo-like content (smooth gradients + noise) so PNG neither stores
    # raw bytes nor collapses to nothing — decode cost tracks real images
    base = np.linspace(0, 255, image_hw, dtype=np.float32)
    grid = (base[:, None, None] + base[None, :, None]) / 2.0

    def make_row(i):
        noise = rng.normal(0, 24, (image_hw, image_hw, 3))
        img = np.clip(grid + noise + (i % 37), 0, 255).astype(np.uint8)
        return {'idx': np.int64(i), 'image': img}

    # rows_per_group is enforced via row_group_size_mb on a known-size
    # payload: measure one encoded row and size groups from it
    with materialize_dataset(url, schema,
                             rows_per_file=max(rows_per_group * 4, rows // 2),
                             row_group_size_mb=max(
                                 0.05, rows_per_group * image_hw * image_hw
                                 * 3 / _MB)) as writer:
        writer.write_rows(make_row(i) for i in range(rows))


def _consume_all(url: str, **reader_kwargs) -> dict:
    """Read the whole store once through ``make_columnar_reader``; returns
    per-pass measurements including the reader's stage telemetry."""
    from petastorm_tpu import make_columnar_reader
    start = time.perf_counter()
    samples = 0
    groups = 0
    with make_columnar_reader(url, num_epochs=1, **reader_kwargs) as reader:
        for batch in reader:
            samples += len(batch.idx)
            groups += 1
        diag = reader.diagnostics
    wall = time.perf_counter() - start
    return {
        'wall_s': round(wall, 4),
        'samples': samples,
        'row_groups': groups,
        'samples_per_sec': round(samples / wall, 1) if wall else 0.0,
        'worker_io_s': round(diag['worker_io_s'], 4),
        'worker_decode_s': round(diag['worker_decode_s'], 4),
        'shared_hits': diag['shared_hits'],
        'shared_misses': diag['shared_misses'],
        'shared_cache_bytes': diag['shared_cache_bytes'],
    }


def _reader_proc(url, seed, kwargs, out_queue):
    """One fleet member (module-level: spawn-picklable)."""
    try:
        out_queue.put(_consume_all(url, seed=seed, **kwargs))
    except BaseException as e:  # noqa: BLE001 - shipped to the parent
        out_queue.put({'error': repr(e)})


def _run_fleet(url: str, k: int, kwargs_fn) -> dict:
    """K concurrent reader processes (``kwargs_fn(i)`` -> reader kwargs).

    The headline rate is total samples over the SLOWEST member's read wall
    (construction + read + teardown, measured inside the child): the
    members overlap, so the slowest one closes the fleet window. Python
    process spawn + import time is excluded — it is identical for every
    cache configuration and is not the system under test (on a starved CI
    host it would otherwise swamp the decode signal); the spawn-inclusive
    wall is reported alongside for context."""
    ctx = multiprocessing.get_context('spawn')
    queue = ctx.Queue()
    procs = [ctx.Process(target=_reader_proc,
                         args=(url, 1000 + i, kwargs_fn(i), queue),
                         daemon=True)
             for i in range(k)]
    start = time.perf_counter()
    for p in procs:
        p.start()
    results = [queue.get(timeout=600) for _ in procs]
    for p in procs:
        p.join(timeout=60)
    spawn_wall = time.perf_counter() - start
    errors = [r['error'] for r in results if 'error' in r]
    if errors:
        raise RuntimeError('fleet reader failed: {}'.format(errors[0]))
    samples = sum(r['samples'] for r in results)
    window = max(r['wall_s'] for r in results)
    return {
        'wall_s': round(window, 4),
        'spawn_inclusive_wall_s': round(spawn_wall, 4),
        'samples': samples,
        'aggregate_samples_per_sec': round(samples / window, 1)
        if window else 0.0,
        'per_reader': results,
    }


def run_shared_cache_bench(quick: bool = False, check: bool = True,
                           k_readers: int = 4) -> dict:
    """The BENCH_r11 protocol; ``quick`` shrinks the store for the tier-1
    smoke (same assertions on decode-once, looser speedup bars)."""
    rows = 256 if quick else 4096
    rows_per_group = 16 if quick else 32
    image_hw = 96 if quick else 160
    workers = 2

    tmpdir = tempfile.mkdtemp(prefix='petastorm_tpu_shared_cache_bench_')
    dataset = os.path.join(tmpdir, 'ds')
    url = 'file://' + dataset
    cache_root = os.path.join(tmpdir, 'shared_cache')
    # the tier-0 segment dir defaults under /dev/shm; point it inside the
    # bench scratch so an aborted run leaves nothing behind in shm
    mem_dir = os.path.join(tmpdir, 'shared_mem')
    try:
        generate_shared_cache_dataset(url, rows=rows,
                                      rows_per_group=rows_per_group,
                                      image_hw=image_hw)

        # 1. roofline: serial io+decode, no pool/cache machinery
        roofline = _consume_all(url, reader_pool_type='dummy',
                                shuffle_row_groups=False)
        n_groups = roofline['row_groups']

        shared_kwargs = dict(
            reader_pool_type='thread', workers_count=workers,
            shuffle_row_groups=True,
            cache_type='shared', cache_location=cache_root,
            cache_size_limit=1 << 30,
            cache_extra_settings={'mem_dir': mem_dir})

        # 2. the shared fleet (cold cache)
        shared = _run_fleet(url, k_readers, lambda i: shared_kwargs)

        # 3. decode-once proof through the PRODUCTION aggregation path
        # (docs/pod_observability.md): the cache root serves
        # /observe/snapshot and a PodObserver polls + certifies; the
        # hand-rolled global_counters read stays as an independent
        # cross-check of the merged totals
        from petastorm_tpu.health import DebugServer
        from petastorm_tpu.podobs import PodObserver, make_observe_fn
        from petastorm_tpu.sharedcache import SharedRowGroupCache
        obs = DebugServer(
            lambda: {'state': 'healthy'},
            observe_fn=make_observe_fn(
                cache_counters_fn=(
                    lambda: SharedRowGroupCache.global_counters(cache_root)),
                host='shared_cache_host'))
        obs.start()
        try:
            observer = PodObserver(['127.0.0.1:{}'.format(obs.port)],
                                   expected_row_groups=n_groups)
            pod_report = observer.report()
        finally:
            obs.stop()
        certificate = pod_report['certificate']
        counters = SharedRowGroupCache.global_counters(cache_root)
        assert certificate['fills'] == counters.get('fills', -1), (
            'PodObserver-merged fills ({}) disagree with the hand-read '
            'global_counters ({})'.format(certificate['fills'],
                                          counters.get('fills')))

        # 4. baseline: K readers, K independent local-disk caches (each
        # decodes everything and ALSO pays the cache write — today's story)
        def baseline_kwargs(i):
            return dict(reader_pool_type='thread', workers_count=workers,
                        shuffle_row_groups=True,
                        cache_type='local-disk',
                        cache_location=os.path.join(tmpdir, 'ld_%d' % i),
                        cache_size_limit=1 << 30)
        baseline = _run_fleet(url, k_readers, baseline_kwargs)

        # 5. warm single reader: pure attach, judged against the roofline
        warm = _consume_all(url, **dict(shared_kwargs,
                                        shuffle_row_groups=False))

        speedup = (shared['aggregate_samples_per_sec']
                   / baseline['aggregate_samples_per_sec']
                   if baseline['aggregate_samples_per_sec'] else 0.0)
        warm_vs_roofline = (warm['samples_per_sec']
                            / roofline['samples_per_sec']
                            if roofline['samples_per_sec'] else 0.0)
        expected_hits = (k_readers - 1) * n_groups + warm['row_groups']
        result = {
            'quick': quick,
            'k_readers': k_readers,
            'rows': rows,
            'row_groups': n_groups,
            'roofline': {
                'samples_per_sec': roofline['samples_per_sec'],
                'io_s': roofline['worker_io_s'],
                'decode_s': roofline['worker_decode_s'],
                'note': 'serial I/O+decode ceiling for a non-cached reader',
            },
            'shared': shared,
            'local_disk_baseline': baseline,
            'warm': {
                'samples_per_sec': warm['samples_per_sec'],
                'shared_hits': warm['shared_hits'],
                'shared_misses': warm['shared_misses'],
                'vs_roofline': round(warm_vs_roofline, 2),
            },
            'speedup_aggregate': round(speedup, 2),
            'shared_counters': counters,
            'certificate': certificate,
            'decoded_once': bool(certificate.get('ok')),
            'expected_hits': expected_hits,
        }
        if check:
            assert counters.get('fills') == n_groups, (
                'K={} readers must decode each of the {} row groups exactly '
                'once; shared counters recorded {} fills'.format(
                    k_readers, n_groups, counters.get('fills')))
            total_counters = SharedRowGroupCache.global_counters(cache_root)
            assert total_counters.get('hits', 0) >= expected_hits, (
                'expected >= {} shared-tier hits (K-1 fleet passes + the '
                'warm pass), counted {}'.format(
                    expected_hits, total_counters.get('hits')))
            # quick mode is the CI mechanics smoke: its sub-second decode
            # window cannot show the headline ratio on a starved host, so it
            # only asserts a sanity floor (shared must not be slower than
            # independent local-disk readers); the >= 2x headline gate runs
            # in full mode, where decode dominates (BENCH_r11.json).
            min_speedup = 0.8 if quick else 2.0
            assert speedup >= min_speedup, (
                'shared fleet must be >= {}x the {} independent local-disk '
                'readers; measured {:.2f}x'.format(
                    min_speedup, k_readers, speedup))
            assert warm['shared_misses'] == 0, (
                'warm pass must be 100% shared-tier hits; {} misses'.format(
                    warm['shared_misses']))
            assert warm_vs_roofline >= 1.0, (
                'a fully-cached pass must beat the measured I/O+decode '
                'roofline; measured {:.2f}x'.format(warm_vs_roofline))
        return result
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description='K concurrent readers / decode-once shared cache bench')
    parser.add_argument('--quick', action='store_true',
                        help='small store for the CI smoke path')
    parser.add_argument('--no-check', action='store_true',
                        help='report only; skip the decode-once/speedup '
                             'assertions')
    parser.add_argument('--readers', type=int, default=4,
                        help='fleet size K (default 4, the BENCH_r11 '
                             'protocol)')
    args = parser.parse_args(argv)
    result = run_shared_cache_bench(quick=args.quick, check=not args.no_check,
                                    k_readers=args.readers)
    print(json.dumps(result, indent=2))
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
