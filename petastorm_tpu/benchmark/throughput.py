"""Reader throughput measurement.

Reference parity: ``petastorm/benchmark/throughput.py:112-172`` — warmup then
measure cycles, reporting samples/sec + RSS + CPU%. Extended with a JAX-loader
mode that measures the device-batch path (the TPU infeed story) instead of the
reference's TF ``tf_tensors`` mode.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

from petastorm_tpu.reader import make_batch_reader, make_reader


@dataclasses.dataclass
class ThroughputResult:
    samples_per_sec: float
    warmup_cycles: int
    measure_cycles: int
    rss_mb: float
    cpu_percent: float
    #: ``Reader.diagnostics`` snapshot taken right after the measured window.
    #: Stats are reset after warmup, so the per-stage wall times (worker
    #: io/decode, serialize/deserialize, queue wait), payload bytes/copies,
    #: gauges and derived ``items_per_s``/``mb_per_s`` cover the measured
    #: samples only.
    diagnostics: Optional[dict] = None
    #: ``infeed_diagnosis(diagnostics, heartbeats=...)`` over the measured
    #: window — the classification (bottleneck + pipeline_state) the CLI's
    #: ``-d`` prints, computed with the live heartbeats folded in so it can
    #: never disagree with the watchdog / ``/healthz``.
    diagnosis: Optional[dict] = None
    #: The lineage coverage audit (``reader.lineage.coverage_report()``)
    #: taken after the run when requested via ``audit=True`` — what the
    #: CLI's ``--audit`` prints. ``None`` when not requested.
    audit: Optional[dict] = None
    #: The roofline profile (``reader.profile()``) of the measured window
    #: when requested via ``profile=True``: measured samples/s vs the
    #: calibrated per-stage ceilings, binding stage, advisor
    #: recommendations — what the CLI's ``--profile`` prints. ``None``
    #: when not requested (see ``docs/profiling.md``).
    profile: Optional[dict] = None
    #: The SLO verdict (``reader.slo.evaluate()``) of the measured window
    #: when ``slo=dict(...)`` targets were passed: per-target checks +
    #: error-budget burn — what the CLI's ``--slo-p99-ms`` prints. ``None``
    #: when no targets were set (see ``docs/latency.md``).
    slo: Optional[dict] = None
    #: The autotune controller's self-grading report
    #: (``reader.autotune.report()``) when the run was launched with
    #: ``autotune=True`` — every knob move with its predicted-vs-measured
    #: delta, what the CLI's ``--autotune`` prints. ``None`` when the
    #: controller was off (see ``docs/autotune.md``).
    autotune: Optional[dict] = None


def _consume(iterator, count: int, batched: bool) -> int:
    """Pull ``count`` samples; returns the actual number consumed (the stream
    restarts via num_epochs=None, so StopIteration is unexpected)."""
    seen = 0
    while seen < count:
        item = next(iterator)
        if batched:
            first = item[0] if isinstance(item, tuple) else next(iter(item.values()))
            seen += len(first)
        else:
            seen += 1
    return seen


def reader_throughput(dataset_url: str,
                      field_regex=None,
                      warmup_cycles: int = 200,
                      measure_cycles: int = 1000,
                      pool_type: str = 'thread',
                      workers_count: int = 3,
                      shuffling_queue_size: int = 500,
                      read_method: str = 'python',
                      batch_reader: bool = False,
                      jax_batch_size: int = 0,
                      prefetch_depth: Optional[int] = None,
                      io_readahead=0,
                      trace=None,
                      trace_path: Optional[str] = None,
                      metrics_interval: float = 0,
                      metrics_out: Optional[str] = None,
                      debug_port=None,
                      stall_timeout: float = 0,
                      audit: bool = False,
                      profile: bool = False,
                      slo: Optional[dict] = None,
                      autotune=False,
                      on_decode_error: str = 'raise',
                      cache_type: str = 'null',
                      cache_location: Optional[str] = None,
                      cache_size_limit: Optional[int] = None,
                      remote_read: Optional[str] = None,
                      storage_options: Optional[dict] = None) -> ThroughputResult:
    """Measure reader throughput on ``dataset_url``.

    ``read_method='python'`` iterates raw reader rows/batches;
    ``read_method='jax'`` wraps the reader in :class:`JaxDataLoader` with
    ``jax_batch_size`` and counts device-batch rows.

    ``trace_path`` enables per-item span tracing and exports the chrome
    trace of the measured window (warmup spans are dropped) there;
    ``metrics_interval``/``metrics_out`` run the continuous metrics emitter
    alongside the measurement; ``debug_port``/``stall_timeout`` arm the live
    health endpoint/watchdog on the benchmarked reader (see
    ``docs/health.md``).
    """
    import psutil

    factory = make_batch_reader if batch_reader else make_reader
    if trace_path is not None and trace is None:
        trace = True
    kwargs = dict(reader_pool_type=pool_type, workers_count=workers_count,
                  num_epochs=None, io_readahead=io_readahead, trace=trace,
                  metrics_interval=metrics_interval, metrics_out=metrics_out,
                  debug_port=debug_port, stall_timeout=stall_timeout,
                  on_decode_error=on_decode_error, cache_type=cache_type,
                  cache_location=cache_location,
                  cache_size_limit=cache_size_limit, slo=slo,
                  autotune=autotune, remote_read=remote_read,
                  storage_options=storage_options)
    if field_regex is not None:
        kwargs['schema_fields'] = field_regex

    proc = psutil.Process()
    with factory(dataset_url, **kwargs) as reader:
        if read_method == 'jax':
            from petastorm_tpu.jax_utils import JaxDataLoader
            loader = JaxDataLoader(reader, batch_size=jax_batch_size or 16,
                                   shuffling_queue_capacity=shuffling_queue_size,
                                   prefetch_depth=prefetch_depth)
            iterator = iter(loader)
            batched = True
        elif read_method == 'python':
            iterator = iter(reader)
            batched = reader.batched_output
        else:
            raise ValueError('Unknown read_method {!r}'.format(read_method))

        _consume(iterator, warmup_cycles, batched)
        # warmup decode/io must not pollute the measured window: the stage
        # times, counters and derived items_per_s/mb_per_s in `diagnostics`
        # cover exactly the measured samples (the trace window likewise)
        if reader.stats is not None:
            reader.stats.reset()
        if reader.tracer is not None:
            reader.tracer.reset()
        proc.cpu_percent()  # reset the cpu counter window
        start = time.perf_counter()
        actual = _consume(iterator, measure_cycles, batched)
        elapsed = time.perf_counter() - start
        cpu = proc.cpu_percent()
        rss = proc.memory_info().rss / (1024.0 * 1024.0)
        diagnostics = reader.diagnostics
        from petastorm_tpu.jax_utils import infeed_diagnosis
        health = getattr(reader, 'health', None)
        watchdog = getattr(reader, 'watchdog', None)
        slo_verdict = None
        monitor = getattr(reader, 'slo', None)
        if monitor is not None:
            slo_verdict = monitor.evaluate()
        diagnosis = infeed_diagnosis(
            diagnostics,
            heartbeats=health.heartbeats() if health is not None else None,
            stall_after_s=watchdog.stall_after_s
            if watchdog is not None else None,
            slo=slo_verdict)
        if trace_path is not None and reader.tracer is not None:
            reader.tracer.export_chrome_trace(trace_path)
        audit_report = None
        if audit:
            lineage = getattr(reader, 'lineage', None)
            audit_report = (lineage.coverage_report()
                            if lineage is not None else {'enabled': False})
        autotune_report = None
        controller = getattr(reader, 'autotune', None)
        if controller is not None:
            autotune_report = controller.report()
        profile_report = None
        if profile:
            # the measured window's own samples/s is the honest numerator
            # (jax mode counts batch rows; row mode counts rows) — probes
            # run after the measurement so they cannot perturb it
            profile_report = reader.profile(
                samples_per_sec=actual / elapsed)
            diagnosis['roofline'] = {
                k: profile_report.get(k)
                for k in ('measured_samples_per_s', 'binding_stage',
                          'binding_ceiling_samples_per_s',
                          'roofline_fraction')}

    return ThroughputResult(samples_per_sec=actual / elapsed,
                            warmup_cycles=warmup_cycles,
                            measure_cycles=actual,
                            rss_mb=rss, cpu_percent=cpu,
                            diagnostics=diagnostics,
                            diagnosis=diagnosis,
                            audit=audit_report,
                            profile=profile_report,
                            slo=slo_verdict,
                            autotune=autotune_report)
