"""Chaos benchmark: hedged + retried reads vs plain reads under injected
heavy-tail read latency, plus the fault plane's clean-path overhead.

The tail-at-scale claim of the hedging layer is that a duplicate read fired
when the primary exceeds the live threshold converts stragglers from
p99-defining events into near-median reads. Local CI disks have no tail, so
this bench injects one with the seeded :mod:`petastorm_tpu.faultfs`
``read-hangs`` scenario (an occasional ``read()`` stalls ``hang_s`` — the
straggling-replica shape; the injector's cooldown window models the
re-request landing on a healthy replica):

1. **Clean pair (overhead gate).** Alternating passes with the fault plane
   OFF (``retry=False, hedge=False``) vs the default-on retry layer: the
   median per-pair delta must stay inside the established <5% noise floor —
   resilience must be free when nothing fails.
2. **Unhedged tail pass.** Retry on, hedge off, hangs injected: every
   straggler lands in full in the end-to-end batch latency, so the e2e p99
   is the hang.
3. **Hedged tail pass.** Same seed-fresh scenario with ``hedge=`` armed: a
   stalled primary is raced by a duplicate read and the p99 collapses
   toward the hedge threshold. Gate: **unhedged e2e p99 >= 2x hedged**.

CLI::

    python -m petastorm_tpu.benchmark.chaos [--quick] [--json]
"""

from __future__ import annotations

import argparse
import json
import shutil
import statistics
import tempfile
import time

from petastorm_tpu.benchmark.readahead import generate_readahead_dataset
from petastorm_tpu.faultfs import FaultInjector, FaultyFilesystem

_HEDGE_THRESHOLD_S = 0.05


def _run_pass(dataset_path: str, filesystem, retry, hedge,
              num_epochs: int) -> dict:
    """One measured read pass (1 thread worker, no shuffle, columnar path);
    returns throughput + the end-to-end p99 + the resilience counters."""
    from petastorm_tpu.cache import NullCache
    from petastorm_tpu.reader import Reader
    from petastorm_tpu.readers.columnar_worker import (ColumnarResultsReader,
                                                       ColumnarWorker)
    from petastorm_tpu.workers.thread_pool import ThreadPool

    pool = ThreadPool(1, 50)
    reader = Reader(lambda: filesystem, dataset_path,
                    worker_class=ColumnarWorker,
                    results_reader_factory=ColumnarResultsReader,
                    shuffle_row_groups=False, num_epochs=num_epochs,
                    cache=NullCache(), pool=pool, is_batched_reader=True,
                    retry=retry, hedge=hedge)
    groups = 0
    rows = 0
    start = time.perf_counter()
    try:
        for batch in reader:
            groups += 1
            rows += len(batch.id)
        reader.audit().assert_complete()
    finally:
        wall = time.perf_counter() - start
        snapshot = reader.stats.snapshot()
        reader.stop()
        reader.join()
    return {
        'wall_s': round(wall, 4),
        'row_groups': groups,
        'rows': rows,
        'items_per_s': round(groups / wall, 2) if wall else 0.0,
        'rows_per_s': round(rows / wall, 1) if wall else 0.0,
        'e2e_p99_s': round(snapshot['e2e_latency_p99_s'], 5),
        'io_retries': snapshot['io_retries'],
        'io_hedges': snapshot['io_hedges'],
        'io_hedge_wins': snapshot['io_hedge_wins'],
    }


def run_chaos_bench(quick: bool = False, check: bool = True) -> dict:
    """Hedged vs unhedged under injected tail latency + clean-path overhead
    pairs; returns one JSON-able dict (the BENCH_r16 protocol)."""
    import fsspec

    rows = 96 if quick else 256
    num_epochs = 2
    pairs = 2 if quick else 3
    hang_s = 0.2 if quick else 0.4
    seed = 1616

    tmpdir = tempfile.mkdtemp(prefix='petastorm_tpu_chaos_bench_')
    try:
        generate_readahead_dataset('file://' + tmpdir, rows=rows,
                                   rows_per_group=8)
        base_fs = fsspec.filesystem('file')

        def tail_fs():
            # a FRESH injector per pass: both passes replay the exact same
            # seeded fault sequence, so hedged-vs-unhedged is apples to
            # apples by construction
            return FaultyFilesystem(base_fs, FaultInjector(
                'read-hangs', seed=seed, hang_rate=0.1, hang_s=hang_s))

        # 1. clean-path overhead: fault plane OFF vs default retry ON,
        # alternating pairs (median-of-pairs, the overhead-bench protocol)
        deltas = []
        off_rates, on_rates = [], []
        for _ in range(pairs):
            off = _run_pass(tmpdir, base_fs, retry=False, hedge=False,
                            num_epochs=num_epochs)
            on = _run_pass(tmpdir, base_fs, retry=True, hedge=False,
                           num_epochs=num_epochs)
            off_rates.append(off['rows_per_s'])
            on_rates.append(on['rows_per_s'])
            deltas.append((off['rows_per_s'] - on['rows_per_s'])
                          / off['rows_per_s'] * 100.0)
        overhead_pct = statistics.median(deltas)
        clean_off_rate = statistics.median(off_rates)
        clean_on_rate = statistics.median(on_rates)

        # 2 + 3. the tail: unhedged vs hedged over the same fault sequence
        unhedged = _run_pass(tmpdir, tail_fs(), retry=True, hedge=False,
                             num_epochs=num_epochs)
        hedged = _run_pass(tmpdir, tail_fs(), retry=True,
                           hedge=_HEDGE_THRESHOLD_S, num_epochs=num_epochs)
        p99_ratio = (unhedged['e2e_p99_s'] / hedged['e2e_p99_s']
                     if hedged['e2e_p99_s'] else 0.0)

        result = {
            'benchmark': 'chaos',
            'quick': quick,
            'rows': rows,
            'epochs': num_epochs,
            'scenario': {'name': 'read-hangs', 'seed': seed,
                         'hang_rate': 0.1, 'hang_s': hang_s,
                         'hedge_threshold_s': _HEDGE_THRESHOLD_S},
            'clean': {
                'pairs': pairs,
                'fault_plane_off_rows_per_s': clean_off_rate,
                'fault_plane_on_rows_per_s': clean_on_rate,
                'overhead_pct': round(overhead_pct, 2),
                'per_pair_deltas_pct': [round(d, 2) for d in deltas],
            },
            'unhedged': unhedged,
            'hedged': hedged,
            'e2e_p99_speedup': round(p99_ratio, 2),
            'throughput_speedup': round(
                hedged['items_per_s'] / unhedged['items_per_s'], 2)
            if unhedged['items_per_s'] else 0.0,
            # roofline context: the fault-plane-off clean pass IS this
            # protocol's ceiling; the default-on plane's fraction of it is
            # the (absence of) clean-path cost
            'roofline': {
                'rows_per_s': clean_off_rate,
                'roofline_pct': round(
                    100.0 * clean_on_rate / clean_off_rate, 2)
                if clean_off_rate else None,
            },
        }
        if check:
            min_ratio = 1.3 if quick else 2.0
            max_overhead = 15.0 if quick else 5.0
            assert hedged['io_hedges'] > 0, 'no hedges fired under the tail'
            assert hedged['io_hedge_wins'] > 0, 'no hedged read ever won'
            assert p99_ratio >= min_ratio, (
                'hedged+retried reads must recover >= {}x the unhedged e2e '
                'p99 under injected tail latency; measured {:.2f}x '
                '(unhedged {:.3f}s vs hedged {:.3f}s)'.format(
                    min_ratio, p99_ratio, unhedged['e2e_p99_s'],
                    hedged['e2e_p99_s']))
            assert overhead_pct <= max_overhead, (
                'fault-plane clean-path overhead {:.2f}% exceeds the {}% '
                'noise floor'.format(overhead_pct, max_overhead))
        return result
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description='hedged vs unhedged reads under injected tail latency')
    parser.add_argument('--quick', action='store_true',
                        help='small store/epochs for the CI smoke path')
    parser.add_argument('--no-check', action='store_true',
                        help='report only; skip the p99/overhead assertions')
    args = parser.parse_args(argv)
    result = run_chaos_bench(quick=args.quick, check=not args.no_check)
    print(json.dumps(result, indent=2))
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
