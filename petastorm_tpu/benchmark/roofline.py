"""Roofline benchmark: the mnist decode line judged against a *measured*
per-stage ceiling (ROADMAP item 1's first deliverable; the VERDICT.md gap —
"no measured I/O ceiling to judge the cached line's samples/sec against" —
closed as a first-class subsystem instead of the one-off inline measurement
``benchmark/shared_cache.py`` carried).

Protocol (see ``docs/profiling.md``):

1. **Calibrate.** Run the profiler's micro-probes against the mnist store:
   storage sequential/parquet read bandwidth, per-codec decode throughput
   through the real ``codecs.py`` paths, ``ZeroCopySerializer`` transport
   bandwidth, ``stage_to_global`` host→device staging. Ceilings are
   rows/sec of THIS dataset's rows on THIS host, cached per
   (host, dataset digest).
2. **Measure.** One warmed, traced pass of the production columnar read
   path over the whole store — the decode line every north-star image
   bench is bound by.
3. **Attribute.** The span intervals of the measured pass, interval-union
   per stage (NOT summed — readahead/decode/infeed overlap by design):
   per-stage busy fraction of the wall, critical stage, overlap seconds.
4. **Verdict + advice.** ``reader.profile()`` reports measured samples/s
   as a % of the binding stage's ceiling, and the what-if advisor replays
   its throughput model for ranked knob recommendations; the model is
   direction-checked against the committed BENCH artifacts.

The check mode asserts the pieces of the acceptance criteria: the mnist
line's binding stage is ``decode``, the roofline fraction is sane (>0 and
bounded above by sampling noise), the advisor's worker model is monotone,
and every artifact replay check passes.

CLI::

    python -m petastorm_tpu.benchmark.roofline [--quick] [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import tempfile
import time

#: Measurement noise bound: the ceilings are probed over SAMPLED row groups,
#: so a full-store measurement can land slightly above them; beyond this the
#: calibration (not the pipeline) is wrong — the same threshold at which
#: ``build_profile`` attaches its buffer-drain/stale-calibration warning.
from petastorm_tpu.profiler import SANE_FRACTION_LIMIT as MAX_SANE_FRACTION


def run_roofline_bench(quick: bool = False, check: bool = True,
                       workers_count: int = None) -> dict:
    """Calibrate + measure + attribute + advise on the mnist decode line."""
    from petastorm_tpu import make_columnar_reader, profiler
    from petastorm_tpu.benchmark.northstar import (
        _default_workers, generate_mnist_images_dataset)

    rows = 2048 if quick else 16384
    workers = workers_count or _default_workers()
    tmpdir = tempfile.mkdtemp(prefix='petastorm_tpu_roofline_bench_')
    dataset = os.path.join(tmpdir, 'ds')
    url = 'file://' + dataset
    # the bench must not depend on (or pollute) the user's calibration
    # cache: point the artifact dir into the bench scratch
    saved_env = os.environ.get(profiler.CALIBRATION_DIR_ENV_VAR)
    os.environ[profiler.CALIBRATION_DIR_ENV_VAR] = os.path.join(tmpdir, 'cal')
    try:
        generate_mnist_images_dataset(url, rows=rows)

        def one_pass(trace):
            n = 0
            groups = 0
            with make_columnar_reader(url, num_epochs=1,
                                      reader_pool_type='thread',
                                      workers_count=workers,
                                      shuffle_row_groups=False,
                                      trace=trace) as reader:
                start = time.perf_counter()
                for batch in reader:
                    n += len(batch.idx)
                    groups += 1
                wall = time.perf_counter() - start
                if not trace:
                    return n, groups, wall, None
                # profile INSIDE the context: probes + attribution run on
                # demand after the measured window, never inside it
                prof = reader.profile(calibrate='auto',
                                      samples_per_sec=n / wall)
            return n, groups, wall, prof

        one_pass(trace=False)                       # warm: page cache, pool
        samples, groups, wall, profile = one_pass(trace=True)
        measured = samples / wall if wall else 0.0

        calibration = profiler.load_calibration(profile['dataset_digest'])
        attribution = profile['attribution']
        # the advisor's monotonicity contract, checked on the live ceilings
        ceilings = {k: float(v) for k, v in profile['ceilings'].items()}
        cpu_count = profile['cpu_count']
        curve = [profiler.predict_throughput(ceilings, workers=w,
                                             cpu_count=cpu_count,
                                             io_overlap=True)
                 for w in range(1, 9)]
        monotone = all(b >= a - 1e-9 for a, b in zip(curve, curve[1:]))
        model_checks = profiler.replay_against_artifacts()
        result = {
            'quick': quick,
            'benchmark': 'roofline_mnist_decode',
            'rows': rows,
            'row_groups': groups,
            'workers': workers,
            'cpu_count': cpu_count,
            'measured_samples_per_sec': round(measured, 1),
            'ceilings_samples_per_sec': profile['ceilings'],
            'effective_ceilings_samples_per_sec':
                profile['effective_ceilings'],
            'roofline': {
                'binding_stage': profile['binding_stage'],
                'binding_ceiling_samples_per_s':
                    profile['binding_ceiling_samples_per_s'],
                'roofline_fraction': profile['roofline_fraction'],
                'roofline_pct': round(
                    100.0 * (profile['roofline_fraction'] or 0.0), 2),
            },
            'attribution': attribution,
            'advisor': profile['advisor'],
            'advisor_worker_curve': [round(c, 1) for c in curve],
            'advisor_monotone': monotone,
            'model_checks': model_checks,
            'probes': {
                'storage': (calibration or {}).get('probes', {}).get(
                    'storage'),
                'decode_per_codec': ((calibration or {}).get('probes', {})
                                     .get('decode') or {}).get('per_codec'),
            },
        }
        if check:
            assert profile['calibrated'], 'calibration probes must have run'
            # the png store is decode-bound PER STREAM by construction: one
            # core must decode slower than it reads warm parquet
            assert ceilings['decode'] < ceilings['io'], (
                'single-stream decode ({:.0f}/s) must undercut the storage '
                'ceiling ({:.0f}/s) on a png store'.format(
                    ceilings['decode'], ceilings['io']))
            effective = {k: float(v)
                         for k, v in profile['effective_ceilings'].items()}
            assert profile['binding_stage'] == min(effective,
                                                   key=effective.get), (
                'binding stage must be the lowest effective ceiling')
            if ceilings['decode'] * min(workers, cpu_count) < ceilings['io']:
                # enough cores can legitimately move the wall to io; only
                # when decode still undercuts io at this worker count must
                # the verdict name it (a many-core host is not a failure)
                assert profile['binding_stage'] == 'decode', (
                    'decode undercuts io at {} workers ({} cores) but the '
                    'verdict named {!r}'.format(
                        workers, cpu_count, profile['binding_stage']))
            fraction = profile['roofline_fraction']
            assert fraction and 0.0 < fraction <= MAX_SANE_FRACTION, (
                'measured/{} ceiling fraction {!r} out of (0, {}]'.format(
                    profile['binding_stage'], fraction, MAX_SANE_FRACTION))
            assert monotone, (
                'the advisor model must never predict fewer samples/s for '
                'more workers: {}'.format(curve))
            bad = [c for c in model_checks if not c['ok']]
            assert not bad, (
                'model replay against committed artifacts failed: '
                '{}'.format(bad))
            assert attribution['source'] == 'spans', (
                'the traced pass must attribute from span intervals')
            stages = attribution['stages']
            assert 'decode' in stages, (
                'attribution lost the decode stage: {}'.format(
                    sorted(stages)))
        return result
    finally:
        if saved_env is None:
            os.environ.pop(profiler.CALIBRATION_DIR_ENV_VAR, None)
        else:
            os.environ[profiler.CALIBRATION_DIR_ENV_VAR] = saved_env
        shutil.rmtree(tmpdir, ignore_errors=True)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description='Roofline benchmark: calibrated per-stage ceilings, '
                    'overlap-aware attribution and advisor checks on the '
                    'mnist decode line')
    parser.add_argument('--quick', action='store_true',
                        help='small store for the CI smoke path')
    parser.add_argument('--no-check', action='store_true',
                        help='report only; skip the binding-stage/'
                             'monotonicity assertions')
    parser.add_argument('--workers', type=int, default=None)
    args = parser.parse_args(argv)
    result = run_roofline_bench(quick=args.quick, check=not args.no_check,
                                workers_count=args.workers)
    print(json.dumps(result, indent=2))
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
