"""Lineage-layer overhead benchmark: items/s with default-on sample lineage
vs ``PETASTORM_TPU_LINEAGE=0``.

The lineage layer's contract is "always-on within noise": one provenance
namedtuple per row-group item on the worker side, one ring insert per item
on the consumer side, and per-row work only as a single vectorized ``int64``
column through the loader's shuffling buffer — no per-row Python objects
anywhere. This bench quantifies that on the row reader + ``JaxDataLoader``
path (the deepest lineage plumbing: envelopes, registration, packed source
columns, batch provenance) with the same alternating-pass protocol as
``benchmark/trace_overhead.py`` / ``health_overhead.py``:

1. **Baseline passes** — ``PETASTORM_TPU_LINEAGE=0`` (no envelopes, no
   ledgers, no source columns), full consumption through the loader.
2. **Lineage passes** — lineage at its default (on), identical
   configuration; each pass also asserts the layer actually ran: every
   batch carries ``_provenance`` and the coverage audit reports the
   consumed epochs complete — the artifact records that the measured run
   exercised the real subsystem.
3. Modes alternate with the within-pair order flipped each pair so monotone
   host drift bills both modes equally; the headline is the **median** of
   each mode and

   ``overhead_pct = 100 * (baseline_median - lineage_median) / baseline_median``.

The full run asserts **overhead < 5%** (the measured figure in
``BENCH_r10.json`` is what ``docs/lineage.md`` quotes; the expectation is
~0); ``--quick`` shrinks the store and asserts a looser bar as the tier-1
smoke (sub-second passes are noise-dominated; the quick gate catches a
rewrite that accidentally puts Python objects on the per-row path, not the
headline number).

CLI (output is always JSON)::

    python -m petastorm_tpu.benchmark.lineage_overhead [--quick] [--no-check]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import statistics
import tempfile
import time

from petastorm_tpu.benchmark.readahead import generate_readahead_dataset
from petastorm_tpu.lineage import LINEAGE_ENV_VAR, PROVENANCE_KEY


def _run_pass(url: str, lineage: bool, epochs: int, workers: int,
              batch_size: int = 16) -> dict:
    """One full loader-consumption pass; returns items/s and, for lineage
    passes, the audit verdict + batch-provenance evidence."""
    from petastorm_tpu.jax_utils import JaxDataLoader
    from petastorm_tpu.reader import make_reader

    saved = os.environ.get(LINEAGE_ENV_VAR)
    os.environ[LINEAGE_ENV_VAR] = '1' if lineage else '0'
    try:
        with make_reader(url, reader_pool_type='thread',
                         workers_count=workers, shuffle_row_groups=False,
                         num_epochs=epochs) as reader:
            loader = JaxDataLoader(reader, batch_size=batch_size,
                                   shuffling_queue_capacity=4 * batch_size)
            start = time.perf_counter()
            rows = 0
            provenanced = 0
            for batch in loader:
                rows += len(batch['id'])
                if PROVENANCE_KEY in batch:
                    provenanced += 1
            wall = time.perf_counter() - start
            out = {
                'rows': rows,
                'wall_s': round(wall, 4),
                'items_per_s': round(rows / wall, 1) if wall else 0.0,
                'provenanced_batches': provenanced,
            }
            if lineage:
                report = reader.lineage.coverage_report()
                out['audit_complete'] = report['complete']
                out['epochs_audited'] = len(report['epochs'])
    finally:
        if saved is None:
            os.environ.pop(LINEAGE_ENV_VAR, None)
        else:
            os.environ[LINEAGE_ENV_VAR] = saved
    return out


def run_lineage_overhead_bench(quick: bool = False, check: bool = True,
                               dataset_path: str = None) -> dict:
    """Alternating lineage-on/off passes; returns one JSON-able dict.
    ``quick`` shrinks the store for the tier-1 smoke (looser overhead bar);
    ``check=False`` reports without asserting."""
    rows = 384 if quick else 4096
    rows_per_group = 8
    epochs = 2 if quick else 3
    workers = 2
    passes = 3 if quick else 7
    max_overhead_pct = 25.0 if quick else 5.0

    tmpdir = None
    if dataset_path is None:
        tmpdir = tempfile.mkdtemp(prefix='petastorm_tpu_lineage_bench_')
        dataset_path = tmpdir
    url = 'file://' + dataset_path
    try:
        generate_readahead_dataset(url, rows=rows,
                                   rows_per_group=rows_per_group)
        # one discarded priming pass: cold page cache / codec compilation
        # must not bill either mode
        _run_pass(url, False, 1, workers)

        # best-of-two attempts in quick mode: transient host load must not
        # flip the sub-second CI smoke (same discipline as trace_overhead)
        baseline = lineage = None
        overhead_pct = 0.0
        for _attempt in range(2 if quick else 1):
            baseline, lineage = [], []
            for i in range(passes):
                # alternate the within-pair order: host drift is monotone
                # over seconds, and a fixed order would bill it to one mode
                if i % 2 == 0:
                    baseline.append(_run_pass(url, False, epochs, workers))
                    lineage.append(_run_pass(url, True, epochs, workers))
                else:
                    lineage.append(_run_pass(url, True, epochs, workers))
                    baseline.append(_run_pass(url, False, epochs, workers))
            base_med = statistics.median(r['items_per_s'] for r in baseline)
            lineage_med = statistics.median(r['items_per_s'] for r in lineage)
            overhead_pct = (100.0 * (base_med - lineage_med) / base_med
                            if base_med else 0.0)
            if overhead_pct < max_overhead_pct:
                break

        last = lineage[-1]
        result = {
            'quick': quick,
            'rows': rows,
            'epochs': epochs,
            'workers': workers,
            'passes_per_mode': passes,
            'baseline_items_per_s': base_med,
            'lineage_items_per_s': lineage_med,
            'overhead_pct': round(overhead_pct, 2),
            'audit_complete': last['audit_complete'],
            'epochs_audited': last['epochs_audited'],
            'provenanced_batches': last['provenanced_batches'],
            'baseline_runs': [r['items_per_s'] for r in baseline],
            'lineage_runs': [r['items_per_s'] for r in lineage],
        }
        if check:
            assert result['audit_complete'] is True, (
                'a clean full-consumption pass must audit complete')
            assert result['provenanced_batches'] > 0, (
                'lineage passes must actually attach batch provenance')
            assert all(r['provenanced_batches'] == 0 for r in baseline), (
                'PETASTORM_TPU_LINEAGE=0 must disable all publication')
            assert overhead_pct < max_overhead_pct, (
                'default-on lineage must cost < {}% items/s on this '
                'protocol; measured {:.2f}% (baseline {} vs lineage {} '
                'items/s)'.format(max_overhead_pct, overhead_pct, base_med,
                                  lineage_med))
        return result
    finally:
        if tmpdir is not None:
            shutil.rmtree(tmpdir, ignore_errors=True)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description='lineage-layer overhead benchmark (items/s on vs off)')
    parser.add_argument('--quick', action='store_true',
                        help='small store/fewer passes for the CI smoke path')
    parser.add_argument('--no-check', action='store_true',
                        help='report only; skip the overhead assertion')
    args = parser.parse_args(argv)
    result = run_lineage_overhead_bench(quick=args.quick,
                                        check=not args.no_check)
    print(json.dumps(result, indent=2))
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
