"""Latency-plane overhead benchmark: items/s with default-on streaming
histograms + SLO monitoring vs ``PETASTORM_TPU_LATENCY=0``.

The tail-latency plane's contract is "always-on within noise": every
observation is one arithmetic bucket index plus two integer adds under a
lock, worker deltas ride the existing accounting message, and the e2e anchor
is one dict insert per ventilated item. This bench quantifies that on the
row reader + ``JaxDataLoader`` path (the deepest latency plumbing: worker
io/decode observations, queue-wait/deserialize at delivery, infeed/train
spans, ventilate→batch e2e correlation, plus an armed ``SLOMonitor``) with
the same alternating-pass protocol as ``benchmark/trace_overhead.py`` /
``health_overhead.py`` / ``lineage_overhead.py``:

1. **Baseline passes** — ``PETASTORM_TPU_LATENCY=0`` (no histograms
   anywhere: ``ReaderStats.latency is None``, workers carry no delta
   accumulators), full consumption through the loader.
2. **Latency passes** — the plane at its default (on) with SLO targets
   armed; each pass asserts the subsystem actually ran: the per-stage
   histograms are populated (io/decode/queue_wait/e2e all counted), the
   derived p99 keys are nonzero, and the SLO verdict evaluated — the
   artifact records that the measured run exercised the real subsystem.
3. Modes alternate with the within-pair order flipped each pair so monotone
   host drift bills both modes equally, and the headline is the **median of
   per-pair deltas** — each pair's two passes run back to back, so the pair
   delta cancels drift slower than one pair, and the median across pairs
   rejects the odd loaded-host outlier pair (a ratio of mode medians compares
   passes minutes apart and inherits the full inter-pass spread):

   ``overhead_pct = median_i(100 * (baseline_i - latency_i) / baseline_i)``.

4. Each pass also records its **process CPU time** (``getrusage``, worker
   threads included — the pool is thread-based). On an oversubscribed shared
   host, wall-clock medians inherit scheduler noise far above the effect
   size (the committed artifact records the pass spread next to the
   headline); CPU time is scheduling-immune and measures the *work* the
   plane actually adds. ``cpu_overhead_pct`` is the tight gate (<2% full
   run); the wall-clock figure gates at the protocol's historical noise
   floor (<5%, the r08 precedent).

The full run asserts **overhead < 5%** (the measured figure in
``BENCH_r14.json`` is what ``docs/latency.md`` quotes; the expectation is
noise) and records the serial io+decode roofline of the store (a dummy-pool
raw-reader pass, the ``shared_cache`` bench's protocol) so the headline
carries roofline context. ``--quick`` shrinks the store and asserts a looser
bar as the tier-1 smoke (sub-second passes are noise-dominated; the quick
gate catches a rewrite that puts per-row Python on the record path, not the
headline number).

CLI (output is always JSON)::

    python -m petastorm_tpu.benchmark.latency_overhead [--quick] [--no-check]
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import shutil
import statistics
import tempfile
import time

from petastorm_tpu.benchmark.readahead import generate_readahead_dataset
from petastorm_tpu.latency import LATENCY_ENV_VAR


def _run_pass(url: str, latency: bool, epochs: int, workers: int,
              batch_size: int = 16) -> dict:
    """One full loader-consumption pass; returns items/s and, for latency
    passes, the populated-histogram + SLO evidence."""
    from petastorm_tpu.jax_utils import JaxDataLoader
    from petastorm_tpu.reader import make_reader

    saved = os.environ.get(LATENCY_ENV_VAR)
    os.environ[LATENCY_ENV_VAR] = '1' if latency else '0'
    try:
        slo = (dict(p99_e2e_ms=60_000.0, min_samples_per_s=0.001)
               if latency else None)
        with make_reader(url, reader_pool_type='thread',
                         workers_count=workers, shuffle_row_groups=False,
                         num_epochs=epochs, slo=slo) as reader:
            loader = JaxDataLoader(reader, batch_size=batch_size,
                                   shuffling_queue_capacity=4 * batch_size)
            usage_before = resource.getrusage(resource.RUSAGE_SELF)
            start = time.perf_counter()
            rows = 0
            for batch in loader:
                rows += len(batch['id'])
            wall = time.perf_counter() - start
            usage_after = resource.getrusage(resource.RUSAGE_SELF)
            cpu_s = ((usage_after.ru_utime - usage_before.ru_utime)
                     + (usage_after.ru_stime - usage_before.ru_stime))
            out = {
                'rows': rows,
                'wall_s': round(wall, 4),
                'cpu_s': round(cpu_s, 4),
                'items_per_s': round(rows / wall, 1) if wall else 0.0,
            }
            if latency:
                summary = reader.latency.summary() if reader.latency else {}
                out['histogram_counts'] = {
                    stage: entry['count'] for stage, entry in summary.items()}
                snap = reader.stats.snapshot()
                out['queue_wait_p99_s'] = round(
                    snap.get('queue_wait_p99_s', 0.0), 6)
                out['e2e_latency_p99_s'] = round(
                    snap.get('e2e_latency_p99_s', 0.0), 6)
                verdict = reader.slo.evaluate()
                out['slo_evaluated'] = verdict['evaluations'] >= 1
                out['slo_breached'] = verdict['breached']
            else:
                out['latency_plane_absent'] = reader.latency is None
    finally:
        if saved is None:
            os.environ.pop(LATENCY_ENV_VAR, None)
        else:
            os.environ[LATENCY_ENV_VAR] = saved
    return out


def _serial_roofline(url: str) -> dict:
    """Serial io+decode ceiling of the store: a dummy-pool raw-reader pass
    (no loader, no threading) — the ``shared_cache`` bench's roofline
    protocol, giving the headline its required roofline context."""
    from petastorm_tpu.reader import make_reader
    with make_reader(url, reader_pool_type='dummy', num_epochs=1,
                     shuffle_row_groups=False) as reader:
        start = time.perf_counter()
        rows = sum(1 for _ in reader)
        wall = time.perf_counter() - start
    return {'rows': rows,
            'samples_per_sec': round(rows / wall, 1) if wall else 0.0}


def run_latency_overhead_bench(quick: bool = False, check: bool = True,
                               dataset_path: str = None) -> dict:
    """Alternating latency-on/off passes; returns one JSON-able dict.
    ``quick`` shrinks the store for the tier-1 smoke (looser overhead bar);
    ``check=False`` reports without asserting."""
    rows = 384 if quick else 4096
    rows_per_group = 8
    epochs = 2 if quick else 3
    workers = 2
    passes = 3 if quick else 7
    # wall-clock gate = this protocol's historical noise floor (r08 recorded
    # 3.9%, r09 recorded -3.5% for layers that measure ~0 in CPU time); the
    # scheduling-immune CPU-time gate is the tight one
    max_overhead_pct = 25.0 if quick else 5.0
    max_cpu_overhead_pct = 10.0 if quick else 2.0

    tmpdir = None
    if dataset_path is None:
        tmpdir = tempfile.mkdtemp(prefix='petastorm_tpu_latency_bench_')
        dataset_path = tmpdir
    url = 'file://' + dataset_path
    try:
        generate_readahead_dataset(url, rows=rows,
                                   rows_per_group=rows_per_group)
        # one discarded priming pass: cold page cache / codec compilation
        # must not bill either mode
        _run_pass(url, False, 1, workers)
        roofline = _serial_roofline(url)

        # best-of-two attempts in quick mode: transient host load must not
        # flip the sub-second CI smoke (same discipline as trace_overhead)
        baseline = latency = None
        overhead_pct = 0.0
        for _attempt in range(2 if quick else 1):
            baseline, latency = [], []
            for i in range(passes):
                # alternate the within-pair order: host drift is monotone
                # over seconds, and a fixed order would bill it to one mode
                if i % 2 == 0:
                    baseline.append(_run_pass(url, False, epochs, workers))
                    latency.append(_run_pass(url, True, epochs, workers))
                else:
                    latency.append(_run_pass(url, True, epochs, workers))
                    baseline.append(_run_pass(url, False, epochs, workers))
            base_med = statistics.median(r['items_per_s'] for r in baseline)
            latency_med = statistics.median(r['items_per_s']
                                            for r in latency)
            pair_deltas = [
                100.0 * (b['items_per_s'] - l['items_per_s'])
                / b['items_per_s']
                for b, l in zip(baseline, latency) if b['items_per_s']]
            overhead_pct = statistics.median(pair_deltas)
            base_cpu = statistics.median(r['cpu_s'] for r in baseline)
            latency_cpu = statistics.median(r['cpu_s'] for r in latency)
            cpu_overhead_pct = (100.0 * (latency_cpu - base_cpu) / base_cpu
                                if base_cpu else 0.0)
            if (overhead_pct < max_overhead_pct
                    and cpu_overhead_pct < max_cpu_overhead_pct):
                break

        last = latency[-1]
        roofline_sps = roofline['samples_per_sec']
        result = {
            'quick': quick,
            'rows': rows,
            'epochs': epochs,
            'workers': workers,
            'passes_per_mode': passes,
            'baseline_items_per_s': base_med,
            'latency_items_per_s': latency_med,
            'overhead_pct': round(overhead_pct, 2),
            'overhead_statistic': 'median of per-pair deltas',
            'pair_deltas_pct': [round(d, 2) for d in pair_deltas],
            'baseline_cpu_s': round(base_cpu, 3),
            'latency_cpu_s': round(latency_cpu, 3),
            'cpu_overhead_pct': round(cpu_overhead_pct, 2),
            'spread_pct': round(
                100.0 * (max(r['items_per_s'] for r in baseline)
                         - min(r['items_per_s'] for r in baseline))
                / base_med, 1) if base_med else None,
            'histogram_counts': last['histogram_counts'],
            'queue_wait_p99_s': last['queue_wait_p99_s'],
            'e2e_latency_p99_s': last['e2e_latency_p99_s'],
            'slo_evaluated': last['slo_evaluated'],
            'baseline_runs': [r['items_per_s'] for r in baseline],
            'latency_runs': [r['items_per_s'] for r in latency],
            # serial io+decode ceiling: the loader path pays collation on
            # top of io+decode, so its fraction of this ceiling is context
            # for the headline, not a target
            'roofline': {
                'samples_per_sec': roofline_sps,
                'protocol': 'serial dummy-pool raw-reader pass '
                            '(shared_cache bench protocol)',
                'roofline_pct': round(100.0 * latency_med / roofline_sps, 2)
                if roofline_sps else None,
            },
        }
        if check:
            counts = result['histogram_counts']
            for stage in ('io', 'decode', 'queue_wait', 'e2e_batch',
                          'infeed_wait'):
                assert counts.get(stage, 0) > 0, (
                    'the measured run must actually populate the {} '
                    'histogram; counts={}'.format(stage, counts))
            assert result['e2e_latency_p99_s'] > 0.0, (
                'the derived e2e p99 must be live in the measured run')
            assert result['slo_evaluated'], (
                'the armed SLO monitor must have evaluated')
            assert all(r.get('latency_plane_absent') for r in baseline), (
                'PETASTORM_TPU_LATENCY=0 must create no histogram state')
            assert overhead_pct < max_overhead_pct, (
                'default-on latency plane must cost < {}% items/s on this '
                'protocol; measured {:.2f}% (baseline {} vs latency {} '
                'items/s)'.format(max_overhead_pct, overhead_pct, base_med,
                                  latency_med))
            assert cpu_overhead_pct < max_cpu_overhead_pct, (
                'default-on latency plane must add < {}% process CPU time '
                '(the scheduling-immune statistic); measured {:.2f}% '
                '({:.3f}s vs {:.3f}s)'.format(
                    max_cpu_overhead_pct, cpu_overhead_pct, base_cpu,
                    latency_cpu))
        return result
    finally:
        if tmpdir is not None:
            shutil.rmtree(tmpdir, ignore_errors=True)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description='latency-plane overhead benchmark (items/s on vs off)')
    parser.add_argument('--quick', action='store_true',
                        help='small store/fewer passes for the CI smoke path')
    parser.add_argument('--no-check', action='store_true',
                        help='report only; skip the overhead assertion')
    args = parser.parse_args(argv)
    result = run_latency_overhead_bench(quick=args.quick,
                                        check=not args.no_check)
    print(json.dumps(result, indent=2))
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
