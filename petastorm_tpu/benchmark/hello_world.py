"""Hello-world dataset generator: the workload behind the reference's headline
throughput number (709.84 samples/sec, ``docs/benchmarks_tutorial.rst:20-21``).

Schema mirrors ``examples/hello_world/petastorm_dataset/generate_petastorm_dataset.py:29-33``:
an int id, a (128, 256, 3) png-compressed image, and a wildcard-shaped uint8
4-d array — written here with the pyarrow-native writer instead of Spark.
"""

from __future__ import annotations

import numpy as np

from petastorm_tpu.codecs import CompressedImageCodec, NdarrayCodec, ScalarCodec
from petastorm_tpu.etl.dataset_metadata import materialize_dataset
from petastorm_tpu.unischema import Unischema, UnischemaField

HelloWorldSchema = Unischema('HelloWorldSchema', [
    UnischemaField('id', np.int32, (), ScalarCodec(), False),
    UnischemaField('image1', np.uint8, (128, 256, 3), CompressedImageCodec('png'), False),
    UnischemaField('array_4d', np.uint8, (None, 128, 30, None), NdarrayCodec(), False),
])


def row_generator(x: int) -> dict:
    rng = np.random.default_rng(x)
    return {'id': np.int32(x),
            'image1': rng.integers(0, 255, dtype=np.uint8, size=(128, 256, 3)),
            'array_4d': rng.integers(0, 255, dtype=np.uint8, size=(4, 128, 30, 3))}


def generate_hello_world_dataset(output_url: str = 'file:///tmp/hello_world_dataset',
                                 rows_count: int = 10,
                                 row_group_size_mb: float = 256) -> str:
    with materialize_dataset(output_url, HelloWorldSchema,
                             row_group_size_mb=row_group_size_mb) as writer:
        writer.write_rows(row_generator(i) for i in range(rows_count))
    return output_url


if __name__ == '__main__':
    import sys
    url = sys.argv[1] if len(sys.argv) > 1 else 'file:///tmp/hello_world_dataset'
    print(generate_hello_world_dataset(url))
