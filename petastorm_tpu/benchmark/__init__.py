"""Benchmark tooling (reference ``petastorm/benchmark/``): reader throughput
measurement with host metrics, plus a synthetic hello-world dataset generator
so benchmarks are reproducible without external data."""
