"""Infeed/compute overlap measurement — the TPU north-star metric
(BASELINE.json: samples/sec/chip + infeed-stall %, target ≥90% overlap).

For each training step we split wall time into *stall* (waiting on the input
pipeline for the next batch) and *compute* (device busy in the step function).
``overlap = compute / (compute + stall)``: 1.0 means the pipeline always had a
batch staged when the device finished, i.e. infeed fully hidden behind
compute.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Callable, Iterable, Optional


@dataclasses.dataclass
class InfeedReport:
    steps: int
    samples: int
    total_time_s: float
    stall_time_s: float
    compute_time_s: float
    #: companion overlap measured with ``dispatch_ahead=0`` (block every
    #: step) on the same warm pipeline — set by :func:`attach_sync_probe`.
    #: Round 4 switched the LM benches to ``dispatch_ahead=2``, which made
    #: the r03<->r04 overlap series cross-protocol; carrying BOTH figures
    #: keeps the series interpretable without reading protocol history.
    overlap_pct_sync: Optional[float] = None

    @property
    def overlap(self) -> float:
        busy = self.compute_time_s + self.stall_time_s
        return self.compute_time_s / busy if busy else 1.0

    @property
    def stall_fraction(self) -> float:
        return 1.0 - self.overlap

    @property
    def samples_per_sec(self) -> float:
        return self.samples / self.total_time_s if self.total_time_s else 0.0

    def as_dict(self):
        out = {'steps': self.steps, 'samples': self.samples,
               'samples_per_sec': round(self.samples_per_sec, 2),
               'infeed_stall_pct': round(100.0 * self.stall_fraction, 2),
               'overlap_pct': round(100.0 * self.overlap, 2)}
        if self.overlap_pct_sync is not None:
            out['overlap_pct_sync'] = round(self.overlap_pct_sync, 2)
        return out


#: default length of the dispatch_ahead=0 probe window and its warmup;
#: bench runners that pre-budget a finite loader's epochs must reserve
#: SYNC_PROBE_STEPS + SYNC_PROBE_WARMUP extra steps
SYNC_PROBE_STEPS = 20
SYNC_PROBE_WARMUP = 6


def attach_sync_probe(report: 'InfeedReport', batch_iterator, step_fn,
                      num_steps: int = SYNC_PROBE_STEPS,
                      count_fn: Optional[Callable] = None) -> 'InfeedReport':
    """Measure a short ``dispatch_ahead=0`` window on the (already warm)
    pipeline and attach its overlap to ``report`` as ``overlap_pct_sync`` —
    the blocking-protocol companion figure (see ``InfeedReport``).

    The probe has its own short warmup: the main run's in-flight drain lets
    prefetch buffers refill, and a probe that starts on a refilled buffer
    would read several zero-stall steps and inflate the sync figure on
    production-bound pipelines."""
    probe = measure_infeed_overlap(batch_iterator, step_fn,
                                   num_steps=num_steps,
                                   warmup_steps=SYNC_PROBE_WARMUP,
                                   count_fn=count_fn, dispatch_ahead=0)
    report.overlap_pct_sync = 100.0 * probe.overlap
    return report


def measure_infeed_overlap(batch_iterator: Iterable, step_fn: Callable,
                           num_steps: int = 100, warmup_steps: int = 5,
                           count_fn: Optional[Callable] = None,
                           dispatch_ahead: int = 0) -> InfeedReport:
    """Drive ``step_fn(batch)`` over ``batch_iterator`` and time stalls.

    :param step_fn: one training/inference step; its result is blocked on
        (``jax.block_until_ready``) so compute time is real device time.
    :param count_fn: ``batch -> int`` sample counter (default: len of the
        first value of a dict batch / first field of a tuple).
    :param dispatch_ahead: number of steps the host may run ahead of the
        device before blocking (0 = block every step). A real JAX training
        loop never blocks per step — XLA dispatch is asynchronous and the
        host only syncs when it reads a metric — so a small window (1-2)
        measures the loop users actually run: sub-millisecond infeed bursts
        are absorbed by the in-flight steps instead of being charged as
        stall. The device-time accounting is unchanged (every step is still
        blocked on before the report closes).
    """
    import jax

    iterator = iter(batch_iterator)

    def batch_size_of(batch):
        if count_fn is not None:
            return count_fn(batch)
        if isinstance(batch, dict):
            first = next(v for k, v in batch.items() if k != '_host')
        else:
            first = batch[0]
        return int(first.shape[0])

    for _ in range(warmup_steps):
        out = step_fn(next(iterator))
        jax.block_until_ready(out)

    stall = compute = 0.0
    samples = 0
    steps = 0
    inflight = collections.deque()
    start = time.perf_counter()
    for _ in range(num_steps):
        t0 = time.perf_counter()
        try:
            batch = next(iterator)
        except StopIteration:
            break
        t1 = time.perf_counter()
        inflight.append(step_fn(batch))
        if len(inflight) > dispatch_ahead:
            jax.block_until_ready(inflight.popleft())
        t2 = time.perf_counter()
        stall += t1 - t0
        compute += t2 - t1
        samples += batch_size_of(batch)
        steps += 1
    t0 = time.perf_counter()
    while inflight:
        jax.block_until_ready(inflight.popleft())
    compute += time.perf_counter() - t0
    total = time.perf_counter() - start
    return InfeedReport(steps=steps, samples=samples, total_time_s=total,
                        stall_time_s=stall, compute_time_s=compute)
