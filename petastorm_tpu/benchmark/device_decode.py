"""Device-decode benchmark: bytes-through ingest with the codec decode run
under ``jax.jit`` on the accelerator vs the host batched-decode baseline.

ISSUE-16's deliverable: on an ``NdarrayCodec`` token store, workers ship
the raw column payload (np.save header + cells, one ``(rows, stride)``
uint8 grid per planned column — ``petastorm_tpu/ops/decode.py``) and the
:class:`~petastorm_tpu.jax_utils.JaxDataLoader` decodes it in a single
jitted program (header strip + bitcast + reshape, fused with any
device-marked ``TransformSpec``). The host stops paying codec CPU per
epoch; what remains on the host side of the decode stage is a zero-copy
buffer slice.

The A/B is the kill switch (``PETASTORM_TPU_DEVICE_DECODE`` on vs off)
over the same store through the same reader + loader stack, median-of-N
full passes. Each pass proves which path ran via the decode-path split
counters (``rows_decoded_device`` vs ``rows_decoded_batched``, plus
``bytes_shipped_raw`` and the derived ``device_decode_fraction``), the
two modes are compared bit-for-bit over the whole epoch, and the
device-on line is judged against the calibrated probe ceilings: the
jitted decode ceiling (``device_decode``) and the raw-bytes staging
ceiling (``ingest``) — the link the paper says should bind once decode
leaves the host (PAPER §5.8).

The full run is the committed ``BENCH_r17.json``, gated by
``ci/check_perf_regression.py``; docs markers in ``docs/decode.md`` are
held to it by ``ci/check_bench_docs.py``.

CLI (output is always JSON)::

    python -m petastorm_tpu.benchmark.device_decode [--quick] [--no-check]
        [--prefetch-depth N]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import statistics
import tempfile
import time
from typing import Optional

from petastorm_tpu.ops.decode import DEVICE_DECODE_ENV_VAR

#: Full-run ceiling on host codec CPU in the device-decode pass. Workers
#: ship zero-copy raw views, so per-sample host decode time should be
#: interconnect-noise small — an order of magnitude under what the host
#: batched path pays on this store (~5-10us/sample with framing).
MAX_DEVICE_PASS_HOST_DECODE_US = 4.0

#: Wholesale-collapse guard on the device-on line vs the host baseline
#: (full mode). On a CPU jax backend the "device" decode pays a real jit
#: dispatch per batch with no accelerator to win it back, so the device
#: line legitimately trails the host line there; drift beyond this is a
#: broken path, and the committed-artifact delta is gated separately by
#: ``ci/check_perf_regression.py``.
MIN_DEVICE_VS_HOST_FRACTION = 0.05


def _run_pass(url: str, device: bool, batch_size: int,
              prefetch_depth: Optional[int] = None) -> dict:
    """One full epoch through ``make_columnar_reader`` + ``JaxDataLoader``
    with the kill switch pinned; returns samples/s, the decode-path split
    counters, per-sample host decode CPU, and an epoch checksum stream."""
    import numpy as np

    from petastorm_tpu import make_columnar_reader
    from petastorm_tpu.jax_utils import JaxDataLoader

    saved = os.environ.get(DEVICE_DECODE_ENV_VAR)
    os.environ[DEVICE_DECODE_ENV_VAR] = 'on' if device else 'off'
    try:
        with make_columnar_reader(url, num_epochs=1,
                                  reader_pool_type='thread',
                                  workers_count=1,
                                  shuffle_row_groups=False) as reader:
            loader = JaxDataLoader(reader, batch_size=batch_size,
                                   prefetch_depth=prefetch_depth)
            chunks = []
            start = time.perf_counter()
            rows = 0
            for batch in loader:
                tokens = np.asarray(batch['tokens'])
                rows += len(tokens)
                chunks.append(tokens)
            wall = time.perf_counter() - start
            snapshot = reader._stats_snapshot()
            diag = reader.diagnostics
    finally:
        if saved is None:
            os.environ.pop(DEVICE_DECODE_ENV_VAR, None)
        else:
            os.environ[DEVICE_DECODE_ENV_VAR] = saved
    epoch = np.concatenate(chunks) if chunks else np.empty((0,))
    decode_s = diag.get('worker_decode_s', 0.0) or 0.0
    return {
        'rows': rows,
        'wall_s': round(wall, 4),
        'samples_per_sec': round(rows / wall, 1) if wall else 0.0,
        'rows_decoded_device': snapshot.get('rows_decoded_device', 0),
        'rows_decoded_batched': snapshot.get('rows_decoded_batched', 0),
        'rows_decoded_percell': snapshot.get('rows_decoded_percell', 0),
        'bytes_shipped_raw': snapshot.get('bytes_shipped_raw', 0),
        'device_decode_fraction': snapshot.get('device_decode_fraction'),
        'host_decode_us_per_sample':
            round(1e6 * decode_s / rows, 3) if rows else None,
        '_epoch': epoch,
    }


def _median_line(runs: list) -> dict:
    """Collapse repeated passes into one artifact line: median samples/s,
    the per-run rates, and the last run's counters (identical across runs
    by construction — every pass decodes the full store one way)."""
    line = {k: v for k, v in runs[-1].items() if k != '_epoch'}
    line['samples_per_sec'] = statistics.median(
        r['samples_per_sec'] for r in runs)
    line['runs'] = [r['samples_per_sec'] for r in runs]
    return line


def _calibration(url: str, samples_per_sec: float) -> dict:
    """Probe ceilings for the store (device_decode + ingest included via
    profiler probe_version 3) and the roofline verdict for the measured
    device-on line against the ingest ceiling."""
    from petastorm_tpu import make_columnar_reader
    with make_columnar_reader(url, num_epochs=1, reader_pool_type='thread',
                              workers_count=1,
                              shuffle_row_groups=False) as reader:
        profile = reader.profile(calibrate='auto',
                                 samples_per_sec=samples_per_sec)
        for _ in reader:   # consume so the context exit joins cleanly
            pass
    ceilings = profile['ceilings']
    ingest = ceilings.get('ingest')
    device = ceilings.get('device_decode')
    return {
        'binding_stage': profile['binding_stage'],
        'binding_ceiling_samples_per_s':
            profile['binding_ceiling_samples_per_s'],
        'roofline_fraction': profile['roofline_fraction'],
        'ceilings': ceilings,
        'cpu_count': profile['cpu_count'],
        'ingest_ceiling_samples_per_s': ingest,
        'device_decode_ceiling_samples_per_s': device,
        'pct_of_ingest_ceiling':
            round(100.0 * samples_per_sec / ingest, 2) if ingest else None,
        'pct_of_device_decode_ceiling':
            round(100.0 * samples_per_sec / device, 2) if device else None,
    }


def run_device_decode_bench(quick: bool = False, check: bool = True,
                            prefetch_depth: Optional[int] = None) -> dict:
    """Kill-switch A/B over an ``NdarrayCodec`` token store + probe-ceiling
    verdict on the device-on line. ``quick`` shrinks the store for the CI
    smoke (plumbing assertions only); the full run carries the headline."""
    import numpy as np

    from petastorm_tpu.benchmark.northstar import generate_token_dataset

    rows = 2048 if quick else 16384
    passes = 3 if quick else 5
    batch_size = 256
    tmpdir = tempfile.mkdtemp(prefix='petastorm_tpu_device_decode_')
    tokens_url = 'file://' + os.path.join(tmpdir, 'tokens')
    # the bench must not depend on (or pollute) the user's calibration
    # cache: point the artifact dir into the bench scratch
    from petastorm_tpu import profiler
    saved_env = os.environ.get(profiler.CALIBRATION_DIR_ENV_VAR)
    os.environ[profiler.CALIBRATION_DIR_ENV_VAR] = os.path.join(tmpdir, 'cal')
    try:
        generate_token_dataset(tokens_url, rows=rows, seq_len=256,
                               ndarray_codec=True)

        # one discarded priming pass: cold page cache and jit compilation
        # must not bill either mode
        _run_pass(tokens_url, True, batch_size, prefetch_depth)
        device_runs, host_runs = [], []
        for i in range(passes):
            # alternate the within-pair order: host drift is monotone over
            # seconds and must bill both modes equally
            if i % 2 == 0:
                device_runs.append(
                    _run_pass(tokens_url, True, batch_size, prefetch_depth))
                host_runs.append(
                    _run_pass(tokens_url, False, batch_size, prefetch_depth))
            else:
                host_runs.append(
                    _run_pass(tokens_url, False, batch_size, prefetch_depth))
                device_runs.append(
                    _run_pass(tokens_url, True, batch_size, prefetch_depth))

        identical = bool(np.array_equal(device_runs[-1]['_epoch'],
                                        host_runs[-1]['_epoch']))
        lines = {'tokens_device': _median_line(device_runs),
                 'tokens_host': _median_line(host_runs)}
        headline = lines['tokens_device']
        roofline = _calibration(tokens_url, headline['samples_per_sec'])

        result = {
            'quick': quick,
            'benchmark': 'device_decode_tokens',
            'rows': rows,
            'cpu_count': roofline['cpu_count'],
            'jax_backend': _backend_name(),
            'protocol': {'passes_per_mode': passes, 'pool': 'thread',
                         'workers': 1, 'batch_size': batch_size,
                         'prefetch_depth': prefetch_depth,
                         'kill_switch': DEVICE_DECODE_ENV_VAR},
            'lines': lines,
            'headline_line': 'tokens_device',
            'identical': identical,
            'roofline': roofline,
        }
        if check:
            _check(result, quick)
        return result
    finally:
        if saved_env is None:
            os.environ.pop(profiler.CALIBRATION_DIR_ENV_VAR, None)
        else:
            os.environ[profiler.CALIBRATION_DIR_ENV_VAR] = saved_env
        shutil.rmtree(tmpdir, ignore_errors=True)


def _backend_name() -> Optional[str]:
    try:
        import jax
        return jax.default_backend()
    except Exception:
        return None


def _check(result: dict, quick: bool) -> None:
    device = result['lines']['tokens_device']
    host = result['lines']['tokens_host']
    rows = result['rows']
    assert result['identical'], (
        'device decode must be bit-identical to the host batched path '
        'over the whole epoch')
    assert device['rows_decoded_device'] >= rows, (
        'the device pass must decode every token cell under jit, got '
        '{}/{}'.format(device['rows_decoded_device'], rows))
    assert device['rows_decoded_batched'] == 0, (
        'a clean device pass must not decode on the host ({} rows did)'
        .format(device['rows_decoded_batched']))
    assert device['bytes_shipped_raw'] > 0, (
        'the device pass must ship raw column payload (bytes_shipped_raw)')
    assert device['device_decode_fraction'] == 1.0, (
        'device_decode_fraction must be 1.0 on the device pass, got {!r}'
        .format(device['device_decode_fraction']))
    assert host['rows_decoded_device'] == 0, (
        '{}=off must force the host batched path'.format(
            DEVICE_DECODE_ENV_VAR))
    assert host['rows_decoded_batched'] >= rows, (
        'the host A/B leg must batch-decode every cell, got {}/{}'.format(
            host['rows_decoded_batched'], rows))
    assert host['bytes_shipped_raw'] == 0, (
        'the host leg must not ship raw payload')
    # sub-second quick passes on a loaded host are noise-dominated; the
    # quick gate only proves the plumbing, the full run holds the bars
    if quick:
        return
    us = device['host_decode_us_per_sample']
    assert us is not None and us <= MAX_DEVICE_PASS_HOST_DECODE_US, (
        'host decode CPU must be near zero under bytes-through: measured '
        '{}us/sample (ceiling {})'.format(us, MAX_DEVICE_PASS_HOST_DECODE_US))
    roofline = result['roofline']
    ingest = roofline['ingest_ceiling_samples_per_s']
    assert ingest and roofline['pct_of_ingest_ceiling'], (
        'the ingest ceiling must be probed and the line judged against it')
    assert ingest >= device['samples_per_sec'], (
        'the measured line cannot exceed the raw-bytes staging ceiling: '
        '{} vs {} samples/s (probe is broken)'.format(
            device['samples_per_sec'], ingest))
    assert roofline['device_decode_ceiling_samples_per_s'], (
        'the jitted-decode ceiling must be probed')
    assert device['samples_per_sec'] >= \
        MIN_DEVICE_VS_HOST_FRACTION * host['samples_per_sec'], (
            'device decode collapsed vs the host baseline: {} vs {} '
            'samples/s'.format(device['samples_per_sec'],
                               host['samples_per_sec']))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description='Bytes-through device decode vs host batched decode on '
                    'an NdarrayCodec token store, probe-ceiling-judged')
    parser.add_argument('--quick', action='store_true',
                        help='small store for the CI smoke path')
    parser.add_argument('--no-check', action='store_true',
                        help='report only; skip the assertions')
    parser.add_argument('--prefetch-depth', type=int, default=None,
                        help='device-staging prefetch depth (default: '
                             'PETASTORM_TPU_PREFETCH_DEPTH or 2)')
    args = parser.parse_args(argv)
    result = run_device_decode_bench(quick=args.quick,
                                     check=not args.no_check,
                                     prefetch_depth=args.prefetch_depth)
    print(json.dumps(result, indent=2))
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
