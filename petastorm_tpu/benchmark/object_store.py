"""Object-store read-plane benchmark (BENCH_r18): serial vs prebuffer vs
coalesced parallel ranged reads under a recorded latency trace, the hedge
clean-path overhead, the raw ranged-ingest ceiling, pod-wide cache dedup,
and trace-replay determinism.

Local CI disks have none of an object store's latency structure, so the
read-plane passes run against :mod:`petastorm_tpu.faultfs`'s
``trace-replay`` scenario: every ``read()`` replays a first-byte-latency +
bandwidth sample drawn deterministically from the committed
``benchmark/traces/s3-us-east-1.json`` trace, keyed on (seed, path, byte
range). Phases (see ``docs/object_store.md``):

1. **Read-plane passes.** Every row group read three ways over a fresh
   seeded trace: ``serial`` (plain ``pq.ParquetFile`` over the store
   handle), ``prebuffer`` (Arrow's coalesced pre-buffered reads) and
   ``ranged`` (:class:`petastorm_tpu.objectstore.ParallelRangeReader` —
   footer-planned, gap-merged, bounded-parallel range fetches). Rows must
   be bit-identical across the three; gate: **ranged >= 2x serial**
   row-group read throughput.
2. **Hedge clean-path overhead.** Alternating ranged passes on the clean
   local store, resilience off vs per-range hedging armed: median
   per-pair delta must stay under the 5% noise floor — per-request
   hedging must be free when nothing straggles.
3. **Ranged-ingest ceiling.** The planned ranges of every row group
   fetched raw (no parquet assembly) on the clean store: the MB/s ceiling
   the ranged read path runs under, recorded as the artifact's roofline
   context.
4. **Pod-wide dedup.** K=3 cache roots ("hosts") x M=2 readers, each
   host's shared cache serving ``GET /peercache/<digest>`` and listing
   the others as ``peers=``. The cold host fills every row group once;
   the remaining hosts then read concurrently and satisfy every miss
   from a peer. Certificate (machine-checked): **sum of ``fills`` across
   roots == row groups** and **sum of ``peer_hits`` == (K-1) x row
   groups** — the pod decoded each group exactly once; aggregate
   samples/s must beat the per-host serial baseline.
5. **Determinism.** Two identical hedged ranged passes over fresh
   same-seed injectors: injected-fault counts, the replayed latency
   tally (rounded to microseconds) and the hedge/retry counters must be
   identical — the trace is a replayable experiment, not a noise source.

CLI::

    python -m petastorm_tpu.benchmark.object_store [--quick] [--no-check]
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import shutil
import statistics
import tempfile
import threading
import time

from petastorm_tpu.faultfs import FaultInjector, FaultyFilesystem

_MB = 1024.0 * 1024.0

TRACE_NAME = 's3-us-east-1'
_SEED = 18
_PEER_FILL_HEDGE_DISABLED = None


def _dataset_pieces(dataset_path: str):
    """``(pieces, rows)`` where pieces are (file path, row group) pairs in
    deterministic (path, ordinal) order."""
    import pyarrow.parquet as pq
    paths = []
    for dirpath, _dirnames, filenames in os.walk(dataset_path):
        for name in filenames:
            if name.endswith('.parquet') and not name.startswith('_'):
                paths.append(os.path.join(dirpath, name))
    pieces, rows = [], 0
    for path in sorted(paths):
        metadata = pq.ParquetFile(path).metadata
        rows += metadata.num_rows
        pieces.extend((path, rg) for rg in range(metadata.num_row_groups))
    return pieces, rows


def _table_digest(digest, table) -> None:
    """Fold one row-group table into a running bit-identity digest (column
    order is schema order, identical across read modes)."""
    for name in table.column_names:
        digest.update(name.encode('utf-8'))
        digest.update(str(table.column(name).to_pylist()).encode('utf-8'))


def _read_plane_pass(filesystem, pieces, mode: str) -> dict:
    """Read every row group through one read mode; returns throughput,
    the bit-identity digest and the store's request accounting."""
    import pyarrow.parquet as pq
    from petastorm_tpu.objectstore import ParallelRangeReader
    digest = hashlib.sha256()
    rows = 0
    ranged = ParallelRangeReader(filesystem) if mode == 'ranged' else None
    start = time.perf_counter()
    for path, row_group in pieces:
        if ranged is not None:
            table = ranged.read_row_group(path, row_group)
        else:
            with filesystem.open(path, 'rb') as handle:
                if mode == 'prebuffer':
                    try:
                        pf = pq.ParquetFile(handle, pre_buffer=True)
                    except TypeError:    # pyarrow predating the kwarg
                        pf = pq.ParquetFile(handle)
                elif mode == 'serial':
                    pf = pq.ParquetFile(handle)
                else:
                    raise ValueError('unknown read mode {!r}'.format(mode))
                table = pf.read_row_group(row_group)
        rows += table.num_rows
        _table_digest(digest, table)
    wall = time.perf_counter() - start
    injector = getattr(filesystem, 'injector', None)
    result = {
        'wall_s': round(wall, 4),
        'rows': rows,
        'row_groups': len(pieces),
        'rows_per_s': round(rows / wall, 1) if wall else 0.0,
        'row_groups_per_s': round(len(pieces) / wall, 2) if wall else 0.0,
        'store_requests': filesystem.read_calls,
        'store_bytes': filesystem.bytes_read,
        'digest': digest.hexdigest(),
    }
    if injector is not None:
        result['trace_reads'] = injector.injected.get('trace_reads', 0)
        result['trace_latency_s'] = round(
            injector.injected_s.get('trace_latency_s', 0.0), 4)
    if ranged is not None:
        result['range_events'] = ranged.take_events()
    return result


#: Clean-path hedge threshold: above the trace's worst injected delay
#: (first-byte clamp 0.45s + sub-ms bandwidth terms), so the hedge plane
#: is ARMED on every range but never fires — the overhead measured is the
#: pure cost of the hedging machinery at realistic request latencies.
_CLEAN_PATH_THRESHOLD_S = 1.0


def _hedge_overhead(traced_fs, pieces, pairs: int, epochs: int) -> dict:
    """Alternating ranged passes under fresh same-seed traces, resilience
    off vs per-range hedge armed (median-of-pairs, the overhead-bench
    protocol). Same seed -> both passes replay the identical latency
    sequence, so the per-pair delta isolates the hedge wrapper itself."""
    from petastorm_tpu.objectstore import ParallelRangeReader
    from petastorm_tpu.resilience import ResilientIO

    hedges_fired = 0

    def ranged_pass(hedged: bool) -> float:
        nonlocal hedges_fired
        resilience = (ResilientIO(hedge_options=dict(
            threshold_s=_CLEAN_PATH_THRESHOLD_S)) if hedged else None)
        reader = ParallelRangeReader(traced_fs(), resilience=resilience)
        rows = 0
        start = time.perf_counter()
        for _ in range(epochs):
            for path, row_group in pieces:
                rows += reader.read_row_group(path, row_group).num_rows
        wall = time.perf_counter() - start
        if resilience is not None:
            resilience.drain()
            hedges_fired += resilience.take_events().get('io_hedges', 0)
        return rows / wall if wall else 0.0

    deltas, plain_rates, hedged_rates = [], [], []
    for _ in range(pairs):
        plain = ranged_pass(hedged=False)
        hedged = ranged_pass(hedged=True)
        plain_rates.append(plain)
        hedged_rates.append(hedged)
        deltas.append((plain - hedged) / plain * 100.0 if plain else 0.0)
    return {
        'pairs': pairs,
        'epochs_per_pass': epochs,
        'threshold_s': _CLEAN_PATH_THRESHOLD_S,
        'hedges_fired': hedges_fired,
        'plain_rows_per_s': round(statistics.median(plain_rates), 1),
        'hedged_rows_per_s': round(statistics.median(hedged_rates), 1),
        'overhead_pct': round(statistics.median(deltas), 2),
        'per_pair_deltas_pct': [round(d, 2) for d in deltas],
    }


def _ranged_ingest_ceiling(base_fs, pieces, rows: int) -> dict:
    """Raw parallel range fetch throughput over every planned row-group
    range on the clean store (no parquet assembly) — the ceiling the
    ranged read path runs under — plus the assembled clean ranged read
    rate, whose fraction of the raw ceiling is the parquet-assembly
    cost."""
    from petastorm_tpu.objectstore import ParallelRangeReader
    reader = ParallelRangeReader(base_fs)
    total = 0
    start = time.perf_counter()
    for path, row_group in pieces:
        total += reader.fetch_row_group_bytes(path, row_group)
    raw_wall = time.perf_counter() - start
    assembled_rows = 0
    start = time.perf_counter()
    for path, row_group in pieces:
        assembled_rows += reader.read_row_group(path, row_group).num_rows
    assembled_wall = time.perf_counter() - start
    return {
        'bytes': total,
        'wall_s': round(raw_wall, 4),
        'mb_per_s': round(total / _MB / raw_wall, 2) if raw_wall else 0.0,
        'rows_per_s': round(rows / raw_wall, 1) if raw_wall else 0.0,
        'assembled_rows_per_s': round(assembled_rows / assembled_wall, 1)
        if assembled_wall else 0.0,
    }


def _determinism(base_fs, pieces) -> dict:
    """Two hedged ranged passes over fresh same-seed trace injectors; the
    injected tallies and the fired hedge/retry counters must replay
    exactly. The hedge threshold sits below the trace's smallest
    first-byte latency so every range hedges in both runs (win/loss split
    is a wall-clock race and is reported, not gated)."""
    from petastorm_tpu.objectstore import ParallelRangeReader
    from petastorm_tpu.resilience import ResilientIO, resolve_retry

    def traced_pass() -> dict:
        injector = FaultInjector('trace-replay', seed=_SEED, trace=TRACE_NAME)
        filesystem = FaultyFilesystem(base_fs, injector)
        resilience = ResilientIO(retry_options=resolve_retry(True),
                                 hedge_options=dict(threshold_s=0.001))
        reader = ParallelRangeReader(filesystem, resilience=resilience)
        for path, row_group in pieces:
            reader.read_row_group(path, row_group)
        resilience.drain()
        events = resilience.take_events()
        return {
            'injected': dict(injector.injected),
            'injected_s': {k: round(v, 6)
                           for k, v in injector.injected_s.items()},
            'io_hedges': events.get('io_hedges', 0),
            'io_hedge_wins': events.get('io_hedge_wins', 0),
            'io_retries': events.get('io_retries', 0),
        }

    first, second = traced_pass(), traced_pass()
    return {
        'runs': 2,
        'first': first,
        'second': second,
        'identical_injected': first['injected'] == second['injected'],
        'identical_injected_s': first['injected_s'] == second['injected_s'],
        'identical_hedge_retry': (
            first['io_hedges'] == second['io_hedges']
            and first['io_retries'] == second['io_retries']),
    }


# -- pod-wide dedup ------------------------------------------------------------

def _consume_all(url: str, **reader_kwargs) -> dict:
    from petastorm_tpu import make_columnar_reader
    start = time.perf_counter()
    samples = 0
    groups = 0
    with make_columnar_reader(url, num_epochs=1, **reader_kwargs) as reader:
        for batch in reader:
            samples += len(batch.id)
            groups += 1
    wall = time.perf_counter() - start
    return {
        'wall_s': round(wall, 4),
        'samples': samples,
        'row_groups': groups,
        'samples_per_sec': round(samples / wall, 1) if wall else 0.0,
    }


def _run_host_fleet(url: str, readers: int, kwargs) -> dict:
    """M concurrent reader threads attaching one host's cache root; the
    fleet window is the slowest member's wall (the members overlap)."""
    results = [None] * readers
    errors = []

    def member(i):
        try:
            results[i] = _consume_all(url, **kwargs)
        except BaseException as e:  # noqa: BLE001 - re-raised in the parent
            errors.append(e)

    threads = [threading.Thread(
        target=member, args=(i,), daemon=True,
        name='petastorm-tpu-objectstore-bench-{}'.format(i))
        for i in range(readers)]
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join()
    if errors:
        raise errors[0]
    samples = sum(r['samples'] for r in results)
    window = max(r['wall_s'] for r in results)
    return {
        'wall_s': round(window, 4),
        'samples': samples,
        'aggregate_samples_per_sec': round(samples / window, 1)
        if window else 0.0,
        'per_reader': results,
    }


def _pod_dedup(url: str, tmpdir: str, k_hosts: int, readers_per_host: int,
               n_groups: int) -> dict:
    """K cache roots, each serving its peers; the cold host fills once,
    the remaining hosts peer-attach concurrently. Sequential peer mode
    (no ``peer_hedge_s``): the fills==row_groups certificate needs the
    fill path gated on an actual all-peers miss, not on a race."""
    from petastorm_tpu.sharedcache import SharedRowGroupCache

    baseline = _consume_all(url, reader_pool_type='dummy',
                            shuffle_row_groups=False)

    roots = [os.path.join(tmpdir, 'pod_host_{}'.format(i))
             for i in range(k_hosts)]
    servers = [SharedRowGroupCache(
        root, 1 << 30, mem_dir=os.path.join(tmpdir, 'pod_mem_{}'.format(i)))
        for i, root in enumerate(roots)]
    try:
        endpoints = ['127.0.0.1:{}'.format(server.serve_peers())
                     for server in servers]

        def host_kwargs(i):
            peers = [ep for j, ep in enumerate(endpoints) if j != i]
            return dict(
                reader_pool_type='thread', workers_count=2,
                shuffle_row_groups=False,
                cache_type='shared', cache_location=roots[i],
                cache_size_limit=1 << 30,
                cache_extra_settings={
                    'mem_dir': os.path.join(tmpdir, 'pod_mem_{}'.format(i)),
                    'peers': peers,
                    'peer_hedge_s': _PEER_FILL_HEDGE_DISABLED})

        # stage 1: the cold host decodes the whole store (intra-host
        # single-flight: its M readers fill each group once)
        cold = _run_host_fleet(url, readers_per_host, host_kwargs(0))
        # stage 2: the remaining hosts read concurrently; every miss is
        # served from the cold host's pod endpoint
        warm_hosts = [None] * (k_hosts - 1)
        warm_errors = []

        def warm_host(i):
            try:
                warm_hosts[i - 1] = _run_host_fleet(url, readers_per_host,
                                                    host_kwargs(i))
            except BaseException as e:  # noqa: BLE001 - re-raised below
                warm_errors.append(e)

        threads = [threading.Thread(
            target=warm_host, args=(i,), daemon=True,
            name='petastorm-tpu-objectstore-pod-{}'.format(i))
            for i in range(1, k_hosts)]
        start = time.perf_counter()
        for thread in threads:
            thread.start()
        for thread in threads:
            thread.join()
        warm_wall = time.perf_counter() - start
        if warm_errors:
            raise warm_errors[0]
    finally:
        for server in servers:
            server.close()

    # the PRODUCTION aggregation path (docs/pod_observability.md): each
    # root serves /observe/snapshot and a PodObserver polls + merges —
    # exactly what a real pod's aggregator runs; the hand-rolled
    # global_counters sums stay below as an independent cross-check
    from petastorm_tpu.health import DebugServer
    from petastorm_tpu.podobs import PodObserver, make_observe_fn
    obs_servers = []
    try:
        for i, root in enumerate(roots):
            obs = DebugServer(
                lambda: {'state': 'healthy'},
                observe_fn=make_observe_fn(
                    cache_counters_fn=(
                        lambda root=root:
                        SharedRowGroupCache.global_counters(root)),
                    host='pod_host_{}'.format(i)))
            obs.start()
            obs_servers.append(obs)
        observer = PodObserver(
            ['127.0.0.1:{}'.format(obs.port) for obs in obs_servers],
            expected_row_groups=n_groups)
        pod_report = observer.report()
    finally:
        for obs in obs_servers:
            obs.stop()
    certificate = pod_report['certificate']

    per_host = [SharedRowGroupCache.global_counters(root) for root in roots]
    fills = sum(c.get('fills', 0) for c in per_host)
    peer_hits = sum(c.get('peer_hits', 0) for c in per_host)
    peer_errors = sum(c.get('peer_errors', 0) for c in per_host)
    assert certificate['fills'] == fills, (
        'PodObserver-merged fills ({}) disagree with the hand-summed '
        'global_counters ({})'.format(certificate['fills'], fills))
    assert certificate['peer_hits'] == peer_hits, (
        'PodObserver-merged peer_hits ({}) disagree with the hand-summed '
        'global_counters ({})'.format(certificate['peer_hits'], peer_hits))
    total_samples = cold['samples'] + sum(h['samples'] for h in warm_hosts)
    total_wall = cold['wall_s'] + warm_wall
    aggregate = total_samples / total_wall if total_wall else 0.0
    return {
        'k_hosts': k_hosts,
        'readers_per_host': readers_per_host,
        'protocol': 'staged: cold host fills once, remaining hosts '
                    'peer-attach concurrently (sequential peer mode)',
        'aggregation': 'PodObserver poll of per-root /observe/snapshot '
                       'endpoints, cross-checked against hand-summed '
                       'global_counters',
        'baseline_samples_per_sec': baseline['samples_per_sec'],
        'cold_host': cold,
        'warm_hosts': warm_hosts,
        'total_samples': total_samples,
        'total_wall_s': round(total_wall, 4),
        'aggregate_samples_per_sec': round(aggregate, 1),
        'fills': fills,
        'peer_hits': peer_hits,
        'peer_errors': peer_errors,
        'row_groups': n_groups,
        'per_host_counters': per_host,
        'certificate': certificate,
        'decoded_once_pod_wide': bool(certificate.get('ok')),
    }


# -- the protocol --------------------------------------------------------------

def run_object_store_bench(quick: bool = False, check: bool = True) -> dict:
    """The BENCH_r18 protocol; ``quick`` shrinks the store for the CI
    smoke (same certificates, looser throughput bars for starved hosts)."""
    import fsspec

    from petastorm_tpu.benchmark.readahead import generate_readahead_dataset

    rows = 96 if quick else 256
    rows_per_group = 8
    # trace-replay sleeps dominate the hedge-overhead passes, so the
    # per-pair windows are already stable at small epoch counts
    pairs = 2 if quick else 3
    epochs = 1 if quick else 2

    tmpdir = tempfile.mkdtemp(prefix='petastorm_tpu_object_store_bench_')
    try:
        dataset = os.path.join(tmpdir, 'ds')
        url = 'file://' + dataset
        generate_readahead_dataset(url, rows=rows,
                                   rows_per_group=rows_per_group)
        base_fs = fsspec.filesystem('file')
        pieces, total_rows = _dataset_pieces(dataset)
        n_groups = len(pieces)

        def traced_fs():
            # a FRESH injector per pass: every mode replays the exact same
            # recorded latency sequence, so serial vs ranged is apples to
            # apples by construction
            return FaultyFilesystem(base_fs, FaultInjector(
                'trace-replay', seed=_SEED, trace=TRACE_NAME))

        # 1. the read plane under the recorded trace
        modes = {mode: _read_plane_pass(traced_fs(), pieces, mode)
                 for mode in ('serial', 'prebuffer', 'ranged')}
        bit_identical = (modes['serial']['digest']
                         == modes['prebuffer']['digest']
                         == modes['ranged']['digest'])
        speedup = (modes['ranged']['rows_per_s']
                   / modes['serial']['rows_per_s']
                   if modes['serial']['rows_per_s'] else 0.0)

        # 2. per-range hedging must be free on the clean path
        hedge = _hedge_overhead(traced_fs, pieces, pairs=pairs,
                                epochs=epochs)

        # 3. the raw ingest ceiling (roofline context for the artifact)
        ingest = _ranged_ingest_ceiling(base_fs, pieces, total_rows)
        clean_ranged = ingest['assembled_rows_per_s']
        roofline_pct = (round(100.0 * clean_ranged / ingest['rows_per_s'], 2)
                        if ingest['rows_per_s'] else None)

        # 4. pod-wide dedup
        pod = _pod_dedup(url, tmpdir, k_hosts=3, readers_per_host=2,
                         n_groups=n_groups)

        # 5. the trace must replay exactly
        determinism = _determinism(base_fs, pieces[:max(4, n_groups // 4)]
                                   if quick else pieces)

        result = {
            'benchmark': 'object_store',
            'quick': quick,
            'rows': total_rows,
            'row_groups': n_groups,
            'trace': {'name': TRACE_NAME, 'seed': _SEED},
            'modes': modes,
            'bit_identical': bit_identical,
            'ranged_vs_serial_speedup': round(speedup, 2),
            'hedge_overhead': hedge,
            'roofline': {
                'ranged_ingest_mb_per_s': ingest['mb_per_s'],
                'ranged_ingest_rows_per_s': ingest['rows_per_s'],
                'clean_ranged_rows_per_s': clean_ranged,
                'roofline_pct': roofline_pct,
                'note': 'raw planned-range fetch throughput (no parquet '
                        'assembly) is the ceiling the ranged read path '
                        'runs under',
            },
            'pod': pod,
            'determinism': determinism,
        }
        if check:
            min_speedup = 1.5 if quick else 2.0
            max_overhead = 15.0 if quick else 5.0
            min_pod_ratio = 0.8 if quick else 1.0
            assert bit_identical, (
                'serial/prebuffer/ranged reads must return bit-identical '
                'rows; digests {} / {} / {}'.format(
                    modes['serial']['digest'][:12],
                    modes['prebuffer']['digest'][:12],
                    modes['ranged']['digest'][:12]))
            assert speedup >= min_speedup, (
                'ranged reads must be >= {}x serial row-group read '
                'throughput under the recorded trace; measured '
                '{:.2f}x'.format(min_speedup, speedup))
            assert hedge['hedges_fired'] == 0, (
                'the clean-path overhead pair must never fire a hedge '
                '(threshold {}s sits above the trace tail); {} '
                'fired'.format(_CLEAN_PATH_THRESHOLD_S,
                               hedge['hedges_fired']))
            assert hedge['overhead_pct'] <= max_overhead, (
                'per-range hedge clean-path overhead {:.2f}% exceeds the '
                '{}% noise floor'.format(hedge['overhead_pct'],
                                         max_overhead))
            assert pod['fills'] == n_groups, (
                'the pod must decode each of the {} row groups exactly '
                'once; counted {} fills across {} roots'.format(
                    n_groups, pod['fills'], pod['k_hosts']))
            assert pod['peer_hits'] == (pod['k_hosts'] - 1) * n_groups, (
                'every warm-host miss must be served by a peer: expected '
                '{} peer hits, counted {}'.format(
                    (pod['k_hosts'] - 1) * n_groups, pod['peer_hits']))
            pod_ratio = (pod['aggregate_samples_per_sec']
                         / pod['baseline_samples_per_sec']
                         if pod['baseline_samples_per_sec'] else 0.0)
            assert pod_ratio >= min_pod_ratio, (
                'pod aggregate must be >= {}x the per-host serial '
                'baseline; measured {:.2f}x'.format(min_pod_ratio,
                                                    pod_ratio))
            assert determinism['identical_injected'], (
                'same seed + trace must inject identical fault counts '
                'across runs')
            assert determinism['identical_injected_s'], (
                'same seed + trace must replay an identical latency tally '
                'across runs')
            assert determinism['identical_hedge_retry'], (
                'same seed + trace must fire identical hedge/retry '
                'counters across runs')
        return result
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description='object-store read plane: ranged reads under a '
                    'recorded trace, pod-wide cache dedup')
    parser.add_argument('--quick', action='store_true',
                        help='small store for the CI smoke path')
    parser.add_argument('--no-check', action='store_true',
                        help='report only; skip the speedup/dedup/'
                             'determinism assertions')
    args = parser.parse_args(argv)
    result = run_object_store_bench(quick=args.quick,
                                    check=not args.no_check)
    print(json.dumps(result, indent=2))
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
