"""Decode-parallel worker-scaling artifact (round-3 verdict item 3).

Runs the mnist northstar train bench at workers ∈ {1, 2, 4, 8} and records
the samples/sec + overlap curve together with the host's core count — the
measured artifact behind the claim that the worker pool scales decode across
cores (``docs/profile_mnist_decode.md``). On a single-core host the curve is
expected (and honestly recorded) to be flat: decode is CPU-bound and the
workers time-slice one core.

Usage::

    python -m petastorm_tpu.benchmark.scaling [output.json]
"""

from __future__ import annotations

import json
import os
import sys


def run(output_path: str = 'BENCH_scaling.json',
        worker_counts=(1, 2, 4, 8), rows: int = 16384,
        batch_size: int = 512, num_steps: int = 60) -> dict:
    import jax

    from petastorm_tpu.benchmark import northstar

    platform = jax.devices()[0].platform
    on_accel = platform != 'cpu'
    if not on_accel:
        rows, batch_size, num_steps = 2048, 128, 15
    path = '/tmp/petastorm_tpu_scaling_mnist_{}'.format(rows)
    url = 'file://' + path
    if not os.path.exists(os.path.join(path, '_common_metadata')):
        northstar.generate_mnist_images_dataset(url, rows=rows)

    hidden = 2048 if on_accel else 256
    curve = []
    for workers in worker_counts:
        report = northstar.run_mnist_train_bench(
            url, batch_size=batch_size, num_steps=num_steps,
            workers_count=workers, hidden=hidden)
        entry = {'workers': workers}
        entry.update(report.as_dict())
        curve.append(entry)
        print('workers={}: {:.0f} samples/sec, {:.2f}% overlap'.format(
            workers, report.samples_per_sec, 100 * report.overlap),
            file=sys.stderr)

    result = {
        'workload': 'mnist_train northstar (png decode -> MLP step)',
        'platform': platform,
        'host_cpu_count': os.cpu_count(),
        'batch_size': batch_size,
        'num_steps': num_steps,
        'rows': rows,
        'curve': curve,
        'note': ('read the two columns separately: SAMPLES/SEC is flat on a '
                 '1-core host (decode is CPU-bound; workers time-slice the '
                 'core, so no real decode scaling is possible), while '
                 'OVERLAP% can still RISE with workers — more workers '
                 'deepen effective read-ahead, so per-step stalls are '
                 'partially absorbed by buffered batches and re-attributed '
                 'from stall to compute. Rising overlap at flat throughput '
                 'is queueing/attribution, NOT decode scaling. True scaling '
                 'needs host_cpu_count real cores to back the pool — '
                 'unverifiable in this 1-core environment (predicted, not '
                 'measured; see docs/profile_mnist_decode.md).'),
    }
    from petastorm_tpu.utils import atomic_write
    atomic_write(output_path, lambda f: json.dump(result, f, indent=2))
    return result


if __name__ == '__main__':
    out = sys.argv[1] if len(sys.argv) > 1 else 'BENCH_scaling.json'
    run(out)
