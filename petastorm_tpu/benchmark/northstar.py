"""North-star benchmark: **samples/sec/chip + infeed-stall %** on real train
steps fed from Parquet (BASELINE.md target: >=90% infeed/compute overlap).

Two workloads, both driven through the full production path
``make_reader -> JaxDataLoader -> prefetch_to_device -> jitted train step``:

- ``mnist``: png-compressed 28x28 images decoded by the worker pool, feeding
  an MLP classifier — the decode-heavy regime where infeed stalls live.
- ``transformer``: int32 token windows (the NGram-style LM pipeline shape)
  feeding the flagship transformer LM — the compute-heavy regime where the
  pipeline must simply keep up.

Measurement protocol is the reference's warmup+measure cycle structure
(``/root/reference/petastorm/benchmark/throughput.py:112-172``) extended with
device-side stall accounting (``petastorm_tpu/benchmark/infeed.py``).
"""

from __future__ import annotations

import numpy as np

from petastorm_tpu.benchmark.infeed import (InfeedReport, attach_sync_probe,
                                            measure_infeed_overlap)
from petastorm_tpu.codecs import ArrowListCodec, CompressedImageCodec, ScalarCodec
from petastorm_tpu.etl.dataset_metadata import materialize_dataset
from petastorm_tpu.unischema import Unischema, UnischemaField

MnistImageSchema = Unischema('MnistImageSchema', [
    UnischemaField('idx', np.int64, (), ScalarCodec(), False),
    UnischemaField('image', np.uint8, (28, 28), CompressedImageCodec('png'), False),
    UnischemaField('label', np.int64, (), ScalarCodec(), False),
])


def generate_mnist_images_dataset(output_url: str, rows: int = 16384,
                                  seed: int = 0,
                                  row_group_size_mb: float = 0.5) -> str:
    """Synthetic MNIST-shaped dataset: png images + labels.

    Small row groups by default: a row group is the unit of worker
    parallelism, and tiny-png groups must outnumber the decode workers."""
    rng = np.random.default_rng(seed)

    def gen():
        for i in range(rows):
            yield {'idx': np.int64(i),
                   'image': rng.integers(0, 255, size=(28, 28), dtype=np.uint8),
                   'label': np.int64(i % 10)}

    with materialize_dataset(output_url, MnistImageSchema,
                             row_group_size_mb=row_group_size_mb) as writer:
        writer.write_rows(gen())
    return output_url


def make_token_schema(seq_len: int, ndarray_codec: bool = False) -> Unischema:
    # arrow_list: token windows decode vectorized in C++ (no per-row
    # np.load). ndarray_codec=True stores np.save payloads instead — the
    # opaque-bytes layout the batched-decode bench A/Bs its vectorized
    # chunk decode against (benchmark/decode_batch.py).
    from petastorm_tpu.codecs import NdarrayCodec
    codec = NdarrayCodec() if ndarray_codec else ArrowListCodec()
    return Unischema('TokenSchema', [
        UnischemaField('tokens', np.int32, (seq_len + 1,), codec, False),
    ])


def generate_token_dataset(output_url: str, rows: int = 2048,
                           seq_len: int = 256, vocab: int = 8192,
                           seed: int = 0,
                           row_group_size_mb: float = 4.0,
                           ndarray_codec: bool = False) -> str:
    """LM token windows: each row holds seq_len+1 tokens (input + shifted
    target), the shape the NGram pipeline emits for next-token training."""
    rng = np.random.default_rng(seed)
    schema = make_token_schema(seq_len, ndarray_codec=ndarray_codec)

    def gen():
        for _ in range(rows):
            yield {'tokens': rng.integers(0, vocab, size=(seq_len + 1,),
                                          dtype=np.int32)}

    with materialize_dataset(output_url, schema,
                             row_group_size_mb=row_group_size_mb) as writer:
        writer.write_rows(gen())
    return output_url


def _default_workers() -> int:
    import os
    return min(8, max(2, os.cpu_count() or 2))


def _make_mnist_step(hidden: int):
    import jax
    import jax.numpy as jnp

    from petastorm_tpu.models import mnist_mlp

    params = mnist_mlp.init(jax.random.PRNGKey(0), hidden=hidden)

    @jax.jit
    def step(params, images_u8, labels):
        images = images_u8.reshape(images_u8.shape[0], -1).astype(jnp.float32) / 255.0
        loss, grads = jax.value_and_grad(mnist_mlp.loss_fn)(params, images, labels)
        params = jax.tree_util.tree_map(lambda p, g: p - 1e-3 * g, params, grads)
        return params, loss

    state = {'params': params}

    def step_fn(batch):
        state['params'], loss = step(state['params'], batch['image'],
                                     batch['label'])
        return loss

    return step_fn


#: Train benches bound the pool's results queue to this many row-group chunks.
#: The default (50) lets workers pre-decode tens of thousands of rows while
#: jit compilation runs during warmup; a short measured window then partially
#: drains pre-decoded buffers and reads ABOVE the pipeline's true rate (the
#: r02 artifact where imagenet_train beat decode-only image_decode). A small
#: bound keeps the measured window steady-state.
_TRAIN_BENCH_QUEUE_CHUNKS = 4


def run_mnist_train_bench(dataset_url: str, batch_size: int = 512,
                          num_steps: int = 120, warmup_steps: int = 5,
                          workers_count: int = None, hidden: int = 2048,
                          prefetch: int = 4) -> InfeedReport:
    """Train the MLP from parquet png images, decoding every epoch from disk;
    report overlap + samples/sec (the decode-bound regime)."""
    from petastorm_tpu import make_columnar_reader
    from petastorm_tpu.jax_utils import JaxDataLoader, prefetch_to_device

    step_fn = _make_mnist_step(hidden)
    with make_columnar_reader(dataset_url, reader_pool_type='thread',
                              workers_count=workers_count or _default_workers(),
                              results_queue_size=_TRAIN_BENCH_QUEUE_CHUNKS,
                              num_epochs=None) as reader:
        loader = JaxDataLoader(reader, batch_size=batch_size, drop_last=True)
        batches = prefetch_to_device(iter(loader), size=prefetch)
        return measure_infeed_overlap(
            batches, step_fn, num_steps=num_steps, warmup_steps=warmup_steps,
            count_fn=lambda b: int(b['label'].shape[0]))


def _shared_cache_kwargs(cache_dir: str) -> dict:
    """Reader kwargs for the host-wide tiered shared cache (ROADMAP item 4:
    the cached north-star lines ride ``cache_type='shared'``, not per-reader
    ``local-disk``). The shared-memory tier is pointed inside the bench
    scratch so an aborted run leaves nothing behind in ``/dev/shm``."""
    import os
    return dict(cache_type='shared', cache_location=cache_dir,
                cache_size_limit=20 * 2**30,
                cache_extra_settings={
                    'mem_dir': os.path.join(cache_dir, 'mem')})


def run_mnist_cached_train_bench(dataset_url: str, rows: int,
                                 batch_size: int = 512,
                                 num_steps: int = 60,
                                 workers_count: int = None,
                                 hidden: int = 2048,
                                 prefetch: int = 4,
                                 cache_location: str = None) -> InfeedReport:
    """Steady-state epochs with the device-side epoch cache: epoch 1 decodes
    from parquet and stages every batch into HBM; epochs 2+ replay the device
    arrays with zero host work (``jax_utils.epoch_cache_on_device``, the
    device-side upgrade of the reference's
    ``BatchedDataLoader(inmemory_cache_all=True)``, ``pytorch.py:292-321``).
    Warmup spans the whole first epoch so the measured window is pure steady
    state. The fill epoch's reader publishes its decoded row groups into the
    host-wide shared cache (``cache_type='shared'``) so concurrent readers
    of the same store skip the decode the device cache already paid."""
    import tempfile

    from petastorm_tpu import make_columnar_reader
    from petastorm_tpu.jax_utils import JaxDataLoader, epoch_cache_on_device

    step_fn = _make_mnist_step(hidden)
    cache_dir = cache_location or tempfile.mkdtemp(
        prefix='petastorm_tpu_mnist_shared_cache_')
    try:
        with make_columnar_reader(dataset_url, reader_pool_type='thread',
                                  workers_count=(workers_count
                                                 or _default_workers()),
                                  num_epochs=1,
                                  **_shared_cache_kwargs(cache_dir)) as reader:
            loader = JaxDataLoader(reader, batch_size=batch_size,
                                   drop_last=True)
            # Warmup must span the entire cache-fill epoch (plus compile
            # steps) so the measured window replays device arrays only.
            steps_per_epoch = max(1, rows // batch_size)
            batches = epoch_cache_on_device(loader)
            return measure_infeed_overlap(
                batches, step_fn, num_steps=num_steps,
                warmup_steps=steps_per_epoch + 2,
                count_fn=lambda b: int(b['label'].shape[0]))
    finally:
        if cache_location is None:
            import shutil
            shutil.rmtree(cache_dir, ignore_errors=True)


def generate_imagenet_dataset(output_url: str, rows: int = 256,
                              classes: int = 16, seed: int = 0,
                              row_group_size_mb: float = 8.0,
                              image_codec: str = 'png') -> str:
    """Synthetic ImageNet-style dataset at realistic sizes (~500x375),
    via the examples/imagenet ETL. ``image_codec='jpeg'`` matches real
    ImageNet files and enables DCT-scaled decode hints."""
    import examples.imagenet.generate_imagenet as gen
    gen.generate(output_url, gen.synthetic_rows(rows, classes=classes, seed=seed),
                 row_group_size_mb=row_group_size_mb, image_codec=image_codec)
    return output_url


def _columnar_throughput(dataset_url: str, workers_count=None,
                         transform_spec=None, decode_hints=None) -> dict:
    """Rows/sec through the vectorized columnar reader (optionally with a
    transform and decode hints).

    A full untimed warmup pass precedes the measurement so the reported
    number is steady state (page cache, codec imports, pool spin-up) —
    without it, decode-only lines read BELOW train benches that do strictly
    more work per sample, because the train benches warm up and this did
    not."""
    import time

    from petastorm_tpu import make_columnar_reader

    def one_pass() -> dict:
        n = 0
        with make_columnar_reader(
                dataset_url, num_epochs=1, reader_pool_type='thread',
                workers_count=workers_count or _default_workers(),
                transform_spec=transform_spec, decode_hints=decode_hints,
                shuffle_row_groups=False) as reader:
            t0 = time.perf_counter()
            for batch in reader:
                n += len(batch[0])     # any column: row count per batch
            dt = time.perf_counter() - t0
        return {'samples': n, 'samples_per_sec': round(n / dt, 2)}

    one_pass()                         # warmup
    return one_pass()


def run_image_decode_bench(dataset_url: str, workers_count: int = None,
                           image_size: int = 224, decode_hints=None) -> dict:
    """Pure pipeline throughput: image decode + resize on the worker pool, no
    accelerator involved (this is where thread vs process pools actually
    differentiate). Returns {'samples_per_sec': ...}."""
    from examples.imagenet.main import make_resize_transform
    return _columnar_throughput(dataset_url, workers_count,
                                make_resize_transform(image_size),
                                decode_hints=decode_hints)


def run_imagenet_train_bench(dataset_url: str, batch_size: int = 32,
                             num_steps: int = 100, warmup_steps: int = 3,
                             workers_count: int = None, num_classes: int = 16,
                             prefetch: int = 4,
                             image_size: int = 224,
                             decode_hints=None) -> InfeedReport:
    """Train the residual CNN from realistic-size parquet images (worker-side
    decode + resize): the ImageNet-class north-star workload.

    ``decode_hints={'image': {'scale': 2}}`` on a jpeg store decodes at half
    resolution during entropy decode — the DCT fast path real (jpeg) ImageNet
    makes available; on png stores hints are a documented no-op."""
    import jax

    from examples.imagenet.main import make_resize_transform
    from petastorm_tpu import make_columnar_reader
    from petastorm_tpu.jax_utils import JaxDataLoader, prefetch_to_device
    from petastorm_tpu.models import image_cnn

    params = image_cnn.init(jax.random.PRNGKey(0), num_classes=num_classes)
    step = image_cnn.make_train_step()
    state = {'params': params}

    def step_fn(batch):
        state['params'], loss = step(state['params'], batch['image'],
                                     batch['label'])
        return loss

    with make_columnar_reader(dataset_url, num_epochs=None,
                              reader_pool_type='thread',
                              workers_count=workers_count or _default_workers(),
                              results_queue_size=_TRAIN_BENCH_QUEUE_CHUNKS,
                              transform_spec=make_resize_transform(image_size),
                              decode_hints=decode_hints,
                              ) as reader:
        loader = JaxDataLoader(reader, batch_size=batch_size, drop_last=True)
        batches = prefetch_to_device(iter(loader), size=prefetch)
        return measure_infeed_overlap(
            batches, step_fn, num_steps=num_steps, warmup_steps=warmup_steps,
            count_fn=lambda b: int(b['label'].shape[0]))


def run_imagenet_cached_train_bench(dataset_url: str, rows: int,
                                    batch_size: int = 32,
                                    num_steps: int = 120,
                                    workers_count: int = None,
                                    num_classes: int = 16,
                                    prefetch: int = 4,
                                    image_size: int = 224,
                                    decode_hints=None,
                                    cache_location: str = None) -> InfeedReport:
    """ImageNet-class training with the host-wide tiered shared cache — the
    epoch≥2 story for stores too big for HBM (device cache) on a decode-poor
    host. Epoch 1 decodes + resizes and the columnar worker publishes the
    POST-transform columns into the shared decoded tier (``cache_type=
    'shared'``: shm segments + disk spill — the reference's
    ``LocalDiskArrowTableCache`` role, ``local_disk_arrow_table_cache.py:
    20-40``, upgraded from the per-reader ``local-disk`` store this line
    used through r11 so every reader on the host shares one fill); epochs
    2+ skip png/jpeg decode AND resize entirely. Warmup spans the whole
    fill epoch so the measured window replays cache only."""
    import tempfile

    import jax

    from examples.imagenet.main import make_resize_transform
    from petastorm_tpu import make_columnar_reader
    from petastorm_tpu.jax_utils import JaxDataLoader, prefetch_to_device
    from petastorm_tpu.models import image_cnn

    params = image_cnn.init(jax.random.PRNGKey(0), num_classes=num_classes)
    step = image_cnn.make_train_step()
    state = {'params': params}

    def step_fn(batch):
        state['params'], loss = step(state['params'], batch['image'],
                                     batch['label'])
        return loss

    cache_dir = cache_location or tempfile.mkdtemp(
        prefix='petastorm_tpu_imagenet_cache_')
    try:
        with make_columnar_reader(dataset_url, num_epochs=None,
                                  reader_pool_type='thread',
                                  workers_count=(workers_count
                                                 or _default_workers()),
                                  results_queue_size=_TRAIN_BENCH_QUEUE_CHUNKS,
                                  transform_spec=make_resize_transform(
                                      image_size),
                                  decode_hints=decode_hints,
                                  **_shared_cache_kwargs(cache_dir)) as reader:
            loader = JaxDataLoader(reader, batch_size=batch_size,
                                   drop_last=True)
            batches = prefetch_to_device(iter(loader), size=prefetch)
            steps_per_epoch = max(1, rows // batch_size)
            return measure_infeed_overlap(
                batches, step_fn, num_steps=num_steps,
                warmup_steps=steps_per_epoch + 4,
                count_fn=lambda b: int(b['label'].shape[0]))
    finally:
        if cache_location is None:
            # a defaulted temp cache is per-run scratch: a fresh dir every
            # invocation with zero reuse would fill /tmp monotonically
            import shutil
            shutil.rmtree(cache_dir, ignore_errors=True)


def run_transformer_train_bench(dataset_url: str, batch_size: int = 64,
                                num_steps: int = 40, warmup_steps: int = 3,
                                workers_count: int = None, prefetch: int = 8,
                                d_model: int = 256, n_layers: int = 4,
                                n_heads: int = 8, d_ff: int = 1024,
                                seq_len: int = 256, vocab: int = 8192,
                                dispatch_ahead: int = 2) -> InfeedReport:
    """Train the flagship LM from parquet token windows.

    The LM step is ~1ms on a v5e chip, so the infeed is latency-bound:
    batches prefetch as raw numpy (``prefetch_batches``) and the jitted
    step's own dispatch performs the transfer — one dispatch per step
    instead of device_put + execute, measured r04 at ~99% overlap vs 86-90%
    with explicit staging. ``dispatch_ahead=2`` measures the loop users
    actually run (async XLA dispatch; see ``measure_infeed_overlap``)."""
    import jax

    from petastorm_tpu import make_columnar_reader
    from petastorm_tpu.jax_utils import JaxDataLoader, prefetch_batches
    from petastorm_tpu.models import transformer_lm as tlm

    config = tlm.TransformerConfig(vocab_size=vocab, d_model=d_model,
                                   n_heads=n_heads, n_layers=n_layers,
                                   d_ff=d_ff, max_seq_len=seq_len)
    params = tlm.init(jax.random.PRNGKey(0), config)
    optimizer, step = tlm.make_train_step(config)
    opt_state = optimizer.init(params)
    state = {'params': params, 'opt': opt_state}

    def step_fn(batch):
        tokens = batch['tokens']
        state['params'], state['opt'], loss = step(
            state['params'], state['opt'], tokens[:, :-1], tokens[:, 1:])
        return loss

    with make_columnar_reader(dataset_url, reader_pool_type='thread',
                              workers_count=workers_count or _default_workers(),
                              results_queue_size=_TRAIN_BENCH_QUEUE_CHUNKS,
                              num_epochs=None) as reader:
        loader = JaxDataLoader(reader, batch_size=batch_size, drop_last=True)
        batches = prefetch_batches(iter(loader), size=prefetch)
        report = measure_infeed_overlap(
            batches, step_fn, num_steps=num_steps, warmup_steps=warmup_steps,
            count_fn=lambda b: int(b['tokens'].shape[0]),
            dispatch_ahead=dispatch_ahead)
        return attach_sync_probe(report, batches, step_fn)


def generate_timeseries_token_dataset(output_url: str, rows: int = 4096,
                                      chunk: int = 64, vocab: int = 8192,
                                      seed: int = 0,
                                      rows_per_group: int = 256) -> str:
    """Timestamped token chunks — the raw material for the NGram LM pipeline
    (SURVEY §5.7: NGram is *the* reference input pipeline for sequence
    models). Each row is one timestep: ``ts`` orders rows, ``tokens`` holds a
    fixed-size chunk; the NGram reader assembles consecutive rows into
    windows at read time.

    ``rows_per_group`` bounds the windows a single ventilated row group can
    pre-assemble: a row group is the streaming bench's unit of read-ahead,
    and huge groups would let a short measured window be served entirely
    from warmup surplus (the r02 invariant bug, window-flavored)."""
    rng = np.random.default_rng(seed)
    schema = Unischema('TimeseriesTokens', [
        UnischemaField('ts', np.int64, (), ScalarCodec(), False),
        UnischemaField('tokens', np.int32, (chunk,), ArrowListCodec(), False),
    ])

    def gen():
        for i in range(rows):
            yield {'ts': np.int64(i),
                   'tokens': rng.integers(0, vocab, size=(chunk,),
                                          dtype=np.int32)}

    with materialize_dataset(output_url, schema, row_group_size_mb=256,
                             rows_per_file=rows_per_group) as writer:
        writer.write_rows(gen())
    return output_url


def _make_ngram_lm_parts(window: int, chunk: int, d_model: int,
                         n_layers: int, n_heads: int, d_ff: int, vocab: int):
    """Shared setup for the NGram LM bench pair: the window spec and a
    ``step_fn`` that concatenates a window's timestep chunks on device into
    one (B, window·chunk) sequence and runs the LM train step on the
    shift-by-one (inputs, targets)."""
    import jax
    import jax.numpy as jnp

    from petastorm_tpu.models import transformer_lm as tlm
    from petastorm_tpu.ngram import NGram

    config = tlm.TransformerConfig(vocab_size=vocab, d_model=d_model,
                                   n_heads=n_heads, n_layers=n_layers,
                                   d_ff=d_ff, max_seq_len=window * chunk)
    params = tlm.init(jax.random.PRNGKey(0), config)
    optimizer, step = tlm.make_train_step(config)
    state = {'params': params, 'opt': optimizer.init(params)}
    fields = {0: ['ts', 'tokens']}
    fields.update({i: ['tokens'] for i in range(1, window)})
    ngram = NGram(fields, delta_threshold=1, timestamp_field='ts')

    @jax.jit
    def concat_and_step(params, opt_state, chunks):
        seq = jnp.concatenate(chunks, axis=1)        # (B, window*chunk)
        return step(params, opt_state, seq[:, :-1], seq[:, 1:])

    def step_fn(batch):
        chunks = [batch[i]['tokens'] for i in range(window)]
        state['params'], state['opt'], loss = concat_and_step(
            state['params'], state['opt'], chunks)
        return loss

    return ngram, step_fn


def run_ngram_transformer_train_bench(dataset_url: str, window: int = 4,
                                      chunk: int = 64, batch_size: int = 64,
                                      num_steps: int = 40,
                                      warmup_steps: int = 8,
                                      workers_count: int = None,
                                      prefetch: int = 8,
                                      d_model: int = 256, n_layers: int = 4,
                                      n_heads: int = 8, d_ff: int = 1024,
                                      vocab: int = 8192,
                                      dispatch_ahead: int = 2) -> InfeedReport:
    """The full NGram → JAX → LM loop: parquet rows → NGram window assembly
    (``make_reader(schema_fields=NGram(...))``) → per-timestep collated
    device batches (``JaxDataLoader``) → flagship LM train step."""
    from petastorm_tpu import make_reader
    from petastorm_tpu.jax_utils import JaxDataLoader, prefetch_batches

    ngram, step_fn = _make_ngram_lm_parts(window, chunk, d_model, n_layers,
                                          n_heads, d_ff, vocab)
    # queue bound of 2 window-group chunks: with ~256-row groups that is a
    # few hundred pre-assembled windows of read-ahead — drainable by the
    # warmup steps, so the measured window is steady state
    with make_reader(dataset_url, schema_fields=ngram,
                     reader_pool_type='thread',
                     workers_count=workers_count or _default_workers(),
                     results_queue_size=2,
                     num_epochs=None) as reader:
        loader = JaxDataLoader(reader, batch_size=batch_size, drop_last=True)
        batches = prefetch_batches(iter(loader), size=prefetch)
        report = measure_infeed_overlap(
            batches, step_fn, num_steps=num_steps, warmup_steps=warmup_steps,
            count_fn=lambda b: int(b[0]['tokens'].shape[0]),
            dispatch_ahead=dispatch_ahead)
        return attach_sync_probe(report, batches, step_fn,
                                 count_fn=lambda b: int(b[0]['tokens'].shape[0]))


def run_indexed_ngram_transformer_train_bench(
        dataset_url: str, window: int = 4, chunk: int = 64,
        batch_size: int = 64, num_steps: int = 40, warmup_steps: int = 8,
        workers_count: int = None, prefetch: int = 16,
        d_model: int = 256, n_layers: int = 4, n_heads: int = 8,
        d_ff: int = 1024, vocab: int = 8192,
        dispatch_ahead: int = 2) -> InfeedReport:
    """The resume-capable NGram LM pipeline: the SAME window workload as
    :func:`run_ngram_transformer_train_bench` (matched worker counts), fed
    by the indexed window loader (vectorized per-offset gathers, O(1) exact
    resume) instead of the streaming row-granular assembler — the pair
    quantifies what the indexed path buys. The loader's own worker pool is
    the prefetch pipeline (no extra wrapper), and warmup drains the
    read-ahead built up during jit compile before the window is measured.

    ``prefetch=16`` absorbs the bench host's scheduling jitter (fused
    assembly sustains 3-4x the step consumption rate, so the depth is
    jitter head-room, not a warmup-surplus reservoir — verified r05 with an
    80-step window at unchanged overlap)."""
    import math

    from petastorm_tpu.indexed_ngram import make_indexed_ngram_loader

    ngram, step_fn = _make_ngram_lm_parts(window, chunk, d_model, n_layers,
                                          n_heads, d_ff, vocab)
    loader = make_indexed_ngram_loader(
        dataset_url, ngram, batch_size=batch_size, num_epochs=1, seed=0,
        workers_count=workers_count or _default_workers(),
        prefetch_batches=prefetch)
    # one index build: bump the epoch budget on the already-built loader
    # (num_epochs is only consulted when iteration starts); the reserve
    # covers the sync-protocol probe window
    from petastorm_tpu.benchmark.infeed import (SYNC_PROBE_STEPS,
                                                SYNC_PROBE_WARMUP)
    loader.num_epochs = max(1, math.ceil(
        (num_steps + warmup_steps + SYNC_PROBE_STEPS + SYNC_PROBE_WARMUP + 2)
        / loader.batches_per_epoch))
    try:
        batches = iter(loader)
        report = measure_infeed_overlap(
            batches, step_fn, num_steps=num_steps,
            warmup_steps=warmup_steps,
            count_fn=lambda b: int(b[0]['tokens'].shape[0]),
            dispatch_ahead=dispatch_ahead)
        return attach_sync_probe(report, batches, step_fn,
                                 count_fn=lambda b: int(b[0]['tokens'].shape[0]))
    finally:
        loader.close()


def run_columnar_read_bench(dataset_url: str, workers_count: int = None) -> dict:
    """Vectorized columnar decode throughput (rows/sec) over a codec dataset —
    the zero-per-row-Python read path the JAX adapter feeds from."""
    return _columnar_throughput(dataset_url, workers_count)
