"""Pod-observability benchmark (BENCH_r19): the default-on cost of the
pod plane, and a K-host merged decode-once certificate through the
production aggregation path.

Phases (see ``docs/pod_observability.md``):

1. **Instrumentation overhead.** Alternating ranged read passes under
   fresh same-seed recorded object-store traces (the BENCH_r18
   trace-replay discipline), read-plane observability OFF vs ON
   (``range_fetch`` spans + ``io_range`` latency recorded per range —
   the exact hot-path cost the default-on discipline must bound): median
   per-pair delta must stay under the 5% noise floor at realistic
   request latencies.
2. **K-host merged certificate.** K=3 shared-cache roots ("hosts"): the
   cold host fills every synthetic row group once, the warm hosts
   peer-attach each one. Every root serves ``/observe/snapshot`` (a real
   ``DebugServer``) and a :class:`~petastorm_tpu.podobs.PodObserver`
   polls + merges: the certificate must certify ``sum(fills) == row
   groups`` with ``peer_hits == (K-1) x row groups`` exact, and the
   pod-merged latency percentiles must be **bit-identical** to a
   histogram that recorded every observation directly (the phase-1 passes
   provide the observations — real recorded ``io_range`` durations split
   across the simulated hosts).
3. **Partial pod.** The same poll with one dead peer appended: the
   verdict must degrade to the named ``partial_pod`` and the certificate
   must refuse to certify (``ok: false``) — never a silent shrink of the
   denominator.

CLI::

    python -m petastorm_tpu.benchmark.podobs [--quick] [--no-check]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import statistics
import tempfile
import time

_OVERHEAD_NOISE_FLOOR_PCT = 5.0


def _dataset_pieces(dataset_path: str):
    import pyarrow.parquet as pq
    paths = []
    for dirpath, _dirnames, filenames in os.walk(dataset_path):
        for name in filenames:
            if name.endswith('.parquet') and not name.startswith('_'):
                paths.append(os.path.join(dirpath, name))
    pieces = []
    for path in sorted(paths):
        metadata = pq.ParquetFile(path).metadata
        pieces.extend((path, rg) for rg in range(metadata.num_row_groups))
    return pieces


def _observe_overhead(traced_fs, pieces, pairs: int, epochs: int):
    """Alternating ranged passes under fresh same-seed recorded traces,
    read-plane observability off vs on (median-of-pairs, the
    overhead-bench protocol — same discipline as BENCH_r18's hedge leg:
    the trace replays identical request latencies in both passes, so the
    per-pair delta isolates the instrumentation at REALISTIC object-store
    latencies, not against a bare page-cache read). Returns the overhead
    record and the REAL ``io_range`` latency deltas the observing passes
    recorded (phase 2's bit-identity input)."""
    from petastorm_tpu.objectstore import ParallelRangeReader

    recorded_deltas = []

    def ranged_pass(observing: bool) -> float:
        reader = ParallelRangeReader(traced_fs(), observe_spans=observing,
                                     observe_latency=observing)
        rows = 0
        start = time.perf_counter()
        for _ in range(epochs):
            for path, row_group in pieces:
                rows += reader.read_row_group(path, row_group).num_rows
        wall = time.perf_counter() - start
        if observing:
            reader.take_spans()
            deltas = reader.take_latency()
            if deltas:
                recorded_deltas.append(deltas)
        return rows / wall if wall else 0.0

    # warmup (discarded): page cache, lazy imports, pyarrow first-touch —
    # the measured pairs must isolate the instrumentation, not cold-start
    ranged_pass(observing=False)
    ranged_pass(observing=True)
    recorded_deltas.clear()
    deltas_pct, off_rates, on_rates = [], [], []
    for _ in range(pairs):
        off = ranged_pass(observing=False)
        on = ranged_pass(observing=True)
        off_rates.append(off)
        on_rates.append(on)
        deltas_pct.append((off - on) / off * 100.0 if off else 0.0)
    overhead = {
        'pairs': pairs,
        'epochs_per_pass': epochs,
        'baseline_items_per_s': round(statistics.median(off_rates), 1),
        'podobs_on_items_per_s': round(statistics.median(on_rates), 1),
        'overhead_pct': round(statistics.median(deltas_pct), 2),
        'per_pair_deltas_pct': [round(d, 2) for d in deltas_pct],
    }
    return overhead, recorded_deltas


def _split_deltas_across_hosts(recorded_deltas, k_hosts: int):
    """Fold the recorded per-pass latency deltas into K per-host
    accumulators AND one direct accumulator (as if a single histogram had
    observed everything) — the merged-vs-direct bit-identity fixture."""
    from petastorm_tpu.latency import LatencyDeltas
    per_host = [LatencyDeltas() for _ in range(k_hosts)]
    direct = LatencyDeltas()
    for i, deltas in enumerate(recorded_deltas):
        per_host[i % k_hosts].absorb(deltas)
        direct.absorb(deltas)
    return per_host, direct


def _deltas_state_map(deltas):
    """A ``LatencyDeltas`` accumulator as the ``{stage: state}`` histogram
    export (``LatencyHistogram.state()`` shape) a snapshot carries."""
    out = {}
    for stage, entry in (deltas.drain() or {}).items():
        out[stage] = {
            'buckets': [[i, n] for i, n in sorted(entry['buckets'].items())
                        if n],
            'sum': entry['sum'],
            'count': entry['count'],
        }
    return out


def _pod_certificate_leg(tmpdir: str, n_groups: int, host_state_maps):
    """K=3 cache roots: cold host fills, warm hosts peer-attach, then the
    PRODUCTION aggregation path (per-root ``/observe/snapshot`` +
    ``PodObserver``) certifies decode-once and merges the per-host
    histograms."""
    import numpy as np

    from petastorm_tpu.health import DebugServer
    from petastorm_tpu.podobs import (PodObserver, make_observe_fn,
                                      state_percentiles)
    from petastorm_tpu.sharedcache import SharedRowGroupCache
    from petastorm_tpu.workers.stats import LATENCY_HISTOGRAMS_KEY

    k_hosts = len(host_state_maps)
    roots = [os.path.join(tmpdir, 'pod_host_{}'.format(i))
             for i in range(k_hosts)]
    cold = SharedRowGroupCache(
        roots[0], 1 << 28, mem_dir=os.path.join(tmpdir, 'pod_mem_0'))

    def payload(group: int):
        return {'x': np.arange(group, group + 64, dtype=np.int64)}

    fills = [0]

    def fill_for(group: int):
        def fill():
            fills[0] += 1
            return payload(group)
        return fill

    try:
        endpoint = '127.0.0.1:{}'.format(cold.serve_peers())
        # cold host decodes every group once
        for group in range(n_groups):
            cold.get('group_{}'.format(group), fill_for(group))
        # warm hosts must be served by the pod, never decode
        warm = [SharedRowGroupCache(
            roots[i], 1 << 28,
            mem_dir=os.path.join(tmpdir, 'pod_mem_{}'.format(i)),
            peers=[endpoint]) for i in range(1, k_hosts)]
        try:
            for cache in warm:
                for group in range(n_groups):
                    cache.get('group_{}'.format(group), fill_for(group))
        finally:
            for cache in warm:
                cache.close()
    finally:
        cold.close()

    obs_servers = []
    try:
        for i, root in enumerate(roots):
            states = host_state_maps[i]
            obs = DebugServer(
                lambda: {'state': 'healthy'},
                observe_fn=make_observe_fn(
                    snapshot_fn=(lambda states=states:
                                 {LATENCY_HISTOGRAMS_KEY: states}),
                    health_fn=lambda: {'state': 'healthy'},
                    cache_counters_fn=(
                        lambda root=root:
                        SharedRowGroupCache.global_counters(root)),
                    host='pod_host_{}'.format(i)))
            obs.start()
            obs_servers.append(obs)
        peers = ['127.0.0.1:{}'.format(obs.port) for obs in obs_servers]
        observer = PodObserver(peers, expected_row_groups=n_groups)
        report = observer.report()
        observer.assert_certificate(report)
        # phase 3: one dead peer -> named partial_pod, certificate refuses
        dead_observer = PodObserver(peers + ['127.0.0.1:9'],
                                    expected_row_groups=n_groups)
        dead_report = dead_observer.report()
    finally:
        for obs in obs_servers:
            obs.stop()

    merged = report['latency_histograms']
    pod_percentiles = {stage: state_percentiles(state)
                       for stage, state in merged.items()}
    return {
        'k_hosts': k_hosts,
        'row_groups': n_groups,
        'local_fill_calls': fills[0],
        'verdict': report['verdict'],
        'certificate': report['certificate'],
        'merged_latency': report['latency'],
        'pod_percentiles': pod_percentiles,
        'partial_pod': {
            'verdict': dead_report['verdict'],
            'unreachable': len(dead_report['unreachable']),
            'certificate_ok': dead_report['certificate']['ok'],
            'problems': dead_report['certificate']['problems'],
        },
    }


def run_podobs_bench(quick: bool = False, check: bool = True) -> dict:
    """The BENCH_r19 protocol; ``quick`` shrinks the store for the CI
    smoke (same certificates, same overhead gate at a looser floor)."""
    import fsspec

    from petastorm_tpu.benchmark.readahead import generate_readahead_dataset
    from petastorm_tpu.podobs import PARTIAL_POD, state_percentiles

    from petastorm_tpu.faultfs import FaultInjector, FaultyFilesystem

    rows = 96 if quick else 256
    rows_per_group = 8
    pairs = 2 if quick else 3
    epochs = 1 if quick else 2
    n_groups = 12 if quick else 32
    k_hosts = 3
    trace_name = 's3-us-east-1'
    seed = 19

    tmpdir = tempfile.mkdtemp(prefix='petastorm_tpu_podobs_bench_')
    try:
        dataset = os.path.join(tmpdir, 'ds')
        generate_readahead_dataset('file://' + dataset, rows=rows,
                                   rows_per_group=rows_per_group)
        base_fs = fsspec.filesystem('file')
        pieces = _dataset_pieces(dataset)

        def traced_fs():
            # a FRESH same-seed injector per pass: both passes replay the
            # identical recorded latency sequence (BENCH_r18 discipline)
            return FaultyFilesystem(base_fs, FaultInjector(
                'trace-replay', seed=seed, trace=trace_name))

        # 1. default-on overhead, alternating passes under the trace
        overhead, recorded = _observe_overhead(traced_fs, pieces,
                                               pairs=pairs, epochs=epochs)

        # 2. + 3. the production aggregation path over K simulated hosts,
        # fed the REAL per-pass recordings phase 1 produced
        per_host, direct = _split_deltas_across_hosts(recorded, k_hosts)
        host_state_maps = [_deltas_state_map(d) for d in per_host]
        direct_state_map = _deltas_state_map(direct)
        pod = _pod_certificate_leg(tmpdir, n_groups, host_state_maps)
        direct_percentiles = {stage: state_percentiles(state)
                              for stage, state in direct_state_map.items()}
        merge_bit_identical = (pod['pod_percentiles'] == direct_percentiles
                               and bool(direct_percentiles))

        result = {
            'benchmark': 'podobs',
            'quick': quick,
            'rows': rows,
            'trace': {'name': trace_name, 'seed': seed},
            'overhead': overhead,
            'pod': pod,
            'merge_bit_identical': merge_bit_identical,
            'direct_percentiles': direct_percentiles,
            'roofline': {
                'baseline_items_per_s': overhead['baseline_items_per_s'],
                'roofline_pct': round(
                    100.0 * overhead['podobs_on_items_per_s']
                    / overhead['baseline_items_per_s'], 2)
                if overhead['baseline_items_per_s'] else None,
                'note': 'podobs-on ranged read throughput as % of the '
                        'podobs-off baseline on the same store — the '
                        'measured ceiling the default-on plane runs under',
            },
        }
        if check:
            max_overhead = 15.0 if quick else _OVERHEAD_NOISE_FLOOR_PCT
            assert overhead['overhead_pct'] <= max_overhead, (
                'default-on pod observability costs {:.2f}% on the ranged '
                'read path — beyond the {}% noise floor'.format(
                    overhead['overhead_pct'], max_overhead))
            certificate = pod['certificate']
            assert certificate['ok'] is True, (
                'the K={} pod certificate must certify: {}'.format(
                    k_hosts, certificate['problems']))
            assert certificate['fills'] == n_groups, (
                'pod fills {} != {} row groups'.format(
                    certificate['fills'], n_groups))
            assert certificate['peer_hits'] == (k_hosts - 1) * n_groups, (
                'expected {} peer hits exactly, counted {}'.format(
                    (k_hosts - 1) * n_groups, certificate['peer_hits']))
            assert merge_bit_identical, (
                'pod-merged percentiles must be bit-identical to direct '
                'recording; merged {} vs direct {}'.format(
                    pod['pod_percentiles'], direct_percentiles))
            assert pod['partial_pod']['verdict'] == PARTIAL_POD, (
                'a dead peer must yield the named {} verdict, got '
                '{}'.format(PARTIAL_POD, pod['partial_pod']['verdict']))
            assert pod['partial_pod']['certificate_ok'] is False, (
                'an unreachable host must make the certificate refuse to '
                'certify')
        return result
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description='pod observability: default-on overhead, K-host '
                    'merged decode-once certificate, partial-pod verdict')
    parser.add_argument('--quick', action='store_true',
                        help='small store for the CI smoke path')
    parser.add_argument('--no-check', action='store_true',
                        help='report only; skip the overhead/certificate '
                             'assertions')
    args = parser.parse_args(argv)
    result = run_podobs_bench(quick=args.quick, check=not args.no_check)
    print(json.dumps(result, indent=2))
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
