"""Goodput-plane benchmark (BENCH_r21): the default-on cost of per-step
goodput accounting, and proof the verdicts point at the right side.

Phases (see ``docs/goodput.md``):

1. **Instrumentation overhead.** Alternating loader epochs over the same
   token store, ``PETASTORM_TPU_GOODPUT`` off vs on (structural off: the
   off pass has no monitor object at all). Median per-pair delta must
   stay under the 5% noise floor — the goodput hooks ride the loader's
   existing instrumented iteration path, so the marginal cost is a few
   dict writes per step.
2. **Stall classification.** Two rigged training loops over the same
   store: a *slow-data* leg (the decode path sleeps, the consumer is
   instant) whose :meth:`~petastorm_tpu.goodput.GoodputMonitor.explain_step`
   must say ``data-stall``, and a *slow-compute* leg (instant data, the
   consumer sleeps each step) that must say ``compute-bound`` — the
   benchmark proving the decomposition attributes blame to the correct
   side before anyone trusts it on a real pod.
3. **Pod merge.** K simulated hosts' summed-seconds states merged by
   :func:`~petastorm_tpu.podobs.check_pod_goodput`: the pod totals must
   be bit-identical to one monitor recording every step directly
   (binary-exact step durations, so float summation order cannot hide
   drift), the per-stage ``device_step`` histograms must merge
   bit-identically, and the rigged straggler host must be **named**.
4. **Kill switch.** ``PETASTORM_TPU_GOODPUT=0`` is structural: no monitor
   on the loader, no registration on the reader, no ``goodput_*``
   seconds or derived fractions in the snapshot, no
   ``device_step``/``host_overhead`` latency observations, and the
   ``/goodput`` route 404s.

CLI::

    python -m petastorm_tpu.benchmark.goodput [--quick] [--no-check]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import statistics
import tempfile
import time

_OVERHEAD_NOISE_FLOOR_PCT = 5.0

#: Binary-exact (infeed_s, train_wall_s) step lists per simulated host —
#: float addition over these is associative, so the pod-merge totals must
#: match direct recording BIT-identically, not approximately.
_POD_HOST_STEPS = {
    'pod_host_0': [(0.25, 0.75), (0.0, 1.0), (0.125, 0.875)],
    'pod_host_1': [(0.5, 0.5), (0.25, 0.75), (0.0, 1.0)],
    'pod_host_2': [(1.5, 0.5), (1.75, 0.25), (2.0, 0.5)],   # the straggler
}


def _loader_pass(url, goodput_on: bool, transform_fn=None,
                 consumer_sleep_s: float = 0.0, batch_size: int = 16):
    """One loader epoch; returns ``(items_per_s, monitor_or_None)``. The
    kill switch is flipped via the env var around loader CONSTRUCTION —
    the structural off path, exactly what a production job toggles."""
    from petastorm_tpu.goodput import GOODPUT_ENV_VAR
    from petastorm_tpu.jax_utils import JaxDataLoader
    from petastorm_tpu.reader import make_columnar_reader

    previous = os.environ.get(GOODPUT_ENV_VAR)
    os.environ[GOODPUT_ENV_VAR] = '1' if goodput_on else '0'
    try:
        rows = 0
        with make_columnar_reader(url, num_epochs=1, workers_count=1,
                                  shuffle_row_groups=False) as reader:
            with JaxDataLoader(reader, batch_size=batch_size,
                               transform_fn=transform_fn) as loader:
                start = time.perf_counter()
                for batch in loader:
                    rows += len(next(iter(batch.values())))
                    if consumer_sleep_s:
                        time.sleep(consumer_sleep_s)
                wall = time.perf_counter() - start
                monitor = loader.goodput
        return (rows / wall if wall else 0.0), monitor
    finally:
        if previous is None:
            os.environ.pop(GOODPUT_ENV_VAR, None)
        else:
            os.environ[GOODPUT_ENV_VAR] = previous


def _overhead_leg(url, pairs: int) -> dict:
    """Alternating off/on epochs, median-of-pairs (the repo's overhead
    protocol: warmup pair discarded, per-pair deltas isolate the
    instrumentation from machine drift)."""
    _loader_pass(url, goodput_on=False)
    _loader_pass(url, goodput_on=True)
    deltas_pct, off_rates, on_rates = [], [], []
    for _ in range(pairs):
        off, _ = _loader_pass(url, goodput_on=False)
        on, _ = _loader_pass(url, goodput_on=True)
        off_rates.append(off)
        on_rates.append(on)
        deltas_pct.append((off - on) / off * 100.0 if off else 0.0)
    return {
        'pairs': pairs,
        'baseline_items_per_s': round(statistics.median(off_rates), 1),
        'goodput_on_items_per_s': round(statistics.median(on_rates), 1),
        'overhead_pct': round(statistics.median(deltas_pct), 2),
        'per_pair_deltas_pct': [round(d, 2) for d in deltas_pct],
    }


def _classification_leg(url, stall_sleep_s: float) -> dict:
    """The rigged slow-data / slow-compute loops; each leg reports the
    explain_step verdict of its worst (longest-stall vs longest-wall)
    step plus the cumulative fractions."""

    def slow_data(batch):
        time.sleep(stall_sleep_s)       # the DATA path is the slow side
        return batch

    _, stalled = _loader_pass(url, goodput_on=True, transform_fn=slow_data)
    _, compute = _loader_pass(url, goodput_on=True,
                              consumer_sleep_s=stall_sleep_s)

    def leg(monitor):
        summary = monitor.summary()
        verdict = monitor.explain_step()
        return {
            'steps': summary['steps'],
            'goodput_fraction': summary['goodput_fraction'],
            'data_stall_fraction': summary['data_stall_fraction'],
            'verdict': verdict['verdict'],
            'explanation': verdict['explanation'],
        }

    return {'stall_sleep_ms': stall_sleep_s * 1000.0,
            'slow_data': leg(stalled), 'slow_compute': leg(compute)}


def _pod_merge_leg(min_goodput: float) -> dict:
    """K per-host monitors vs one direct recorder: summed-seconds totals
    and device_step histograms must merge bit-identically, and
    ``check_pod_goodput`` must name the rigged straggler."""
    from petastorm_tpu.goodput import GoodputMonitor
    from petastorm_tpu.latency import PipelineLatency
    from petastorm_tpu.podobs import (check_pod_goodput,
                                      merge_histogram_states,
                                      state_percentiles)

    monitors, planes = {}, {}
    direct = GoodputMonitor()
    direct_plane = PipelineLatency()
    for host in sorted(_POD_HOST_STEPS):
        plane = planes[host] = PipelineLatency()
        monitor = monitors[host] = GoodputMonitor(latency=plane, host=host)
        for infeed_s, wall_s in _POD_HOST_STEPS[host]:
            monitor.note_fetch(infeed_s)
            monitor.finish_step(wall_s)
            direct.note_fetch(infeed_s)
            direct.finish_step(wall_s)
            direct_plane.record('device_step', wall_s)

    pod = check_pod_goodput(
        {host: monitor.summary() for host, monitor in monitors.items()},
        min_goodput=min_goodput)
    direct_state = direct.state()
    totals_bit_identical = all(
        pod['totals'][key] == direct_state[key]
        for key in ('steps', 'total_s', 'stall_s', 'h2d_s', 'device_s',
                    'host_s'))
    merged = merge_histogram_states(
        [{'device_step': planes[h].histograms['device_step'].state()}
         for h in sorted(planes)])['device_step']
    direct_hist = direct_plane.histograms['device_step'].state()
    histograms_bit_identical = (
        merged['buckets'] == direct_hist['buckets']
        and merged['count'] == direct_hist['count'])
    return {
        'k_hosts': len(monitors),
        'min_goodput': min_goodput,
        'pod_goodput_fraction': pod['goodput_fraction'],
        'pod_data_stall_fraction': pod['data_stall_fraction'],
        'straggler': pod['straggler'],
        'ok': pod['ok'],
        'problems': pod['problems'],
        'totals_bit_identical': totals_bit_identical,
        'histograms_bit_identical': histograms_bit_identical,
        'merged_device_step_percentiles': state_percentiles(merged),
    }


def _kill_switch_leg(url) -> dict:
    """Structural-off proof: no monitor, no registration, no counters, no
    latency stages, and a live debug server whose ``/goodput`` 404s."""
    from http.client import HTTPConnection

    from petastorm_tpu.goodput import GOODPUT_ENV_VAR
    from petastorm_tpu.jax_utils import JaxDataLoader
    from petastorm_tpu.reader import make_columnar_reader
    from petastorm_tpu.workers.stats import LATENCY_HISTOGRAMS_KEY

    previous = os.environ.get(GOODPUT_ENV_VAR)
    os.environ[GOODPUT_ENV_VAR] = '0'
    try:
        with make_columnar_reader(url, num_epochs=1, workers_count=1,
                                  shuffle_row_groups=False,
                                  debug_port=0) as reader:
            with JaxDataLoader(reader, batch_size=16) as loader:
                no_monitor = loader.goodput is None
                for _ in loader:
                    pass
                not_registered = reader._goodput is None
                snapshot = reader._stats_snapshot()
                # probe while the reader (and its debug server) is live —
                # the loader's __exit__ joins the reader
                conn = HTTPConnection('127.0.0.1', reader.debug_port,
                                      timeout=10)
                try:
                    conn.request('GET', '/goodput')
                    route_status = conn.getresponse().status
                finally:
                    conn.close()
        histograms = snapshot.get(LATENCY_HISTOGRAMS_KEY) or {}
        return {
            'no_monitor_object': no_monitor,
            'not_registered_on_reader': not_registered,
            'no_seconds_recorded': snapshot.get('goodput_total_s', 0.0) == 0.0,
            'no_derived_fractions': 'goodput_fraction' not in snapshot,
            'no_stage_observations': all(
                histograms.get(stage, {}).get('count', 0) == 0
                for stage in ('device_step', 'host_overhead')),
            'goodput_route_status': route_status,
        }
    finally:
        if previous is None:
            os.environ.pop(GOODPUT_ENV_VAR, None)
        else:
            os.environ[GOODPUT_ENV_VAR] = previous


def run_goodput_bench(quick: bool = False, check: bool = True) -> dict:
    """The BENCH_r21 protocol; ``quick`` shrinks the store and pair count
    for the CI smoke (same classification and merge proofs, the overhead
    gate at a looser floor)."""
    from petastorm_tpu.benchmark.northstar import generate_token_dataset

    rows = 192 if quick else 1024
    seq_len = 32 if quick else 64
    pairs = 2 if quick else 4
    stall_sleep_s = 0.01 if quick else 0.02
    min_goodput = 0.75

    tmpdir = tempfile.mkdtemp(prefix='petastorm_tpu_goodput_bench_')
    try:
        url = 'file://' + os.path.join(tmpdir, 'tok')
        generate_token_dataset(url, rows=rows, seq_len=seq_len, vocab=256,
                               seed=21, row_group_size_mb=0.05,
                               ndarray_codec=True)

        overhead = _overhead_leg(url, pairs=pairs)
        classification = _classification_leg(url, stall_sleep_s)
        pod = _pod_merge_leg(min_goodput)
        kill_switch = _kill_switch_leg(url)

        result = {
            'benchmark': 'goodput',
            'quick': quick,
            'rows': rows,
            'overhead': overhead,
            'classification': classification,
            'pod': pod,
            'kill_switch': kill_switch,
            'roofline': {
                'baseline_items_per_s': overhead['baseline_items_per_s'],
                'roofline_pct': round(
                    100.0 * overhead['goodput_on_items_per_s']
                    / overhead['baseline_items_per_s'], 2)
                if overhead['baseline_items_per_s'] else None,
                'note': 'goodput-on loader throughput as % of the '
                        'goodput-off baseline on the same store — the '
                        'measured ceiling the default-on plane runs under',
            },
        }
        if check:
            max_overhead = 15.0 if quick else _OVERHEAD_NOISE_FLOOR_PCT
            assert overhead['overhead_pct'] <= max_overhead, (
                'default-on goodput accounting costs {:.2f}% on the loader '
                'path — beyond the {}% noise floor'.format(
                    overhead['overhead_pct'], max_overhead))
            assert classification['slow_data']['verdict'] == 'data-stall', (
                'the rigged slow-data loop must classify as data-stall, '
                'got {!r}'.format(classification['slow_data']['verdict']))
            assert (classification['slow_compute']['verdict']
                    == 'compute-bound'), (
                'the rigged slow-compute loop must classify as '
                'compute-bound, got {!r}'.format(
                    classification['slow_compute']['verdict']))
            assert pod['totals_bit_identical'], (
                'pod goodput totals must be bit-identical to direct '
                'recording')
            assert pod['histograms_bit_identical'], (
                'merged device_step histograms must be bit-identical to '
                'direct recording')
            assert pod['straggler']['host'] == 'pod_host_2', (
                'the rigged straggler must be named, got {!r}'.format(
                    pod['straggler']))
            assert pod['ok'] is False and any(
                'pod_host_2' in p for p in pod['problems']), (
                'the min_goodput breach must name the straggler host')
            assert all(kill_switch[key] for key in (
                'no_monitor_object', 'not_registered_on_reader',
                'no_seconds_recorded', 'no_derived_fractions',
                'no_stage_observations')), (
                'the kill switch must be structural: {}'.format(kill_switch))
            assert kill_switch['goodput_route_status'] == 404, (
                '/goodput must 404 under the kill switch, got {}'.format(
                    kill_switch['goodput_route_status']))
        return result
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description='goodput plane: default-on overhead, slow-data vs '
                    'slow-compute classification, pod merge + straggler, '
                    'structural kill switch')
    parser.add_argument('--quick', action='store_true',
                        help='small store for the CI smoke path')
    parser.add_argument('--no-check', action='store_true',
                        help='report only; skip the overhead/verdict '
                             'assertions')
    args = parser.parse_args(argv)
    result = run_goodput_bench(quick=args.quick, check=not args.no_check)
    print(json.dumps(result, indent=2))
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
