"""Loader micro-benchmark over a zero-I/O dummy reader.

Reference parity: ``petastorm/benchmark/dummy_reader.py:26-85`` — measures the
pure consumer-side overhead of DataLoader vs BatchedDataLoader vs JaxDataLoader
at several batch sizes, isolating loader cost from storage/decode cost.
"""

from __future__ import annotations

import time

import numpy as np

from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
from petastorm_tpu.unischema import Unischema, UnischemaField

BenchmarkSchema = Unischema('BenchmarkSchema', [
    UnischemaField('int_col', np.int64, (), ScalarCodec(), False),
    UnischemaField('float_col', np.float64, (), ScalarCodec(), False),
    UnischemaField('vector', np.float32, (64,), NdarrayCodec(), False),
])


class DummyBatchReader(object):
    """Batched reader yielding a constant pre-built column batch."""

    def __init__(self, chunk_size: int = 1000, num_chunks: int = 100):
        self.schema = BenchmarkSchema
        self.ngram = None
        self.batched_output = True
        self.last_row_consumed = False
        self._num_chunks = num_chunks
        self._produced = 0
        self._chunk = self.schema.make_batch_namedtuple(
            int_col=np.arange(chunk_size, dtype=np.int64),
            float_col=np.random.default_rng(0).random(chunk_size),
            vector=np.zeros((chunk_size, 64), np.float32))

    def __iter__(self):
        return self

    def __next__(self):
        if self._produced >= self._num_chunks:
            self.last_row_consumed = True
            raise StopIteration
        self._produced += 1
        return self._chunk

    def reset(self):
        self._produced = 0
        self.last_row_consumed = False

    def stop(self):
        pass

    def join(self):
        pass


def _measure(make_loader, label: str, rows_total: int) -> float:
    start = time.perf_counter()
    count = 0
    for batch in make_loader():
        first = batch[next(iter(batch))]
        count += len(first)
    elapsed = time.perf_counter() - start
    rate = count / elapsed
    print('{:>24}: {:>12.0f} samples/sec ({} rows)'.format(label, rate, count))
    return rate


def main() -> int:
    from petastorm_tpu.jax_utils import JaxDataLoader

    for batch_size in (10, 100, 1000, 10000):
        reader = DummyBatchReader()
        rows = 1000 * 100
        _measure(lambda: JaxDataLoader(reader, batch_size=batch_size),
                 'JaxDataLoader bs={}'.format(batch_size), rows)
        try:
            from petastorm_tpu.pytorch import BatchedDataLoader
            reader2 = DummyBatchReader()
            _measure(lambda: BatchedDataLoader(reader2, batch_size=batch_size),
                     'BatchedDataLoader bs={}'.format(batch_size), rows)
        except ImportError:
            pass
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
