"""Tracing overhead benchmark: items/s with the span tracer on vs off.

The tracer's contract is "low-overhead, off-by-default": per-item spans must
be cheap enough to leave enabled on a production pipeline while diagnosing
it. This bench quantifies that on the row reader path (the chattiest
consumer: one published item per row group, spans for ventilate / parquet
read / decode / process / queue wait per item):

1. **Baseline passes** — ``make_reader`` over a small codec store,
   ``trace=False`` (forced off, immune to ``PETASTORM_TPU_TRACE``), full
   consumption, items/s recorded.
2. **Traced passes** — identical reader with ``trace=True``; every stage
   records spans and the consumer-side tracer buffers them.
3. Modes alternate (off, on, off, on, ...) so drift in host load hits both
   equally; the headline is the **median** of each mode and

   ``overhead_pct = 100 * (baseline_median - traced_median) / baseline_median``.

The traced run also exports a chrome trace to a temp file and validates it
(JSON loads, complete events carry ph/ts/dur/pid/tid) so the artifact
records that the exported timeline is well-formed, not just cheap.

The full run asserts **overhead < 5%** (the BENCH_r08 acceptance bar);
``--quick`` shrinks the store and asserts a looser bar as the tier-1 smoke
(sub-second passes are noise-dominated; the quick gate exists to catch a
rewrite that makes tracing accidentally hot, not to re-prove the 5% claim).

CLI (output is always JSON)::

    python -m petastorm_tpu.benchmark.trace_overhead [--quick] [--no-check]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import statistics
import tempfile
import time

from petastorm_tpu.benchmark.readahead import generate_readahead_dataset


def _run_pass(url: str, trace, epochs: int, workers: int) -> dict:
    """One full consumption pass on the row reader; returns items/s (rows)
    and, when traced, the span count + export validity."""
    from petastorm_tpu.reader import make_reader

    with make_reader(url, reader_pool_type='thread', workers_count=workers,
                     shuffle_row_groups=False, num_epochs=epochs,
                     trace=trace) as reader:
        start = time.perf_counter()
        rows = sum(1 for _ in reader)
        wall = time.perf_counter() - start
        out = {
            'rows': rows,
            'wall_s': round(wall, 4),
            'items_per_s': round(rows / wall, 1) if wall else 0.0,
        }
        if reader.tracer is not None:
            out['spans'] = len(reader.tracer)
            out['export'] = _validate_export(reader.tracer)
    return out


def _validate_export(tracer) -> dict:
    """Export the chrome trace to a temp file and check the schema the
    Perfetto loader depends on (also asserted by ``tests/test_tracing.py``)."""
    fd, path = tempfile.mkstemp(suffix='.json',
                                prefix='petastorm_tpu_trace_')
    os.close(fd)
    try:
        written = tracer.export_chrome_trace(path)
        with open(path) as f:
            blob = json.load(f)
        events = blob['traceEvents']
        span_events = [e for e in events if e.get('ph') == 'X']
        required = all(
            isinstance(e.get('name'), str) and 'ts' in e and 'dur' in e
            and 'pid' in e and 'tid' in e for e in span_events)
        timestamps = [e['ts'] for e in span_events]
        return {
            'valid': bool(required and written == len(span_events)
                          and timestamps == sorted(timestamps)),
            'span_events': len(span_events),
        }
    finally:
        os.unlink(path)


def run_trace_overhead_bench(quick: bool = False, check: bool = True,
                             dataset_path: str = None) -> dict:
    """Alternating traced/untraced passes; returns one JSON-able dict.
    ``quick`` shrinks the store for the tier-1 smoke (looser overhead bar);
    ``check=False`` reports without asserting."""
    rows = 384 if quick else 4096
    rows_per_group = 8
    epochs = 2 if quick else 3
    workers = 2
    passes = 3 if quick else 7
    max_overhead_pct = 25.0 if quick else 5.0

    tmpdir = None
    if dataset_path is None:
        tmpdir = tempfile.mkdtemp(prefix='petastorm_tpu_trace_bench_')
        dataset_path = tmpdir
    url = 'file://' + dataset_path
    try:
        generate_readahead_dataset(url, rows=rows,
                                   rows_per_group=rows_per_group)
        # one discarded priming pass: first touch streams from cold page
        # cache and compiles codec paths — neither mode should pay it.
        # Baseline passes force trace=False (not None): None defers to
        # PETASTORM_TPU_TRACE, and an inherited env var would silently turn
        # the "off" arm into traced-vs-traced.
        _run_pass(url, False, 1, workers)

        # Quick mode is a sub-second CI smoke: take the best of two attempts
        # so transient host load cannot flip the gate (the readahead quick
        # bench uses the same discipline).
        baseline = traced = None
        overhead_pct = 0.0
        for _attempt in range(2 if quick else 1):
            baseline, traced = [], []
            for i in range(passes):
                # alternate the within-pair order: host drift (thermal,
                # page-cache, background load) is monotone over seconds, so a
                # fixed off-then-on order would bill the drift to tracing
                if i % 2 == 0:
                    baseline.append(_run_pass(url, False, epochs, workers))
                    traced.append(_run_pass(url, True, epochs, workers))
                else:
                    traced.append(_run_pass(url, True, epochs, workers))
                    baseline.append(_run_pass(url, False, epochs, workers))
            base_med = statistics.median(r['items_per_s'] for r in baseline)
            traced_med = statistics.median(r['items_per_s'] for r in traced)
            overhead_pct = (100.0 * (base_med - traced_med) / base_med
                            if base_med else 0.0)
            if overhead_pct < max_overhead_pct:
                break

        last_traced = traced[-1]
        result = {
            'quick': quick,
            'rows': rows,
            'epochs': epochs,
            'workers': workers,
            'passes_per_mode': passes,
            'baseline_items_per_s': base_med,
            'traced_items_per_s': traced_med,
            'overhead_pct': round(overhead_pct, 2),
            'spans_recorded': last_traced['spans'],
            'export_valid': last_traced['export']['valid'],
            'export_span_events': last_traced['export']['span_events'],
            'baseline_runs': [r['items_per_s'] for r in baseline],
            'traced_runs': [r['items_per_s'] for r in traced],
        }
        if check:
            assert result['export_valid'], (
                'chrome trace export failed schema validation')
            assert result['spans_recorded'] > 0, 'traced run recorded no spans'
            assert overhead_pct < max_overhead_pct, (
                'tracing must cost < {}% items/s on this protocol; measured '
                '{:.2f}% (baseline {} vs traced {} items/s)'.format(
                    max_overhead_pct, overhead_pct, base_med, traced_med))
        return result
    finally:
        if tmpdir is not None:
            shutil.rmtree(tmpdir, ignore_errors=True)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description='span-tracer overhead benchmark (items/s on vs off)')
    parser.add_argument('--quick', action='store_true',
                        help='small store/fewer passes for the CI smoke path')
    parser.add_argument('--no-check', action='store_true',
                        help='report only; skip the overhead assertion')
    args = parser.parse_args(argv)
    result = run_trace_overhead_bench(quick=args.quick,
                                      check=not args.no_check)
    print(json.dumps(result, indent=2))
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
