"""Transport microbenchmark: pickle-blob vs zero-copy worker→loader payloads.

Two measurements on realistic decoded-image payloads (a dict of column
arrays, the columnar worker's publish unit):

1. **In-process serializer round-trip** — ``serialize_multipart`` +
   ``deserialize_multipart`` back-to-back, isolating pure transport cost
   (MB/s and full-payload memcpys) from pool/process overhead.
2. **3-worker ProcessPool stream** — the same payloads shipped through a real
   ZMQ process pool, counting copies on both sides of the boundary via the
   serializer copy counters (worker-side counts ride back in the
   accounting control messages).

The zero-copy path must move the stream with **strictly fewer payload
copies** than pickle, and (for payloads ≥ 1 MB) at ≥ 1.5× the in-process
MB/s — both asserted by :func:`run_transport_bench` unless ``check=False``.

CLI::

    python -m petastorm_tpu.benchmark.transport [--quick] [--payload-mb N]
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

from petastorm_tpu.workers.serializers import PickleSerializer, ZeroCopySerializer
from petastorm_tpu.workers.worker_base import WorkerBase

_MB = 1024.0 * 1024.0


def make_image_payload(rows: int, height: int, width: int) -> dict:
    """A decoded-image column batch: ``(rows, h, w, 3)`` uint8 plus labels —
    deterministic content (benchmarks must not vary with RNG state)."""
    n = rows * height * width * 3
    image = (np.arange(n, dtype=np.uint32) % 251).astype(np.uint8)
    return {
        'image': image.reshape(rows, height, width, 3),
        'label': np.arange(rows, dtype=np.int64),
    }


def payload_nbytes(payload: dict) -> int:
    return sum(v.nbytes for v in payload.values())


def serializer_roundtrip_bench(serializer, payload: dict, rounds: int) -> dict:
    """Serialize+deserialize ``payload`` ``rounds`` times; report MB/s and the
    serializer's copy counter."""
    nbytes = payload_nbytes(payload)
    # warmup (allocator, pickle dispatch tables)
    frames = serializer.serialize_multipart(payload)
    serializer.deserialize_multipart(frames)
    copies_before = serializer.copies
    start = time.perf_counter()
    for _ in range(rounds):
        frames = serializer.serialize_multipart(payload)
        result = serializer.deserialize_multipart(frames)
    elapsed = time.perf_counter() - start
    np.testing.assert_array_equal(result['label'], payload['label'])
    return {
        'rounds': rounds,
        'payload_mb': round(nbytes / _MB, 3),
        'mb_per_s': round(rounds * nbytes / _MB / elapsed, 1) if elapsed else float('inf'),
        'copies': serializer.copies - copies_before,
        'copies_per_roundtrip': (serializer.copies - copies_before) / rounds,
    }


class ImageStreamWorker(WorkerBase):
    """Publishes one decoded-image column batch per ventilated item (module
    level so spawned worker interpreters can import it)."""

    def __init__(self, worker_id, publish_func, args):
        super().__init__(worker_id, publish_func, args)
        self._payload = make_image_payload(args['rows'], args['height'],
                                           args['width'])

    def process(self, item_index):
        self.publish_func(self._payload)


def pool_stream_bench(serializer, workers: int, items: int,
                      rows: int, height: int, width: int) -> dict:
    """Ship ``items`` decoded-image batches through a real ``ProcessPool`` and
    report wall time, MB/s, and total payload copies (worker + consumer)."""
    from petastorm_tpu.workers import EmptyResultError
    from petastorm_tpu.workers.process_pool import ProcessPool
    from petastorm_tpu.workers.ventilator import ConcurrentVentilator

    # Resolve the worker class through its canonical module: under
    # ``python -m`` this file is ``__main__`` and the class would be
    # serialized by value, detached from its module globals.
    from petastorm_tpu.benchmark import transport as canonical
    worker_class = canonical.ImageStreamWorker

    pool = ProcessPool(workers, serializer=serializer)
    vent = ConcurrentVentilator(pool.ventilate,
                                [{'item_index': i} for i in range(items)],
                                iterations=1)
    pool.start(worker_class,
               worker_args={'rows': rows, 'height': height, 'width': width},
               ventilator=vent)
    received = 0
    start = time.perf_counter()
    try:
        while True:
            batch = pool.get_results(timeout=120)
            received += 1
            assert batch['image'].shape == (rows, height, width, 3)
    except EmptyResultError:
        pass
    elapsed = time.perf_counter() - start
    snapshot = pool.stats.snapshot()
    pool.stop()
    pool.join()
    # payload_copies covers both ends of the hop: worker-side copies arrive
    # via the accounting messages, consumer-side deserialize copies are
    # folded in by get_results
    total_copies = snapshot['payload_copies']
    return {
        'workers': workers,
        'items': received,
        'bytes_moved_mb': round(snapshot['bytes_moved'] / _MB, 1),
        'mb_per_s': round(snapshot['bytes_moved'] / _MB / elapsed, 1) if elapsed else 0.0,
        'payload_copies': total_copies,
        'copies_per_item': total_copies / received if received else None,
        'serialize_s': round(snapshot['serialize_s'], 4),
        'deserialize_s': round(snapshot['deserialize_s'], 4),
    }


def run_transport_bench(quick: bool = False, payload_mb: float = None,
                        check: bool = True) -> dict:
    """Full pickle-vs-zero-copy comparison; returns one JSON-able dict.

    ``quick`` shrinks rounds/items for the CI smoke path but keeps the
    payload ≥ 1 MB so the speedup assertion stays meaningful.
    """
    if payload_mb is None:
        payload_mb = 1.5 if quick else 8.0
    # rows of 128x128 RGB ≈ 48 KiB each
    rows = max(1, int(payload_mb * _MB / (128 * 128 * 3)))
    payload = make_image_payload(rows, 128, 128)
    rounds = 5 if quick else 30
    items = 6 if quick else 24

    inproc = {
        'pickle': serializer_roundtrip_bench(PickleSerializer(), payload, rounds),
        'zero_copy': serializer_roundtrip_bench(ZeroCopySerializer(), payload, rounds),
    }
    pool = {
        'pickle': pool_stream_bench(PickleSerializer(), 3, items, rows, 128, 128),
        'zero_copy': pool_stream_bench(ZeroCopySerializer(), 3, items, rows, 128, 128),
    }
    speedup = (inproc['zero_copy']['mb_per_s'] / inproc['pickle']['mb_per_s']
               if inproc['pickle']['mb_per_s'] else float('inf'))
    result = {
        'payload_mb': inproc['pickle']['payload_mb'],
        'inprocess_roundtrip': inproc,
        'pool_stream': pool,
        'speedup_inprocess': round(speedup, 2),
        'quick': quick,
    }
    if check:
        assert pool['zero_copy']['payload_copies'] < pool['pickle']['payload_copies'], (
            'zero-copy transport must make strictly fewer payload copies: '
            '{} vs {}'.format(pool['zero_copy']['payload_copies'],
                              pool['pickle']['payload_copies']))
        if result['payload_mb'] >= 1.0:
            assert speedup >= 1.5, (
                'zero-copy transport must be >=1.5x pickle MB/s on >=1MB '
                'payloads; measured {:.2f}x'.format(speedup))
    return result


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description='pickle vs zero-copy transport microbenchmark')
    parser.add_argument('--quick', action='store_true',
                        help='small rounds/items for the CI smoke path')
    parser.add_argument('--payload-mb', type=float, default=None)
    parser.add_argument('--no-check', action='store_true',
                        help='report only; skip the copy/speedup assertions')
    args = parser.parse_args(argv)
    result = run_transport_bench(quick=args.quick, payload_mb=args.payload_mb,
                                 check=not args.no_check)
    print(json.dumps(result, indent=2))
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
