"""Health-layer overhead benchmark: items/s with heartbeats + watchdog +
debug endpoint on vs fully off.

The live health layer's contract is "always-on cheap": worker heartbeats are
a few attribute assignments per stage, the watchdog is one low-frequency
evaluation thread, and the debug endpoint an idle accept loop — none of it
on the per-item hot path. Expected overhead: ~0. This bench quantifies that
on the row reader path with the same alternating-pass protocol as
``benchmark/trace_overhead.py``:

1. **Baseline passes** — ``make_reader`` with ``PETASTORM_TPU_HEALTH=0``
   (every beat call site compiled out, no watchdog, no endpoint), full
   consumption, items/s recorded.
2. **Health passes** — identical reader with heartbeats on (the default)
   PLUS the full live layer armed: ``stall_timeout=2`` (watchdog ticking at
   0.5 s) and ``debug_port=0`` (HTTP server bound and accepting).
3. Modes alternate (off, on, off, on, ...) with the within-pair order
   flipped each pair, so monotone host drift bills both modes equally; the
   headline is the **median** of each mode and

   ``overhead_pct = 100 * (baseline_median - health_median) / baseline_median``.

Each health pass also asserts the layer actually ran: heartbeat entities
were published for the ventilator and every worker, and the watchdog's
verdict on the completed pass is ``healthy`` — the artifact records that the
measured run exercised the real subsystem, not a disabled stub.

The full run asserts **overhead < 5%** (the measured figure in
``BENCH_r09.json`` is what the docs quote; the expectation is ~0);
``--quick`` shrinks the store and asserts a looser bar as the tier-1 smoke
(sub-second passes are noise-dominated; the quick gate catches a rewrite
that makes heartbeats accidentally hot, not the headline number).

CLI (output is always JSON)::

    python -m petastorm_tpu.benchmark.health_overhead [--quick] [--no-check]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import statistics
import tempfile
import time

from petastorm_tpu.benchmark.readahead import generate_readahead_dataset
from petastorm_tpu.health import HEALTH_ENV_VAR


def _run_pass(url: str, health: bool, epochs: int, workers: int) -> dict:
    """One full consumption pass on the row reader; returns items/s and,
    for health passes, the published entities + watchdog verdict."""
    from petastorm_tpu.reader import make_reader

    saved = os.environ.get(HEALTH_ENV_VAR)
    os.environ[HEALTH_ENV_VAR] = '1' if health else '0'
    kwargs = {}
    if health:
        # the whole live layer, armed: heartbeats + watchdog + endpoint
        kwargs = dict(stall_timeout=2, debug_port=0)
    try:
        with make_reader(url, reader_pool_type='thread',
                         workers_count=workers, shuffle_row_groups=False,
                         num_epochs=epochs, **kwargs) as reader:
            start = time.perf_counter()
            rows = sum(1 for _ in reader)
            wall = time.perf_counter() - start
            out = {
                'rows': rows,
                'wall_s': round(wall, 4),
                'items_per_s': round(rows / wall, 1) if wall else 0.0,
            }
            if health:
                heartbeats = reader.health.heartbeats()
                out['entities'] = sorted(heartbeats)
                out['verdict'] = reader.watchdog.evaluate()['state']
                out['debug_port'] = reader.debug_port
    finally:
        if saved is None:
            os.environ.pop(HEALTH_ENV_VAR, None)
        else:
            os.environ[HEALTH_ENV_VAR] = saved
    return out


def run_health_overhead_bench(quick: bool = False, check: bool = True,
                              dataset_path: str = None) -> dict:
    """Alternating health-on/health-off passes; returns one JSON-able dict.
    ``quick`` shrinks the store for the tier-1 smoke (looser overhead bar);
    ``check=False`` reports without asserting."""
    rows = 384 if quick else 4096
    rows_per_group = 8
    epochs = 2 if quick else 3
    workers = 2
    passes = 3 if quick else 7
    max_overhead_pct = 25.0 if quick else 5.0

    tmpdir = None
    if dataset_path is None:
        tmpdir = tempfile.mkdtemp(prefix='petastorm_tpu_health_bench_')
        dataset_path = tmpdir
    url = 'file://' + dataset_path
    try:
        generate_readahead_dataset(url, rows=rows,
                                   rows_per_group=rows_per_group)
        # one discarded priming pass: cold page cache / codec compilation
        # must not bill either mode
        _run_pass(url, False, 1, workers)

        # best-of-two attempts in quick mode: transient host load must not
        # flip the sub-second CI smoke (same discipline as trace_overhead)
        baseline = health = None
        overhead_pct = 0.0
        for _attempt in range(2 if quick else 1):
            baseline, health = [], []
            for i in range(passes):
                # alternate the within-pair order: host drift is monotone
                # over seconds, and a fixed order would bill it to one mode
                if i % 2 == 0:
                    baseline.append(_run_pass(url, False, epochs, workers))
                    health.append(_run_pass(url, True, epochs, workers))
                else:
                    health.append(_run_pass(url, True, epochs, workers))
                    baseline.append(_run_pass(url, False, epochs, workers))
            base_med = statistics.median(r['items_per_s'] for r in baseline)
            health_med = statistics.median(r['items_per_s'] for r in health)
            overhead_pct = (100.0 * (base_med - health_med) / base_med
                            if base_med else 0.0)
            if overhead_pct < max_overhead_pct:
                break

        last_health = health[-1]
        result = {
            'quick': quick,
            'rows': rows,
            'epochs': epochs,
            'workers': workers,
            'passes_per_mode': passes,
            'baseline_items_per_s': base_med,
            'health_items_per_s': health_med,
            'overhead_pct': round(overhead_pct, 2),
            'entities': last_health['entities'],
            'verdict': last_health['verdict'],
            'baseline_runs': [r['items_per_s'] for r in baseline],
            'health_runs': [r['items_per_s'] for r in health],
        }
        if check:
            assert result['verdict'] == 'healthy', (
                'a clean full-consumption pass must classify healthy, got '
                '{!r}'.format(result['verdict']))
            assert 'ventilator' in result['entities'] and any(
                e.startswith('worker-') for e in result['entities']), (
                'health passes must actually publish heartbeats, got '
                '{}'.format(result['entities']))
            assert overhead_pct < max_overhead_pct, (
                'the health layer must cost < {}% items/s on this protocol; '
                'measured {:.2f}% (baseline {} vs health {} items/s)'.format(
                    max_overhead_pct, overhead_pct, base_med, health_med))
        return result
    finally:
        if tmpdir is not None:
            shutil.rmtree(tmpdir, ignore_errors=True)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description='health-layer overhead benchmark (items/s on vs off)')
    parser.add_argument('--quick', action='store_true',
                        help='small store/fewer passes for the CI smoke path')
    parser.add_argument('--no-check', action='store_true',
                        help='report only; skip the overhead assertion')
    args = parser.parse_args(argv)
    result = run_health_overhead_bench(quick=args.quick,
                                       check=not args.no_check)
    print(json.dumps(result, indent=2))
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
