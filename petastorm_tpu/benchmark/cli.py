"""``petastorm-tpu-throughput`` CLI (reference ``petastorm/benchmark/cli.py``).

Usage::

    python -m petastorm_tpu.benchmark.cli file:///tmp/hello_world_dataset \
        -w 3 -p thread -m 200 -n 1000
"""

from __future__ import annotations

import argparse
import json
import logging

from petastorm_tpu.benchmark.throughput import reader_throughput


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        description='Measure petastorm_tpu reader throughput')
    parser.add_argument('dataset_url', help='e.g. file:///tmp/hello_world_dataset')
    parser.add_argument('-f', '--field-regex', nargs='+', default=None,
                        help='Read only fields matching these regexes')
    parser.add_argument('-w', '--workers-count', type=int, default=3)
    parser.add_argument('-p', '--pool-type', default='thread',
                        choices=['thread', 'process', 'dummy'])
    parser.add_argument('-m', '--warmup-cycles', type=int, default=200)
    parser.add_argument('-n', '--measure-cycles', type=int, default=1000)
    parser.add_argument('-q', '--shuffling-queue-size', type=int, default=500)
    parser.add_argument('--batch-reader', action='store_true',
                        help='Use make_batch_reader (vectorized path)')
    parser.add_argument('--read-method', default='python',
                        choices=['python', 'jax'])
    parser.add_argument('--io-readahead', default='0',
                        help="per-worker row-group read prefetch depth: an "
                             "int or 'auto' (overlap storage I/O with "
                             "decode; see docs/readahead.md)")
    parser.add_argument('--jax-batch-size', type=int, default=16)
    parser.add_argument('--prefetch-depth', type=int, default=None,
                        help='device-staging prefetch depth for the jax read '
                             'method (batches materialized ahead of the '
                             'consumer; default: '
                             'PETASTORM_TPU_PREFETCH_DEPTH or 2 — see '
                             'docs/readahead.md; owned by this flag, the '
                             'autotuner does not actuate it)')
    parser.add_argument('-r', '--runs', type=int, default=1,
                        help='Repeat the measurement N times and report '
                             'best/median/min + spread (noisy shared hosts '
                             'need dispersion, not one sample)')
    parser.add_argument('-d', '--diagnostics', action='store_true',
                        help='Print the per-stage pipeline telemetry '
                             '(Reader.diagnostics) of the median run')
    parser.add_argument('--trace', metavar='PATH', default=None,
                        help='Record per-item pipeline spans and export a '
                             'Chrome trace-event JSON (open in Perfetto / '
                             'chrome://tracing) covering the measured window '
                             'to PATH; with -r, each run overwrites it, so '
                             'the last run wins (see docs/tracing.md)')
    parser.add_argument('--metrics-interval', type=float, default=0,
                        help='Snapshot reader stats every N seconds into '
                             '--metrics-out while the benchmark runs')
    parser.add_argument('--metrics-out', metavar='PATH', default=None,
                        help='Metrics emitter output: JSON-lines, or '
                             'Prometheus text exposition for .prom paths')
    parser.add_argument('--debug-port', type=int, default=None,
                        help='Serve the live health endpoints on '
                             '127.0.0.1:PORT while the benchmark runs '
                             '(/healthz /metrics /diagnostics /stacks; 0 = '
                             'ephemeral; see docs/health.md)')
    parser.add_argument('--stall-timeout', type=float, default=0,
                        help='Arm the pipeline watchdog: classify the reader '
                             'stalled (and write a flight-recorder JSON) '
                             'after N seconds without entity progress')
    parser.add_argument('--audit', action='store_true',
                        help='Print the lineage coverage audit of the median '
                             'run: per-epoch exactly-once verdicts, dup/drop '
                             'row groups, shuffle quality, quarantine totals. '
                             'The benchmark stops mid-stream after its '
                             'measured samples, so the in-flight tail epoch '
                             'honestly reads as dropped; judge the fully '
                             'consumed epochs (see docs/lineage.md)')
    parser.add_argument('--profile', action='store_true',
                        help='Roofline-profile the median run: calibrate '
                             'per-stage ceilings against this dataset '
                             '(storage, codec decode, transport, device '
                             'staging; cached per host+dataset), report '
                             'measured samples/sec as a %% of the binding '
                             "stage's ceiling, and print the what-if "
                             "advisor's ranked knob recommendations (see "
                             'docs/profiling.md)')
    parser.add_argument('--cache-type', default='null',
                        choices=['null', 'local-disk', 'shared'],
                        help="row-group cache: 'null' (none), 'local-disk' "
                             "(per-reader pickle-on-disk), 'shared' (host-"
                             'wide tiered decoded cache that concurrent '
                             'readers attach to; see docs/cache.md)')
    parser.add_argument('--cache-location', metavar='DIR', default=None,
                        help='cache directory (required for local-disk and '
                             'shared; for shared it is the host-wide root '
                             'every attaching reader must agree on)')
    parser.add_argument('--cache-size-limit', type=int, default=None,
                        help='cache byte budget (required for local-disk '
                             'and shared; shared bounds the disk tier, with '
                             'the shared-memory tier capped at min(this, '
                             '1 GiB))')
    parser.add_argument('--slo-p99-ms', type=float, default=None,
                        help='arm the SLO monitor with a p99 end-to-end '
                             'batch-latency target (milliseconds over the '
                             'rolling window); the verdict — per-target '
                             'checks + error-budget burn — prints after the '
                             'run (see docs/latency.md)')
    parser.add_argument('--slo-min-samples-per-s', type=float, default=None,
                        help='add a minimum samples/s target to the SLO '
                             'monitor (window rate from ReaderStats)')
    parser.add_argument('--autotune', action='store_true',
                        help='run the model-predictive pipeline controller '
                             'on the benchmarked reader: live worker/'
                             'readahead/window/queue tuning with hysteresis '
                             'and revert-on-regression; the controller '
                             'report (every move, predicted vs measured) '
                             'prints after the run (see docs/autotune.md)')
    parser.add_argument('--on-decode-error', default='raise',
                        choices=['raise', 'skip', 'quarantine'],
                        help="bad-sample policy: 'raise' propagates decode/"
                             "transform errors, 'skip' drops failing rows "
                             "counting them, 'quarantine' drops AND records "
                             'provenance-tagged quarantine records')
    parser.add_argument('--remote-read', default=None,
                        choices=['serial', 'prebuffer', 'ranged', 'auto'],
                        help='row-group fetch strategy against the object '
                             "store: 'serial' opens and reads sequentially, "
                             "'prebuffer' uses the Arrow pre-buffered reads, "
                             "'ranged' plans coalesced parallel range reads "
                             'from the Parquet footer (see '
                             "docs/object_store.md); 'auto'/omitted picks "
                             'per-protocol')
    parser.add_argument('--storage-options', metavar='JSON', default=None,
                        help='JSON object of fsspec storage options handed '
                             'to the filesystem resolver, e.g. '
                             '\'{"anon": true}\' for public s3:// buckets')
    parser.add_argument('-v', action='store_true', help='INFO logging')
    return parser


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.v:
        logging.basicConfig(level=logging.INFO)
    io_readahead = (args.io_readahead if args.io_readahead == 'auto'
                    else int(args.io_readahead))
    if args.metrics_interval and not args.metrics_out:
        raise SystemExit('--metrics-interval needs --metrics-out PATH')
    if args.cache_type != 'null' and not (args.cache_location
                                          and args.cache_size_limit):
        raise SystemExit('--cache-type {} needs --cache-location and '
                         '--cache-size-limit'.format(args.cache_type))
    storage_options = None
    if args.storage_options:
        storage_options = json.loads(args.storage_options)
        if not isinstance(storage_options, dict):
            raise SystemExit('--storage-options must be a JSON object, got '
                             '{!r}'.format(args.storage_options))
    slo = {}
    if args.slo_p99_ms is not None:
        slo['p99_e2e_ms'] = args.slo_p99_ms
    if args.slo_min_samples_per_s is not None:
        slo['min_samples_per_s'] = args.slo_min_samples_per_s
    results = [reader_throughput(
        args.dataset_url, field_regex=args.field_regex,
        warmup_cycles=args.warmup_cycles, measure_cycles=args.measure_cycles,
        pool_type=args.pool_type, workers_count=args.workers_count,
        shuffling_queue_size=args.shuffling_queue_size,
        read_method=args.read_method, batch_reader=args.batch_reader,
        jax_batch_size=args.jax_batch_size,
        prefetch_depth=args.prefetch_depth,
        io_readahead=io_readahead, trace_path=args.trace,
        metrics_interval=args.metrics_interval,
        metrics_out=args.metrics_out, debug_port=args.debug_port,
        stall_timeout=args.stall_timeout, audit=args.audit,
        profile=args.profile, slo=slo or None, autotune=args.autotune,
        on_decode_error=args.on_decode_error, cache_type=args.cache_type,
        cache_location=args.cache_location,
        cache_size_limit=args.cache_size_limit,
        remote_read=args.remote_read, storage_options=storage_options)
        for _ in range(max(1, args.runs))]
    # headline = median run: the honest central figure (best would overstate)
    by_rate = sorted(results, key=lambda r: r.samples_per_sec)
    result = by_rate[len(by_rate) // 2]
    print('Average sample read rate: {:.2f} samples/sec; RAM {:.2f} MB (rss); '
          'CPU {:.2f}%'.format(result.samples_per_sec, result.rss_mb,
                               result.cpu_percent))
    if len(results) > 1:
        rates = [r.samples_per_sec for r in by_rate]
        median = result.samples_per_sec
        print('Dispersion over {} runs: min {:.2f} / median {:.2f} / best '
              '{:.2f} samples/sec (spread {:.1f}%)'.format(
                  len(rates), rates[0], median, rates[-1],
                  100.0 * (rates[-1] - rates[0]) / median if median else 0.0))
    if args.diagnostics and result.diagnostics is not None:
        print('Pipeline telemetry (median run): {}'.format(
            json.dumps({k: (round(v, 6) if isinstance(v, float) else v)
                        for k, v in sorted(result.diagnostics.items())
                        # raw histogram states belong to /metrics scrapes;
                        # the derived *_p50_s/*_p99_s keys print here
                        if not k.startswith('_')})))
        if result.diagnosis is not None:
            # the same classification the watchdog / GET /healthz makes
            # (infeed_diagnosis over the snapshot + live heartbeats)
            print('Infeed diagnosis (median run): {}'.format(
                json.dumps(result.diagnosis, sort_keys=True)))
    if args.profile and result.profile is not None:
        from petastorm_tpu.profiler import explain
        print('Roofline (median run): {}'.format(explain(result.profile)))
        print('Roofline profile: {}'.format(
            json.dumps(result.profile, sort_keys=True, default=str)))
    if slo and result.slo is not None:
        print('SLO verdict (median run): {}'.format(
            json.dumps(result.slo, sort_keys=True, default=str)))
    if args.autotune and result.autotune is not None:
        report = dict(result.autotune)
        report['actions'] = report.get('actions', [])[-10:]
        print('Autotune report (median run): {}'.format(
            json.dumps(report, sort_keys=True, default=str)))
    if args.audit and result.audit is not None:
        print('Coverage audit (median run): {}'.format(
            json.dumps(result.audit, sort_keys=True, default=str)))
    if args.trace:
        print('Chrome trace written to {} (open in https://ui.perfetto.dev)'
              .format(args.trace))
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
