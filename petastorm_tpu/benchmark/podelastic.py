"""Elastic pod membership benchmark (BENCH_r20): the clean-path cost of
the lease plane, and host-death recovery vs a simulated full restart.

Every delivered batch pays one REAL ranged row-group read through the
recorded object-store trace (the BENCH_r18/r19 trace-replay discipline):
the lease plane's per-batch cost — heartbeat, delivery-claim fence,
cursor checkpoint — is measured against realistic infeed fetch
latencies, not against a bare page-cache ``gather``.

Phases (see ``docs/robustness.md``):

1. **Clean-path overhead.** Alternating single-host epoch passes over the
   identical lease grid under fresh same-seed traces: baseline delivers
   every batch straight off the
   :class:`~petastorm_tpu.podelastic.LeasePlan` grid (no membership, no
   ledger), elastic-on runs the full plane. Median per-pair delta must
   stay under the 5% noise floor — the plane is default-off, but when on
   it must not tax the un-failed path.
2. **Rebalance latency.** K hosts register, one leaves; a survivor's
   ``rebalance()`` (observe the death, rendezvous-reassign, read the dead
   host's cursors + delivery claims) is timed standalone over several
   trials — the wall-clock gap between "a host is observably dead" and
   "its rows are flowing again".
3. **Recovery vs full restart.** A K-host epoch under the deterministic
   ``host-death`` chaos scenario: the epoch completes on survivors and
   the pod certificate must certify exactly-once. The elastic wall time
   is compared against the simulated alternative — tear the whole pod
   down at the death point and re-run the epoch from scratch
   (``restart_total_s = time_to_death + clean_epoch_s``), the recovery
   story a static-shard pod is stuck with.

CLI::

    python -m petastorm_tpu.benchmark.podelastic [--quick] [--no-check]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import statistics
import tempfile
import time

_OVERHEAD_NOISE_FLOOR_PCT = 5.0
_CHAOS_SPEC = 'host-death:42'
_TRACE_NAME = 's3-us-east-1'


def _make_dataset(tmpdir: str, rows: int):
    import numpy as np

    from petastorm_tpu.codecs import ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import materialize_dataset
    from petastorm_tpu.indexed import IndexedDatasetReader
    from petastorm_tpu.unischema import Unischema, UnischemaField

    schema = Unischema('ElasticBench', [
        UnischemaField('idx', np.int64, (), ScalarCodec(), False),
    ])
    path = os.path.join(tmpdir, 'ds')
    url = 'file://' + path
    with materialize_dataset(url, schema, row_group_size_mb=0.001) as w:
        w.write_rows([{'idx': np.int64(i)} for i in range(rows)])
    return IndexedDatasetReader(url)


def _traced_reader(seed: int):
    """A fresh ranged reader over the recorded object-store trace — a
    fresh same-seed injector per pass replays the identical latency
    sequence, so alternating passes compare the coordination plane, not
    store noise."""
    import fsspec

    from petastorm_tpu.faultfs import FaultInjector, FaultyFilesystem
    from petastorm_tpu.objectstore import ParallelRangeReader

    return ParallelRangeReader(FaultyFilesystem(
        fsspec.filesystem('file'),
        FaultInjector('trace-replay', seed=seed, trace=_TRACE_NAME)))


def _batch_fetch(dataset, reader, rows):
    """The per-batch infeed fetch: ranged reads of the batch's two leading
    distinct row groups through the traced store. (A production infeed
    reads EVERY group the shuffled batch touches — ~7 here — so this is a
    conservative per-batch cost and the measured plane overhead is an
    upper bound.)"""
    import numpy as np
    piece_ids = np.unique(np.searchsorted(
        dataset.row_offsets, rows, side='right') - 1)[:2]
    for piece_id in piece_ids:
        piece = dataset.pieces[int(piece_id)]
        reader.read_row_group(piece.path, piece.row_group)


def _clean_overhead(dataset, tmpdir: str, batch_size: int, pairs: int,
                    seed: int):
    """Alternating single-host passes over the identical lease grid under
    the trace: plain grid delivery vs the full elastic plane
    (median-of-pairs, the overhead-bench protocol)."""
    from petastorm_tpu.podelastic import ElasticPodSim, LeasePlan

    plan = LeasePlan(dataset.row_offsets, batch_size,
                     min(len(dataset.pieces), 2), seed=seed)
    total_rows = plan.total_batches() * batch_size

    def baseline_pass() -> float:
        reader = _traced_reader(seed)
        start = time.perf_counter()
        for lease in range(plan.num_leases):
            for batch in range(plan.batches_per_lease(lease)):
                rows = plan.batch_rows(lease, 0, batch)
                dataset.gather(rows)
                _batch_fetch(dataset, reader, rows)
        wall = time.perf_counter() - start
        return total_rows / wall if wall else 0.0

    def elastic_pass(tag: str) -> float:
        reader = _traced_reader(seed)
        coord = os.path.join(tmpdir, 'overhead_{}'.format(tag))
        sim = ElasticPodSim(dataset, coord, k_hosts=1,
                            batch_size=batch_size,
                            num_leases=plan.num_leases, seed=seed)
        delivered = [0]

        def on_batch(cols, lease, batch):
            delivered[0] += len(cols['idx'])
            # the bench dataset's idx column IS the global row index
            _batch_fetch(dataset, reader, cols['idx'])

        start = time.perf_counter()
        sim.run_epoch(0, on_batch=on_batch)
        wall = time.perf_counter() - start
        sim.close()
        return delivered[0] / wall if wall else 0.0

    # warmup (discarded): page cache, lazy imports, footer first-touch
    baseline_pass()
    elastic_pass('warmup')
    deltas_pct, off_rates, on_rates = [], [], []
    for i in range(pairs):
        off = baseline_pass()
        on = elastic_pass('p{}'.format(i))
        off_rates.append(off)
        on_rates.append(on)
        deltas_pct.append((off - on) / off * 100.0 if off else 0.0)
    return {
        'pairs': pairs,
        'baseline_samples_per_s': round(statistics.median(off_rates), 1),
        'elastic_on_samples_per_s': round(statistics.median(on_rates), 1),
        'overhead_pct': round(statistics.median(deltas_pct), 2),
        'per_pair_deltas_pct': [round(d, 2) for d in deltas_pct],
    }


def _rebalance_latency(dataset, tmpdir: str, batch_size: int, k_hosts: int,
                       trials: int, seed: int):
    """Time a survivor's full takeover step — observe the death,
    rendezvous-reassign, read the dead host's cursors + delivery claims —
    standalone, over fresh pods."""
    from petastorm_tpu.podelastic import (ElasticHost, LeaseLedger,
                                          LeasePlan, PodMembership)

    plan = LeasePlan(dataset.row_offsets, batch_size,
                     min(len(dataset.pieces), 2 * k_hosts), seed=seed)
    samples = []
    for trial in range(trials):
        coord = os.path.join(tmpdir, 'rebalance_{}'.format(trial))
        members = [PodMembership(coord, host_id='host-{}'.format(i))
                   for i in range(k_hosts)]
        ledger = LeaseLedger(coord)
        hosts = [ElasticHost(dataset, plan, members[i], ledger,
                             host_index=i) for i in range(k_hosts)]
        for host in hosts:
            host.rebalance(0)
        # every host makes some progress, then the last one dies
        for _ in range(3):
            for host in hosts:
                host.step(0)
        members[-1].leave()
        survivor = hosts[0]
        start = time.perf_counter()
        survivor.rebalance(0)
        samples.append(time.perf_counter() - start)
        for member in members[:-1]:
            member.leave()
    return {
        'trials': trials,
        'rebalance_latency_s': round(statistics.median(samples), 6),
        'rebalance_latency_max_s': round(max(samples), 6),
    }


def _recovery_leg(dataset, tmpdir: str, batch_size: int, k_hosts: int,
                  seed: int):
    """A K-host epoch under deterministic host-death chaos (every batch
    paying its traced infeed fetch), timed against the simulated
    full-restart alternative."""
    from petastorm_tpu.faultfs import CHAOS_ENV_VAR, reset_chaos_cache
    from petastorm_tpu.podelastic import ElasticPodSim

    def timed_epoch(tag: str, chaos: bool):
        prior = os.environ.get(CHAOS_ENV_VAR)
        if chaos:
            os.environ[CHAOS_ENV_VAR] = _CHAOS_SPEC
        else:
            os.environ.pop(CHAOS_ENV_VAR, None)
        reset_chaos_cache()
        try:
            reader = _traced_reader(seed)
            coord = os.path.join(tmpdir, 'recovery_{}'.format(tag))
            sim = ElasticPodSim(dataset, coord, k_hosts=k_hosts,
                                batch_size=batch_size, seed=seed)
            rows = [0]
            death_elapsed = [None]
            start = time.perf_counter()

            def on_batch(cols, lease, batch):
                rows[0] += len(cols['idx'])
                _batch_fetch(dataset, reader, cols['idx'])
                if sim.deaths and death_elapsed[0] is None:
                    death_elapsed[0] = time.perf_counter() - start

            report = sim.run_epoch(0, on_batch=on_batch)
            wall = time.perf_counter() - start
            certificate = sim.certificate(0)
            sim.close()
            return wall, rows[0], death_elapsed[0], report, certificate
        finally:
            if prior is None:
                os.environ.pop(CHAOS_ENV_VAR, None)
            else:
                os.environ[CHAOS_ENV_VAR] = prior
            reset_chaos_cache()

    clean_s, clean_rows, _, _, _ = timed_epoch('clean', chaos=False)
    elastic_s, rows, death_elapsed, report, certificate = timed_epoch(
        'death', chaos=True)
    # a static-shard pod must throw away the partial epoch and re-run it
    # from scratch: time-to-death is sunk cost, then one full clean epoch
    time_to_death = death_elapsed if death_elapsed is not None else 0.0
    restart_s = time_to_death + clean_s
    return {
        'k_hosts': k_hosts,
        'deaths': report['deaths'],
        'rows_delivered': rows,
        'time_to_death_s': round(time_to_death, 4),
        'elastic_total_s': round(elastic_s, 4),
        'restart_total_s': round(restart_s, 4),
        'elastic_samples_per_s': round(rows / elastic_s, 1)
        if elastic_s else 0.0,
        'restart_samples_per_s': round(rows / restart_s, 1)
        if restart_s else 0.0,
        'speedup_x': round(restart_s / elastic_s, 2) if elastic_s else 0.0,
        'leases_rebalanced': report['counters']['leases_rebalanced'],
        'rows_resumed': report['counters']['rows_resumed'],
        'certificate_ok': certificate['ok'],
        'certificate_problems': certificate['problems'],
    }


def run_podelastic_bench(quick: bool = False, check: bool = True) -> dict:
    """The BENCH_r20 protocol; ``quick`` shrinks the dataset for the CI
    smoke (same certificates, same overhead gate at a looser floor)."""
    rows = 240 if quick else 720
    batch_size = 8
    pairs = 2 if quick else 3
    trials = 3 if quick else 5
    k_hosts = 3
    seed = 20

    tmpdir = tempfile.mkdtemp(prefix='petastorm_tpu_podelastic_bench_')
    try:
        dataset = _make_dataset(tmpdir, rows)
        try:
            overhead = _clean_overhead(dataset, tmpdir, batch_size,
                                       pairs=pairs, seed=seed)
            rebalance = _rebalance_latency(dataset, tmpdir, batch_size,
                                           k_hosts, trials=trials,
                                           seed=seed)
            recovery = _recovery_leg(dataset, tmpdir, batch_size, k_hosts,
                                     seed=seed)
        finally:
            dataset.close()

        result = {
            'benchmark': 'podelastic',
            'quick': quick,
            'rows': rows,
            'k_hosts': k_hosts,
            'trace': {'name': _TRACE_NAME, 'seed': seed},
            'clean': overhead,
            'rebalance': rebalance,
            'recovery': recovery,
            'roofline': {
                'baseline_samples_per_s':
                    overhead['baseline_samples_per_s'],
                'roofline_pct': round(
                    100.0 * overhead['elastic_on_samples_per_s']
                    / overhead['baseline_samples_per_s'], 2)
                if overhead['baseline_samples_per_s'] else None,
                'note': 'elastic-on single-host epoch throughput as % of '
                        'the plain lease-grid delivery baseline on the '
                        'same traced store — the ceiling the lease plane '
                        'runs under when nothing fails',
            },
        }
        if check:
            max_overhead = 15.0 if quick else _OVERHEAD_NOISE_FLOOR_PCT
            assert overhead['overhead_pct'] <= max_overhead, (
                'the elastic lease plane costs {:.2f}% on the clean path '
                '— beyond the {}% floor'.format(
                    overhead['overhead_pct'], max_overhead))
            assert recovery['deaths'], (
                'the host-death scenario must have killed a host')
            assert recovery['certificate_ok'] is True, (
                'exactly-once must certify across the rebalance: '
                '{}'.format(recovery['certificate_problems']))
            assert recovery['leases_rebalanced'] >= 1, (
                'the dead host\'s leases must have moved to survivors')
            assert recovery['elastic_total_s'] < \
                recovery['restart_total_s'], (
                    'elastic recovery ({}s) must beat tear-down-and-'
                    'restart ({}s)'.format(recovery['elastic_total_s'],
                                           recovery['restart_total_s']))
        return result
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description='elastic pod membership: clean-path overhead, '
                    'rebalance latency, host-death recovery vs restart')
    parser.add_argument('--quick', action='store_true',
                        help='small dataset for the CI smoke path')
    parser.add_argument('--no-check', action='store_true',
                        help='report only; skip the overhead/certificate '
                             'assertions')
    args = parser.parse_args(argv)
    result = run_podelastic_bench(quick=args.quick, check=not args.no_check)
    print(json.dumps(result, indent=2))
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
