"""Readahead benchmark: serial vs prefetched row-group reads on a synthetic
slow-IO filesystem shim.

The tentpole claim of the readahead layer is that storage I/O and decode CPU
overlap instead of serializing. Local CI disks are too fast to show that, so
this bench wraps the local filesystem in :class:`SlowFilesystem` — every
``read()`` call sleeps a fixed latency, modelling a remote object store —
and pins the io:decode ratio at ≈ 1:1 by construction:

1. **Calibration pass** (no delay): counts the shim's ``read()`` calls per
   row group, so a per-read delay can be derived that costs each row group a
   known synthetic I/O time.
2. The decode side gets the same budget via a busy-spin
   ``TransformSpec`` (transform time is decode-stage time by the
   ``finalize_item_times`` contract), on top of the natural codec decode.
3. **Serial pass** (``io_readahead=0``, 1 worker): reads and decode
   serialize — per-group cost ≈ io + decode.
4. **Readahead pass** (``io_readahead=2``, 1 worker): the background reader
   hides the next group's read behind the current decode — per-group cost
   ≈ max(io, decode). With io ≈ decode that is the classic ~2x.

A single worker isolates the overlap effect: with many workers, one
worker's read already overlaps another's decode, which is parallelism, not
pipelining. The full (non-quick) run asserts **≥ 1.5x items/s** over serial
and **overlap fraction > 0.5** (the BENCH_r07 acceptance bar); ``--quick``
shrinks the store and asserts looser bars as the tier-1 smoke.

CLI::

    python -m petastorm_tpu.benchmark.readahead [--quick] [--json]
"""

from __future__ import annotations

import argparse
import json
import shutil
import tempfile
import time

import numpy as np

from petastorm_tpu.faultfs import FaultInjector, FaultyFilesystem
from petastorm_tpu.workers.stats import readahead_hit_rate

_MB = 1024.0 * 1024.0


class SlowFilesystem(FaultyFilesystem):
    """fsspec-filesystem wrapper whose opened files sleep
    ``seconds_per_read`` on every ``read()`` call (and ``seconds_per_mb /
    MB`` per byte) — the BENCH_r07 shim, now the ``fixed-latency`` scenario
    of the general chaos injector (:mod:`petastorm_tpu.faultfs`).
    Thread-safe: the worker thread and the readahead thread sleep
    independently, exactly like two in-flight remote range requests."""

    def __init__(self, inner, seconds_per_read: float = 0.0,
                 seconds_per_mb: float = 0.0):
        super().__init__(inner, FaultInjector(
            'fixed-latency', seconds_per_read=seconds_per_read,
            seconds_per_mb=seconds_per_mb))
        self.seconds_per_read = seconds_per_read
        self.seconds_per_mb = seconds_per_mb


def _decode_work_transform(seconds_per_group: float):
    """A columnar TransformSpec whose func burns ~``seconds_per_group`` of
    real decompression CPU per row group — a stand-in for codec/augmentation
    work with a known cost. Uses ``zlib.decompress`` (not a Python busy
    spin) because real decode paths release the GIL; a GIL-holding spin
    would starve the background reader thread and understate the overlap
    any real pipeline gets."""
    import zlib

    from petastorm_tpu.transform import TransformSpec

    blob = zlib.compress(
        np.random.default_rng(0).integers(0, 255, 1 << 20,
                                          dtype=np.uint8).tobytes(), 1)
    start = time.perf_counter()
    calib_rounds = 5
    for _ in range(calib_rounds):
        zlib.decompress(blob)
    per_call = max(1e-5, (time.perf_counter() - start) / calib_rounds)
    repeats = max(1, round(seconds_per_group / per_call))

    def decode_work(columns):
        for _ in range(repeats):
            zlib.decompress(blob)
        return columns

    return TransformSpec(func=decode_work)


def generate_readahead_dataset(url: str, rows: int, rows_per_group: int = 8):
    """Small petastorm store with one compressed-ndarray payload column."""
    from petastorm_tpu.codecs import CompressedNdarrayCodec, ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import materialize_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField

    schema = Unischema('ReadaheadBench', [
        UnischemaField('id', np.int64, (), ScalarCodec(), False),
        UnischemaField('payload', np.uint8, (32, 32, 3),
                       CompressedNdarrayCodec(), False),
    ])
    # incompressible payload: row-group byte size tracks row count, so the
    # row_group_size_mb knob maps to rows_per_group deterministically
    payload_bytes = 32 * 32 * 3
    row_group_size_mb = rows_per_group * payload_bytes / _MB
    row_dicts = []
    for i in range(rows):
        rng = np.random.default_rng(i)
        row_dicts.append({
            'id': np.int64(i),
            'payload': rng.integers(0, 255, (32, 32, 3), dtype=np.uint8),
        })
    with materialize_dataset(url, schema, row_group_size_mb=row_group_size_mb,
                             rows_per_file=max(rows_per_group * 4, rows // 2)
                             ) as writer:
        writer.write_rows(row_dicts)
    return schema


def _run_pass(dataset_path: str, slow_fs: SlowFilesystem, io_readahead,
              num_epochs: int, transform_spec) -> dict:
    """One measured read pass: 1 thread worker, no shuffle, columnar path."""
    from petastorm_tpu.cache import NullCache
    from petastorm_tpu.reader import Reader
    from petastorm_tpu.readers.columnar_worker import (ColumnarResultsReader,
                                                       ColumnarWorker)
    from petastorm_tpu.workers.thread_pool import ThreadPool

    pool = ThreadPool(1, 50)
    reader = Reader(lambda: slow_fs, dataset_path,
                    worker_class=ColumnarWorker,
                    results_reader_factory=ColumnarResultsReader,
                    shuffle_row_groups=False, num_epochs=num_epochs,
                    transform_spec=transform_spec, cache=NullCache(),
                    pool=pool, is_batched_reader=True,
                    io_readahead=io_readahead)
    reads_before = slow_fs.read_calls
    groups = 0
    rows = 0
    start = time.perf_counter()
    try:
        for batch in reader:
            groups += 1
            rows += len(batch.id)
    finally:
        wall = time.perf_counter() - start
        diag = reader.diagnostics
        reader.stop()
        reader.join()
    return {
        'wall_s': round(wall, 4),
        'row_groups': groups,
        'rows': rows,
        'items_per_s': round(groups / wall, 2) if wall else 0.0,
        'rows_per_s': round(rows / wall, 1) if wall else 0.0,
        'read_calls': slow_fs.read_calls - reads_before,
        'worker_io_s': round(diag['worker_io_s'], 4),
        'worker_decode_s': round(diag['worker_decode_s'], 4),
        'readahead_io_s': round(diag['readahead_io_s'], 4),
        'readahead_wait_s': round(diag['readahead_wait_s'], 4),
        'readahead_hits': diag['readahead_hits'],
        'readahead_misses': diag['readahead_misses'],
        'io_overlap_fraction': round(diag['io_overlap_fraction'], 4),
    }


def run_readahead_bench(quick: bool = False, check: bool = True,
                        dataset_path: str = None) -> dict:
    """Serial vs readahead comparison on the slow-IO shim; returns one
    JSON-able dict. ``quick`` shrinks the store/epochs for the tier-1 smoke
    (looser assertion bars); ``check=False`` reports without asserting."""
    import fsspec

    rows = 64 if quick else 192
    rows_per_group = 8
    num_epochs = 2 if quick else 3
    stage_budget_s = 0.008 if quick else 0.02   # io AND decode per row group

    tmpdir = None
    if dataset_path is None:
        tmpdir = tempfile.mkdtemp(prefix='petastorm_tpu_readahead_bench_')
        dataset_path = tmpdir
    try:
        generate_readahead_dataset('file://' + dataset_path, rows=rows,
                                   rows_per_group=rows_per_group)
        base_fs = fsspec.filesystem('file')
        transform = _decode_work_transform(stage_budget_s)

        # 1. calibration: how many shim read() calls does one row group cost?
        cal_fs = SlowFilesystem(base_fs)
        calibration = _run_pass(dataset_path, cal_fs, 0, 1, transform)
        groups_per_epoch = calibration['row_groups']
        reads_per_group = max(1.0,
                              calibration['read_calls'] / groups_per_epoch)
        delay_per_read = stage_budget_s / reads_per_group

        # 2+3. serial (blocking read then decode, io:decode pinned ~1:1) vs
        # readahead (background reads overlap the decompression decode).
        # Quick mode is a CI smoke on sub-second passes: take the best of two
        # attempts so transient host load cannot flip the gate.
        min_speedup = 1.15 if quick else 1.5
        serial = readahead = None
        speedup = 0.0
        for _attempt in range(2 if quick else 1):
            serial_fs = SlowFilesystem(base_fs,
                                       seconds_per_read=delay_per_read)
            serial = _run_pass(dataset_path, serial_fs, 0, num_epochs,
                               transform)
            ra_fs = SlowFilesystem(base_fs, seconds_per_read=delay_per_read)
            readahead = _run_pass(dataset_path, ra_fs, 2, num_epochs,
                                  transform)
            speedup = (readahead['items_per_s'] / serial['items_per_s']
                       if serial['items_per_s'] else 0.0)
            if speedup >= min_speedup:
                break

        result = {
            'quick': quick,
            'rows': rows,
            'row_groups_per_epoch': groups_per_epoch,
            'epochs': num_epochs,
            'calibration': {
                'stage_budget_ms_per_group': stage_budget_s * 1000.0,
                'reads_per_group': round(reads_per_group, 1),
                'delay_per_read_ms': round(delay_per_read * 1000.0, 3),
                'natural_decode_s_per_epoch': calibration['worker_decode_s'],
            },
            'serial': serial,
            'readahead': readahead,
            'speedup_items_per_s': round(speedup, 2),
            'readahead_hit_rate': round(readahead_hit_rate(readahead), 3),
        }
        if not quick:
            # the stats-driven sizing story: same store, depth picked live
            auto_fs = SlowFilesystem(base_fs, seconds_per_read=delay_per_read)
            result['readahead_auto'] = _run_pass(dataset_path, auto_fs,
                                                 'auto', num_epochs, transform)
        if check:
            min_overlap = 0.25 if quick else 0.5
            assert result['speedup_items_per_s'] >= min_speedup, (
                'readahead must be >= {}x serial items/s on the slow-IO shim '
                'with io:decode ~1:1; measured {}x'.format(
                    min_speedup, result['speedup_items_per_s']))
            assert readahead['io_overlap_fraction'] > min_overlap, (
                'readahead must hide > {} of its read time behind decode; '
                'measured overlap fraction {}'.format(
                    min_overlap, readahead['io_overlap_fraction']))
            assert readahead['readahead_hits'] > 0, 'no prefetched reads hit'
        return result
    finally:
        if tmpdir is not None:
            shutil.rmtree(tmpdir, ignore_errors=True)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description='serial vs readahead row-group read benchmark')
    parser.add_argument('--quick', action='store_true',
                        help='small store/epochs for the CI smoke path')
    parser.add_argument('--no-check', action='store_true',
                        help='report only; skip the speedup/overlap assertions')
    args = parser.parse_args(argv)
    result = run_readahead_bench(quick=args.quick, check=not args.no_check)
    print(json.dumps(result, indent=2))
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
