"""Autotune benchmark: a deliberately mis-tuned reader must recover, an
already-tuned reader must not be degraded.

The controller's value claim is closed-loop: tuning knowledge
(docs/readahead.md's depth guidance, BENCH_r13's "more workers can be
slower") should stop being something a user must discover by hand. Local CI
disks are too fast to leave a mis-tuned reader anything to recover — with
io essentially free, every knob is within noise of every other — so this
bench runs the mnist-image line through the ``SlowFilesystem`` shim
(BENCH_r07's remote-object-store protocol), with the per-read delay pinned
so the storage ceiling ≈ the measured decode ceiling (io:decode ≈ 1:1, the
regime where readahead is worth ~2x and ``io_readahead=0`` is a real
mis-tuning). The protocol:

1. **Pin the shim**: a no-delay counting pass measures reads-per-row-group;
   a cold probe measures the decode ceiling; the per-read delay is derived
   so one row group's synthetic I/O ≈ its decode time.
2. **Calibrate cold** through the delayed shim (``profiler.calibrate``,
   saved): the controller's first tick loads this cached artifact instead
   of probing under load — probes during the measured window would both
   perturb it and under-measure the ceilings.
3. **Hand-tune by measurement**: a small grid WITHOUT the controller —
   ``(w=1, ra=1)``, ``(w=1, ra=2)``, ``(w=default, ra=1)`` — best measured
   rows/s is the hand-tuned reference (the grid, not an assumption,
   decides; on a 1-core host w=1 wins, on a big host more workers may).
4. **Recovery**: a mis-tuned reader (``workers=1, io_readahead=0``) streams
   under the controller; the trailing-window rate is sampled each second.
   Full gate: **>= 80% of the hand-tuned rate within 60s**, with the
   action log, time-to-threshold and final config recorded.
5. **Steady guard**: the hand-tuned config, controller OFF vs ON, in the
   alternating-pair protocol (order flipped per pair, headline = median of
   per-pair deltas — the r08/r14 drift-cancelling discipline). Full gate:
   the controller costs **<= 5%** on a reader that is already right — its
   hysteresis and quarantine must keep it quiet.

The artifact carries roofline context (the recovered rate vs the calibrated
binding ceiling), the controller's own prediction grading, and the model
replay checks (including the BENCH_r13 negative-scaling direction check).
``--quick`` shrinks the store and loosens the gates to a smoke.

CLI (output is always JSON)::

    python -m petastorm_tpu.benchmark.autotune [--quick] [--no-check]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import tempfile
import time
from collections import deque

from petastorm_tpu.benchmark.readahead import SlowFilesystem

#: Trailing window (seconds) the recovery loop rates over: long enough to
#: smooth row-group granularity, short enough to watch convergence happen.
TRAIL_S = 5.0


def _make_reader(dataset_path, slow_fs, workers, io_readahead, num_epochs,
                 autotune=False):
    """A columnar reader over the shim filesystem (the readahead-bench
    construction: ``Reader`` directly, so the filesystem factory can be the
    wrapped instance)."""
    from petastorm_tpu.cache import NullCache
    from petastorm_tpu.reader import Reader
    from petastorm_tpu.readers.columnar_worker import (ColumnarResultsReader,
                                                       ColumnarWorker)
    from petastorm_tpu.workers.thread_pool import ThreadPool
    return Reader(lambda: slow_fs, dataset_path,
                  worker_class=ColumnarWorker,
                  results_reader_factory=ColumnarResultsReader,
                  shuffle_row_groups=False, num_epochs=num_epochs,
                  cache=NullCache(), pool=ThreadPool(workers, 50),
                  is_batched_reader=True, io_readahead=io_readahead,
                  autotune=autotune)


def _measure_rate(dataset_path, slow_fs, workers, io_readahead,
                  duration_s: float, warm_s: float = 1.0,
                  autotune=False) -> dict:
    """Stream continuously; rows/s over ``duration_s`` after a ``warm_s``
    discard window."""
    reader = _make_reader(dataset_path, slow_fs, workers, io_readahead,
                          num_epochs=None, autotune=autotune)
    rows = 0
    marked = None
    rate = 0.0
    report = None
    try:
        start = time.perf_counter()
        for batch in reader:
            rows += len(batch.idx)
            now = time.perf_counter()
            if marked is None and now - start >= warm_s:
                marked = (now, rows)
            if marked is not None and now - marked[0] >= duration_s:
                rate = (rows - marked[1]) / (now - marked[0])
                break
        if reader.autotune is not None:
            report = reader.autotune.report()
    finally:
        reader.stop()
        reader.join()
    return {'samples_per_sec': round(rate, 1), 'autotune': report}


def _recovery_run(dataset_path, slow_fs, target_rate: float,
                  budget_s: float, scratch: str) -> dict:
    """Stream a mis-tuned reader (w1, ra0) under the controller; sample the
    trailing-window rate until it clears ``target_rate`` and settles, or
    the budget runs out."""
    reader = _make_reader(
        dataset_path, slow_fs, workers=1, io_readahead=0, num_epochs=None,
        autotune=dict(tick_interval_s=1.0, calibrate='auto',
                      scratch_dir=scratch))
    assert reader.autotune is not None
    samples = []            # (elapsed_s, trailing_rate)
    reached_at = None
    try:
        start = time.perf_counter()
        window = deque()    # (ts, rows_cumulative)
        rows = 0
        last_sample = start
        for batch in reader:
            rows += len(batch.idx)
            now = time.perf_counter()
            window.append((now, rows))
            while window and now - window[0][0] > TRAIL_S:
                window.popleft()
            elapsed = now - start
            if now - last_sample >= 1.0 and len(window) >= 2:
                last_sample = now
                span = window[-1][0] - window[0][0]
                trailing = ((window[-1][1] - window[0][1]) / span
                            if span > 0 else 0.0)
                samples.append((round(elapsed, 2), round(trailing, 1)))
                if reached_at is None and trailing >= target_rate:
                    reached_at = elapsed
                # converged: threshold held long enough for the controller
                # to grade its move — no need to burn the whole budget
                if reached_at is not None and elapsed >= reached_at + 8.0:
                    break
            if elapsed >= budget_s:
                break
        span = (window[-1][0] - window[0][0]) if len(window) >= 2 else 0.0
        final_rate = ((window[-1][1] - window[0][1]) / span
                      if span > 0 else 0.0)
        report = reader.autotune.report()
    finally:
        reader.stop()
        reader.join()
    return {
        'samples_per_sec': round(final_rate, 1),
        'seconds_to_threshold': (round(reached_at, 2)
                                 if reached_at is not None else None),
        'timeline': samples[-30:],
        'final_config': report['config'],
        'actions_total': report['actions_total'],
        'reverts_total': report['reverts_total'],
        'actions': [{k: a.get(k) for k in
                     ('tick', 'knob', 'direction', 'from', 'applied',
                      'policy', 'predicted_gain_pct', 'measured_delta_pct',
                      'prediction_error_pct', 'graded')}
                    for a in report['actions']],
        'prediction': report['prediction'],
    }


def run_autotune_bench(quick: bool = False, check: bool = True) -> dict:
    import fsspec

    from petastorm_tpu import profiler
    from petastorm_tpu.autotune import AUTOTUNE_DIR_ENV_VAR
    from petastorm_tpu.benchmark.northstar import (
        _default_workers, generate_mnist_images_dataset)
    from petastorm_tpu.etl.dataset_metadata import (infer_or_load_unischema,
                                                    load_row_groups)

    rows = 512 if quick else 2048
    pass_s = 2.0 if quick else 4.0
    budget_s = 30.0 if quick else 60.0
    pairs = 2 if quick else 3
    tmpdir = tempfile.mkdtemp(prefix='petastorm_tpu_autotune_bench_')
    dataset_path = os.path.join(tmpdir, 'ds')
    scratch = os.path.join(tmpdir, 'arbitration')
    saved_cal = os.environ.get(profiler.CALIBRATION_DIR_ENV_VAR)
    saved_arb = os.environ.get(AUTOTUNE_DIR_ENV_VAR)
    os.environ[profiler.CALIBRATION_DIR_ENV_VAR] = os.path.join(tmpdir, 'cal')
    os.environ[AUTOTUNE_DIR_ENV_VAR] = scratch
    try:
        # small row groups: the row group is the readahead/ventilation unit,
        # and the knobs need granularity to show up in a trailing window
        generate_mnist_images_dataset('file://' + dataset_path, rows=rows,
                                      row_group_size_mb=0.05)
        base_fs = fsspec.filesystem('file')
        cpu = os.cpu_count() or 1

        # 1. pin the shim: reads per row group (counting pass) + decode
        # ceiling (cold probe) -> per-read delay for io:decode = 1:1
        counting_fs = SlowFilesystem(base_fs)
        groups = 0
        reader = _make_reader(dataset_path, counting_fs, 1, 0, num_epochs=1)
        try:
            for _ in reader:
                groups += 1
        finally:
            reader.stop()
            reader.join()
        reads_per_group = max(1.0, counting_fs.read_calls / groups)
        rows_per_group = rows / groups
        schema, _ = infer_or_load_unischema(base_fs, dataset_path)
        pieces = load_row_groups(base_fs, dataset_path)
        cold = profiler.calibrate(base_fs, dataset_path, pieces, schema,
                                  save=False)
        decode_ceiling = (cold.get('ceilings') or {}).get('decode') or 1.0
        io_s_per_group = rows_per_group / decode_ceiling
        delay_per_read = io_s_per_group / reads_per_group

        def make_slow_fs():
            return SlowFilesystem(base_fs, seconds_per_read=delay_per_read)

        # 2. calibrate COLD through the delayed shim and cache the artifact:
        # the controller's first tick loads it instead of probing under load
        calibration = profiler.calibrate(make_slow_fs(), dataset_path,
                                         pieces, schema, save=True)

        # 3. hand-tune by measurement
        grid_configs = {'w1_ra1': (1, 1), 'w1_ra2': (1, 2)}
        default_workers = _default_workers()
        if default_workers > 1:
            grid_configs['w{}_ra1'.format(default_workers)] = (
                default_workers, 1)
        grid = {name: _measure_rate(dataset_path, make_slow_fs(), w, ra,
                                    pass_s)['samples_per_sec']
                for name, (w, ra) in grid_configs.items()}
        hand_key = max(grid, key=grid.get)
        hand_tuned = grid[hand_key]
        hand_workers, hand_ra = grid_configs[hand_key]

        # mis-tuned start rate (no controller) for the artifact's "before"
        mistuned = _measure_rate(dataset_path, make_slow_fs(), 1, 0,
                                 pass_s)['samples_per_sec']

        # 4. recovery under the controller
        recovery = _recovery_run(dataset_path, make_slow_fs(),
                                 0.8 * hand_tuned, budget_s, scratch)
        recovery['recovery_fraction'] = round(
            recovery['samples_per_sec'] / hand_tuned, 4) if hand_tuned else 0

        # 5. steady guard: hand-tuned config, controller off vs on, paired
        deltas, pair_records = [], []
        for pair in range(pairs):
            order = (False, True) if pair % 2 == 0 else (True, False)
            rates = {}
            for tuned in order:
                options = (dict(tick_interval_s=1.0, calibrate='auto',
                                scratch_dir=scratch) if tuned else False)
                rates[tuned] = _measure_rate(
                    dataset_path, make_slow_fs(), hand_workers, hand_ra,
                    pass_s, autotune=options)['samples_per_sec']
            baseline, tuned_rate = rates[False], rates[True]
            delta = (100.0 * (baseline - tuned_rate) / baseline
                     if baseline else 0.0)
            deltas.append(delta)
            pair_records.append({'baseline': baseline,
                                 'autotuned': tuned_rate,
                                 'delta_pct': round(delta, 2)})
        deltas.sort()
        steady_delta = deltas[len(deltas) // 2]

        ceilings = calibration.get('ceilings') or {}
        binding = min((s for s in ('io', 'decode') if ceilings.get(s)),
                      key=lambda s: ceilings[s], default=None)
        binding_ceiling = ceilings.get(binding) if binding else None
        roofline_fraction = (
            round(recovery['samples_per_sec'] / binding_ceiling, 4)
            if binding_ceiling else None)
        result = {
            'quick': quick,
            'benchmark': 'autotune_mnist_slow_io',
            'rows': rows,
            'cpu_count': cpu,
            'protocol': {
                'pass_duration_s': pass_s,
                'recovery_budget_s': budget_s,
                'trailing_window_s': TRAIL_S,
                'steady_pairs': pairs,
                'tick_interval_s': 1.0,
                'pool': 'thread',
                'rows_per_group': round(rows_per_group, 1),
                'delay_per_read_s': round(delay_per_read, 6),
                'reads_per_group': round(reads_per_group, 1),
            },
            'ceilings_samples_per_sec': {
                k: v for k, v in ceilings.items() if v},
            'hand_tuned': {
                'config': {'workers': hand_workers,
                           'io_readahead': hand_ra},
                'samples_per_sec': hand_tuned,
                'grid': grid,
            },
            'mistuned': {
                'config': {'workers': 1, 'io_readahead': 0},
                'samples_per_sec': mistuned,
            },
            'recovered': recovery,
            'steady': {
                'config': {'workers': hand_workers,
                           'io_readahead': hand_ra},
                'median_delta_pct': round(steady_delta, 2),
                'pairs': pair_records,
            },
            'roofline': {
                'binding_stage': binding,
                'binding_ceiling_samples_per_s': binding_ceiling,
                'roofline_fraction': roofline_fraction,
                'roofline_pct': (round(100.0 * roofline_fraction, 2)
                                 if roofline_fraction is not None else None),
            },
            'model_checks': profiler.replay_against_artifacts(),
        }
        if check:
            _check(result, quick)
        return result
    finally:
        if saved_cal is None:
            os.environ.pop(profiler.CALIBRATION_DIR_ENV_VAR, None)
        else:
            os.environ[profiler.CALIBRATION_DIR_ENV_VAR] = saved_cal
        if saved_arb is None:
            os.environ.pop(AUTOTUNE_DIR_ENV_VAR, None)
        else:
            os.environ[AUTOTUNE_DIR_ENV_VAR] = saved_arb
        shutil.rmtree(tmpdir, ignore_errors=True)


def _check(result: dict, quick: bool) -> None:
    recovered = result['recovered']
    assert recovered['actions_total'] >= 1, (
        'the controller took no action on a reader mis-tuned by '
        'construction')
    if quick:
        # Quick mode runs sub-second windows on a possibly loaded CI host,
        # where ABSOLUTE rates drift far more than the effect size across
        # passes minutes apart. The robust smoke signal is the controller's
        # OWN grading — pre/post windows measured back to back around its
        # move — which must show the move helped.
        graded = [a for a in recovered['actions']
                  if a.get('measured_delta_pct') is not None]
        assert graded and max(a['measured_delta_pct'] for a in graded) > 0, (
            'no controller move measured a positive delta — actions: '
            '{}'.format(recovered['actions']))
    else:
        recovery = recovered['recovery_fraction']
        assert recovery >= 0.8, (
            'mis-tuned reader recovered to only {:.0%} of the hand-tuned '
            'rate (gate: >= 80% within the budget) — controller actions: '
            '{}'.format(recovery, recovered['actions']))
        assert recovered['seconds_to_threshold'] is not None, (
            'the 80% threshold was never reached inside the {}s budget'
            .format(result['protocol']['recovery_budget_s']))
    steady = result['steady']['median_delta_pct']
    bar = 15.0 if quick else 5.0
    assert steady <= bar, (
        'the controller degraded an already-tuned reader by {:.1f}% '
        '(gate: <= {:.0f}% median-of-pairs)'.format(steady, bar))
    failed = [c for c in result['model_checks'] if not c['ok']]
    assert not failed, 'model replay checks failed: {}'.format(failed)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description='autotune controller benchmark: mis-tuned recovery + '
                    'already-tuned non-degradation on the slow-io mnist '
                    'line')
    parser.add_argument('--quick', action='store_true',
                        help='small store, loose smoke gates (CI lane)')
    parser.add_argument('--no-check', action='store_true',
                        help='measure and print without asserting gates')
    parser.add_argument('--out', metavar='PATH', default=None,
                        help='also write the JSON result to PATH')
    args = parser.parse_args(argv)
    result = run_autotune_bench(quick=args.quick, check=not args.no_check)
    blob = json.dumps(result, indent=2, sort_keys=True)
    print(blob)
    if args.out:
        from petastorm_tpu.utils import atomic_write
        atomic_write(args.out, lambda f: f.write(blob + '\n'))
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
