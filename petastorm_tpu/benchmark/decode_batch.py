"""Batched-decode benchmark: the mnist decode line with row-group-vectorized
codec decode vs the per-cell loop, judged against the calibrated ceilings.

ROADMAP item 1a's deliverable (the VERDICT item-4 "slice contiguous views"
plan): BENCH_r12 pinned the mnist decode line at 8.56% of its calibrated
2-core decode ceiling with the decode track busy 0.99 of wall — the gap was
per-row Python framework work, not the codecs. This bench measures what the
batched boundary (``DataframeColumnCodec.make_column_decoder``,
``docs/decode.md``) recovers, at two levels:

1. **Column decode** (the codec boundary in isolation): one row group's
   codec column pushed through ``_column_to_numpy`` with the vectorized
   path on vs off, min-of-reps. ``NdarrayCodec`` decodes the whole chunk
   with one header compare + one contiguous copy — order(s)-of-magnitude
   over the per-cell loop; ``CompressedImageCodec`` keeps per-cell work to
   the actual image decompression.
2. **End-to-end** (the production columnar read path): alternating
   batched/per-cell full passes (``PETASTORM_TPU_BATCHED_DECODE``),
   median-of-N, at 1 and 2 workers. The 1-worker line is the headline:
   it is judged against the calibrated **single-stream** decode ceiling,
   the apples-to-apples roofline. The 2-worker line is recorded as
   context: on small-image stores, thread workers ping-pong the GIL
   around ~10us ``cv2.imdecode`` calls (each call releases and re-acquires
   it), and the handoff convoy can make 2 decode threads SLOWER than one —
   the artifact records that measured reality instead of hiding it, and
   the batched path's smaller GIL-held sections are what keep the
   multi-worker line usable at all.

Each measured pass also proves the split it claims to measure: batched
passes must decode every codec cell through the vectorized path
(``rows_decoded_batched``), per-cell passes none of them, and one row
group is decoded both ways and compared bit-for-bit.

The full run is the committed ``BENCH_r13.json``; the acceptance bar is
the headline line at >= 25% of its calibrated decode ceiling (>= 3x the
BENCH_r12 figure), gated by ``ci/check_perf_regression.py``.

CLI (output is always JSON)::

    python -m petastorm_tpu.benchmark.decode_batch [--quick] [--no-check]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import statistics
import tempfile
import time

from petastorm_tpu.codecs import BATCHED_DECODE_ENV_VAR

#: Acceptance bar for the headline line's %-of-ceiling (full mode); the
#: quick smoke only asserts the plumbing (split counters, bit-identity).
MIN_HEADLINE_ROOFLINE_PCT = 25.0

#: Column-decode speedup floor for the pure-vectorization codec
#: (``NdarrayCodec``: one memcpy per chunk vs N Python calls). The measured
#: figure is ~25x; 5x keeps the assertion far from host noise while still
#: catching a rewrite that silently loses the vectorized path.
MIN_NDARRAY_COLUMN_SPEEDUP = 5.0


def _column_decode_rates(url: str, field_name: str, reps: int) -> dict:
    """Min-of-reps decode rate of one row group's codec column through the
    real ``_column_to_numpy`` path, vectorized vs per-cell, plus a
    bit-identity verdict over that row group."""
    import numpy as np
    import pyarrow.parquet as pq

    from petastorm_tpu.etl.dataset_metadata import (infer_or_load_unischema,
                                                    load_row_groups)
    from petastorm_tpu.fs import get_filesystem_and_path_or_paths
    from petastorm_tpu.readers.columnar_worker import _column_to_numpy

    fs, path, _ = get_filesystem_and_path_or_paths(url)
    pieces = load_row_groups(fs, path)
    schema, _ = infer_or_load_unischema(fs, path)
    field = schema.fields[field_name]
    piece = pieces[len(pieces) // 2]
    with fs.open(piece.path, 'rb') as handle:
        table = pq.ParquetFile(handle).read_row_group(piece.row_group)
    column = table.column(field_name)
    n = table.num_rows

    def timed(batched: bool):
        counts = {'batched': 0, 'percell': 0}
        out = _column_to_numpy(column, field, None, batched=batched,
                               path_counts=counts)           # warm
        best = None
        for _ in range(reps):
            counts = {'batched': 0, 'percell': 0}
            start = time.perf_counter()
            out = _column_to_numpy(column, field, None, batched=batched,
                                   path_counts=counts)
            took = time.perf_counter() - start
            best = took if best is None else min(best, took)
        return out, best, counts

    batched_out, batched_s, batched_counts = timed(True)
    percell_out, percell_s, _ = timed(False)
    identical = (batched_out.dtype == percell_out.dtype
                 and batched_out.shape == percell_out.shape
                 and bool(np.array_equal(batched_out, percell_out)))
    return {
        'rows': n,
        'codec': type(field.codec).__name__,
        'batched_rows_per_s': round(n / batched_s, 1) if batched_s else None,
        'percell_rows_per_s': round(n / percell_s, 1) if percell_s else None,
        'speedup_x': round(percell_s / batched_s, 2) if batched_s else None,
        'batched_cells': batched_counts['batched'],
        'identical': identical,
    }


def _run_pass(url: str, batched: bool, workers: int) -> dict:
    """One full columnar-reader consumption pass; returns samples/s plus the
    decode-path split counters proving which path ran."""
    from petastorm_tpu import make_columnar_reader

    saved = os.environ.get(BATCHED_DECODE_ENV_VAR)
    os.environ[BATCHED_DECODE_ENV_VAR] = '1' if batched else '0'
    try:
        with make_columnar_reader(url, num_epochs=1,
                                  reader_pool_type='thread',
                                  workers_count=workers,
                                  shuffle_row_groups=False) as reader:
            start = time.perf_counter()
            rows = 0
            groups = 0
            for batch in reader:
                rows += len(batch.idx)
                groups += 1
            wall = time.perf_counter() - start
            snapshot = reader.diagnostics
    finally:
        if saved is None:
            os.environ.pop(BATCHED_DECODE_ENV_VAR, None)
        else:
            os.environ[BATCHED_DECODE_ENV_VAR] = saved
    return {
        'rows': rows,
        'row_groups': groups,
        'wall_s': round(wall, 4),
        'samples_per_sec': round(rows / wall, 1) if wall else 0.0,
        'rows_decoded_batched': snapshot.get('rows_decoded_batched', 0),
        'rows_decoded_percell': snapshot.get('rows_decoded_percell', 0),
    }


def _profile_line(url: str, workers: int, samples_per_sec: float) -> dict:
    """The roofline verdict for one measured line: its samples/s against the
    calibrated decode ceiling effective at this worker count (probing on
    the first call, cached per host+dataset digest afterwards)."""
    from petastorm_tpu import make_columnar_reader
    with make_columnar_reader(url, num_epochs=1, reader_pool_type='thread',
                              workers_count=workers,
                              shuffle_row_groups=False) as reader:
        profile = reader.profile(calibrate='auto',
                                 samples_per_sec=samples_per_sec)
        # consume the epoch so the context exit joins a finished reader
        for _ in reader:
            pass
    return {
        'binding_stage': profile['binding_stage'],
        'binding_ceiling_samples_per_s':
            profile['binding_ceiling_samples_per_s'],
        'roofline_fraction': profile['roofline_fraction'],
        'roofline_pct': round(
            100.0 * (profile['roofline_fraction'] or 0.0), 2),
        'ceilings': profile['ceilings'],
        'cpu_count': profile['cpu_count'],
    }


def run_decode_batch_bench(quick: bool = False, check: bool = True) -> dict:
    """Column-decode A/B + alternating end-to-end passes + roofline verdict
    on the mnist decode line. ``quick`` shrinks the store for the CI smoke
    (plumbing assertions only); the full run carries the headline."""
    from petastorm_tpu.benchmark.northstar import (
        generate_mnist_images_dataset, generate_token_dataset)

    rows = 2048 if quick else 16384
    token_rows = 512 if quick else 2048
    passes = 3 if quick else 5
    reps = 5 if quick else 9
    tmpdir = tempfile.mkdtemp(prefix='petastorm_tpu_decode_batch_')
    mnist_url = 'file://' + os.path.join(tmpdir, 'mnist')
    tokens_url = 'file://' + os.path.join(tmpdir, 'tokens')
    # the bench must not depend on (or pollute) the user's calibration
    # cache: point the artifact dir into the bench scratch
    from petastorm_tpu import profiler
    saved_env = os.environ.get(profiler.CALIBRATION_DIR_ENV_VAR)
    os.environ[profiler.CALIBRATION_DIR_ENV_VAR] = os.path.join(tmpdir, 'cal')
    try:
        generate_mnist_images_dataset(mnist_url, rows=rows)
        generate_token_dataset(tokens_url, rows=token_rows, seq_len=256,
                               ndarray_codec=True)

        column_decode = {
            'png_images': _column_decode_rates(mnist_url, 'image', reps),
            'ndarray_tokens': _column_decode_rates(tokens_url, 'tokens',
                                                   reps),
        }

        # one discarded priming pass per worker count: cold page cache and
        # pool spin-up must not bill either mode
        lines = {}
        for workers in (1, 2):
            _run_pass(mnist_url, True, workers)
            batched_runs, percell_runs = [], []
            for i in range(passes):
                # alternate the within-pair order: host drift is monotone
                # over seconds and must bill both modes equally
                if i % 2 == 0:
                    batched_runs.append(_run_pass(mnist_url, True, workers))
                    percell_runs.append(_run_pass(mnist_url, False, workers))
                else:
                    percell_runs.append(_run_pass(mnist_url, False, workers))
                    batched_runs.append(_run_pass(mnist_url, True, workers))
            for mode, runs in (('batched', batched_runs),
                               ('percell', percell_runs)):
                med = statistics.median(r['samples_per_sec'] for r in runs)
                lines['mnist_w{}_{}'.format(workers, mode)] = {
                    'workers': workers,
                    'samples_per_sec': med,
                    'runs': [r['samples_per_sec'] for r in runs],
                    'rows_decoded_batched': runs[-1]['rows_decoded_batched'],
                    'rows_decoded_percell': runs[-1]['rows_decoded_percell'],
                }

        # roofline verdicts for the batched lines (same calibration artifact
        # both times; the 1-worker line is the headline)
        for workers in (1, 2):
            key = 'mnist_w{}_batched'.format(workers)
            lines[key]['roofline'] = _profile_line(
                mnist_url, workers, lines[key]['samples_per_sec'])
            lines[key]['roofline_pct'] = \
                lines[key]['roofline']['roofline_pct']

        headline = lines['mnist_w1_batched']
        result = {
            'quick': quick,
            'benchmark': 'decode_batch_mnist',
            'rows': rows,
            'cpu_count': headline['roofline']['cpu_count'],
            'protocol': {'passes_per_mode': passes, 'pool': 'thread',
                         'token_rows': token_rows,
                         'column_decode_reps': reps},
            'column_decode': column_decode,
            'lines': lines,
            'headline_line': 'mnist_w1_batched',
            'roofline': {
                'binding_stage': headline['roofline']['binding_stage'],
                'binding_ceiling_samples_per_s':
                    headline['roofline']['binding_ceiling_samples_per_s'],
                'roofline_fraction':
                    headline['roofline']['roofline_fraction'],
                'roofline_pct': headline['roofline_pct'],
            },
        }
        if check:
            _check(result, quick)
        return result
    finally:
        if saved_env is None:
            os.environ.pop(profiler.CALIBRATION_DIR_ENV_VAR, None)
        else:
            os.environ[profiler.CALIBRATION_DIR_ENV_VAR] = saved_env
        shutil.rmtree(tmpdir, ignore_errors=True)


def _check(result: dict, quick: bool) -> None:
    column_decode = result['column_decode']
    for name, entry in column_decode.items():
        assert entry['identical'], (
            '{}: batched decode must be bit-identical to per-cell'
            .format(name))
        assert entry['batched_cells'] == entry['rows'], (
            '{}: the vectorized path must have decoded every cell of the '
            'batched A/B leg, got {}/{}'.format(name, entry['batched_cells'],
                                                entry['rows']))
    nd = column_decode['ndarray_tokens']
    assert nd['speedup_x'] and nd['speedup_x'] >= MIN_NDARRAY_COLUMN_SPEEDUP, (
        'NdarrayCodec column decode must vectorize (one memcpy per chunk); '
        'measured only {}x over per-cell'.format(nd['speedup_x']))
    for key, line in result['lines'].items():
        batched_line = key.endswith('_batched')
        if batched_line:
            assert line['rows_decoded_percell'] == 0, (
                '{}: a clean batched pass must not fall back per-cell '
                '({} cells did)'.format(key, line['rows_decoded_percell']))
            assert line['rows_decoded_batched'] >= result['rows'], (
                '{}: the batched pass must decode every image cell '
                'vectorized, got {}'.format(key,
                                            line['rows_decoded_batched']))
        else:
            assert line['rows_decoded_batched'] == 0, (
                '{}: {}=0 must force the per-cell loop'.format(
                    key, BATCHED_DECODE_ENV_VAR))
    # sub-second quick passes on a loaded host are noise-dominated; the
    # quick gate only catches a wholesale regression, the full run holds
    # the honest bar
    tolerance = 0.5 if quick else 0.75
    for workers in (1, 2):
        batched = result['lines']['mnist_w{}_batched'.format(workers)]
        percell = result['lines']['mnist_w{}_percell'.format(workers)]
        assert batched['samples_per_sec'] >= \
            tolerance * percell['samples_per_sec'], (
                'batched decode must not regress the end-to-end line beyond '
                'noise at {} workers: {} vs {} samples/s'.format(
                    workers, batched['samples_per_sec'],
                    percell['samples_per_sec']))
    assert result['roofline']['binding_stage'] == 'decode', (
        'the mnist line must stay decode-bound, got {!r}'.format(
            result['roofline']['binding_stage']))
    pct = result['roofline']['roofline_pct']
    if quick:
        assert pct and pct > 0.0, 'headline roofline_pct must be measured'
    else:
        assert pct and pct >= MIN_HEADLINE_ROOFLINE_PCT, (
            'the batched mnist decode line must reach >= {}% of its '
            'calibrated decode ceiling (the ISSUE-11 acceptance bar, 3x '
            'BENCH_r12); measured {}%'.format(MIN_HEADLINE_ROOFLINE_PCT,
                                              pct))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description='Batched vs per-cell codec decode on the mnist line, '
                    'roofline-judged')
    parser.add_argument('--quick', action='store_true',
                        help='small store for the CI smoke path')
    parser.add_argument('--no-check', action='store_true',
                        help='report only; skip the assertions')
    args = parser.parse_args(argv)
    result = run_decode_batch_bench(quick=args.quick, check=not args.no_check)
    print(json.dumps(result, indent=2))
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
