"""Per-field codecs: encode numpy values into Parquet-storable cells and back.

Reference parity: ``petastorm/codecs.py`` (``CompressedImageCodec`` :58-130,
``NdarrayCodec`` :133-171, ``CompressedNdarrayCodec`` :174-212, ``ScalarCodec``
:215-271, shape check ``_is_compliant_shape`` :274-294).

Deviation from the reference (deliberate): codecs are serialized to **JSON by
registered name**, never pickled, so codec class paths are not an ABI
(the reference admits the pickle-ABI trap at ``codecs.py:20-21``). Storage types
are expressed as ``pyarrow`` types instead of Spark SQL types — the write path is
pyarrow-native, no JVM.
"""

from __future__ import annotations

import inspect
import io
import os
import re
from abc import ABC, abstractmethod
from itertools import repeat
from typing import Any, Dict, Type

import numpy as np
import pyarrow as pa

#: Environment variable gating the row-group-vectorized (batched) decode
#: path (default on). ``0``/``false``/``off`` forces every codec column
#: through the per-cell loop — the uniform observability/behavior kill
#: switch shape (``PETASTORM_TPU_HEALTH``, ``PETASTORM_TPU_LINEAGE``,
#: ``PETASTORM_TPU_PROFILER``). The two paths are bit-identical by
#: contract (``docs/decode.md``); the switch exists for A/B measurement
#: (``benchmark/decode_batch.py``) and as an escape hatch.
BATCHED_DECODE_ENV_VAR = 'PETASTORM_TPU_BATCHED_DECODE'


def batched_decode_enabled() -> bool:
    """The :data:`BATCHED_DECODE_ENV_VAR` gate (default on). Read once per
    worker at construction, never per cell."""
    value = os.environ.get(BATCHED_DECODE_ENV_VAR, '').strip().lower()
    return value not in ('0', 'false', 'off')


def split_binary_chunk(chunk: pa.Array):
    """``(offsets, data)`` of one (large_)binary arrow chunk: the int
    offsets vector and the shared ``uint8`` data buffer, both zero-copy.
    Cell ``i`` is ``data[offsets[i]:offsets[i + 1]]`` — the one
    buffer-splitting primitive under every batched decoder and the
    per-cell view builder."""
    n = len(chunk)
    _validity, offsets_buf, data_buf = chunk.buffers()
    off_dtype = np.dtype(
        np.int64 if pa.types.is_large_binary(chunk.type) else np.int32)
    offsets = np.frombuffer(offsets_buf, dtype=off_dtype, count=n + 1,
                            offset=chunk.offset * off_dtype.itemsize)
    data = (np.frombuffer(data_buf, dtype=np.uint8)
            if data_buf is not None else np.empty(0, np.uint8))
    return offsets, data


class DataframeColumnCodec(ABC):
    """Abstract codec translating one field's numpy value to a storable cell.

    Mirrors the reference ABC at ``codecs.py:36-55``.
    """

    #: Registry key; subclasses must set a unique stable name (it is written
    #: into dataset metadata and must remain valid across versions).
    codec_name: str = None

    @abstractmethod
    def encode(self, unischema_field, value):
        """Encode ``value`` (numpy) into an arrow-storable python value."""

    @abstractmethod
    def decode(self, unischema_field, value):
        """Decode a storable value back to the numpy form declared by the field."""

    def make_cell_decoder(self, unischema_field):
        """Return a callable decoding ONE cell of this field's column.

        The columnar reader calls this once per column and then invokes the
        returned callable per cell, so per-column setup (module lookups, flag
        resolution) hoists out of the hot loop. Cells arrive as zero-copy
        ``uint8`` ndarray views over the arrow data buffer; this default
        adapter converts them to ``bytes`` for codecs whose :meth:`decode`
        expects that. Override for a per-cell fast path."""
        def decode_cell(cell):
            return self.decode(
                unischema_field,
                cell.tobytes() if isinstance(cell, np.ndarray) else cell)
        return decode_cell

    def make_column_decoder(self, unischema_field):
        """Return ``decode_chunk(chunk: pa.Array) -> Optional[np.ndarray]``
        decoding one null-free (large_)binary column chunk in a single
        shot, or ``None`` when this codec has no vectorized path.

        Contract (``docs/decode.md``): the reader calls the returned
        callable only for fixed-shape fields on null-free chunks, with no
        per-field decode override in play. The callable returns the decoded
        ``(len(chunk), *shape)`` array **bit-identical** to what the
        per-cell loop produces for the same chunk, or ``None`` to punt a
        chunk it cannot vectorize; it may also raise on corrupt data — the
        reader then retries the column per cell, so quarantine row offsets
        and error semantics are exactly the per-cell loop's. Never return
        an approximation."""
        return None

    def device_decode_unsupported_reason(self, unischema_field):
        """``None`` when this codec's stored cells for ``unischema_field``
        can decode on the accelerator under ``jax.jit``
        (``ops/decode.py``), else a human-readable decline reason. The
        default is ineligible: device decode is opt-in per codec, and a
        decline routes the column to the host matrix — it never owns an
        error."""
        return 'codec {} has no device-decode path'.format(
            type(self).__name__)

    @abstractmethod
    def arrow_type(self, unischema_field) -> pa.DataType:
        """The pyarrow storage type used for this field's column."""

    def to_json_dict(self) -> Dict[str, Any]:
        return {'codec': self.codec_name}

    @classmethod
    def from_json_dict(cls, d: Dict[str, Any]) -> 'DataframeColumnCodec':
        return cls()

    def __eq__(self, other):
        return isinstance(other, type(self)) and self.to_json_dict() == other.to_json_dict()

    def __hash__(self):
        return hash(repr(sorted(self.to_json_dict().items())))

    def __repr__(self):
        return '{}()'.format(type(self).__name__)


_CODEC_REGISTRY: Dict[str, Type[DataframeColumnCodec]] = {}


def register_codec(cls: Type[DataframeColumnCodec]) -> Type[DataframeColumnCodec]:
    """Class decorator adding a codec to the JSON (de)serialization registry."""
    assert cls.codec_name, 'codec_name must be set'
    _CODEC_REGISTRY[cls.codec_name] = cls
    return cls


def codec_from_json_dict(d: Dict[str, Any]) -> DataframeColumnCodec:
    name = d['codec']
    if name not in _CODEC_REGISTRY:
        raise ValueError('Unknown codec name {!r}; known: {}'.format(name, sorted(_CODEC_REGISTRY)))
    return _CODEC_REGISTRY[name].from_json_dict(d)


def _is_compliant_shape(actual: tuple, expected: tuple) -> bool:
    """True if ``actual`` matches ``expected`` where ``None`` is a wildcard.

    Reference: ``codecs.py:274-294``.
    """
    if len(actual) != len(expected):
        return False
    for a, e in zip(actual, expected):
        if e is not None and a != e:
            return False
    return True


def _check_shape(field, value: np.ndarray):
    if not _is_compliant_shape(value.shape, field.shape):
        raise ValueError(
            'Field {!r} with shape {} got a value of non-compliant shape {}'.format(
                field.name, field.shape, value.shape))


def _check_dtype(field, value: np.ndarray):
    """dtype compliance; string/bytes fields match by kind since unicode/bytes
    itemsize varies per value."""
    declared = field.numpy_dtype
    if declared is str:
        ok = value.dtype.kind == 'U'
    elif declared is bytes:
        ok = value.dtype.kind == 'S'
    else:
        declared = np.dtype(declared)
        ok = (value.dtype.kind == declared.kind if declared.kind in 'US'
              else value.dtype == declared)
    if not ok:
        raise ValueError('Field {!r} expected dtype {} got {}'.format(
            field.name, field.numpy_dtype, value.dtype))


# Strict matcher for the header np.save itself generates. np.load parses this
# dict with ast.literal_eval (compile + AST walk) on EVERY cell — ~8% of a
# decode-bound reader's CPU in profiles. Payloads matching this exact
# machine-generated form take the fast path; anything else (fortran order,
# structured/object dtypes, hand-crafted files) falls back to np.load.
_NPY_FAST_HEADER = re.compile(
    rb"^\{'descr': '([<>=|][a-zA-Z]\d*)', 'fortran_order': False, "
    rb"'shape': \((\d*(?:, ?\d+)*,?)\), \}\s*$")


def _parse_fast_npy_header(value):
    """``(dtype, shape, header_end)`` of a standard-form ``np.save`` v1
    payload prefix, or ``None`` when the header is not machine-generated
    v1 (fortran order, object dtype, hand-crafted). ``value`` is any
    sliceable buffer (bytes or memoryview)."""
    # magic \x93NUMPY, version (1,0), little-endian u2 header length
    if len(value) < 10 or bytes(value[:8]) != b'\x93NUMPY\x01\x00':
        return None
    hlen = value[8] | (value[9] << 8)
    header_end = 10 + hlen
    m = _NPY_FAST_HEADER.match(value[10:header_end])
    if m is None:
        return None
    dtype = np.dtype(m.group(1).decode())
    if dtype.hasobject:          # pickled payload — np.load territory
        return None
    shape_src = m.group(2)
    shape = tuple(int(p) for p in shape_src.replace(b' ', b'').split(b',') if p) \
        if shape_src else ()
    return dtype, shape, header_end


def _fast_npy_decode(value):
    """Decode an ``np.save`` payload without ast-based header parsing;
    returns None when the payload is not in the standard v1 form.
    ``value`` may be ``bytes`` or any buffer-protocol object (the columnar
    reader passes zero-copy uint8 ndarray views).

    Returns a WRITABLE array (one memcpy), matching what ``np.load`` gives
    consumers on the fallback path — an in-place transform must not work for
    one serialization form and crash for another."""
    if isinstance(value, np.ndarray):
        value = memoryview(value)
    parsed = _parse_fast_npy_header(value)
    if parsed is None:
        return None
    dtype, shape, header_end = parsed
    flat = np.frombuffer(value, dtype=dtype, offset=header_end)
    return flat.reshape(shape).copy()


@register_codec
class NdarrayCodec(DataframeColumnCodec):
    """Lossless ndarray <-> bytes via ``np.save`` (reference ``codecs.py:133-171``)."""

    codec_name = 'ndarray'

    def encode(self, unischema_field, value):
        _check_dtype(unischema_field, value)
        _check_shape(unischema_field, value)
        memfile = io.BytesIO()
        np.save(memfile, value)
        return memfile.getvalue()

    def decode(self, unischema_field, value):
        fast = _fast_npy_decode(value)
        if fast is not None:
            return fast
        memfile = io.BytesIO(value)
        return np.load(memfile)

    def make_cell_decoder(self, unischema_field):
        # _fast_npy_decode and BytesIO both take buffer views directly; no
        # bytes materialization needed.
        def decode_cell(cell):
            fast = _fast_npy_decode(cell)
            if fast is not None:
                return fast
            return np.load(io.BytesIO(cell))
        return decode_cell

    def make_column_decoder(self, unischema_field):
        """Vectorized whole-chunk decode: when every cell is the same
        machine-generated ``np.save`` v1 payload (identical header bytes,
        identical stride — the invariant a fixed-shape column written by
        :meth:`encode` satisfies by construction), the entire chunk decodes
        with ONE header compare and ONE contiguous copy instead of N
        Python calls. Anything else punts to the per-cell loop."""
        shape = unischema_field.shape
        if shape is None or any(s is None for s in shape):
            return None   # wildcard fields keep the per-cell object contract

        def decode_chunk(chunk):
            if chunk.null_count:
                return None
            n = len(chunk)
            offsets, data = split_binary_chunk(chunk)
            stride = int(offsets[1]) - int(offsets[0])
            if stride <= 10 or not bool(
                    np.all(np.diff(offsets) == stride)):
                return None
            block = data[int(offsets[0]):int(offsets[-1])]
            parsed = _parse_fast_npy_header(memoryview(block[:stride]))
            if parsed is None:
                return None
            dtype, cell_shape, header_end = parsed
            expected = int(np.prod(cell_shape, dtype=np.int64)) * dtype.itemsize
            if stride - header_end != expected:
                return None
            grid = block.reshape(n, stride)
            # one vectorized compare proves every cell shares the first
            # cell's exact header (dtype AND shape), so one copy decodes all
            if not bool((grid[:, :header_end] == grid[0, :header_end]).all()):
                return None
            payload = np.ascontiguousarray(grid[:, header_end:])
            if not payload.flags.writeable:
                # a 1-row chunk's payload slice is already contiguous, so
                # ascontiguousarray returns the read-only arrow-buffer view
                # itself; the per-cell path promises WRITABLE arrays
                payload = payload.copy()
            if not expected:      # zero-size cells: nothing to reinterpret
                return np.empty((n,) + cell_shape, dtype=dtype)
            return payload.view(dtype).reshape((n,) + cell_shape)
        return decode_chunk

    def device_decode_unsupported_reason(self, unischema_field):
        """Eligible when the stored layout is statically provable: fixed
        shape (every cell shares one ``np.save`` header), non-nullable
        (the raw grid has no slot for missing cells), plain little-endian
        numeric/bool dtype (``lax.bitcast_convert_type`` reinterprets
        native-order bytes only)."""
        import sys
        shape = unischema_field.shape
        if shape is None or any(s is None for s in shape):
            return 'wildcard shape: cells do not share one np.save header'
        if unischema_field.nullable:
            return 'nullable field: the raw grid has no missing-cell slot'
        try:
            dtype = np.dtype(unischema_field.numpy_dtype)
        except TypeError:
            return 'field dtype is not a numpy dtype'
        if dtype.kind not in 'biuf':
            return 'dtype kind {!r} is not device-representable'.format(
                dtype.kind)
        if dtype.itemsize > 1 and (dtype.str[0] == '>'
                                   or sys.byteorder != 'little'):
            return 'big-endian payload: device bitcast is little-endian'
        return None

    def arrow_type(self, unischema_field):
        return pa.binary()


@register_codec
class ArrowListCodec(DataframeColumnCodec):
    """Numeric ndarrays stored as **native arrow list columns** instead of
    opaque ``np.save`` bytes.

    TPU-first design, no reference analogue: with values living in arrow's own
    layout, the columnar reader decodes an entire row group with zero Python
    per row (``flatten().to_numpy().reshape``), which matters for token/embed
    pipelines feeding accelerators. Requires a numeric dtype; the shape may
    contain wildcards only if it is 1-D (arrow lists are variable-length).
    """

    codec_name = 'arrow_list'

    def encode(self, unischema_field, value):
        value = np.asarray(value)
        _check_dtype(unischema_field, value)
        _check_shape(unischema_field, value)
        return value.ravel()

    def decode(self, unischema_field, value):
        arr = np.asarray(value, dtype=np.dtype(unischema_field.numpy_dtype))
        shape = unischema_field.shape
        if shape and all(s is not None for s in shape):
            return arr.reshape(shape)
        return arr

    def arrow_type(self, unischema_field):
        dtype = np.dtype(unischema_field.numpy_dtype)
        if dtype.kind not in 'biuf':
            raise ValueError('ArrowListCodec requires a numeric dtype; field '
                             '{!r} has {}'.format(unischema_field.name, dtype))
        shape = unischema_field.shape
        if shape and any(s is None for s in shape) and len(shape) != 1:
            raise ValueError('ArrowListCodec wildcard shapes must be 1-D; field '
                             '{!r} has shape {}'.format(unischema_field.name, shape))
        return pa.list_(pa.from_numpy_dtype(dtype))


@register_codec
class CompressedNdarrayCodec(DataframeColumnCodec):
    """Zlib-compressed ndarray via ``np.savez_compressed`` (reference ``codecs.py:174-212``)."""

    codec_name = 'compressed_ndarray'

    def encode(self, unischema_field, value):
        _check_dtype(unischema_field, value)
        _check_shape(unischema_field, value)
        memfile = io.BytesIO()
        np.savez_compressed(memfile, arr=value)
        return memfile.getvalue()

    def decode(self, unischema_field, value):
        memfile = io.BytesIO(value)
        return np.load(memfile)['arr']

    def make_cell_decoder(self, unischema_field):
        def decode_cell(cell):   # BytesIO accepts buffer views directly
            return np.load(io.BytesIO(cell))['arr']
        return decode_cell

    def device_decode_unsupported_reason(self, unischema_field):
        """zlib streams stay a host decode: there is no jittable inflate.
        The device-eligible route for compressed stores is an ETL-time
        repack to the raw ``NdarrayCodec`` layout
        (``etl/repack.py::repack_to_ndarray_codec``)."""
        return ('zlib inflate has no device path — repack the store to '
                'NdarrayCodec via etl.repack to make it device-eligible')

    def arrow_type(self, unischema_field):
        return pa.binary()


@register_codec
class CompressedImageCodec(DataframeColumnCodec):
    """png/jpeg image compression via OpenCV (reference ``codecs.py:58-130``).

    Values are uint8 (or uint16 for png) HxW or HxWx3 arrays in **RGB** channel
    order; cv2's BGR convention is converted at the codec boundary exactly as the
    reference does (``codecs.py:99-103,117-121``).
    """

    codec_name = 'compressed_image'

    def __init__(self, image_codec='png', quality=80):
        if image_codec not in ('png', 'jpeg', 'jpg'):
            raise ValueError('image_codec must be png or jpeg, got {!r}'.format(image_codec))
        self._image_codec = '.' + image_codec
        self._quality = int(quality)

    @property
    def image_codec(self):
        return self._image_codec[1:]

    @property
    def quality(self):
        return self._quality

    def encode(self, unischema_field, value):
        import cv2
        _check_dtype(unischema_field, value)
        _check_shape(unischema_field, value)
        image_bgr_or_gray = value
        if value.ndim == 3 and value.shape[2] == 3:
            image_bgr_or_gray = cv2.cvtColor(value, cv2.COLOR_RGB2BGR)
        if self._image_codec in ('.jpeg', '.jpg'):
            params = [int(cv2.IMWRITE_JPEG_QUALITY), self._quality]
        else:
            params = []
        ok, contents = cv2.imencode(self._image_codec, image_bgr_or_gray, params)
        if not ok:
            raise ValueError('cv2.imencode failed for field {!r}'.format(unischema_field.name))
        return contents.tobytes()

    def decode(self, unischema_field, value):
        return self._decode_flag(unischema_field, value, None)

    def make_cell_decoder(self, unischema_field):
        # Hot-loop variant of decode(): cv2 attribute lookups and the flag
        # resolve once per column; ndarray cell views feed imdecode directly
        # (it takes any uint8 array, so no frombuffer for the common case).
        import cv2
        imdecode, cvt_color = cv2.imdecode, cv2.cvtColor
        bgr2rgb, flag = cv2.COLOR_BGR2RGB, cv2.IMREAD_UNCHANGED
        name = unischema_field.name

        def decode_cell(cell):
            if not isinstance(cell, np.ndarray):
                cell = np.frombuffer(cell, np.uint8)
            img = imdecode(cell, flag)
            if img is None:
                raise ValueError(
                    'cv2.imdecode failed for field {!r}'.format(name))
            if img.ndim == 3 and img.shape[2] == 3:
                return cvt_color(img, bgr2rgb)
            return img
        return decode_cell

    def make_column_decoder(self, unischema_field):
        """Batched buffer-splitting decode: the chunk's cells are sliced
        from the arrow data buffer in one offsets pass, the only per-cell
        work is the actual image decompression (a C-level ``map`` over
        ``cv2.imdecode`` — no Python loop machinery between cells), and
        the decoded frames assemble straight into one dense array.
        Mixed-geometry or corrupt chunks punt to the per-cell loop, which
        owns the exact error/quarantine semantics."""
        import cv2
        imdecode, cvt_color = cv2.imdecode, cv2.cvtColor
        bgr2rgb, flag = cv2.COLOR_BGR2RGB, cv2.IMREAD_UNCHANGED

        def decode_chunk(chunk):
            if chunk.null_count:
                return None
            offsets, data = split_binary_chunk(chunk)
            cells = list(map(data.__getitem__,
                             map(slice, offsets[:-1].tolist(),
                                 offsets[1:].tolist())))
            decoded = list(map(imdecode, cells, repeat(flag)))
            # a failed imdecode must surface as the per-cell path's
            # field-named ValueError at the exact row: punt, don't guess
            if any(img is None for img in decoded):
                return None
            first = decoded[0]
            if first.ndim == 3 and first.shape[2] == 3:
                # cvtColor raising on a mixed gray/color chunk propagates
                # to the caller, which retries per cell (same punt)
                decoded = list(map(cvt_color, decoded, repeat(bgr2rgb)))
                first = decoded[0]
            out = np.empty((len(decoded),) + first.shape, first.dtype)
            for i, img in enumerate(decoded):
                out[i] = img      # shape mismatch raises -> per-cell retry
            return out
        return decode_chunk

    def validate_decode_hint(self, unischema_field, min_shape=None,
                             scale=None, allow_upscale=False):
        """Construction-time value check for :meth:`decode_scaled` kwargs —
        bad hint VALUES must fail at the factory, not per-cell in workers."""
        if min_shape is not None and scale is not None:
            raise ValueError("decode hint takes 'min_shape' or 'scale', "
                             'not both')
        if scale is not None and scale not in (2, 4, 8):
            raise ValueError('scale must be one of 2, 4, 8 (jpeg DCT '
                             'denominators), got {!r}'.format(scale))
        if min_shape is not None:
            import operator
            try:        # any 2-sequence of integral values (tuple/list/ndarray)
                vals = [operator.index(s) for s in min_shape]
                ok = len(vals) == 2 and all(v > 0 for v in vals)
            except TypeError:
                ok = False
            if not ok:
                raise ValueError(
                    'min_shape must be a (height, width) pair of positive '
                    'ints, got {!r}'.format(min_shape))

    def _scalable_payload(self, unischema_field) -> bool:
        """Payload-level scalability: jpeg only (png REDUCED rounds instead of
        ceiling), uint8 only, gray or 3-channel. Spatial dims may be unknown
        (an explicit ``scale`` hint does not need them)."""
        shape = unischema_field.shape
        return (self._image_codec in ('.jpg', '.jpeg')
                and np.dtype(unischema_field.numpy_dtype) == np.uint8
                and shape is not None and len(shape) >= 2
                and (len(shape) == 2 or (len(shape) == 3 and shape[2] == 3)))

    def can_scale(self, unischema_field) -> bool:
        """Whether a ``min_shape`` hint can ever reduce this field: a scalable
        payload WITH known spatial dims (the denominator choice needs them)."""
        shape = unischema_field.shape
        return (self._scalable_payload(unischema_field)
                and all(s is not None for s in shape[:2]))

    def _reduced_flag(self, unischema_field, denom):
        import cv2
        color = len(unischema_field.shape) > 2
        return {2: cv2.IMREAD_REDUCED_COLOR_2 if color else cv2.IMREAD_REDUCED_GRAYSCALE_2,
                4: cv2.IMREAD_REDUCED_COLOR_4 if color else cv2.IMREAD_REDUCED_GRAYSCALE_4,
                8: cv2.IMREAD_REDUCED_COLOR_8 if color else cv2.IMREAD_REDUCED_GRAYSCALE_8}[denom]

    def decode_scaled(self, unischema_field, value, min_shape=None,
                      scale=None, allow_upscale=False):
        """Decode at reduced resolution when the consumer will downscale
        anyway — the jpeg DCT denominator (2/4/8) is applied during entropy
        decode, substantially cheaper than decode-then-resize. TPU-first
        addition (the reference always decodes at full resolution); same
        trick as torchvision's ``decode_jpeg(..., size=...)``.

        Two hint forms:

        - ``min_shape=(h, w)``: picks the largest denominator whose output
          still covers ``min_shape`` (or, with ``allow_upscale``, stays
          within one halving of it). Needs the field's stored shape to be
          fully known; otherwise falls back to a full decode.
        - ``scale=2|4|8``: applies that denominator unconditionally — the
          form for variable-shape fields (e.g. raw ImageNet), where the
          caller asserts the reduced size still covers its resize target.

        Either form silently falls back to a full decode for payloads that
        cannot scale (png, uint16, RGBA)."""
        if scale is not None:
            if not self._scalable_payload(unischema_field):
                return self.decode(unischema_field, value)
            return self._decode_flag(unischema_field, value,
                                     self._reduced_flag(unischema_field, scale))
        shape = unischema_field.shape
        if min_shape is None or not self.can_scale(unischema_field):
            return self.decode(unischema_field, value)
        min_h, min_w = int(min_shape[0]), int(min_shape[1])
        chosen = None
        for denom in (8, 4, 2):
            h, w = -(-shape[0] // denom), -(-shape[1] // denom)
            if (h >= min_h and w >= min_w) or \
                    (allow_upscale and 2 * h >= min_h and 2 * w >= min_w):
                chosen = self._reduced_flag(unischema_field, denom)
                break
        return self._decode_flag(unischema_field, value, chosen)

    def _decode_flag(self, unischema_field, value, flag):
        import cv2
        image_bgr_or_gray = cv2.imdecode(
            np.frombuffer(value, dtype=np.uint8),
            cv2.IMREAD_UNCHANGED if flag is None else flag)
        if image_bgr_or_gray is None:
            raise ValueError('cv2.imdecode failed for field {!r}'.format(unischema_field.name))
        if image_bgr_or_gray.ndim == 3 and image_bgr_or_gray.shape[2] == 3:
            return cv2.cvtColor(image_bgr_or_gray, cv2.COLOR_BGR2RGB)
        return image_bgr_or_gray

    def arrow_type(self, unischema_field):
        return pa.binary()

    def to_json_dict(self):
        return {'codec': self.codec_name, 'image_codec': self.image_codec,
                'quality': self._quality}

    @classmethod
    def from_json_dict(cls, d):
        return cls(image_codec=d.get('image_codec', 'png'), quality=d.get('quality', 80))

    def __repr__(self):
        return 'CompressedImageCodec({!r}, quality={})'.format(self.image_codec, self._quality)


@register_codec
class ScalarCodec(DataframeColumnCodec):
    """Stores a scalar natively in the column, with dtype-directed casts.

    The reference variant (``codecs.py:215-271``) is parameterized by a Spark SQL
    type; ours is parameterized by a numpy dtype (defaulting to the field's own
    dtype) and maps it to an arrow type via ``pa.from_numpy_dtype``.
    """

    codec_name = 'scalar'

    def __init__(self, numpy_dtype=None):
        self._dtype = np.dtype(numpy_dtype) if numpy_dtype is not None else None

    def _storage_dtype(self, unischema_field):
        return self._dtype if self._dtype is not None else np.dtype(unischema_field.numpy_dtype)

    def encode(self, unischema_field, value):
        if isinstance(value, np.ndarray) and value.ndim > 0:
            raise TypeError('Field {!r} is scalar but got an array of shape {}'.format(
                unischema_field.name, value.shape))
        dtype = self._storage_dtype(unischema_field)
        if dtype.kind in ('U', 'S', 'O'):
            return value if isinstance(value, (str, bytes)) else str(value)
        if dtype.kind == 'b':
            return bool(value)
        # .item() converts numpy scalars to native python so arrow accepts them.
        return np.asarray(value).astype(dtype).item()

    def decode(self, unischema_field, value):
        dtype = np.dtype(unischema_field.numpy_dtype)
        if dtype.kind in ('U', 'S', 'O'):
            return value
        return dtype.type(value)

    def make_column_decoder(self, unischema_field):
        """Pass-through fields (string/bytes/object dtypes, whose
        :meth:`decode` returns the stored value unchanged) decode a binary
        chunk with one ``to_pylist`` call instead of a per-cell
        view->bytes->decode loop. Numeric-from-binary fields keep the
        per-cell path (its contract is one numpy scalar per cell)."""
        try:
            kind = np.dtype(unischema_field.numpy_dtype).kind
        except TypeError:   # a non-dtype-able declaration: per-cell decides
            return None
        if kind not in ('U', 'S', 'O'):
            return None

        def decode_chunk(chunk):
            if chunk.null_count:
                return None
            out = np.empty(len(chunk), dtype=object)
            out[:] = chunk.to_pylist()
            return out
        return decode_chunk

    def arrow_type(self, unischema_field):
        dtype = self._storage_dtype(unischema_field)
        if dtype.kind in ('U', 'O'):
            return pa.string()
        if dtype.kind == 'S':
            return pa.binary()
        if dtype.kind == 'M':  # datetime64
            return pa.timestamp('ns')
        return pa.from_numpy_dtype(dtype)

    def to_json_dict(self):
        d = {'codec': self.codec_name}
        if self._dtype is not None:
            d['dtype'] = self._dtype.str
        return d

    @classmethod
    def from_json_dict(cls, d):
        return cls(numpy_dtype=d.get('dtype'))

    def __repr__(self):
        return 'ScalarCodec({})'.format(self._dtype if self._dtype is not None else '')


def build_decode_overrides(schema, decode_hints):
    """``{field: callable(value)}`` from reader-level decode hints.

    ``decode_hints`` maps field name -> kwargs for the codec's
    ``decode_scaled`` (e.g. ``{'image': {'min_shape': (112, 112)}}``).
    Validates at reader construction that every hinted field exists and its
    codec supports scaled decoding. Built inside workers from the plain hint
    dicts so nothing unpicklable crosses the pool boundary."""
    if not decode_hints:
        return {}
    overrides = {}
    for name, hint in decode_hints.items():
        field = schema.fields.get(name)
        if field is None:
            raise ValueError('decode_hints names unknown field {!r}'.format(name))
        scaled = getattr(field.codec, 'decode_scaled', None)
        if scaled is None:
            raise ValueError(
                'decode_hints for field {!r}: codec {!r} has no decode_scaled'
                .format(name, type(field.codec).__name__))
        try:      # typo'd kwargs must fail here, not per-cell inside workers
            inspect.signature(scaled).bind(field, b'', **hint)
        except TypeError as e:
            raise ValueError(
                'decode_hints for field {!r} do not match {}.decode_scaled: {}'
                .format(name, type(field.codec).__name__, e))
        validate = getattr(field.codec, 'validate_decode_hint', None)
        if validate is not None:  # value-level check (types/arity of kwargs)
            validate(field, **hint)
        def _decode(value, _fn=scaled, _field=field, _kw=dict(hint)):
            return _fn(_field, value, **_kw)
        overrides[name] = _decode
    return overrides
