"""O(1) exact checkpoint/resume for NGram window pipelines.

Round-3 verdict ("what's weak" #6): streaming NGram pipelines could only
resume via replay fallback (``checkpoint.py``) because the queue-based reader
is not deterministically addressable. This module closes that gap the same
way :mod:`petastorm_tpu.indexed` did for row pipelines: make the unit of
addressing — here a *window* — a pure function of ``(dataset, ngram, seed,
epoch, batch)``.

The window universe is deterministic: within each row group, rows sort by the
timestamp field and a window starts at every position whose consecutive
timestamp deltas all stay within ``delta_threshold`` (with
``timestamp_overlap=False``, a greedy left-to-right selection of
non-overlapping windows — exactly ``NGram.form_ngram_dicts``'s semantics,
reference ``petastorm/ngram.py:225-270``). The index is built once from a
timestamp-column-only scan; each batch then assembles through ONE fused
:meth:`IndexedDatasetReader.gather` over the rows of every offset (a window
never crosses a row group, so all timesteps share the same row-group LRU
cache entries).

Batches arrive **pre-collated** in the JAX adapter's NGram layout:
``{offset: {field: (B, ...) array}}`` — the same shape
``JaxDataLoader`` produces for streaming NGram readers.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional

import numpy as np

from petastorm_tpu.errors import NoDataAvailableError
from petastorm_tpu.indexed import IndexedBatchLoader, IndexedDatasetReader
from petastorm_tpu.ngram import NGram, valid_window_starts
from petastorm_tpu.transform import apply_columnar_transform, transform_schema

logger = logging.getLogger(__name__)


def _scan_timestamps(dataset: IndexedDatasetReader, ts_name: str,
                     predicate=None) -> List[tuple]:
    """Per piece: ``(timestamp column, survivor local row indices or None)``,
    one pass through :meth:`IndexedDatasetReader.scan_columns`.

    With a ``predicate``, its fields are read alongside the timestamps and
    rows it rejects are dropped BEFORE window formation — the streaming NGram
    semantics (``row_worker._load_rows_with_predicate`` filters rows, then
    ``form_ngram_dicts`` scans the survivors), so filtering can create
    timestamp gaps that ``delta_threshold`` then rejects."""
    from petastorm_tpu.readers.columnar_worker import (
        predicate_row_mask, validate_predicate_fields)

    pred_fields = (validate_predicate_fields(predicate, dataset.full_schema)
                   if predicate is not None else [])
    out: List[tuple] = []
    for _, cols, n in dataset.scan_columns({ts_name} | set(pred_fields)):
        ts = cols[ts_name]
        if predicate is None:
            out.append((ts, None))
        else:
            mask = predicate_row_mask(predicate, pred_fields, cols, n)
            idx = np.nonzero(mask)[0].astype(np.int64)
            out.append((ts[idx], idx))
    return out


class IndexedNGramLoader(IndexedBatchLoader):
    """Deterministic NGram window batches with O(1) exact resume.

    Yields ``{offset: {field: (batch_size, ...) array}}`` batches; the
    stream is a pure function of ``(dataset, ngram, seed)``, so
    ``state_dict()`` / ``load_state_dict()`` restore byte-exactly with any
    worker count — the capability the streaming NGram reader can only
    approximate by replay.

    Shuffling operates at WINDOW granularity (each window stays internally
    timestamp-consecutive); ``shuffle_window_groups`` windows of row groups
    shuffle together, mirroring the row loader.
    """

    def __init__(self, dataset: IndexedDatasetReader, ngram: NGram,
                 batch_size: int, **kwargs):
        if kwargs.get('pad_spec') is not None:
            # no NGram path supports pad_spec anywhere (window fields are
            # fixed-shape per timestep) — don't suggest a fallback
            raise ValueError('IndexedNGramLoader does not support pad_spec '
                             '(NGram window fields are fixed-shape per '
                             'timestep)')
        # predicate/transform run at WINDOW addressing / assembly here, not
        # through the row superclass (whose row-level selection would fight
        # the window permutation): the predicate fixes the surviving ROW set
        # during the index scan (streaming semantics — windows form over
        # survivors), the columnar transform runs per assembled batch.
        predicate = kwargs.pop('predicate', None)
        self._window_transform = kwargs.pop('transform_spec', None)
        ngram.resolve_regex_field_names(dataset.full_schema)
        self._ngram = ngram
        # Read only the NGram's field universe: without this, read_piece
        # would decode — and every gather would batch-materialize — every
        # column of a wide store, only for the per-timestep filter to drop
        # them. The narrowing stays ON THE LOADER (an explicit column list
        # threaded through gather), so a dataset shared with other loaders
        # keeps its schema intact.
        used = [n for n in ngram.get_all_field_names()
                if n in dataset.full_schema.fields]
        self._read_fields = tuple(used)
        view = dataset.full_schema.create_schema_view(
            [dataset.full_schema.fields[n] for n in used])
        if self._window_transform is not None:
            # timestep views filter on POST-transform names; the transform
            # itself receives the full read universe per gathered batch. The
            # window universe is fixed at index build, so the transform must
            # not alter the timestamp ordering (it runs after addressing).
            self._transformed_schema = transform_schema(
                view, self._window_transform)
        else:
            self._transformed_schema = view
        visible = set(self._transformed_schema.fields)
        self._offsets, self._base_offset, self._fields_at = \
            ngram.timestep_layout(visible)
        # fused-gather slices are views into the (n_offsets*B, ...) base
        # array; a field exposed at every offset covers its base entirely,
        # but a field exposed at FEW offsets (an image at offset 0 of a long
        # window) would pin n_offsets/1 times the useful memory for the
        # batch's buffered lifetime — those slices are copied out instead
        present_count: Dict[str, int] = {}
        for names in self._fields_at.values():
            for n in names:
                present_count[n] = present_count.get(n, 0) + 1
        self._copy_fields = {n for n, c in present_count.items()
                             if c < len(self._offsets)}
        span = ngram.length

        scan = _scan_timestamps(dataset, ngram.timestamp_field_name,
                                predicate=predicate)
        win_starts: List[np.ndarray] = []
        counts = []
        # sorted-position -> global row, flattened over pieces: entry
        # row_offsets[p] + s is the global row index of the s-th
        # timestamp-sorted SURVIVING row of piece p (all rows survive without
        # a predicate). One vectorized lookup replaces the per-window Python
        # loops of the round-4 assembler.
        pos_to_row = np.empty(dataset.total_rows, np.int64)
        for p, (ts, survivors) in enumerate(scan):
            order = np.argsort(ts, kind='stable')
            lo = dataset.row_offsets[p]
            if survivors is None:
                pos_to_row[lo:lo + len(ts)] = lo + order
            else:
                pos_to_row[lo:lo + len(ts)] = lo + survivors[order]
            starts = valid_window_starts(ts[order], span,
                                         ngram.delta_threshold,
                                         ngram.timestamp_overlap)
            win_starts.append(starts)
            counts.append(len(starts))
        self._pos_to_row = pos_to_row
        counts = np.asarray(counts, np.int64)
        win_offsets = np.concatenate([[0], np.cumsum(counts)])
        # global window id -> (piece id, ts-sorted start position): flat
        # arrays so _assemble never loops in Python
        self._win_piece = np.repeat(np.arange(len(counts), dtype=np.int64),
                                    counts)
        self._flat_starts = (np.concatenate(win_starts) if win_starts
                             else np.empty(0, np.int64))

        super().__init__(dataset, batch_size, **kwargs)
        # public attrs must report the ACTIVE config (super saw neither
        # kwarg): the window loader owns predicate/transform handling, and
        # .schema is the post-transform view of the NGram's read universe
        self.predicate = predicate
        self.transform_spec = self._window_transform
        self.schema = self._transformed_schema
        # re-point the deterministic addressing at the WINDOW universe: the
        # permutation shuffles windows (grouped by piece), not rows
        self.total_rows = int(win_offsets[-1])       # total windows
        self._perm_offsets = win_offsets
        self.batches_per_epoch = self.total_rows // batch_size
        if self.batches_per_epoch == 0:
            raise NoDataAvailableError(
                'Dataset yields {} NGram windows < batch_size {}'.format(
                    self.total_rows, batch_size))

    @property
    def total_windows(self) -> int:
        return self.total_rows

    def _assemble(self, epoch: int, batch: int) -> Dict[int, Dict[str, np.ndarray]]:
        """One fused gather per batch: the rows of ALL offsets share row
        groups by construction (a window never crosses a piece), so gathering
        the ``(n_offsets, B)`` row matrix in one call amortizes the per-gather
        searchsorted/unique/cache-lock overhead that serialized the round-4
        per-offset loop (the 83.75%-overlap stall in BENCH_r04)."""
        win_ids = self._batch_rows(epoch, batch)     # global window indices
        piece_ids = self._win_piece[win_ids]
        # global ts-sorted position of each window's base row
        base_pos = self._dataset.row_offsets[piece_ids] + self._flat_starts[win_ids]
        rel = np.asarray(self._offsets, np.int64) - self._base_offset
        rows = self._pos_to_row[(base_pos[None, :] + rel[:, None]).ravel()]
        cols = self._dataset.gather(rows, self._read_fields)
        if self._window_transform is not None:
            # one columnar transform over the whole (n_offsets*B) gather —
            # row-wise by contract, so transforming the stacked offsets once
            # equals the streaming path's per-row transform-then-window order
            cols = apply_columnar_transform(self._window_transform,
                                            self._transformed_schema, cols)
        n = len(win_ids)
        out: Dict[int, Dict[str, np.ndarray]] = {}
        for i, offset in enumerate(self._offsets):
            sl = slice(i * n, (i + 1) * n)
            out[int(offset)] = {
                name: (cols[name][sl].copy() if name in self._copy_fields
                       else cols[name][sl])
                for name in self._fields_at[offset] if name in cols}
        return out


class ShardedIndexedNGramLoader(IndexedNGramLoader):
    """Deterministic GSPMD NGram batches: O(1) exact resume + global
    ``jax.Array`` window batches over a mesh.

    ``batch_size`` is the GLOBAL window batch. Every process derives the
    same (seed, epoch, batch)-addressed window permutation and assembles
    only the windows at the global positions its mesh devices own; each
    timestep's columns lift into global arrays via
    ``jax.make_array_from_process_local_data`` (the nested ``{offset:
    {field: ...}}`` layout stages per offset). All hosts stay in lockstep by
    construction — the schedule is a pure function of the cursor, so no
    per-step readiness collective is needed (unlike the streaming
    ``ShardedJaxLoader``)."""

    def __init__(self, dataset: IndexedDatasetReader, ngram: NGram,
                 batch_size: int, mesh, batch_axis: str = 'data', **kwargs):
        from petastorm_tpu.indexed import sharded_batch_setup
        sharding, local_positions = sharded_batch_setup(mesh, batch_axis,
                                                        batch_size)
        super().__init__(dataset, ngram, batch_size, **kwargs)
        self.mesh = mesh
        self.batch_axis = batch_axis
        self._sharding = sharding
        self._local_positions = local_positions

    def _batch_rows(self, epoch: int, batch: int) -> np.ndarray:
        return super()._batch_rows(epoch, batch)[self._local_positions]

    def __iter__(self):
        from petastorm_tpu.jax_utils import stage_to_global
        for batch in super().__iter__():
            yield {off: stage_to_global(cols, self._sharding)
                   for off, cols in batch.items()}


def make_indexed_ngram_loader(dataset_url, ngram: NGram, batch_size: int,
                              num_epochs: int = 1, seed: int = 0,
                              shuffle: bool = True,
                              shuffle_window_groups: int = 4,
                              workers_count: int = 4,
                              prefetch_batches: int = 8,
                              storage_options=None,
                              cache_groups=None, mesh=None,
                              batch_axis: str = 'data',
                              predicate=None,
                              transform_spec=None) -> IndexedNGramLoader:
    """Factory: deterministic, O(1)-resumable NGram window batches — host
    numpy batches, or global ``jax.Array`` batches over ``mesh``
    (``batch_size`` is then the global window batch).

    ``predicate`` drops rows BEFORE window formation during the index scan
    (windows form over the survivors, exactly like the streaming NGram
    reader's worker pushdown); ``transform_spec`` applies the columnar
    transform contract per assembled batch (it must not alter the timestamp
    field — the window universe is fixed at index build). Both preserve the
    pure-function-of-cursor resume guarantee.

    ::

        loader = make_indexed_ngram_loader(url, ngram, batch_size=64,
                                           num_epochs=10, seed=0)
        loader.load_state_dict(saved)        # exact mid-epoch restore
        for batch in loader:                 # {offset: {field: (B, ...)}}
            ...
    """
    dataset = IndexedDatasetReader(
        dataset_url, storage_options=storage_options,
        cache_groups=(cache_groups if cache_groups is not None
                      else max(8, shuffle_window_groups + workers_count)))
    kwargs = dict(num_epochs=num_epochs, seed=seed, shuffle=shuffle,
                  shuffle_window_groups=shuffle_window_groups,
                  workers_count=workers_count,
                  prefetch_batches=prefetch_batches,
                  predicate=predicate, transform_spec=transform_spec)
    if mesh is None:
        return IndexedNGramLoader(dataset, ngram, batch_size, **kwargs)
    return ShardedIndexedNGramLoader(dataset, ngram, batch_size, mesh=mesh,
                                     batch_axis=batch_axis, **kwargs)
