"""FIFO of arrow data re-chunked into fixed-size tables.

Reference parity: ``petastorm/pyarrow_helpers/batching_table_queue.py:20-79``.
Put arbitrarily-sized ``pa.Table``s/RecordBatches in; get exactly
``batch_size``-row tables out (zero-copy slices/concats).
"""

from __future__ import annotations

import collections

import pyarrow as pa


class BatchingTableQueue(object):
    def __init__(self, batch_size: int):
        if batch_size < 1:
            raise ValueError('batch_size must be positive')
        self._batch_size = batch_size
        self._chunks = collections.deque()
        self._rows = 0

    def put(self, table) -> None:
        if isinstance(table, pa.RecordBatch):
            table = pa.Table.from_batches([table])
        if table.num_rows:
            self._chunks.append(table)
            self._rows += table.num_rows

    def empty(self) -> bool:
        """True when fewer than ``batch_size`` rows are buffered."""
        return self._rows < self._batch_size

    def get(self) -> pa.Table:
        """Pop exactly ``batch_size`` rows as one table."""
        if self.empty():
            raise IndexError('Not enough rows buffered; check empty() first')
        need = self._batch_size
        parts = []
        while need > 0:
            chunk = self._chunks[0]
            if chunk.num_rows <= need:
                parts.append(self._chunks.popleft())
                need -= chunk.num_rows
            else:
                parts.append(chunk.slice(0, need))
                self._chunks[0] = chunk.slice(need)
                need = 0
        self._rows -= self._batch_size
        return pa.concat_tables(parts) if len(parts) > 1 else parts[0]
