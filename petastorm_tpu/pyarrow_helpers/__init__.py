"""pyarrow utilities (reference ``petastorm/pyarrow_helpers/``)."""

from petastorm_tpu.pyarrow_helpers.batching_table_queue import BatchingTableQueue

__all__ = ['BatchingTableQueue']
