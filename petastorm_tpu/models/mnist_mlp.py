"""Minimal MLP classifier: the end-to-end "aha" slice of SURVEY §7.6
(hello_world schema → parquet → make_reader → jnp batches → train step).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def init(rng, input_dim: int = 784, hidden: int = 512, num_classes: int = 10,
         dtype=jnp.float32):
    k1, k2 = jax.random.split(rng)
    scale1 = (2.0 / input_dim) ** 0.5
    scale2 = (2.0 / hidden) ** 0.5
    return {
        'w1': (jax.random.normal(k1, (input_dim, hidden)) * scale1).astype(dtype),
        'b1': jnp.zeros((hidden,), dtype),
        'w2': (jax.random.normal(k2, (hidden, num_classes)) * scale2).astype(dtype),
        'b2': jnp.zeros((num_classes,), dtype),
    }


def forward(params, images):
    """images: (B, 784) float32 in [0, 1] → logits (B, 10)."""
    h = jax.nn.relu(images @ params['w1'] + params['b1'])
    return h @ params['w2'] + params['b2']


def loss_fn(params, images, labels):
    logits = forward(params, images)
    logp = jax.nn.log_softmax(logits)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1).squeeze(-1)
    return jnp.mean(nll)


@jax.jit
def train_step(params, images, labels, lr=1e-3):
    loss, grads = jax.value_and_grad(loss_fn)(params, images, labels)
    params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
    return params, loss


@jax.jit
def accuracy(params, images, labels):
    return jnp.mean(jnp.argmax(forward(params, images), axis=-1) == labels)
