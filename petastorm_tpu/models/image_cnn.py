"""Compact residual CNN for image classification, written MXU-first.

Convs run in NHWC with bfloat16 compute (params float32), channel counts are
multiples of 8/128 where it matters, and the whole step jits to a single XLA
program — the image-side analogue of the transformer flagship. Used by
``examples/imagenet`` and the image-decode north-star bench.
"""

from __future__ import annotations

import math
from typing import Any, Dict

import jax
import jax.numpy as jnp


def _conv(x, w, stride=1):
    return jax.lax.conv_general_dilated(
        x, w.astype(x.dtype), window_strides=(stride, stride), padding='SAME',
        dimension_numbers=('NHWC', 'HWIO', 'NHWC'))


def _norm(x, scale, bias):
    # GroupNorm(1) == LayerNorm over (H, W, C): batch-size independent, no
    # running stats to shard — friendlier than BatchNorm under dp sharding.
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=(1, 2, 3), keepdims=True)
    var = jnp.var(x32, axis=(1, 2, 3), keepdims=True)
    out = (x32 - mean) * jax.lax.rsqrt(var + 1e-5)
    return (out * scale + bias).astype(x.dtype)


def init(rng, num_classes: int = 1000, widths=(64, 128, 256),
         blocks_per_stage: int = 2) -> Dict[str, Any]:
    """Parameters for a ResNet-style net: stem conv + ``len(widths)`` stages of
    ``blocks_per_stage`` residual blocks + linear head."""
    def conv_w(key, kh, kw, cin, cout):
        scale = math.sqrt(2.0 / (kh * kw * cin))
        return jax.random.normal(key, (kh, kw, cin, cout), jnp.float32) * scale

    keys = iter(jax.random.split(rng, 4 + 4 * len(widths) * blocks_per_stage))
    params: Dict[str, Any] = {
        'stem': conv_w(next(keys), 7, 7, 3, widths[0]),
        'stem_scale': jnp.ones((widths[0],), jnp.float32),
        'stem_bias': jnp.zeros((widths[0],), jnp.float32),
        'stages': [],
    }
    cin = widths[0]
    for width in widths:
        stage = []
        for b in range(blocks_per_stage):
            block = {
                'conv1': conv_w(next(keys), 3, 3, cin, width),
                'scale1': jnp.ones((width,), jnp.float32),
                'bias1': jnp.zeros((width,), jnp.float32),
                'conv2': conv_w(next(keys), 3, 3, width, width),
                'scale2': jnp.ones((width,), jnp.float32),
                'bias2': jnp.zeros((width,), jnp.float32),
            }
            if cin != width:
                block['proj'] = conv_w(next(keys), 1, 1, cin, width)
            stage.append(block)
            cin = width
        params['stages'].append(stage)
    params['head_w'] = jax.random.normal(
        next(keys), (cin, num_classes), jnp.float32) / math.sqrt(cin)
    params['head_b'] = jnp.zeros((num_classes,), jnp.float32)
    return params


def forward(params, images, dtype=jnp.bfloat16):
    """images (B, H, W, 3) float in [0, 1] → logits (B, num_classes) f32."""
    x = images.astype(dtype)
    x = _conv(x, params['stem'], stride=2)
    x = _norm(x, params['stem_scale'], params['stem_bias'])
    x = jax.nn.relu(x)
    x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 3, 3, 1),
                              (1, 2, 2, 1), 'SAME')
    for s, stage in enumerate(params['stages']):
        for b, block in enumerate(stage):
            stride = 2 if (s > 0 and b == 0) else 1
            h = _conv(x, block['conv1'], stride=stride)
            h = _norm(h, block['scale1'], block['bias1'])
            h = jax.nn.relu(h)
            h = _conv(h, block['conv2'])
            h = _norm(h, block['scale2'], block['bias2'])
            shortcut = x
            if 'proj' in block:
                shortcut = _conv(x, block['proj'], stride=stride)
            elif stride != 1:
                shortcut = x[:, ::stride, ::stride, :]
            x = jax.nn.relu(h + shortcut)
    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))          # global pool
    return x @ params['head_w'] + params['head_b']


def loss_fn(params, images, labels, dtype=jnp.bfloat16):
    logits = forward(params, images, dtype)
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))


def make_train_step(lr: float = 1e-3, dtype=jnp.bfloat16):
    """Jitted SGD step over uint8 NHWC batches (normalization fused in)."""
    @jax.jit
    def step(params, images_u8, labels):
        images = images_u8.astype(jnp.float32) / 255.0
        loss, grads = jax.value_and_grad(loss_fn)(params, images, labels, dtype)
        params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
        return params, loss

    return step
