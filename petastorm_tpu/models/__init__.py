"""Demonstration models fed by the petastorm_tpu data pipeline.

The reference library ships no model code (SURVEY §0) — its examples train
external TF/torch models from the reader. Here the example models are
TPU-native JAX programs wired to the reader + JAX adapter, used by the
benchmarks and the multi-chip dry run:

- ``mnist_mlp`` — the hello-world slice (parquet → reader → jnp batches → MLP).
- ``transformer_lm`` — flagship decoder-only LM with data/tensor/sequence/
  expert parallel shardings over a named mesh; its token pipeline is the NGram
  windowed reader.
"""

from petastorm_tpu.models import mnist_mlp, transformer_lm

__all__ = ['mnist_mlp', 'transformer_lm']
