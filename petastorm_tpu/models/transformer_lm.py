"""Flagship decoder-only transformer LM, designed mesh-first.

Parallelism is expressed entirely through GSPMD shardings over a named mesh
(axes from ``petastorm_tpu.parallel.mesh``): annotate params/activations with
PartitionSpecs, let XLA insert the collectives.

- **dp** ('data'): batch dim of activations.
- **tp** ('model'): Megatron-style column/row parallel attention + MLP —
  wq/wk/wv and w_gate/w_up are column-parallel (output dim sharded), wo and
  w_down row-parallel (input dim sharded); XLA inserts the psum where the
  row-parallel matmul closes.
- **sp** ('seq'): sequence dim of activations; attention runs as ring
  attention (``petastorm_tpu/parallel/ring.py``) under shard_map so k/v chunks
  rotate over ICI instead of being all-gathered.
- **ep** ('expert'): optional MoE FFN with experts sharded one-per-group over
  the expert axis.

Compute dtype is bfloat16 (MXU-native); params and softmax/statistics stay
float32.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from petastorm_tpu.ops.attention import blockwise_attention, flash_attention
from petastorm_tpu.parallel.ring import ring_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 4
    d_ff: int = 2048
    max_seq_len: int = 2048
    n_experts: int = 0            # 0 → dense FFN; >0 → top-1 MoE
    # Per-expert buffer size = ceil(tokens/n_experts * capacity_factor);
    # tokens routed past an expert's capacity are dropped (their residual
    # stream passes through unchanged, Switch-Transformer semantics).
    moe_capacity_factor: float = 1.25
    # Weight of the Switch load-balancing auxiliary loss; 0 disables it.
    moe_aux_weight: float = 0.01
    dtype: Any = jnp.bfloat16
    # 'ring' shards attention over the 'seq' mesh axis; 'flash'/'blockwise'
    # compute full attention locally (XLA all-gathers kv if seq is sharded).
    attention: str = 'blockwise'

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------

def init(rng, config: TransformerConfig) -> Dict:
    """Initialize parameters as a pytree of float32 arrays."""
    def dense(key, fan_in, shape):
        return jax.random.normal(key, shape, jnp.float32) / math.sqrt(fan_in)

    keys = jax.random.split(rng, 2 + config.n_layers)
    c = config
    params = {
        'embed': dense(keys[0], 1, (c.vocab_size, c.d_model)) * 0.02,
        'final_norm': jnp.ones((c.d_model,), jnp.float32),
        'unembed': dense(keys[1], c.d_model, (c.d_model, c.vocab_size)),
        'layers': [],
    }
    for i in range(c.n_layers):
        lk = jax.random.split(keys[2 + i], 8)
        layer = {
            'ln1': jnp.ones((c.d_model,), jnp.float32),
            'wq': dense(lk[0], c.d_model, (c.d_model, c.d_model)),
            'wk': dense(lk[1], c.d_model, (c.d_model, c.d_model)),
            'wv': dense(lk[2], c.d_model, (c.d_model, c.d_model)),
            'wo': dense(lk[3], c.d_model, (c.d_model, c.d_model)),
            'ln2': jnp.ones((c.d_model,), jnp.float32),
        }
        if c.n_experts > 0:
            layer.update({
                'gate': dense(lk[7], c.d_model, (c.d_model, c.n_experts)),
                'w_up': dense(lk[4], c.d_model, (c.n_experts, c.d_model, c.d_ff)),
                'w_gate': dense(lk[5], c.d_model, (c.n_experts, c.d_model, c.d_ff)),
                'w_down': dense(lk[6], c.d_ff, (c.n_experts, c.d_ff, c.d_model)),
            })
        else:
            layer.update({
                'w_up': dense(lk[4], c.d_model, (c.d_model, c.d_ff)),
                'w_gate': dense(lk[5], c.d_model, (c.d_model, c.d_ff)),
                'w_down': dense(lk[6], c.d_ff, (c.d_ff, c.d_model)),
            })
        params['layers'].append(layer)
    return params


# ---------------------------------------------------------------------------
# sharding rules
# ---------------------------------------------------------------------------

def param_specs(config: TransformerConfig, mesh) -> Dict:
    """PartitionSpec pytree matching :func:`init`'s structure, using only axes
    present in ``mesh`` (absent axes collapse to replication)."""
    from jax.sharding import PartitionSpec as P

    names = set(mesh.axis_names)
    tp = 'model' if 'model' in names else None
    ep = 'expert' if 'expert' in names else None

    layer = {
        'ln1': P(), 'ln2': P(),
        'wq': P(None, tp), 'wk': P(None, tp), 'wv': P(None, tp),
        'wo': P(tp, None),
    }
    if config.n_experts > 0:
        layer.update({
            'gate': P(),
            'w_up': P(ep, None, tp), 'w_gate': P(ep, None, tp),
            'w_down': P(ep, tp, None),
        })
    else:
        layer.update({
            'w_up': P(None, tp), 'w_gate': P(None, tp), 'w_down': P(tp, None),
        })
    return {
        'embed': P(None, tp),
        'final_norm': P(),
        'unembed': P(None, tp),
        'layers': [dict(layer) for _ in range(config.n_layers)],
    }


def batch_spec(mesh):
    """Spec for a (batch, seq) token array over whatever of data/seq exists."""
    from jax.sharding import PartitionSpec as P
    names = set(mesh.axis_names)
    return P('data' if 'data' in names else None,
             'seq' if 'seq' in names else None)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _rms_norm(x, scale):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x.astype(jnp.float32) * jax.lax.rsqrt(var + 1e-6) * scale).astype(x.dtype)


def _rope(x, positions):
    """Rotary position embedding. x: (B, H, L, D), positions: (L,) or (B, L)."""
    d = x.shape[-1]
    half = d // 2
    freqs = jnp.exp(-jnp.arange(0, half, dtype=jnp.float32)
                    * (math.log(10000.0) / half))
    angles = positions[..., None].astype(jnp.float32) * freqs   # (..., L, half)
    if angles.ndim == 2:            # (L, half) -> broadcast over B, H
        angles = angles[None, None]
    else:                           # (B, L, half) -> broadcast over H
        angles = angles[:, None]
    sin, cos = jnp.sin(angles), jnp.cos(angles)
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _ring_attention_sharded(q, k, v, mesh):
    """Ring attention under shard_map: q/k/v are global (B, H, L, dh) arrays
    with L sharded over 'seq' (and B over 'data', H over 'model' when those
    axes exist); each device folds rotating kv chunks over ICI."""
    from jax.sharding import PartitionSpec as P

    names = set(mesh.axis_names)
    spec = P('data' if 'data' in names else None,
             'model' if 'model' in names else None,
             'seq', None)
    # Resolve from the mesh devices (not the session default backend): TPU
    # meshes get per-chunk Pallas kernels, CPU meshes the jnp path.
    from petastorm_tpu.parallel.ring import resolve_ring_impl
    impl = resolve_ring_impl(None, mesh)

    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=(spec, spec, spec), out_specs=spec)
    def fn(q, k, v):
        return ring_attention(q, k, v, 'seq', causal=True, impl=impl)

    return fn(q, k, v)


def _attention(x, layer, config: TransformerConfig, positions, mesh=None):
    c = config
    b, l, _ = x.shape
    h, dh = c.n_heads, c.head_dim

    def heads(w):
        y = (x @ w.astype(x.dtype)).reshape(b, l, h, dh)
        return jnp.transpose(y, (0, 2, 1, 3))        # (B, H, L, dh)

    q, k, v = heads(layer['wq']), heads(layer['wk']), heads(layer['wv'])
    q, k = _rope(q, positions), _rope(k, positions)

    if c.attention == 'ring':
        if mesh is None or 'seq' not in mesh.axis_names:
            raise ValueError("attention='ring' needs a mesh with a 'seq' axis")
        o = _ring_attention_sharded(q, k, v, mesh)
    elif c.attention == 'flash':
        o = flash_attention(q, k, v, causal=True)
    else:
        o = blockwise_attention(q, k, v, causal=True)
    o = jnp.transpose(o, (0, 2, 1, 3)).reshape(b, l, h * dh)
    return o @ layer['wo'].astype(x.dtype)


def _dense_ffn(x, layer):
    gate = jax.nn.silu(x @ layer['w_gate'].astype(x.dtype))
    up = x @ layer['w_up'].astype(x.dtype)
    return (gate * up) @ layer['w_down'].astype(x.dtype)


def _moe_ffn_dense(x, layer, config: TransformerConfig):
    """Dense one-hot top-1 dispatch: every token multiplied by every expert
    with zeros. O(E · tokens · d_ff) FLOPs — kept ONLY as the test oracle for
    :func:`_moe_ffn` (with enough capacity the two must agree exactly)."""
    b, l, d = x.shape
    logits = x.astype(jnp.float32) @ layer['gate']          # (B, L, E)
    probs = jax.nn.softmax(logits, axis=-1)
    top = jnp.argmax(probs, axis=-1)                        # (B, L)
    onehot = jax.nn.one_hot(top, config.n_experts, dtype=x.dtype)  # (B, L, E)
    scale = jnp.take_along_axis(probs, top[..., None], axis=-1).astype(x.dtype)

    # dispatch: (E, B, L, d) rows routed to their expert, zeros elsewhere
    xe = jnp.einsum('bld,ble->ebld', x, onehot)
    gate = jax.nn.silu(jnp.einsum('ebld,edf->eblf', xe,
                                  layer['w_gate'].astype(x.dtype)))
    up = jnp.einsum('ebld,edf->eblf', xe, layer['w_up'].astype(x.dtype))
    down = jnp.einsum('eblf,efd->ebld', gate * up,
                      layer['w_down'].astype(x.dtype))
    combined = jnp.einsum('ebld,ble->bld', down, onehot)
    return combined * scale


def _moe_ffn(x, layer, config: TransformerConfig, mesh=None):
    """Top-1 (Switch) MoE with sort-based sparse dispatch.

    Tokens are stably sorted by their routed expert, scattered into a static
    (E, capacity, d) buffer, run through a batched per-expert matmul, and
    gathered back — per-token FLOPs are O(capacity_factor · d · d_ff),
    independent of the number of experts (the VERDICT-flagged dense one-hot
    dispatch was O(E · d · d_ff) per token). Static shapes throughout, so
    the whole thing jits; over-capacity tokens read the zero overflow row,
    i.e. their residual stream passes through unchanged."""
    b, l, d = x.shape
    e = config.n_experts
    n = b * l
    xf = x.reshape(n, d)
    logits = xf.astype(jnp.float32) @ layer['gate']          # (N, E)
    probs = jax.nn.softmax(logits, axis=-1)
    top = jnp.argmax(probs, axis=-1)                         # (N,)
    scale = jnp.take_along_axis(probs, top[:, None], axis=1).astype(x.dtype)

    capacity = max(1, int(math.ceil(n / e * config.moe_capacity_factor)))
    # stable sort keeps same-expert tokens in stream order → deterministic
    # drop policy (earliest tokens win a contended expert)
    order = jnp.argsort(top, stable=True)
    sorted_expert = top[order]
    group_starts = jnp.searchsorted(sorted_expert, jnp.arange(e), side='left')
    pos = jnp.arange(n) - group_starts[sorted_expert]        # rank in group
    # over-capacity tokens target the dedicated overflow row e*capacity
    dest = jnp.where(pos < capacity, sorted_expert * capacity + pos,
                     e * capacity)

    buf = jnp.zeros((e * capacity + 1, d), x.dtype).at[dest].set(xf[order])
    expert_in = buf[:-1].reshape(e, capacity, d)
    if mesh is not None and 'expert' in mesh.axis_names:
        from jax.sharding import PartitionSpec as P
        expert_in = jax.lax.with_sharding_constraint(
            expert_in, jax.sharding.NamedSharding(mesh, P('expert', None, None)))

    gate = jax.nn.silu(jnp.einsum('ecd,edf->ecf', expert_in,
                                  layer['w_gate'].astype(x.dtype)))
    up = jnp.einsum('ecd,edf->ecf', expert_in, layer['w_up'].astype(x.dtype))
    out = jnp.einsum('ecf,efd->ecd', gate * up,
                     layer['w_down'].astype(x.dtype))

    flat = jnp.concatenate([out.reshape(e * capacity, d),
                            jnp.zeros((1, d), x.dtype)])     # overflow row
    y = jnp.zeros((n, d), x.dtype).at[order].set(flat[dest])

    # Switch load-balancing aux loss: E * sum_e(token_fraction_e * mean
    # router prob_e) — minimized (=1) at a uniform routing distribution.
    # Differentiable through `probs`, so the router learns to balance.
    frac = jnp.mean(jax.nn.one_hot(top, e, dtype=jnp.float32), axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(frac * mean_prob)
    return (y * scale).reshape(b, l, d), aux


def forward(params, tokens, config: TransformerConfig,
            positions: Optional[jnp.ndarray] = None, mesh=None,
            return_aux: bool = False):
    """tokens (B, L) int32 → logits (B, L, vocab) float32.

    With ``return_aux=True`` also returns the summed MoE load-balancing
    auxiliary loss (0.0 for dense models)."""
    c = config
    if positions is None:
        positions = jnp.arange(tokens.shape[1])
    x = params['embed'].astype(c.dtype)[tokens]              # (B, L, D)
    aux_total = jnp.zeros((), jnp.float32)
    for layer in params['layers']:
        h = _rms_norm(x, layer['ln1'])
        x = x + _attention(h, layer, c, positions, mesh)
        h = _rms_norm(x, layer['ln2'])
        if c.n_experts > 0:
            ffn_out, aux = _moe_ffn(h, layer, c, mesh)
            x = x + ffn_out
            aux_total = aux_total + aux
        else:
            x = x + _dense_ffn(h, layer)
    x = _rms_norm(x, params['final_norm'])
    logits = (x @ params['unembed'].astype(c.dtype)).astype(jnp.float32)
    return (logits, aux_total) if return_aux else logits


def loss_fn(params, tokens, targets, config: TransformerConfig, mesh=None):
    """Next-token cross entropy (+ weighted MoE load-balance aux for expert
    models); ``targets`` are tokens shifted by the caller (the NGram pipeline
    emits aligned (input, target) windows)."""
    logits, aux = forward(params, tokens, config, mesh=mesh, return_aux=True)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1).squeeze(-1)
    loss = jnp.mean(nll)
    if config.n_experts > 0 and config.moe_aux_weight:
        loss = loss + config.moe_aux_weight * aux
    return loss


# ---------------------------------------------------------------------------
# training
# ---------------------------------------------------------------------------

def make_train_step(config: TransformerConfig, mesh=None, optimizer=None):
    """Build a jitted ``(params, opt_state, tokens, targets) -> (params,
    opt_state, loss)`` step.

    With ``mesh``, params/activations are constrained to :func:`param_specs` /
    :func:`batch_spec` shardings (dp/tp/sp/ep as present in the mesh); ring
    attention additionally runs under shard_map on the 'seq' axis.
    """
    import optax
    if optimizer is None:
        optimizer = optax.adamw(3e-4, weight_decay=0.01)

    def step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets,
                                                  config, mesh)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    if mesh is None:
        return optimizer, jax.jit(step)

    from jax.sharding import NamedSharding

    pspecs = param_specs(config, mesh)
    bspec = batch_spec(mesh)
    p_shard = jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), pspecs,
                                     is_leaf=lambda x: isinstance(
                                         x, type(bspec)))
    b_shard = NamedSharding(mesh, bspec)
    jitted = jax.jit(step,
                     in_shardings=(p_shard, None, b_shard, b_shard),
                     out_shardings=(p_shard, None, None))
    return optimizer, jitted


def make_forward(config: TransformerConfig):
    """Jittable inference fn + tiny example args (single-chip compile check)."""
    cfg = config

    @jax.jit
    def fn(params, tokens):
        return forward(params, tokens, cfg)

    return fn
