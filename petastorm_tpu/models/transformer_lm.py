"""Flagship decoder-only transformer LM, designed mesh-first.

Parallelism is expressed entirely through GSPMD shardings over a named mesh
(axes from ``petastorm_tpu.parallel.mesh``): annotate params/activations with
PartitionSpecs, let XLA insert the collectives.

- **dp** ('data'): batch dim of activations.
- **tp** ('model'): Megatron-style column/row parallel attention + MLP —
  wq/wk/wv and w_gate/w_up are column-parallel (output dim sharded), wo and
  w_down row-parallel (input dim sharded); XLA inserts the psum where the
  row-parallel matmul closes.
- **sp** ('seq'): sequence dim of activations; attention runs as ring
  attention (``petastorm_tpu/parallel/ring.py``) under shard_map so k/v chunks
  rotate over ICI instead of being all-gathered.
- **ep** ('expert'): optional MoE FFN with experts sharded one-per-group over
  the expert axis.

Compute dtype is bfloat16 (MXU-native); params and softmax/statistics stay
float32.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from petastorm_tpu.ops.attention import blockwise_attention, flash_attention
from petastorm_tpu.parallel.ring import ring_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    # kv heads for grouped-query attention; None = n_heads (MHA). The
    # 'flash' path reads shared kv natively (no repeated kv in HBM);
    # 'blockwise'/'ring' repeat kv heads explicitly.
    n_kv_heads: Optional[int] = None
    n_layers: int = 4
    d_ff: int = 2048
    max_seq_len: int = 2048
    n_experts: int = 0            # 0 → dense FFN; >0 → top-k MoE
    # Experts consulted per token: 1 = Switch routing (scale by the raw top
    # prob), >1 = GShard-style (scales normalized over the selected experts).
    moe_top_k: int = 1
    # Per-expert buffer size = ceil(dispatch_units/n_experts *
    # capacity_factor) with dispatch_units = tokens · top_k; units routed
    # past an expert's capacity are dropped (that choice contributes zero —
    # for top-1 the token's residual stream passes through unchanged,
    # Switch-Transformer semantics).
    moe_capacity_factor: float = 1.25
    # Weight of the Switch load-balancing auxiliary loss; 0 disables it.
    # Deviation from the GShard paper for top_k > 1: the dispatch fraction in
    # the aux term counts ALL k choices per token, not just the first choice —
    # this pressures the router to balance the full dispatch load (what the
    # capacity buffers actually see) rather than first-choice load only.
    moe_aux_weight: float = 0.01
    dtype: Any = jnp.bfloat16
    # 'ring' shards attention over the 'seq' mesh axis; 'flash'/'blockwise'
    # compute full attention locally (XLA all-gathers kv if seq is sharded).
    attention: str = 'blockwise'
    # sliding-window size: each token attends only the previous N positions
    # ('flash'/'blockwise' training and the KV-cache decode honor it; not
    # supported with 'ring').
    attention_window: Optional[int] = None

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads if self.n_kv_heads is not None else self.n_heads


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------

def init(rng, config: TransformerConfig) -> Dict:
    """Initialize parameters as a pytree of float32 arrays."""
    def dense(key, fan_in, shape):
        return jax.random.normal(key, shape, jnp.float32) / math.sqrt(fan_in)

    keys = jax.random.split(rng, 2 + config.n_layers)
    c = config
    params = {
        'embed': dense(keys[0], 1, (c.vocab_size, c.d_model)) * 0.02,
        'final_norm': jnp.ones((c.d_model,), jnp.float32),
        'unembed': dense(keys[1], c.d_model, (c.d_model, c.vocab_size)),
        'layers': [],
    }
    if c.n_heads % c.kv_heads != 0:
        raise ValueError('n_heads (%d) must be a multiple of n_kv_heads (%d)'
                         % (c.n_heads, c.kv_heads))
    if c.n_experts > 0 and not 1 <= c.moe_top_k <= c.n_experts:
        raise ValueError('moe_top_k (%d) must be in [1, n_experts=%d]'
                         % (c.moe_top_k, c.n_experts))
    kv_dim = c.kv_heads * c.head_dim
    for i in range(c.n_layers):
        lk = jax.random.split(keys[2 + i], 8)
        layer = {
            'ln1': jnp.ones((c.d_model,), jnp.float32),
            'wq': dense(lk[0], c.d_model, (c.d_model, c.d_model)),
            'wk': dense(lk[1], c.d_model, (c.d_model, kv_dim)),
            'wv': dense(lk[2], c.d_model, (c.d_model, kv_dim)),
            'wo': dense(lk[3], c.d_model, (c.d_model, c.d_model)),
            'ln2': jnp.ones((c.d_model,), jnp.float32),
        }
        if c.n_experts > 0:
            layer.update({
                'gate': dense(lk[7], c.d_model, (c.d_model, c.n_experts)),
                'w_up': dense(lk[4], c.d_model, (c.n_experts, c.d_model, c.d_ff)),
                'w_gate': dense(lk[5], c.d_model, (c.n_experts, c.d_model, c.d_ff)),
                'w_down': dense(lk[6], c.d_ff, (c.n_experts, c.d_ff, c.d_model)),
            })
        else:
            layer.update({
                'w_up': dense(lk[4], c.d_model, (c.d_model, c.d_ff)),
                'w_gate': dense(lk[5], c.d_model, (c.d_model, c.d_ff)),
                'w_down': dense(lk[6], c.d_ff, (c.d_ff, c.d_model)),
            })
        params['layers'].append(layer)
    return params


# ---------------------------------------------------------------------------
# sharding rules
# ---------------------------------------------------------------------------

def param_specs(config: TransformerConfig, mesh) -> Dict:
    """PartitionSpec pytree matching :func:`init`'s structure, using only axes
    present in ``mesh`` (absent axes collapse to replication)."""
    from jax.sharding import PartitionSpec as P

    names = set(mesh.axis_names)
    tp = 'model' if 'model' in names else None
    ep = 'expert' if 'expert' in names else None

    layer = {
        'ln1': P(), 'ln2': P(),
        'wq': P(None, tp), 'wk': P(None, tp), 'wv': P(None, tp),
        'wo': P(tp, None),
    }
    if config.n_experts > 0:
        layer.update({
            'gate': P(),
            'w_up': P(ep, None, tp), 'w_gate': P(ep, None, tp),
            'w_down': P(ep, tp, None),
        })
    else:
        layer.update({
            'w_up': P(None, tp), 'w_gate': P(None, tp), 'w_down': P(tp, None),
        })
    return {
        'embed': P(None, tp),
        'final_norm': P(),
        'unembed': P(None, tp),
        'layers': [dict(layer) for _ in range(config.n_layers)],
    }


def batch_spec(mesh):
    """Spec for a (batch, seq) token array over whatever of data/seq exists."""
    from jax.sharding import PartitionSpec as P
    names = set(mesh.axis_names)
    return P('data' if 'data' in names else None,
             'seq' if 'seq' in names else None)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _rms_norm(x, scale):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x.astype(jnp.float32) * jax.lax.rsqrt(var + 1e-6) * scale).astype(x.dtype)


def _rope(x, positions):
    """Rotary position embedding. x: (B, H, L, D), positions: (L,) or (B, L)."""
    d = x.shape[-1]
    half = d // 2
    freqs = jnp.exp(-jnp.arange(0, half, dtype=jnp.float32)
                    * (math.log(10000.0) / half))
    angles = positions[..., None].astype(jnp.float32) * freqs   # (..., L, half)
    if angles.ndim == 2:            # (L, half) -> broadcast over B, H
        angles = angles[None, None]
    else:                           # (B, L, half) -> broadcast over H
        angles = angles[:, None]
    sin, cos = jnp.sin(angles), jnp.cos(angles)
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _ring_attention_sharded(q, k, v, mesh):
    """Ring attention under shard_map: q/k/v are global (B, H, L, dh) arrays
    with L sharded over 'seq' (and B over 'data', H over 'model' when those
    axes exist); each device folds rotating kv chunks over ICI."""
    from jax.sharding import PartitionSpec as P

    names = set(mesh.axis_names)
    spec = P('data' if 'data' in names else None,
             'model' if 'model' in names else None,
             'seq', None)
    # Resolve from the mesh devices (not the session default backend): TPU
    # meshes get per-chunk Pallas kernels, CPU meshes the jnp path.
    from petastorm_tpu.parallel.ring import resolve_ring_impl
    impl = resolve_ring_impl(None, mesh)

    from petastorm_tpu.parallel.mesh import shard_map_fn

    @functools.partial(shard_map_fn(), mesh=mesh,
                       in_specs=(spec, spec, spec), out_specs=spec)
    def fn(q, k, v):
        return ring_attention(q, k, v, 'seq', causal=True, impl=impl)

    return fn(q, k, v)


def _attention(x, layer, config: TransformerConfig, positions, mesh=None,
               segment_ids=None):
    c = config
    b, l, _ = x.shape
    h, hkv, dh = c.n_heads, c.kv_heads, c.head_dim

    def heads(w, n):
        y = (x @ w.astype(x.dtype)).reshape(b, l, n, dh)
        return jnp.transpose(y, (0, 2, 1, 3))        # (B, n, L, dh)

    q = heads(layer['wq'], h)
    k = heads(layer['wk'], hkv)
    v = heads(layer['wv'], hkv)
    q, k = _rope(q, positions), _rope(k, positions)
    if hkv != h and c.attention == 'blockwise':
        # flash reads shared kv natively, and ring handles GQA itself
        # (kernel head map on TPU — smaller rotating ppermute payloads —
        # or an internal repeat on the jnp path); only blockwise needs the
        # explicit head repeat here
        k = jnp.repeat(k, h // hkv, axis=1)
        v = jnp.repeat(v, h // hkv, axis=1)

    if c.attention == 'ring':
        if segment_ids is not None:
            raise ValueError('packed segment_ids are not supported with '
                             "attention='ring' (use 'flash'/'blockwise', or "
                             'shard unpacked sequences)')
        if c.attention_window is not None:
            raise ValueError('attention_window is not supported with '
                             "attention='ring'")
        if mesh is None or 'seq' not in mesh.axis_names:
            raise ValueError("attention='ring' needs a mesh with a 'seq' axis")
        o = _ring_attention_sharded(q, k, v, mesh)
    elif c.attention == 'flash':
        o = flash_attention(q, k, v, causal=True, segment_ids=segment_ids,
                            window=c.attention_window)
    else:
        o = blockwise_attention(q, k, v, causal=True,
                                segment_ids=segment_ids,
                                window=c.attention_window)
    o = jnp.transpose(o, (0, 2, 1, 3)).reshape(b, l, h * dh)
    return o @ layer['wo'].astype(x.dtype)


def _dense_ffn(x, layer):
    gate = jax.nn.silu(x @ layer['w_gate'].astype(x.dtype))
    up = x @ layer['w_up'].astype(x.dtype)
    return (gate * up) @ layer['w_down'].astype(x.dtype)


def _moe_ffn_dense(x, layer, config: TransformerConfig):
    """Dense one-hot top-k dispatch: every token multiplied by every expert
    with zeros. O(E · tokens · d_ff) FLOPs — kept ONLY as the test oracle for
    :func:`_moe_ffn` (with enough capacity the two must agree exactly)."""
    b, l, d = x.shape
    k = config.moe_top_k
    logits = x.astype(jnp.float32) @ layer['gate']          # (B, L, E)
    probs = jax.nn.softmax(logits, axis=-1)
    top_idx, top_probs = _moe_router(probs, k)              # (B, L, k)
    # combine weight per expert = Σ over the choices that picked it
    combine = jnp.einsum('blk,blke->ble', top_probs.astype(jnp.float32),
                         jax.nn.one_hot(top_idx, config.n_experts,
                                        dtype=jnp.float32)).astype(x.dtype)
    onehot = (combine != 0).astype(x.dtype)                 # (B, L, E)

    # dispatch: (E, B, L, d) rows routed to their expert, zeros elsewhere
    xe = jnp.einsum('bld,ble->ebld', x, onehot)
    gate = jax.nn.silu(jnp.einsum('ebld,edf->eblf', xe,
                                  layer['w_gate'].astype(x.dtype)))
    up = jnp.einsum('ebld,edf->eblf', xe, layer['w_up'].astype(x.dtype))
    down = jnp.einsum('eblf,efd->ebld', gate * up,
                      layer['w_down'].astype(x.dtype))
    return jnp.einsum('ebld,ble->bld', down, combine)


def _moe_router(probs, k: int):
    """(N, E) router probs → per-token expert choices (N, k) and combine
    scales (N, k): the raw top prob for k=1 (Switch), normalized over the
    selected experts for k>1 (GShard top-2 convention)."""
    top_probs, top_idx = jax.lax.top_k(probs, k)
    if k > 1:
        top_probs = top_probs / jnp.sum(top_probs, axis=-1, keepdims=True)
    return top_idx, top_probs


def _moe_ffn(x, layer, config: TransformerConfig, mesh=None,
             capacity: Optional[int] = None):
    """Top-k MoE with sort-based sparse dispatch (k=1: Switch; k>1: GShard).

    Every (token, choice) pair is one dispatch unit: units are stably sorted
    by their routed expert, scattered into a static (E, capacity, d) buffer,
    run through a batched per-expert matmul, and gathered back as a
    scale-weighted sum over the token's k choices — per-unit FLOPs are
    O(capacity_factor · d · d_ff), independent of the number of experts (the
    VERDICT-flagged dense one-hot dispatch was O(E · d · d_ff) per token).
    Static shapes throughout, so the whole thing jits; over-capacity units
    read the zero overflow row (that choice contributes nothing)."""
    b, l, d = x.shape
    e = config.n_experts
    k = config.moe_top_k
    n = b * l
    n_units = n * k
    xf = x.reshape(n, d)
    logits = xf.astype(jnp.float32) @ layer['gate']          # (N, E)
    probs = jax.nn.softmax(logits, axis=-1)
    top_idx, top_probs = _moe_router(probs, k)               # (N, k) each
    unit_expert = top_idx.reshape(n_units)                   # unit u ↔ token u//k
    scale = top_probs.astype(x.dtype)                        # (N, k)

    if capacity is None:
        capacity = max(1, int(math.ceil(n_units / e
                                        * config.moe_capacity_factor)))
    # stable sort keeps same-expert units in stream order → deterministic
    # drop policy (earliest tokens win a contended expert)
    order = jnp.argsort(unit_expert, stable=True)
    sorted_expert = unit_expert[order]
    group_starts = jnp.searchsorted(sorted_expert, jnp.arange(e), side='left')
    pos = jnp.arange(n_units) - group_starts[sorted_expert]  # rank in group
    # over-capacity units target the dedicated overflow row e*capacity
    dest = jnp.where(pos < capacity, sorted_expert * capacity + pos,
                     e * capacity)

    unit_token = order // k                                  # token of each unit
    buf = jnp.zeros((e * capacity + 1, d), x.dtype).at[dest].set(xf[unit_token])
    expert_in = buf[:-1].reshape(e, capacity, d)
    if mesh is not None and 'expert' in mesh.axis_names:
        from jax.sharding import PartitionSpec as P
        expert_in = jax.lax.with_sharding_constraint(
            expert_in, jax.sharding.NamedSharding(mesh, P('expert', None, None)))

    gate = jax.nn.silu(jnp.einsum('ecd,edf->ecf', expert_in,
                                  layer['w_gate'].astype(x.dtype)))
    up = jnp.einsum('ecd,edf->ecf', expert_in, layer['w_up'].astype(x.dtype))
    out = jnp.einsum('ecf,efd->ecd', gate * up,
                     layer['w_down'].astype(x.dtype))

    flat = jnp.concatenate([out.reshape(e * capacity, d),
                            jnp.zeros((1, d), x.dtype)])     # overflow row
    # un-sort to unit order (N, k, d), then scale-weighted sum over choices
    unit_out = jnp.zeros((n_units, d), x.dtype).at[order].set(flat[dest])
    y = jnp.einsum('nkd,nk->nd', unit_out.reshape(n, k, d), scale)

    # Switch load-balancing aux loss: E * sum_e(dispatch_fraction_e * mean
    # router prob_e) — minimized (=1) at a uniform routing distribution;
    # fractions count all k choices. Differentiable through `probs`, so the
    # router learns to balance.
    frac = jnp.mean(jax.nn.one_hot(unit_expert, e, dtype=jnp.float32), axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(frac * mean_prob)
    return y.reshape(b, l, d), aux


def _segment_positions(segment_ids):
    """Per-document positions derived from (B, L) segment ids: 0, 1, 2, …
    restarting wherever the segment changes (matches
    ``packing.pack_documents``' positions for contiguous segments)."""
    seg = jnp.asarray(segment_ids)
    idx = jnp.arange(seg.shape[-1])
    boundary = jnp.concatenate(
        [jnp.ones_like(seg[..., :1], bool),
         seg[..., 1:] != seg[..., :-1]], axis=-1)
    starts = jax.lax.cummax(jnp.where(boundary, idx, 0), axis=seg.ndim - 1)
    return idx - starts


def forward(params, tokens, config: TransformerConfig,
            positions: Optional[jnp.ndarray] = None, mesh=None,
            return_aux: bool = False, segment_ids=None):
    """tokens (B, L) int32 → logits (B, L, vocab) float32.

    With ``return_aux=True`` also returns the summed MoE load-balancing
    auxiliary loss (0.0 for dense models). ``segment_ids`` (B, L) enables
    packed multi-document batches (see ``petastorm_tpu.packing``): attention
    is masked to same-segment pairs — pass the packer's per-document
    ``positions`` too so rotary offsets restart per document."""
    c = config
    if positions is None:
        if segment_ids is not None:
            # restart rotary offsets at every document boundary — silently
            # continuing a neighbor's offsets would train position encodings
            # inconsistent with unpacked inference
            positions = _segment_positions(segment_ids)
        else:
            positions = jnp.arange(tokens.shape[1])
    x = params['embed'].astype(c.dtype)[tokens]              # (B, L, D)
    aux_total = jnp.zeros((), jnp.float32)
    for layer in params['layers']:
        h = _rms_norm(x, layer['ln1'])
        x = x + _attention(h, layer, c, positions, mesh, segment_ids)
        h = _rms_norm(x, layer['ln2'])
        if c.n_experts > 0:
            ffn_out, aux = _moe_ffn(h, layer, c, mesh)
            x = x + ffn_out
            aux_total = aux_total + aux
        else:
            x = x + _dense_ffn(h, layer)
    x = _rms_norm(x, params['final_norm'])
    logits = (x @ params['unembed'].astype(c.dtype)).astype(jnp.float32)
    return (logits, aux_total) if return_aux else logits


def loss_fn(params, tokens, targets, config: TransformerConfig, mesh=None,
            *, positions=None, segment_ids=None, weights=None):
    """Next-token cross entropy (+ weighted MoE load-balance aux for expert
    models); ``targets`` are tokens shifted by the caller (the NGram pipeline
    emits aligned (input, target) windows).

    Packed multi-document batches (``petastorm_tpu.packing``): pass the
    packer's ``positions``/``segment_ids`` plus the ``weights`` from
    ``packed_lm_targets`` — attention is segment-masked, rotary offsets
    restart per document, and padding/document-boundary slots get zero loss
    weight (mean over weighted slots only)."""
    logits, aux = forward(params, tokens, config, positions=positions,
                          mesh=mesh, return_aux=True,
                          segment_ids=segment_ids)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1).squeeze(-1)
    if weights is None:
        loss = jnp.mean(nll)
    else:
        loss = (jnp.sum(nll * weights)
                / jnp.maximum(jnp.sum(weights), 1.0))
    if config.n_experts > 0 and config.moe_aux_weight:
        loss = loss + config.moe_aux_weight * aux
    return loss


# ---------------------------------------------------------------------------
# decoding (KV-cache autoregressive generation)
# ---------------------------------------------------------------------------

def init_kv_cache(config: TransformerConfig, batch_size: int, max_len: int):
    """Per-layer key/value caches ``(B, kv_heads, max_len, head_dim)`` in the
    model compute dtype."""
    c = config
    shape = (batch_size, c.kv_heads, max_len, c.head_dim)
    return [{'k': jnp.zeros(shape, c.dtype), 'v': jnp.zeros(shape, c.dtype)}
            for _ in range(c.n_layers)]


def _attend_cache(q, ck, cv, index, window=None):
    """One-token attention against the cache: q ``(B, H, 1, dh)``, cache
    ``(B, Hkv, max, dh)``; positions > ``index`` (and, with ``window``,
    positions ≤ index − window) are masked. GQA-aware (q head groups share a
    cache head)."""
    b, h, _, dh = q.shape
    hkv = ck.shape[1]
    g = h // hkv
    qg = q.reshape(b, hkv, g, dh)
    s = jnp.einsum('bkgd,bkld->bkgl', qg.astype(jnp.float32),
                   ck.astype(jnp.float32)) / math.sqrt(dh)
    pos = jnp.arange(ck.shape[2])[None, None, None, :]
    mask = pos <= index
    if window is not None:
        mask = mask & (index - pos < window)
    s = jnp.where(mask, s, _NEG_INF_LOGIT)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum('bkgl,bkld->bkgd', p, cv.astype(jnp.float32))
    return o.reshape(b, h, 1, dh).astype(q.dtype)


def _decode_layer(x, layer, config: TransformerConfig, cache, index):
    """One transformer layer for ONE token per sequence (x ``(B, 1, D)``),
    reading/extending the kv cache at ``index``. Returns (x, cache)."""
    c = config
    b = x.shape[0]
    h, hkv, dh = c.n_heads, c.kv_heads, c.head_dim
    positions = jnp.reshape(index, (1,))

    hn = _rms_norm(x, layer['ln1'])

    def heads(w, n):
        y = (hn @ w.astype(hn.dtype)).reshape(b, 1, n, dh)
        return jnp.transpose(y, (0, 2, 1, 3))

    q = _rope(heads(layer['wq'], h), positions)
    k_new = _rope(heads(layer['wk'], hkv), positions)
    v_new = heads(layer['wv'], hkv)
    ck = jax.lax.dynamic_update_slice(
        cache['k'], k_new.astype(cache['k'].dtype), (0, 0, index, 0))
    cv = jax.lax.dynamic_update_slice(
        cache['v'], v_new.astype(cache['v'].dtype), (0, 0, index, 0))
    att = _attend_cache(q, ck, cv, index, window=c.attention_window)
    x = x + (jnp.transpose(att, (0, 2, 1, 3)).reshape(b, 1, h * dh)
             @ layer['wo'].astype(x.dtype))

    h2 = _rms_norm(x, layer['ln2'])
    if c.n_experts > 0:
        # capacity = all units of the step: per-step routing sees only B
        # units (vs B·L at training), so the trained capacity_factor could
        # silently drop choices and make decode diverge from teacher forcing
        ffn_out, _ = _moe_ffn(h2, layer, c,
                              capacity=b * c.moe_top_k)  # aux unused at decode
        x = x + ffn_out
    else:
        x = x + _dense_ffn(h2, layer)
    return x, {'k': ck, 'v': cv}


_NEG_INF_LOGIT = -1e30


def _sample_logits(logits, temperature: float, top_k, top_p, rng):
    """One sampling step over ``(B, vocab)`` float32 logits. temperature 0 =
    greedy; otherwise categorical after optional top-k truncation and
    top-p (nucleus) truncation — the smallest set of tokens whose
    probabilities sum to ≥ top_p."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    if top_k is not None or top_p is not None:
        # one descending argsort serves both truncations (this runs inside
        # the scanned per-token decode loop). The keep-mask is built over
        # sorted *ranks* and scattered back through the permutation —
        # comparing against the k-th/threshold value would leak every token
        # tied with the cutoff into the candidate set
        order = jnp.argsort(logits, axis=-1)[..., ::-1]
        sorted_desc = jnp.take_along_axis(logits, order, axis=-1)
        keep = jnp.ones(sorted_desc.shape, bool)
        if top_k is not None:
            keep &= jnp.arange(keep.shape[-1]) < top_k
        if top_p is not None:
            probs = jax.nn.softmax(
                jnp.where(keep, sorted_desc, _NEG_INF_LOGIT), axis=-1)
            exclusive_cum = jnp.cumsum(probs, axis=-1) - probs
            keep &= exclusive_cum < top_p       # always keeps the top token
        inverse = jnp.argsort(order, axis=-1)
        logits = jnp.where(jnp.take_along_axis(keep, inverse, axis=-1),
                           logits, _NEG_INF_LOGIT)
    return jax.random.categorical(rng, logits)


def generate(params, tokens, config: TransformerConfig, max_new_tokens: int,
             *, temperature: float = 0.0, top_k: Optional[int] = None,
             top_p: Optional[float] = None, rng=None):
    """Autoregressive decoding with per-layer KV caches.

    ``tokens`` ``(B, Lp)`` int32 prompts (same length across the batch) →
    ``(B, max_new_tokens)`` sampled continuations. ``temperature`` 0 =
    greedy argmax, > 0 = categorical sampling (seeded by ``rng``) with
    optional ``top_k`` / ``top_p`` (nucleus) truncation. The
    prompt is prefilled through the same single-token decode path, so
    prefill and decode are numerically identical; works for dense, MoE, and
    GQA configs (the cache carries ``kv_heads`` heads). The config's
    ``attention`` mode only affects training — decode always attends the
    cache directly. MoE decode routes with capacity = all units of the step
    (B·top_k), so per-step routing can never drop a choice and decode
    matches teacher forcing for every config (training capacity_factor only
    shapes the training-time drop policy)."""
    c = config
    b, prompt_len = tokens.shape
    total = prompt_len + max_new_tokens
    if c.attention_window is not None and c.attention_window < 1:
        raise ValueError('attention_window must be >= 1, got %r'
                         % (c.attention_window,))
    if top_k is not None and not 1 <= top_k <= c.vocab_size:
        raise ValueError('top_k must be in [1, vocab_size]')
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError('top_p must be in (0, 1]')
    if rng is None:
        rng = jax.random.PRNGKey(0)
    caches = init_kv_cache(c, b, total)
    buf = jnp.concatenate(
        [tokens, jnp.zeros((b, max_new_tokens), tokens.dtype)], axis=1)

    def step(carry, t):
        buf, caches, rng = carry
        x = params['embed'].astype(c.dtype)[buf[:, t]][:, None, :]
        new_caches = []
        for layer, cache in zip(params['layers'], caches):
            x, cache = _decode_layer(x, layer, c, cache, t)
            new_caches.append(cache)
        x = _rms_norm(x, params['final_norm'])
        logits = (x @ params['unembed'].astype(c.dtype))[:, 0].astype(
            jnp.float32)
        rng, sub = jax.random.split(rng)
        nxt = _sample_logits(logits, temperature, top_k, top_p,
                             sub).astype(buf.dtype)
        # keep prompt tokens during prefill; write samples after it
        buf = buf.at[:, t + 1].set(
            jnp.where(t + 1 < prompt_len, buf[:, t + 1], nxt))
        return (buf, new_caches, rng), None

    (buf, _, _), _ = jax.lax.scan(step, (buf, caches, rng),
                                  jnp.arange(total - 1))
    return buf[:, prompt_len:]


# ---------------------------------------------------------------------------
# training
# ---------------------------------------------------------------------------

def make_train_step(config: TransformerConfig, mesh=None, optimizer=None):
    """Build a jitted ``(params, opt_state, tokens, targets) -> (params,
    opt_state, loss)`` step.

    With ``mesh``, params/activations are constrained to :func:`param_specs` /
    :func:`batch_spec` shardings (dp/tp/sp/ep as present in the mesh); ring
    attention additionally runs under shard_map on the 'seq' axis.
    """
    import optax
    if optimizer is None:
        optimizer = optax.adamw(3e-4, weight_decay=0.01)

    def step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets,
                                                  config, mesh)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    if mesh is None:
        return optimizer, jax.jit(step)

    from jax.sharding import NamedSharding

    pspecs = param_specs(config, mesh)
    bspec = batch_spec(mesh)
    p_shard = jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), pspecs,
                                     is_leaf=lambda x: isinstance(
                                         x, type(bspec)))
    b_shard = NamedSharding(mesh, bspec)
    jitted = jax.jit(step,
                     in_shardings=(p_shard, None, b_shard, b_shard),
                     out_shardings=(p_shard, None, None))
    return optimizer, jitted


def make_forward(config: TransformerConfig):
    """Jittable inference fn + tiny example args (single-chip compile check)."""
    cfg = config

    @jax.jit
    def fn(params, tokens):
        return forward(params, tokens, cfg)

    return fn
