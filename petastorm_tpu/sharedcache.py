"""Host-wide tiered shared row-group cache (ROADMAP item 4).

The per-reader caches in :mod:`petastorm_tpu.cache` store **compressed or
pickled** payloads per reader: K concurrent readers on one host decode the
same row groups K times. This module is the structural fix — a host-wide
cache of **post-decode** payloads that every reader (and each of its
process-pool workers) attaches to:

- **Tier 0 — shared memory.** Decoded payloads are published as mmap-backed
  segment files in ``/dev/shm`` (falling back to the cache location when no
  shm filesystem exists). ``pa.Table`` payloads are written as an Arrow IPC
  stream and re-opened zero-copy over the mapping; numpy-column dicts and
  row lists travel as pickle protocol-5 frames whose out-of-band buffers
  reconstruct as **read-only** ndarray views over the mapping — the same
  buffer-protocol deserialization contract as the PR-1 zero-copy transport
  (``docs/transport.md``). A hit costs an ``mmap`` + pointer fix-up; no
  storage read, no codec decode.
- **Tier 1 — disk.** Segments evicted from tier 0 spill to a disk directory
  in the same format (superseding the pickle ``LocalDiskCache`` for
  row-group payloads); a tier-1 hit is promoted back to tier 0.
- **Tier 2 — remote prefetch.** Misses fall through to the worker's normal
  read path, where the PR-2 readahead planner prefetches the exact
  ``(file, row_group, columns)`` read in the background and remote
  filesystems use ``pre_buffer`` coalesced range reads
  (``ParquetPieceWorker._plan_item`` consults :meth:`SharedRowGroupCache.contains`
  so only *missing* keys are prefetched).
- **Pod tier — peer caches** (``docs/object_store.md``). With ``peers=``
  configured, a host-local miss checks the other hosts' caches over a
  minimal HTTP segment protocol (:class:`PeerCacheServer`, served via
  :meth:`SharedRowGroupCache.serve_peers`) before touching the object
  store; a fetched segment is re-validated and republished locally.
  ``peer_hedge_s`` races the peer fetch against the local decode under the
  shared :class:`~petastorm_tpu.resilience.HedgedRead` plane. Peer-sourced
  payloads count ``peer_hits`` — never ``fills`` — so summing ``fills``
  across every root's :meth:`SharedRowGroupCache.global_counters` proves
  each row group was decoded once per **pod**.

Concurrency and crash-safety contracts:

- **Lock-free reads.** A segment is located by the digest of its key (the
  directory IS the index); readers never take a lock. Writers publish via
  write-to-temp + ``os.replace``, so a reader observes either the complete
  previous segment or the complete new one.
- **Single-flight fills.** The first process to miss a key takes a lock
  file (``O_CREAT | O_EXCL``) and decodes; concurrent missers wait for the
  segment instead of decoding the same bytes again. A lock whose holder pid
  is dead (or that outlives ``lock_timeout_s``) is stolen — a crashed
  filler never wedges the host.
- **Ref-counted pins.** Attaching a segment drops a pin file naming the
  attaching pid; eviction skips pinned segments. A dead reader's pins
  expire automatically (pid liveness is checked at eviction time), so a
  crash never leaks pinned memory. Unpinned eviction while a mapping is
  live is still safe on POSIX — the unlinked file's pages stay valid until
  the last view drops.
- **Truncation detection.** Every segment carries a sized header and a
  trailer magic; a segment whose byte length disagrees with its frame table
  (a torn copy, a truncated spill) is dropped and refilled, never served.

Keys are built by ``ParquetPieceWorker._cache_key``:
``(payload kind, dataset digest, column-view digest, file, row_group,
decode-hints digest)`` — everything that changes what a decoded row group
contains partitions the cache.

Kill switch: ``PETASTORM_TPU_SHARED_CACHE=0`` makes ``cache_type='shared'``
fall back to :class:`~petastorm_tpu.cache.NullCache` — no attachment, no
files, no shared state. See ``docs/cache.md``.
"""

from __future__ import annotations

import errno
import hashlib
import json
import logging
import mmap
import os
import pickle
import struct
import tempfile
import threading
import time
import uuid
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from petastorm_tpu.cache import CacheBase

logger = logging.getLogger(__name__)

#: Set to ``0``/``false``/``off`` to disable shared-cache attachment
#: entirely: ``cache_type='shared'`` then degrades to a NullCache.
SHARED_CACHE_ENV_VAR = 'PETASTORM_TPU_SHARED_CACHE'

_SEGMENT_MAGIC = b'PTSC'
_SEGMENT_TRAILER = b'CSTP'
_SEGMENT_VERSION = 1
#: Segment payload encodings.
KIND_PICKLE5 = 1     # frames: [pickle meta, out-of-band buffer 0..N]
KIND_ARROW_IPC = 2   # frames: [arrow IPC stream]

_HEADER = struct.Struct('<4sHHI')     # magic, version, kind, nframes
_FRAME_LEN = struct.Struct('<Q')
#: Frame payloads start on 64-byte boundaries so reconstructed ndarray views
#: are cache-line aligned (numpy tolerates unaligned, but why pay for it).
_FRAME_ALIGN = 64

#: Buffers below this pickle in-band (framing a tiny array costs more than
#: one memcpy); large decoded columns go out-of-band and attach zero-copy.
_OOB_THRESHOLD_BYTES = 4096

#: Default tier-0 (shared-memory) budget when the caller only sizes the
#: disk tier. /dev/shm defaults to half of RAM; stay well under it.
_DEFAULT_MEM_LIMIT_BYTES = 1 << 30

#: How many attached segments a single cache instance keeps pinned; older
#: attachments are unpinned (their mappings stay alive for as long as any
#: returned array references them).
_DEFAULT_ATTACH_LIMIT = 16

#: Counter flush granularity: per-process counter files are rewritten every
#: N events (and at close) so `global_counters` lags bounded, not forever.
_COUNTER_FLUSH_EVERY = 32

#: Counter files of DEAD processes older than this are swept at attach
#: time, bounding the counters directory on a long-lived cache root. The
#: TTL keeps recently-exited readers summable (the decode-once benchmark
#: reads `global_counters` after its fleet exits); note totals therefore
#: accumulate across runs within the TTL — compare deltas, or use a fresh
#: root, when asserting per-run invariants.
_COUNTER_TTL_S = 3600.0


def shared_cache_enabled() -> bool:
    """The :data:`SHARED_CACHE_ENV_VAR` kill switch (default: enabled)."""
    return os.environ.get(SHARED_CACHE_ENV_VAR, '1').strip().lower() \
        not in ('0', 'false', 'off', 'no')


class CorruptSegmentError(Exception):
    """A segment file failed structural validation (truncated or torn);
    it is dropped and refilled, never served."""


def _fs_now(root: str) -> float:
    """Filesystem-clock "now": the mtime of a freshly created probe file.

    Lock and counter ages are computed as ``fs_now - st.st_mtime`` — both
    sides read from the SAME clock (the filesystem's), so a step in this
    process's wall clock (NTP correction) or a host whose clock disagrees
    with the filesystem server's (network mounts) can neither steal a live
    single-flight lock (forward step → duplicate decode) nor keep a dead
    one un-stealable (backward step → wedged waiters). ``time.time()``
    arithmetic against mtimes had exactly that hazard. Raises ``OSError`` when
    the directory is gone (cache tearing down) — callers treat that as
    "age unknown"."""
    fd, tmp = tempfile.mkstemp(dir=root, suffix='.clk')
    try:
        os.close(fd)
        return os.stat(tmp).st_mtime
    finally:
        try:
            os.remove(tmp)
        except OSError:
            pass


def _pid_alive(pid: int) -> bool:
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True      # exists, owned by someone else
    except OSError:
        return False
    return True


# -- segment file format -------------------------------------------------------

def write_segment(path: str, kind: int, frames: List) -> int:
    """Atomically publish ``frames`` as a segment file at ``path``; returns
    the byte size written. Frames may be any buffer-protocol objects."""
    views = [memoryview(f) for f in frames]
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path), suffix='.tmp')
    try:
        with os.fdopen(fd, 'wb') as f:
            f.write(_HEADER.pack(_SEGMENT_MAGIC, _SEGMENT_VERSION, kind,
                                 len(views)))
            for view in views:
                f.write(_FRAME_LEN.pack(view.nbytes))
            offset = _HEADER.size + _FRAME_LEN.size * len(views)
            for view in views:
                pad = (-offset) % _FRAME_ALIGN
                if pad:
                    f.write(b'\0' * pad)
                    offset += pad
                f.write(view)
                offset += view.nbytes
            f.write(_SEGMENT_TRAILER)
            size = offset + len(_SEGMENT_TRAILER)
        os.replace(tmp, path)
        return size
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def read_segment(path: str) -> Tuple[int, List[memoryview], mmap.mmap]:
    """Map a segment file and return ``(kind, frame views, mapping)``.

    The views are zero-copy, **read-only** slices of the mapping; they (and
    anything reconstructed over them) keep the mapping alive via their
    ``obj`` reference, so the caller may drop the returned mapping handle
    freely. Raises :class:`CorruptSegmentError` on any structural mismatch
    — a truncated segment is detected here, before a single payload byte is
    interpreted."""
    with open(path, 'rb') as f:
        try:
            mapping = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        except ValueError:
            # a zero-length file cannot be mapped; it is also not a segment
            raise CorruptSegmentError('empty segment file')
    try:
        total = len(mapping)
        if total < _HEADER.size + len(_SEGMENT_TRAILER):
            raise CorruptSegmentError('segment shorter than its header')
        magic, version, kind, nframes = _HEADER.unpack_from(mapping, 0)
        if magic != _SEGMENT_MAGIC or version != _SEGMENT_VERSION:
            raise CorruptSegmentError('bad segment magic/version')
        table_end = _HEADER.size + _FRAME_LEN.size * nframes
        if total < table_end + len(_SEGMENT_TRAILER):
            raise CorruptSegmentError('segment truncated inside frame table')
        lengths = [_FRAME_LEN.unpack_from(
            mapping, _HEADER.size + i * _FRAME_LEN.size)[0]
            for i in range(nframes)]
        offset = table_end
        spans = []
        for length in lengths:
            offset += (-offset) % _FRAME_ALIGN
            spans.append((offset, length))
            offset += length
        if (total != offset + len(_SEGMENT_TRAILER)
                or mapping[offset:offset + len(_SEGMENT_TRAILER)]
                != _SEGMENT_TRAILER):
            raise CorruptSegmentError('segment truncated (size/trailer '
                                      'mismatch)')
        view = memoryview(mapping)
        return kind, [view[lo:lo + n] for lo, n in spans], mapping
    except CorruptSegmentError:
        mapping.close()
        raise
    except (struct.error, ValueError, OverflowError) as e:
        mapping.close()
        raise CorruptSegmentError(str(e))


def _serialize_payload(value) -> Tuple[int, List]:
    """``value -> (kind, frames)``. ``pa.Table`` uses the Arrow IPC stream
    (zero-copy re-open); everything else uses pickle protocol 5 with large
    buffers out-of-band (zero-copy ndarray views on attach)."""
    import pyarrow as pa
    if isinstance(value, pa.Table):
        from petastorm_tpu.workers.serializers import ArrowTableSerializer
        return KIND_ARROW_IPC, [ArrowTableSerializer().serialize(value)]
    frames: List = [None]

    def keep_out_of_band(pickle_buffer):
        try:
            raw = pickle_buffer.raw()
        except BufferError:          # non-contiguous exporter: in-band
            return True
        if raw.nbytes < _OOB_THRESHOLD_BYTES:
            return True
        frames.append(raw)
        return False

    frames[0] = pickle.dumps(value, protocol=5,
                             buffer_callback=keep_out_of_band)
    return KIND_PICKLE5, frames


def _deserialize_payload(kind: int, frames: List[memoryview]):
    if kind == KIND_ARROW_IPC:
        import pyarrow as pa
        with pa.ipc.open_stream(pa.py_buffer(frames[0])) as reader:
            return reader.read_all()
    if kind == KIND_PICKLE5:
        return pickle.loads(frames[0], buffers=frames[1:])
    raise CorruptSegmentError('unknown segment kind {}'.format(kind))


# -- one tier ------------------------------------------------------------------

class _SegmentStore:
    """One directory of segment files with approximate-LRU byte-bounded
    eviction (the :class:`~petastorm_tpu.cache.LocalDiskCache` accounting
    discipline: a running per-process total, re-seeded by a scan whenever it
    crosses the limit or goes stale). Evictions either spill into
    ``spill_store`` or unlink. Pinned segments (see ``_PinRegistry``) are
    skipped unless their pinning pid is dead."""

    def __init__(self, root: str, size_limit_bytes: int, pins: '_PinRegistry',
                 spill_store: Optional['_SegmentStore'] = None):
        self.root = root
        self._size_limit = size_limit_bytes
        self._pins = pins
        self._spill = spill_store
        self._lock = threading.Lock()
        self._approx_total: Optional[int] = None
        self.evictions = 0
        self.spills = 0
        os.makedirs(root, exist_ok=True)

    def path_for(self, digest: str) -> str:
        return os.path.join(self.root, digest + '.seg')

    def contains(self, digest: str) -> bool:
        return os.path.exists(self.path_for(digest))

    def _entries(self):
        try:
            names = os.listdir(self.root)
        except OSError:
            return
        for name in names:
            if not name.endswith('.seg'):
                continue
            full = os.path.join(self.root, name)
            try:
                st = os.stat(full)
            except OSError:
                continue
            yield full, st.st_size, st.st_mtime

    def size_bytes(self) -> int:
        return sum(size for _, size, _ in self._entries())

    def approx_size_bytes(self) -> int:
        with self._lock:
            if self._approx_total is None:
                self._approx_total = self.size_bytes()
            return max(0, self._approx_total)

    def put(self, digest: str, kind: int, frames: List) -> None:
        path = self.path_for(digest)
        incoming = sum(memoryview(f).nbytes for f in frames) + _HEADER.size
        try:
            replaced = os.stat(path).st_size
        except OSError:
            replaced = 0
        self._evict_if_needed(incoming - replaced)
        size = write_segment(path, kind, frames)
        with self._lock:
            if self._approx_total is not None:
                # the pre-charge above used the frame-byte estimate; correct
                # to the actual on-disk size (padding, frame table)
                self._approx_total += size - incoming
        os.utime(path, None)

    def put_file(self, digest: str, src_path: str) -> None:
        """Publish an existing *validated* segment file's bytes (tier
        promotion / spill). Copies — the tiers usually live on different
        filesystems, so a rename cannot move between them."""
        try:
            size = os.stat(src_path).st_size
        except OSError:
            return
        path = self.path_for(digest)
        try:
            replaced = os.stat(path).st_size
        except OSError:
            replaced = 0
        # charge only the delta when re-spilling over an identical existing
        # segment, or the running total inflates on every spill cycle
        self._evict_if_needed(size - replaced)
        fd, tmp = tempfile.mkstemp(dir=self.root, suffix='.tmp')
        try:
            with os.fdopen(fd, 'wb') as out, open(src_path, 'rb') as src:
                while True:
                    chunk = src.read(1 << 20)
                    if not chunk:
                        break
                    out.write(chunk)
            os.replace(tmp, path)
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise

    def touch(self, digest: str) -> None:
        try:
            os.utime(self.path_for(digest), None)
        except OSError:
            pass

    def drop(self, digest: str) -> None:
        path = self.path_for(digest)
        try:
            size = os.stat(path).st_size
            os.remove(path)
        except OSError:
            return
        with self._lock:
            if self._approx_total is not None:
                self._approx_total -= size

    def _evict_if_needed(self, incoming_bytes: int) -> None:
        evict_plan = None
        with self._lock:
            if self._approx_total is None:
                self._approx_total = self.size_bytes()
            self._approx_total += incoming_bytes
            if self._approx_total < 0:
                # per-process running totals drift under concurrent
                # multi-process writers; a negative total is proof of
                # staleness — re-seed from a scan
                self._approx_total = self.size_bytes() + max(0, incoming_bytes)
            if self._approx_total <= self._size_limit:
                return
            entries = list(self._entries())
            total = sum(size for _, size, _ in entries) + max(0, incoming_bytes)
            self._approx_total = total
            if total <= self._size_limit:
                return
            evict_plan = (entries, total)
        entries, total = evict_plan
        for full, size, _mtime in sorted(entries, key=lambda e: e[2]):
            if total <= self._size_limit:
                break
            digest = os.path.basename(full)[:-len('.seg')]
            if self._pins.is_pinned(digest):
                continue
            if self._spill is not None:
                try:
                    self._spill.put_file(digest, full)
                    self.spills += 1
                except OSError as e:
                    logger.warning('shared cache spill failed: %s', e)
            try:
                os.remove(full)
            except OSError:
                continue
            self.evictions += 1
            total -= size
        with self._lock:
            self._approx_total = total


# -- pins ----------------------------------------------------------------------

class _PinRegistry:
    """Cross-process advisory pins: one ``<digest>.<pid>.<token>.pin`` file
    per attachment. Eviction consults :meth:`is_pinned`; pins whose pid is
    dead are expired (removed) on sight — a crashed reader cannot pin a
    segment forever."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def pin(self, digest: str, token: str) -> str:
        path = os.path.join(self.root, '{}.{}.{}.pin'.format(
            digest, os.getpid(), token))
        try:
            with open(path, 'w'):
                pass
        except OSError as e:
            logger.warning('failed to pin shared-cache segment: %s', e)
        return path

    @staticmethod
    def unpin(pin_path: str) -> None:
        try:
            os.remove(pin_path)
        except OSError:
            pass

    def is_pinned(self, digest: str) -> bool:
        prefix = digest + '.'
        try:
            names = os.listdir(self.root)
        except OSError:
            return False
        for name in names:
            if not (name.startswith(prefix) and name.endswith('.pin')):
                continue
            try:
                pid = int(name[len(prefix):].split('.', 1)[0])
            except ValueError:
                pid = -1
            if _pid_alive(pid):
                return True
            # dead-reader pin expiry: reclaim the marker so it never again
            # costs a liveness probe
            try:
                os.remove(os.path.join(self.root, name))
            except OSError:
                pass
        return False


# -- the cache -----------------------------------------------------------------

class _Attachment:
    __slots__ = ('mapping', 'pin_path')

    def __init__(self, mapping, pin_path):
        self.mapping = mapping
        self.pin_path = pin_path


class SharedRowGroupCache(CacheBase):
    """Tiered host-wide cache of decoded row-group payloads.

    :param path: host-shared root directory. Tier-1 segments, pins, locks
        and counters live here; tier 0 lives in ``/dev/shm`` keyed by a
        digest of this path (every cache built on the same ``path`` attaches
        to the same tiers), or under ``path`` when no shm mount exists.
    :param size_limit_bytes: tier-1 (disk) byte budget.
    :param mem_size_limit_bytes: tier-0 (shared-memory) byte budget;
        defaults to ``min(size_limit_bytes, 1 GiB)``.
    :param mem_dir: explicit tier-0 directory (overrides the shm default;
        tests point it at tmpfs-free scratch).
    :param attach_limit: how many attached segments this instance keeps
        pinned (LRU); older attachments unpin but their mappings survive as
        long as returned arrays reference them.
    :param lock_timeout_s: single-flight wait bound. A missing reader waits
        this long for another process's in-flight fill before decoding
        locally (correctness over decode-once).
    :param cleanup: remove this cache's directories on :meth:`cleanup`.
    :param peers: pod tier (``docs/object_store.md``): ``['host:port', ...]``
        peer-cache endpoints (each the :meth:`serve_peers` port of another
        host's cache root). A local miss then checks the pod before
        decoding: a validated peer segment is republished locally and
        counts ``peer_hits`` — never ``fills`` — so summing ``fills`` over
        every root's :meth:`global_counters` certifies each row group was
        decoded once per POD, not once per host.
    :param peer_timeout_s: per-peer HTTP timeout for pod-tier fetches.
    :param peer_hedge_s: when set, a pod-tier fetch is *hedged* against the
        local fill: the peer fetch runs as the primary and the local
        decode fires as the hedge after this many seconds — a slow/wedged
        peer costs bounded latency, while a fast peer still saves the
        decode (a single once-gate keeps the fill exactly-once either
        way). ``None`` = sequential peers-then-fill.

    Instances are picklable (process-pool ``worker_args``): the unpickled
    copy re-attaches to the same tiers with fresh local state.
    """

    #: Bound on pending un-drained ``peer_fetch`` spans (matches the
    #: read-plane bound in :mod:`petastorm_tpu.objectstore`): if nobody
    #: drains, capture saturates instead of growing without bound.
    MAX_PENDING_SPANS = 2048

    def __init__(self, path: str, size_limit_bytes: int,
                 mem_size_limit_bytes: Optional[int] = None,
                 mem_dir: Optional[str] = None,
                 attach_limit: int = _DEFAULT_ATTACH_LIMIT,
                 lock_timeout_s: float = 30.0,
                 cleanup: bool = False,
                 peers: Optional[List[str]] = None,
                 peer_timeout_s: float = 2.0,
                 peer_hedge_s: Optional[float] = None,
                 peer_dead_cooldown_s: float = 30.0):
        if not path:
            raise ValueError("cache_type='shared' needs a cache_location "
                             'directory shared by every attaching reader')
        if size_limit_bytes <= 0:
            raise ValueError('size_limit_bytes must be positive, got '
                             '{!r}'.format(size_limit_bytes))
        self._path = os.path.abspath(path)
        self._size_limit = int(size_limit_bytes)
        self._mem_limit = int(mem_size_limit_bytes
                              or min(self._size_limit,
                                     _DEFAULT_MEM_LIMIT_BYTES))
        self._mem_dir_override = mem_dir
        self._attach_limit = max(1, attach_limit)
        self._lock_timeout_s = lock_timeout_s
        self._cleanup_on_exit = cleanup
        self._peers = list(peers or [])
        self._peer_timeout_s = peer_timeout_s
        self._peer_hedge_s = peer_hedge_s
        self._peer_dead_cooldown_s = float(peer_dead_cooldown_s)
        self._init_runtime()

    def _init_runtime(self) -> None:
        self._instance_token = uuid.uuid4().hex[:8]
        self._lock = threading.Lock()
        self._closed = False
        mem_dir = self._mem_dir_override or self._default_mem_dir(self._path)
        os.makedirs(self._path, exist_ok=True)
        self._pins = _PinRegistry(os.path.join(self._path, 'pins'))
        self._disk = _SegmentStore(os.path.join(self._path, 'disk'),
                                   self._size_limit, self._pins)
        self._mem = _SegmentStore(mem_dir, self._mem_limit, self._pins,
                                  spill_store=self._disk)
        self._locks_dir = os.path.join(self._path, 'locks')
        self._counters_dir = os.path.join(self._path, 'counters')
        os.makedirs(self._locks_dir, exist_ok=True)
        os.makedirs(self._counters_dir, exist_ok=True)
        self._attached: 'OrderedDict[str, _Attachment]' = OrderedDict()
        self._events = {'shared_hits': 0, 'shared_misses': 0,
                        'shared_evictions': 0, 'shared_put_failures': 0,
                        'shared_peer_hits': 0, 'shared_peer_misses': 0,
                        'shared_peer_errors': 0,
                        'shared_peer_skipped_dead': 0}
        self._totals = {'hits': 0, 'misses': 0, 'fills': 0, 'evictions': 0,
                        'spills': 0, 'corrupt_dropped': 0, 'lock_waits': 0,
                        'lock_steals': 0, 'put_failures': 0,
                        'peer_hits': 0, 'peer_misses': 0, 'peer_errors': 0,
                        'peer_bytes': 0, 'peer_skipped_dead': 0}
        # dead-peer cooldown (docs/cache.md): a peer that errored/timed out
        # is skipped until its monotonic deadline passes, so one dead host
        # does not tax every subsequent miss with a full peer_timeout_s
        self._peer_dead_until: Dict[str, float] = {}
        # pod-observability capture (docs/pod_observability.md): per-attempt
        # peer_fetch spans + latency deltas accumulate here (gated on
        # PETASTORM_TPU_PODOBS) until the owning worker drains them via
        # take_spans()/take_latency(); peer requests carry the trace id
        from petastorm_tpu.podobs import new_trace_id, podobs_enabled
        self._observe_pod = podobs_enabled()
        self._trace_id = new_trace_id() if self._observe_pod else ''
        self._pod_spans: list = []
        self._pod_latency: Dict[str, dict] = {}
        #: the pod-tier hedge plane (docs/object_store.md): a fixed-threshold
        #: HedgedRead racing "fetch from a peer's cache" against "decode
        #: locally" — the same primitive the range reader uses per range
        self._peer_hedge = None
        if self._peers and self._peer_hedge_s is not None:
            from petastorm_tpu.resilience import HedgedRead
            self._peer_hedge = HedgedRead(
                dict(threshold_s=float(self._peer_hedge_s)),
                on_event=self._peer_hedge_event)
        self._peer_server: Optional['PeerCacheServer'] = None
        self._events_since_flush = 0
        self._counter_path = os.path.join(
            self._counters_dir,
            '{}-{}.json'.format(os.getpid(), self._instance_token))
        self._fs_clock_cache: Optional[Tuple[float, float]] = None
        self._sweep_stale_counters()

    def _sweep_stale_counters(self) -> None:
        """Reclaim counter files of long-dead processes so a production
        cache root does not accumulate one file per reader forever (the
        pin registry's dead-pid expiry, applied to counters — but with a
        TTL, so a just-finished fleet stays summable)."""
        try:
            now = _fs_now(self._counters_dir)
            names = os.listdir(self._counters_dir)
        except OSError:
            return
        for name in names:
            if not name.endswith('.json'):
                continue
            try:
                pid = int(name.split('-', 1)[0])
            except ValueError:
                pid = -1
            full = os.path.join(self._counters_dir, name)
            try:
                old = (now - os.stat(full).st_mtime) > _COUNTER_TTL_S
            except OSError:
                continue
            if old and not _pid_alive(pid):
                try:
                    os.remove(full)
                except OSError:
                    pass

    @staticmethod
    def _default_mem_dir(path: str) -> str:
        digest = hashlib.md5(path.encode('utf-8')).hexdigest()[:12]
        if os.path.isdir('/dev/shm'):
            return os.path.join('/dev/shm', 'petastorm-tpu-' + digest)
        return os.path.join(path, 'mem')

    # pickling: worker_args cross the process-pool boundary; runtime state
    # (mmaps, pins, counters) is per-process and rebuilt on arrival
    def __getstate__(self):
        return {'path': self._path, 'size_limit': self._size_limit,
                'mem_limit': self._mem_limit,
                'mem_dir': self._mem_dir_override,
                'attach_limit': self._attach_limit,
                'lock_timeout_s': self._lock_timeout_s,
                'cleanup': self._cleanup_on_exit,
                'peers': self._peers,
                'peer_timeout_s': self._peer_timeout_s,
                'peer_hedge_s': self._peer_hedge_s,
                'peer_dead_cooldown_s': self._peer_dead_cooldown_s}

    def __setstate__(self, state):
        self._path = state['path']
        self._size_limit = state['size_limit']
        self._mem_limit = state['mem_limit']
        self._mem_dir_override = state['mem_dir']
        self._attach_limit = state['attach_limit']
        self._lock_timeout_s = state['lock_timeout_s']
        self._cleanup_on_exit = state['cleanup']
        self._peers = state.get('peers', [])
        self._peer_timeout_s = state.get('peer_timeout_s', 2.0)
        self._peer_hedge_s = state.get('peer_hedge_s')
        self._peer_dead_cooldown_s = state.get('peer_dead_cooldown_s', 30.0)
        self._init_runtime()

    # -- lookup ----------------------------------------------------------------

    @staticmethod
    def _digest(key: str) -> str:
        return hashlib.md5(key.encode('utf-8')).hexdigest()

    def contains(self, key: str) -> bool:
        """Whether ``key`` is currently served by tier 0 or tier 1 (no
        attachment, no locks — the readahead planner's peek)."""
        digest = self._digest(key)
        return self._mem.contains(digest) or self._disk.contains(digest)

    def _try_attach(self, digest: str):
        """``(payload,)`` on a tier hit, ``None`` on a miss. Promotes tier-1
        hits into tier 0; drops (and mischarges as a miss) corrupt
        segments."""
        for store, promote in ((self._mem, False), (self._disk, True)):
            path = store.path_for(digest)
            if not os.path.exists(path):
                continue
            try:
                kind, frames, mapping = read_segment(path)
            except OSError:
                continue
            except CorruptSegmentError:
                # truncated/torn segments are dropped, never served
                store.drop(digest)
                with self._lock:
                    self._totals['corrupt_dropped'] += 1
                continue
            try:
                payload = _deserialize_payload(kind, frames)
            except CorruptSegmentError:
                mapping.close()
                store.drop(digest)
                with self._lock:
                    self._totals['corrupt_dropped'] += 1
                continue
            if promote:
                try:
                    self._mem.put_file(digest, path)
                except OSError:
                    pass
                else:
                    # the segment now lives in tier 0; keeping the disk
                    # copy too would double-count it against both budgets
                    # (tier-0 eviction re-spills it when the time comes)
                    store.drop(digest)
            else:
                store.touch(digest)
            self._register_attachment(digest, mapping)
            return (payload,)
        return None

    def _register_attachment(self, digest: str, mapping) -> None:
        pin_path = self._pins.pin(digest, self._instance_token)
        with self._lock:
            old = self._attached.pop(digest, None)
            self._attached[digest] = _Attachment(mapping, pin_path)
            dropped = []
            while len(self._attached) > self._attach_limit:
                dropped.append(self._attached.popitem(last=False)[1])
        if old is not None:
            dropped.append(old)
        for att in dropped:
            # unpin only; the mapping object stays alive for as long as any
            # payload view references it (refcounted via memoryview.obj)
            self._pins.unpin(att.pin_path)

    # -- single-flight fill ----------------------------------------------------

    def _lock_path(self, digest: str) -> str:
        return os.path.join(self._locks_dir, digest + '.lock')

    @property
    def _lock_id(self) -> str:
        return '{}:{}'.format(os.getpid(), self._instance_token)

    def _try_lock(self, digest: str) -> bool:
        # link() an already-complete file into place: the lock is atomic AND
        # its holder id is readable from the first instant it exists — an
        # O_CREAT|O_EXCL + write pair has a window where a concurrent
        # staleness probe reads an empty file and wrongly steals. The temp
        # name is unique PER CALL: thread-pool workers share one instance,
        # so an instance-scoped name would let two same-key missers race on
        # one temp file (one thread's cleanup making the other's link fail
        # with ENOENT, escaping into the read path).
        path = self._lock_path(digest)
        tmp = '{}.{}.{}'.format(path, self._lock_id, uuid.uuid4().hex[:8])
        try:
            with open(tmp, 'w') as f:
                f.write(self._lock_id)
            try:
                os.link(tmp, path)
            except OSError as e:
                if e.errno in (errno.EEXIST, errno.ENOENT):
                    return False     # lost the race (ENOENT: tmp dir raced)
                raise
            return True
        finally:
            try:
                os.remove(tmp)
            except OSError:
                pass

    def _unlock(self, digest: str) -> None:
        # only remove OUR lock: a holder that overran lock_timeout_s may
        # have been stolen from, and blindly unlinking would release the
        # thief's fresh lock (best-effort — the read+unlink pair is not
        # atomic, but the residual window needs a second overrun inside it)
        path = self._lock_path(digest)
        try:
            with open(path) as f:
                if f.read().strip() != self._lock_id:
                    return
            os.remove(path)
        except OSError:
            pass

    @staticmethod
    def _parse_lock_holder(content: str) -> int:
        try:
            return int(content.strip().split(':', 1)[0] or -1)
        except ValueError:
            return -1

    def _read_lock_state(self, path: str):
        """``(holder_pid, mtime)`` of a lock file, or ``None`` when it
        vanished/is unreadable."""
        try:
            st = os.stat(path)
            with open(path) as f:
                holder = self._parse_lock_holder(f.read())
        except OSError:
            return None
        return holder, st.st_mtime

    def _lock_age(self, mtime: float) -> Optional[float]:
        """Age of ``mtime`` against the filesystem clock, or ``None`` when
        the clock cannot be probed (locks dir unwritable/full). The probe
        is cached for 1 s and advanced with ``time.monotonic()`` deltas in
        between — a clock step cannot land inside a monotonic delta, and
        waiters polling at 2-20 ms stop paying a create+stat+unlink of
        metadata ops per poll."""
        mono = time.monotonic()
        cached = self._fs_clock_cache
        if cached is not None and mono - cached[1] < 1.0:
            return cached[0] + (mono - cached[1]) - mtime
        try:
            fs = _fs_now(self._locks_dir)
        except OSError:
            self._fs_clock_cache = None
            return None
        self._fs_clock_cache = (fs, mono)
        return fs - mtime

    def _lock_stale(self, digest: str) -> bool:
        state = self._read_lock_state(self._lock_path(digest))
        if state is None:
            return False      # lock vanished: not stale
        holder, mtime = state
        if holder >= 0 and not _pid_alive(holder):
            return True       # dead holder: stale, no clock needed
        # live (or unparsable) holder: only age can prove staleness; an
        # unprobeable filesystem clock proves nothing — the pid-liveness
        # path above still steals dead locks even on a full disk
        age = self._lock_age(mtime)
        return age is not None and age > self._lock_timeout_s

    def _steal_lock(self, digest: str) -> bool:
        """Claim-then-validate steal of a stale lock. Renaming the lock to
        a unique claim name is atomic, so of N waiters that all observed
        the same stale lock exactly ONE wins the claim — unconditional
        unlink here would let one stealer delete another stealer's freshly
        re-acquired lock and re-admit the duplicate decode the lock exists
        to prevent. The claimed file is re-validated before being
        discarded; a lock that turned out live is restored (unless a new
        one already took its place)."""
        path = self._lock_path(digest)
        claim = '{}.claim.{}.{}'.format(path, self._lock_id,
                                        uuid.uuid4().hex[:8])
        try:
            os.rename(path, claim)
        except OSError:
            return False      # someone else claimed it / it vanished
        state = self._read_lock_state(claim)
        stale = True
        if state is not None:
            holder, mtime = state
            if holder >= 0 and _pid_alive(holder):
                age = self._lock_age(mtime)
                stale = age is not None and age > self._lock_timeout_s
        if not stale:
            # mis-steal (the holder renewed between observation and claim):
            # put it back unless a new lock already exists
            try:
                os.link(claim, path)
            except OSError:
                pass
        try:
            os.remove(claim)
        except OSError:
            pass
        if stale:
            with self._lock:
                self._totals['lock_steals'] += 1
        return stale

    def _wait_for_fill(self, digest: str):
        """Another process holds the fill lock: wait for its segment (or a
        stale lock to steal). Returns an attached ``(payload,)`` or ``None``
        (caller decodes locally)."""
        deadline = time.monotonic() + self._lock_timeout_s
        delay = 0.002
        with self._lock:
            self._totals['lock_waits'] += 1
        while time.monotonic() < deadline:
            time.sleep(delay)
            # capped backoff: a decode takes tens of ms, so a coarse poll
            # would tax every waiter ~a poll period per awaited fill
            delay = min(delay * 2, 0.02)
            attached = self._try_attach(digest)
            if attached is not None:
                return attached
            if not os.path.exists(self._lock_path(digest)):
                # filler finished (or died post-unlock) without a segment
                # we can see yet: one last attach attempt, then fill locally
                return self._try_attach(digest)
            if self._lock_stale(digest) and self._steal_lock(digest):
                return None
        return None

    # -- pod tier (peer caches; docs/object_store.md) --------------------------

    def _bump(self, total_key: str, event_key: str, n: int = 1) -> None:
        with self._lock:
            self._totals[total_key] = self._totals.get(total_key, 0) + n
            self._events[event_key] = self._events.get(event_key, 0) + n

    def _peer_hedge_event(self, name: str, n: int = 1) -> None:
        # io_hedges / io_hedge_wins / io_hedge_losses from the pod-tier
        # HedgedRead, renamed into the cache's own counter families
        short = name.replace('io_', 'peer_')
        self._bump(short, 'shared_' + short, n)

    def _observe_peer_fetch(self, peer: str, start_s: float, outcome: str,
                            nbytes: int) -> None:
        """Record one pod-tier peer attempt as a ``peer_fetch`` span plus a
        ``peer_fetch`` latency observation (docs/pod_observability.md). The
        owning worker drains both via :meth:`take_spans` /
        :meth:`take_latency`. No-op unless the pod observability plane is
        on."""
        if not self._observe_pod:
            return
        dur_s = time.perf_counter() - start_s
        span = ('peer_fetch', 'io', start_s, dur_s,
                {'peer': peer, 'outcome': outcome, 'bytes': nbytes})
        from petastorm_tpu.latency import bucket_index
        index = bucket_index(dur_s)
        with self._lock:
            if len(self._pod_spans) < self.MAX_PENDING_SPANS:
                self._pod_spans.append(span)
            entry = self._pod_latency.setdefault(
                'peer_fetch', {'buckets': {}, 'sum': 0.0, 'count': 0})
            entry['buckets'][index] = entry['buckets'].get(index, 0) + 1
            entry['sum'] += dur_s
            entry['count'] += 1

    def take_spans(self) -> list:
        """Drain pending ``peer_fetch`` spans (``(name, cat, start_s,
        dur_s, args)`` tuples on the monotonic clock); empty unless the pod
        observability plane recorded any."""
        with self._lock:
            spans, self._pod_spans = self._pod_spans, []
        return spans

    def take_latency(self) -> Optional[Dict[str, dict]]:
        """Drain pending ``peer_fetch`` latency deltas in the
        ``LatencyDeltas.drain()`` shape, or ``None`` when nothing was
        recorded."""
        with self._lock:
            latency, self._pod_latency = self._pod_latency, {}
        return latency or None

    def segment_bytes(self, digest: str) -> Optional[bytes]:
        """Raw bytes of a resident segment, tier 0 first (the peer-protocol
        server side; ``None`` = miss). Lock-free like every read: publishers
        ``os.replace`` whole files, so the bytes read are a complete segment
        (the fetching peer re-validates header+trailer before publishing)."""
        for store in (self._mem, self._disk):
            try:
                with open(store.path_for(digest), 'rb') as f:
                    return f.read()
            except OSError:
                continue
        return None

    def _mark_peer_dead(self, peer: str) -> None:
        """Open ``peer``'s dead-peer cooldown window: subsequent misses
        skip it (counted ``peer_skipped_dead``) until the monotonic
        deadline passes, instead of paying the full ``peer_timeout_s`` on
        every one."""
        if self._peer_dead_cooldown_s <= 0:
            return
        with self._lock:
            self._peer_dead_until[peer] = (time.perf_counter()
                                           + self._peer_dead_cooldown_s)

    def _peer_fetch(self, digest: str):
        """Try each configured peer for ``digest``: download the segment,
        validate it, republish it into the LOCAL tiers (so one pod transfer
        serves this host's later readers too) and attach. Returns the
        attached ``(payload,)`` or ``None``. A peer that errors is skipped
        — the pod tier degrades to a local fill, never fails the read.
        A peer inside its dead-peer cooldown window (it errored or timed
        out within the last ``peer_dead_cooldown_s`` seconds) is skipped
        without a request — counted as ``peer_skipped_dead`` — so a dead
        host taxes at most one miss per window instead of every one."""
        import urllib.error
        import urllib.request
        for peer in self._peers:
            with self._lock:
                dead_until = self._peer_dead_until.get(peer)
            if dead_until is not None:
                if time.perf_counter() < dead_until:
                    self._bump('peer_skipped_dead', 'shared_peer_skipped_dead')
                    continue
                with self._lock:
                    self._peer_dead_until.pop(peer, None)
            url = 'http://{}/peercache/{}'.format(peer, digest)
            tmp = None
            nbytes = 0
            attempt_start = time.perf_counter()
            request = urllib.request.Request(url)
            if self._observe_pod:
                # trace propagation (docs/pod_observability.md): the serving
                # peer echoes this id, stitching both hosts into one track
                from petastorm_tpu.podobs import TRACE_HEADER
                request.add_header(TRACE_HEADER, self._trace_id)
            try:
                with urllib.request.urlopen(
                        request, timeout=self._peer_timeout_s) as resp:
                    fd, tmp = tempfile.mkstemp(dir=self._path,
                                               suffix='.peer')
                    with os.fdopen(fd, 'wb') as out:
                        while True:
                            chunk = resp.read(1 << 20)
                            if not chunk:
                                break
                            out.write(chunk)
                            nbytes += len(chunk)
                # validate BEFORE publishing: a torn transfer must be
                # dropped, never served (header + trailer + frame table)
                _kind, frames, mapping = read_segment(tmp)
                for frame in frames:
                    frame.release()
                mapping.close()
                self._mem.put_file(digest, tmp)
            except urllib.error.HTTPError as e:
                if e.code != 404:    # 404 is an honest peer miss
                    self._bump('peer_errors', 'shared_peer_errors')
                    self._mark_peer_dead(peer)
                    self._observe_peer_fetch(peer, attempt_start, 'error',
                                             nbytes)
                else:
                    self._observe_peer_fetch(peer, attempt_start, 'miss',
                                             nbytes)
                continue
            except (OSError, CorruptSegmentError, ValueError) as e:
                logger.warning('peer-cache fetch %s failed (degrading to '
                               'next peer / local fill): %s', url, e)
                self._bump('peer_errors', 'shared_peer_errors')
                self._mark_peer_dead(peer)
                self._observe_peer_fetch(peer, attempt_start, 'error',
                                         nbytes)
                continue
            finally:
                if tmp is not None:
                    try:
                        os.remove(tmp)
                    except OSError:
                        pass
            attached = self._try_attach(digest)
            if attached is not None:
                self._bump('peer_hits', 'shared_peer_hits')
                with self._lock:
                    self._totals['peer_bytes'] += nbytes
                    self._peer_dead_until.pop(peer, None)
                self._observe_peer_fetch(peer, attempt_start, 'hit', nbytes)
                return attached
            self._observe_peer_fetch(peer, attempt_start, 'miss', nbytes)
        self._bump('peer_misses', 'shared_peer_misses')
        return None

    def serve_peers(self, port: int = 0) -> int:
        """Start this cache root's pod endpoint (``GET /peercache/<digest>``
        on ``127.0.0.1``) and return the bound port — what other hosts list
        in their ``peers=``. Idempotent; stopped by :meth:`close`."""
        with self._lock:
            server = self._peer_server
        if server is not None:
            return server.port
        server = PeerCacheServer(self, port=port).start()
        with self._lock:
            if self._peer_server is None:
                self._peer_server = server
                server = None
        if server is not None:    # lost a start race: keep the first
            server.stop()
        return self._peer_server.port

    # -- CacheBase -------------------------------------------------------------

    def get(self, key: str, fill_cache_func):
        digest = self._digest(key)
        attached = self._try_attach(digest)
        if attached is not None:
            self._record(hit=True)
            return attached[0]
        got_lock = self._try_lock(digest)
        if not got_lock:
            if self._lock_stale(digest) and self._steal_lock(digest):
                got_lock = self._try_lock(digest)
            if not got_lock:
                attached = self._wait_for_fill(digest)
                if attached is not None:
                    self._record(hit=True)
                    return attached[0]
                got_lock = self._try_lock(digest)
        try:
            # re-check under the lock: the previous holder may have
            # published between our miss and our acquisition
            attached = self._try_attach(digest)
            if attached is not None:
                self._record(hit=True)
                return attached[0]
            if self._peers:
                return self._pod_fill(digest, fill_cache_func)
            return self._publish_fill(digest, fill_cache_func)
        finally:
            if got_lock:
                self._unlock(digest)

    def _publish_fill(self, digest: str, fill_cache_func):
        """Decode locally and publish — the single-flight fill body. Every
        ``fills`` increment in the pod comes from here, which is what makes
        ``sum(fills over roots) == row groups`` a decode-once certificate."""
        value = fill_cache_func()
        self._record(hit=False)
        try:
            # chaos hook (docs/robustness.md): the cache-enospc scenario
            # raises here, exercising the same degrade path a genuinely
            # full /dev/shm or spill disk takes
            from petastorm_tpu.faultfs import maybe_inject_cache_fault
            maybe_inject_cache_fault(digest)
            kind, frames = _serialize_payload(value)
            self._mem.put(digest, kind, frames)
            with self._lock:
                self._totals['fills'] += 1
        except (OSError, pickle.PicklingError, TypeError,
                ValueError) as e:
            # cache publication failures must never fail the read path:
            # the freshly decoded value is served directly, the event is
            # counted (shared_put_failures -> ReaderStats -> a named
            # 'degraded' cause in /healthz), and the pipeline runs on
            # without the cache tier
            logger.warning('failed to publish shared-cache segment '
                           '(degrading to direct decode): %s', e)
            with self._lock:
                self._events['shared_put_failures'] += 1
                self._totals['put_failures'] += 1
        return value

    def _pod_fill(self, digest: str, fill_cache_func):
        """A local miss with a pod configured: peers before the object
        store. Sequential mode tries peers then fills; hedged mode races
        the peer fetch (primary) against the local decode (hedge, fired
        after ``peer_hedge_s``) — a once-gate keeps the fill exactly-once
        even when both sides of the race reach it."""
        if self._peer_hedge is None:
            attached = self._peer_fetch(digest)
            if attached is not None:
                self._record(hit=False)   # a local miss the pod served
                return attached[0]
            return self._publish_fill(digest, fill_cache_func)
        gate = {'mutex': threading.Lock(), 'done': False, 'value': None}

        def gated_fill():
            # the gate mutex intentionally blocks the second arrival for
            # the duration of the fill: it must WAIT for the first fill,
            # not decode (and count) the same row group again
            with gate['mutex']:
                if not gate['done']:
                    gate['value'] = self._publish_fill(digest,
                                                       fill_cache_func)
                    gate['done'] = True
                return gate['value']

        def peers_then_fill():
            attached = self._peer_fetch(digest)
            if attached is not None:
                self._record(hit=False)
                return attached[0]
            return gated_fill()
        return self._peer_hedge.call(
            peers_then_fill, hedge_fn=gated_fill,
            description='peer_fill({})'.format(digest[:8]))

    # -- telemetry -------------------------------------------------------------

    def _record(self, hit: bool) -> None:
        with self._lock:
            if hit:
                self._events['shared_hits'] += 1
                self._totals['hits'] += 1
            else:
                self._events['shared_misses'] += 1
                self._totals['misses'] += 1
            evictions = self._mem.evictions + self._disk.evictions
            new_evictions = evictions - self._totals['evictions']
            if new_evictions:
                self._events['shared_evictions'] += new_evictions
                self._totals['evictions'] = evictions
            self._totals['spills'] = self._mem.spills
            self._events_since_flush += 1
            flush = self._events_since_flush >= _COUNTER_FLUSH_EVERY
            if flush:
                self._events_since_flush = 0
        if flush:
            self._flush_counters()

    def take_events(self) -> Dict[str, int]:
        """Drain the ``ReaderStats``-shaped counter deltas accumulated since
        the last drain (``shared_hits``/``shared_misses``/
        ``shared_evictions``); the owning worker records them after each
        cache access."""
        with self._lock:
            events = dict(self._events)
            for name in self._events:
                self._events[name] = 0
        return events

    def occupancy_bytes(self) -> int:
        """Approximate bytes resident across both tiers (running totals; no
        directory scan on the hot path)."""
        return self._mem.approx_size_bytes() + self._disk.approx_size_bytes()

    def size_bytes(self) -> int:
        """Exact resident bytes (directory scan; diagnostics/tests only)."""
        return self._mem.size_bytes() + self._disk.size_bytes()

    def counters(self) -> Dict[str, int]:
        """This instance's lifetime totals."""
        with self._lock:
            return dict(self._totals)

    def host_counters(self) -> Dict[str, int]:
        """This HOST's totals over every process attached to this cache
        root (:meth:`global_counters` of our own path, flushing first so
        this instance's unflushed tail is included) — the per-host ``cache``
        section of the pod observability snapshot, whose pod-wide sum of
        ``fills`` the decode-once certificate checks."""
        self._flush_counters()
        return self.global_counters(self._path)

    def _flush_counters(self) -> None:
        with self._lock:
            if self._closed:
                return
            payload = dict(self._totals, pid=os.getpid())
        try:
            fd, tmp = tempfile.mkstemp(dir=self._counters_dir, suffix='.tmp')
            with os.fdopen(fd, 'w') as f:
                json.dump(payload, f)
            os.replace(tmp, self._counter_path)
        except OSError:
            pass

    @staticmethod
    def global_counters(path: str) -> Dict[str, int]:
        """Host-wide totals summed over every attaching process's flushed
        counter file — how the acceptance benchmark proves "decoded once"
        across K reader processes."""
        totals: Dict[str, int] = {}
        counters_dir = os.path.join(os.path.abspath(path), 'counters')
        try:
            names = os.listdir(counters_dir)
        except OSError:
            return totals
        for name in names:
            if not name.endswith('.json'):
                continue
            try:
                with open(os.path.join(counters_dir, name)) as f:
                    blob = json.load(f)
            except (OSError, ValueError):
                continue
            for k, v in blob.items():
                if isinstance(v, int) and k != 'pid':
                    totals[k] = totals.get(k, 0) + v
        return totals

    # -- lifecycle -------------------------------------------------------------

    def close(self) -> None:
        """Flush counters, stop the pod endpoint (when served), drain
        in-flight pod hedge races and release this instance's pins.
        Idempotent; the piece workers call it from ``shutdown()``. Attached
        mappings are NOT force-closed — payload views own them
        refcounted."""
        self._flush_counters()
        with self._lock:
            if self._closed:
                return
            self._closed = True
            attached, self._attached = self._attached, OrderedDict()
            server, self._peer_server = self._peer_server, None
        if self._peer_hedge is not None:
            # an abandoned race loser may still be mid-fetch/mid-fill; give
            # it a bounded join before the interpreter starts finalizing
            self._peer_hedge.drain()
        if server is not None:
            server.stop()
        for att in attached.values():
            self._pins.unpin(att.pin_path)

    def cleanup(self):
        self.close()
        if not self._cleanup_on_exit:
            return
        import shutil
        shutil.rmtree(self._mem.root, ignore_errors=True)
        shutil.rmtree(self._path, ignore_errors=True)


# -- pod peer protocol (docs/object_store.md) ----------------------------------

_HEX_DIGITS = frozenset('0123456789abcdef')


class PeerCacheServer:
    """One host's side of the pod cache protocol: ``GET
    /peercache/<digest>`` returns the raw segment bytes of a locally
    resident decoded row group (tier 0 before tier 1), 404 on a miss.

    Deliberately minimal — stdlib HTTP on the :class:`DebugServer` plumbing
    (``ThreadingHTTPServer`` on ``127.0.0.1``, daemon request threads,
    quiet logs), because the *fetching* side carries all the correctness:
    every transferred segment is re-validated against its header/trailer/
    frame table before being republished, so a torn response degrades to a
    local fill instead of serving garbage. The digest is hex-checked before
    it touches a filesystem path. Failure semantics: any server-side error
    is a 500 the client counts as ``peer_errors`` and routes around — a
    down peer never fails a read, it just costs the pod one extra decode.
    """

    def __init__(self, cache: SharedRowGroupCache, port: int = 0):
        self._cache = cache
        self._requested_port = port
        self._server = None
        self._thread: Optional[threading.Thread] = None
        #: The bound port (differs from the requested one when it was 0).
        self.port: Optional[int] = None

    @staticmethod
    def _pod_headers(handler) -> Dict[str, str]:
        """Trace propagation on the pod cache protocol
        (docs/pod_observability.md): echo the caller's ``X-Petastorm-Trace``
        id and stamp this host's monotonic clock so the fetching side can
        estimate the pod clock offset. Empty (no extra headers) when the pod
        observability plane is off."""
        from petastorm_tpu.podobs import (CLOCK_HEADER, TRACE_HEADER,
                                          podobs_enabled)
        if not podobs_enabled():
            return {}
        headers = {CLOCK_HEADER: repr(time.perf_counter())}
        trace = handler.headers.get(TRACE_HEADER)
        if trace:
            headers[TRACE_HEADER] = trace
        return headers

    def start(self) -> 'PeerCacheServer':
        if self._server is not None:
            return self
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet by default
                logger.debug('peer-cache endpoint: ' + fmt, *args)

            def _reply(self, status: int, body: bytes,
                       content_type: str = 'text/plain'):
                self.send_response(status)
                self.send_header('Content-Type', content_type)
                self.send_header('Content-Length', str(len(body)))
                for name, value in outer._pod_headers(self).items():
                    self.send_header(name, value)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 - http.server API
                try:
                    route = self.path.split('?', 1)[0]
                    if not route.startswith('/peercache/'):
                        self._reply(404, b'unknown route; try '
                                         b'/peercache/<digest>\n')
                        return
                    digest = route[len('/peercache/'):]
                    if not digest or not set(digest) <= _HEX_DIGITS:
                        # the digest lands in a filesystem path: hex-only,
                        # no traversal surface
                        self._reply(400, b'bad digest\n')
                        return
                    data = outer._cache.segment_bytes(digest)
                    if data is None:
                        self._reply(404, b'miss\n')
                        return
                    self._reply(200, data, 'application/octet-stream')
                # a failed segment read (evicted/truncated mid-request) must
                # become a 500 the fetching peer counts and routes around —
                # never a dropped connection or a dead serve loop
                except Exception as e:  # petalint: disable=exception-hygiene
                    logger.exception('peer-cache request failed')
                    try:
                        self._reply(500, 'error: {}\n'.format(e).encode())
                    except OSError:
                        pass

        self._server = ThreadingHTTPServer(
            ('127.0.0.1', self._requested_port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            kwargs={'poll_interval': 0.1}, daemon=True,
            name='petastorm-tpu-peercache-http')
        self._thread.start()
        logger.info('petastorm_tpu peer-cache endpoint on '
                    'http://127.0.0.1:%d/peercache/', self.port)
        return self

    def stop(self) -> None:
        server, self._server = self._server, None
        if server is None:
            return
        server.shutdown()
        server.server_close()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=10)
