"""NGram: windowed sequence assembly over timestamp-sorted rows.

Reference parity: ``petastorm/ngram.py`` — constructor semantics (:102-125),
``form_ngram`` window scan (:225-270), ``_ngram_pass_threshold`` (:179-193),
per-timestep schema views (:215-223), regex field resolution (:195-203).
Sequences never cross row-group boundaries (doc :85-91) — for the TPU build
this is the input pipeline for transformer-LM token windows (BASELINE.json
config #5), so window length is bounded by row-group size by design.

An n-gram is a dict ``{offset: row}`` where offsets are the keys of ``fields``.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Union

import numpy as np

from petastorm_tpu.unischema import Unischema, UnischemaField, match_unischema_fields


def valid_window_starts(ts_sorted: np.ndarray, span: int, delta_threshold,
                        timestamp_overlap: bool) -> np.ndarray:
    """Start positions (in ts-sorted order) of all valid windows — the
    vectorized equivalent of :meth:`NGram.form_ngram_dicts`'s scan. Shared by
    the indexed window loader and the streaming row worker's columnar path."""
    n = len(ts_sorted)
    if n < span:
        return np.empty(0, np.int64)
    if span == 1:
        starts = np.arange(n, dtype=np.int64)
    else:
        gap_ok = (np.diff(ts_sorted) <= delta_threshold).astype(np.int32)
        cum = np.concatenate([[0], np.cumsum(gap_ok)])
        # valid[s] <=> all of gap_ok[s : s+span-1]
        valid = (cum[span - 1:] - cum[:n - span + 1]) == span - 1
        starts = np.nonzero(valid)[0].astype(np.int64)
    if timestamp_overlap or not len(starts):
        return starts
    # greedy non-overlapping selection; skipped-invalid windows do not
    # advance the previous-end marker (matches the streaming scan)
    keep = []
    previous_end = None
    for s in starts:
        if previous_end is None or ts_sorted[s] > previous_end:
            keep.append(s)
            previous_end = ts_sorted[s + span - 1]
    return np.asarray(keep, np.int64)


class NGramWindowChunk:
    """All valid windows of one row group, columnar: ``columns`` maps field
    name -> the group's decoded column in timestamp-sorted order, ``starts``
    holds the ts-sorted start position of every valid window. The window at
    offset ``off`` of window ``i`` is row ``starts[i] + off - base_offset``
    of every column — consumers slice windows out instead of receiving
    per-window Python dicts (the round-4 streaming assembler's GIL cost)."""

    __slots__ = ('columns', 'starts')

    def __init__(self, columns: Dict[str, np.ndarray], starts: np.ndarray):
        self.columns = columns
        self.starts = starts

    def __len__(self) -> int:
        return len(self.starts)


class NGram:
    """Defines a sliding window over consecutive rows.

    :param fields: ``{offset: [UnischemaField | regex string, ...]}`` — which
        fields are produced at each timestep. Offsets are integers (any
        start, negative allowed, gaps allowed — a window spans
        ``max(offsets) - min(offsets) + 1`` consecutive rows and emits
        entries only for the declared offsets, reference
        ``tests/test_ngram_end_to_end.py:510-529``).
    :param delta_threshold: maximum allowed timestamp delta between two
        consecutive rows of a window; larger gaps reject the window.
    :param timestamp_field: the :class:`UnischemaField` (or name) ordering rows.
    :param timestamp_overlap: if False, emitted windows must not overlap in
        timestamp ranges (reference ``ngram.py:117-125``).
    """

    def __init__(self, fields: Dict[int, List], delta_threshold,
                 timestamp_field: Union[UnischemaField, str],
                 timestamp_overlap: bool = True):
        import numbers
        from datetime import timedelta
        if not fields:
            raise ValueError('NGram fields must have at least one timestep')
        if not all(isinstance(k, numbers.Integral) for k in fields.keys()):
            raise TypeError('NGram offsets must be integers, got {}'.format(
                sorted(map(repr, fields.keys()))))
        if not all(isinstance(v, (list, tuple)) for v in fields.values()):
            raise TypeError('NGram fields values must be lists of fields')
        # numbers.Number covers int/float/np scalars/Decimal; timedelta for
        # datetime-typed timestamp fields — anything the window comparison
        # itself supports must pass
        if not isinstance(delta_threshold, (numbers.Number, timedelta)):
            raise TypeError('delta_threshold must be numeric, got {!r}'
                            .format(delta_threshold))
        self._offsets = sorted(fields.keys())
        self._fields = {k: list(v) for k, v in fields.items()}
        self._delta_threshold = delta_threshold
        self._timestamp_field = timestamp_field
        self._timestamp_overlap = timestamp_overlap
        # offset -> (schema, view); avoids rebuilding the view (and its
        # namedtuple class) per window. Identity-checked against the schema so
        # a different schema never gets a stale view; dropped on pickle (the
        # namedtuple classes are not picklable)
        self._view_cache: Dict = {}

    @property
    def fields(self) -> Dict[int, List]:
        return self._fields

    @property
    def delta_threshold(self):
        return self._delta_threshold

    @property
    def length(self) -> int:
        """Window SPAN in rows: ``max(offsets) - min(offsets) + 1`` (equals
        the timestep count only when offsets are consecutive — gapped offsets
        still consume the in-between rows, reference ``ngram.py:127-139``)."""
        return self._offsets[-1] - self._offsets[0] + 1

    @property
    def timestamp_field_name(self) -> str:
        if isinstance(self._timestamp_field, UnischemaField):
            return self._timestamp_field.name
        return self._timestamp_field

    @property
    def timestamp_overlap(self) -> bool:
        return self._timestamp_overlap

    def resolve_regex_field_names(self, schema: Unischema) -> None:
        """Replace regex strings in ``fields`` with matching schema fields
        (reference ``ngram.py:195-203``)."""
        for offset, field_list in self._fields.items():
            resolved = []
            for f in field_list:
                if isinstance(f, str):
                    matched = match_unischema_fields(schema, [f])
                    if not matched:
                        raise ValueError('NGram regex {!r} matched no fields'.format(f))
                    resolved.extend(matched)
                else:
                    resolved.append(f)
            # dedupe preserving order
            seen = set()
            self._fields[offset] = [f for f in resolved
                                    if not (f.name in seen or seen.add(f.name))]

    def get_field_names_at_timestep(self, timestep: int) -> List[str]:
        return [f.name for f in self._fields.get(timestep, [])]

    def get_schema_at_timestep(self, schema: Unischema, timestep: int) -> Unischema:
        """Schema view holding only this timestep's fields
        (reference ``ngram.py:215-223``)."""
        return schema.create_schema_view(
            [f for f in self._fields.get(timestep, []) if f.name in schema.fields])

    def get_all_field_names(self) -> List[str]:
        """Union of all timesteps' fields plus the timestamp field — the columns
        a worker must read."""
        names = {self.timestamp_field_name}
        for field_list in self._fields.values():
            names.update(f.name if isinstance(f, UnischemaField) else f
                         for f in field_list)
        return sorted(names)

    def timestep_layout(self, field_names):
        """``(offsets, base_offset, {offset: [field, ...]})`` with each
        timestep's fields filtered to ``field_names`` — the one derivation of
        'which fields at which offset' shared by the per-window results
        reader, the chunked JAX collation, and the indexed window loader."""
        offsets = sorted(self._fields.keys())
        fields_at = {off: [n for n in self.get_field_names_at_timestep(off)
                           if n in field_names]
                     for off in offsets}
        return offsets, offsets[0], fields_at

    def _window_passes_threshold(self, window: List[dict]) -> bool:
        ts_name = self.timestamp_field_name
        for previous, current in zip(window, window[1:]):
            if current[ts_name] - previous[ts_name] > self._delta_threshold:
                return False
        return True

    def form_ngram_dicts(self, data: List[dict],
                         schema: Unischema) -> List[Dict[int, dict]]:
        """Scan timestamp-sorted rows and emit all valid windows as
        ``{offset: {field: value}}`` dicts (reference ``ngram.py:225-270``).

        Plain dicts, not namedtuples: this runs on pool WORKERS, and the
        dynamically generated namedtuple classes of schema views cannot be
        unpickled on the consumer side of a process pool. Namedtuple assembly
        happens consumer-side in :meth:`make_namedtuples`."""
        ts_name = self.timestamp_field_name
        rows = sorted(data, key=lambda r: r[ts_name])
        offsets = self._offsets
        base = offsets[0]
        ngrams = []
        previous_window_end_ts = None
        for start in range(len(rows) - self.length + 1):
            window = rows[start:start + self.length]
            if not self._window_passes_threshold(window):
                continue
            if (not self._timestamp_overlap and previous_window_end_ts is not None
                    and window[0][ts_name] <= previous_window_end_ts):
                continue
            ngram = {}
            for offset in offsets:   # gapped offsets skip the rows between
                row = window[offset - base]
                view = self._timestep_view(schema, offset)
                ngram[offset] = {name: row[name] for name in view.fields}
            ngrams.append(ngram)
            previous_window_end_ts = window[-1][ts_name]
        return ngrams

    def form_windows_columnar(self, columns: Dict[str, np.ndarray]
                              ) -> Optional[NGramWindowChunk]:
        """Vectorized :meth:`form_ngram_dicts`: sort the decoded columns of
        one row group by timestamp, scan window starts with
        :func:`valid_window_starts`, and return them as a columnar
        :class:`NGramWindowChunk` (``None`` when no window is valid). Window
        semantics are identical to the per-row scan — same stable sort, same
        delta/overlap rules (guarded by the universe-equivalence tests)."""
        ts = np.asarray(columns[self.timestamp_field_name])
        order = np.argsort(ts, kind='stable')
        ts_sorted = ts[order]
        starts = valid_window_starts(ts_sorted, self.length,
                                     self._delta_threshold,
                                     self._timestamp_overlap)
        if not len(starts):
            return None
        # ship only what consumers can read: fields some timestep declares
        # (the timestamp column is worker-side scan input unless declared),
        # sliced to the envelope of valid windows — a sparse-window group
        # must not pickle thousands of dead rows across a process pool
        declared = set()
        for field_list in self._fields.values():
            declared.update(f.name if isinstance(f, UnischemaField) else f
                            for f in field_list)
        lo = int(starts[0])
        hi = int(starts[-1]) + self.length
        sorted_cols = {name: np.asarray(col)[order[lo:hi]]
                       for name, col in columns.items() if name in declared}
        return NGramWindowChunk(sorted_cols, starts - lo)

    def _timestep_view(self, schema: Unischema, offset: int) -> Unischema:
        cached = self._view_cache.get(offset)
        if cached is not None and cached[0] is schema:
            return cached[1]
        view = self.get_schema_at_timestep(schema, offset)
        self._view_cache[offset] = (schema, view)
        return view

    def __getstate__(self):
        state = dict(self.__dict__)
        state['_view_cache'] = {}
        return state

    def make_namedtuples(self, window: Dict[int, dict],
                         schema: Unischema) -> Dict[int, object]:
        """Consumer-side: convert one dict window into per-timestep schema-view
        namedtuples."""
        return {offset: self._timestep_view(schema, offset).make_namedtuple(**row)
                for offset, row in window.items()}

    def form_ngram(self, data: List[dict], schema: Unischema) -> List[Dict[int, object]]:
        """Windows as ``{offset: namedtuple}`` — single-process convenience
        composing :meth:`form_ngram_dicts` + :meth:`make_namedtuples`."""
        return [self.make_namedtuples(w, schema)
                for w in self.form_ngram_dicts(data, schema)]
