"""Pipeline roofline profiler: calibrated per-stage ceilings, overlap-aware
attribution, and a what-if advisor.

The sensors built in PRs 1-5 (``ReaderStats``, spans, heartbeats) answer
*what the pipeline did*; none of them answer *what the host could have
done* — VERDICT.md's standing complaint is that the decode-bound image
lines have "no measured I/O ceiling to judge the cached line's samples/sec
against". This module is the model layer on top of the sensors (the role
tf.data's AUTOTUNE analysis layer plays over its raw counters):

- **Calibration micro-probes** (:func:`calibrate`) measure this host's
  per-stage ceilings against the *actual dataset*: storage sequential-read
  bandwidth for the dataset's filesystem (plain vs ``pre_buffer`` parquet
  opens — the two open modes the workers pick between), per-codec decode
  throughput over sampled row groups pushed through the real
  ``codecs.py``/``columnar_worker`` decode paths, serializer/transport
  bandwidth (``ZeroCopySerializer`` roundtrip), and host→device staging
  bandwidth via the production ``stage_to_global``. Probes run on demand
  (never on the hot path) and the result is cached as a JSON calibration
  artifact keyed by ``(host, dataset digest)`` — re-probing only when the
  dataset's row-group composition changes.
- **Overlap-aware attribution** (:func:`attribute`) consumes a
  ``ReaderStats`` snapshot plus ``Tracer`` span intervals and produces
  per-stage busy/idle time by **interval union per stage** — readahead,
  decode and infeed deliberately overlap, so naive stage-time sums
  over-count; the union of each stage's span intervals against the observed
  wall is the honest utilization.
- **Roofline verdict** (:func:`build_profile`): "measured X samples/s =
  Y% of the binding stage's ceiling Z", where the binding stage is the
  calibrated stage with the lowest ceiling for the current configuration.
- **What-if advisor** (:func:`advise`): ranked knob recommendations
  (``workers_count``, ``io_readahead``, ``cache_type='shared'``,
  ``reader_pool_type``) with predicted samples/s deltas from the same
  throughput model (:func:`predict_throughput`), validated for direction
  against the committed BENCH artifacts
  (:func:`replay_against_artifacts`).

Surfaces: ``reader.profile()`` / ``reader.explain_throughput()``, the
``GET /profile`` route on the debug endpoint, a ``roofline`` section in
flight records and ``infeed_diagnosis``, ``petastorm-tpu-throughput
--profile``, and the ``stage_ceiling_*`` / ``roofline_fraction`` gauges in
``/metrics`` and the metrics emitter. See ``docs/profiling.md``.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import socket
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

#: Environment variable gating the profiler surfaces (default on).
#: ``0``/``false``/``off`` removes the ``/profile`` route and makes
#: ``reader.profile()`` raise — observability layers keep uniform kill
#: switches (``PETASTORM_TPU_HEALTH``, ``PETASTORM_TPU_LINEAGE``, this).
PROFILER_ENV_VAR = 'PETASTORM_TPU_PROFILER'

#: Where calibration artifacts live when ``cache_dir`` is not passed.
CALIBRATION_DIR_ENV_VAR = 'PETASTORM_TPU_CALIBRATION_DIR'

#: Probe-methodology version stamped into every calibration artifact and
#: required on load: a cached ceiling is only comparable to measurements
#: taken through the SAME decode path. Bumped to 2 when the decode probe
#: moved onto the row-group-vectorized path (docs/decode.md) — a per-cell
#: ceiling served against batched measurements would misreport
#: roofline_fraction by up to the batched speedup. Bumped to 3 when the
#: device-decode probe family landed: per-codec entries now record which
#: path (``host-batched`` / ``per-cell`` / ``device``) produced each
#: ceiling, and ``device_decode`` / ``ingest`` ceilings joined the
#: artifact — pre-upgrade artifacts carry neither and must not judge
#: device measurements, so they read as a cache miss. Bumped to 4 when the
#: storage probe gained the coalesced-ranged read mode (``objectstore``):
#: the io ceiling is now max over three open modes, so a version-3 io
#: ceiling would under-report the store a ranged reader actually has.
PROBE_SCHEMA_VERSION = 4

#: Pipeline stages a ceiling is calibrated for, in pipeline order.
#: ``device_decode`` (jitted bytes-through decode) and ``ingest`` (raw
#: payload host→device transfer) are probe-only ceilings consumed by the
#: device-decode benchmark gate; they never bind the host span attribution.
CEILING_STAGES = ('io', 'decode', 'serialize', 'device_stage')

#: Span name -> attribution stage. Spans whose name is not listed keep their
#: category as the stage (so future span kinds degrade gracefully instead of
#: vanishing from the attribution).
SPAN_STAGE = {
    'parquet_read': 'io',
    'readahead_read': 'io',
    'decode_columns': 'decode',
    'decode_rows': 'decode',
    'transform': 'decode',
    'serialize': 'serialize',
    'deserialize': 'deserialize',
    'device_stage': 'device_stage',
    'train_step': 'train',
    'queue_wait': 'consumer_wait',
    'infeed_wait': 'consumer_wait',
    'process_item': 'worker',
    'ventilate': 'ventilate',
}

#: Stages that mean "waiting, not working": excluded from binding-stage
#: selection (a pipeline is never *bound* by its own idle time).
IDLE_ATTRIBUTION_STAGES = frozenset({'consumer_wait', 'ventilate', 'worker'})

#: A roofline fraction above this is not a fast pipeline, it is a broken
#: measurement: the measured window drained pre-decoded buffers (too short
#: to be steady-state) or the calibration is stale for this host.
SANE_FRACTION_LIMIT = 1.3

_MB = 1024.0 * 1024.0


def profiler_enabled() -> bool:
    """The :data:`PROFILER_ENV_VAR` gate (default on)."""
    value = os.environ.get(PROFILER_ENV_VAR, '').strip().lower()
    return value not in ('0', 'false', 'off')


# ---------------------------------------------------------------------------
# dataset digest + calibration cache
# ---------------------------------------------------------------------------

def dataset_digest(pieces, schema=None) -> str:
    """Content digest of a dataset's row-group composition — every
    ``(path, row_group, num_rows)`` triple — plus the column view when a
    ``schema`` is given. Regenerating a store in place (different rows,
    different grouping) changes the digest, so a stale calibration can
    never be served for it; a pure re-read does not. The view component
    matters because ceilings are per-view: a reader pruned to scalar
    columns decodes orders of magnitude faster than the full image view,
    and the two must not share a calibration artifact."""
    h = hashlib.md5()
    for piece in sorted(pieces, key=lambda p: (str(p.path), p.row_group)):
        h.update('{}:{}:{}\n'.format(piece.path, piece.row_group,
                                     piece.num_rows).encode())
    if schema is not None:
        h.update('view:{}\n'.format(
            ','.join(sorted(schema.fields))).encode())
    return h.hexdigest()[:16]


def calibration_dir(cache_dir: Optional[str] = None) -> str:
    if cache_dir:
        return str(cache_dir)
    env = os.environ.get(CALIBRATION_DIR_ENV_VAR, '').strip()
    if env:
        return env
    return os.path.join(os.path.expanduser('~'), '.cache', 'petastorm_tpu')


def calibration_path(digest: str, cache_dir: Optional[str] = None) -> str:
    """The calibration artifact path for ``(this host, digest)``."""
    host = socket.gethostname().split('.')[0] or 'host'
    return os.path.join(calibration_dir(cache_dir),
                        'roofline_{}_{}.json'.format(host, digest))


def load_calibration(digest: str,
                     cache_dir: Optional[str] = None) -> Optional[dict]:
    """The cached calibration for ``digest`` on this host, or ``None`` on a
    miss, an unreadable artifact, a digest mismatch (defense in depth —
    the digest is in the filename AND the payload), or a probe-version
    mismatch (ceilings measured through an older decode path must not
    judge this one's measurements)."""
    path = calibration_path(digest, cache_dir)
    try:
        with open(path) as f:
            cal = json.load(f)
    except (OSError, ValueError):
        return None
    if cal.get('dataset_digest') != digest:
        return None
    if cal.get('probe_version') != PROBE_SCHEMA_VERSION:
        return None
    return cal


def save_calibration(calibration: dict,
                     cache_dir: Optional[str] = None) -> str:
    from petastorm_tpu.utils import atomic_write
    out_dir = calibration_dir(cache_dir)
    os.makedirs(out_dir, exist_ok=True)
    path = calibration_path(calibration['dataset_digest'], cache_dir)
    return atomic_write(path, lambda f: json.dump(calibration, f, indent=2,
                                                  sort_keys=True))


# ---------------------------------------------------------------------------
# calibration micro-probes
# ---------------------------------------------------------------------------

def _sample_pieces(pieces, sample_row_groups: int):
    """Spread the sampled row groups across the dataset (first/last/middle)
    instead of taking a prefix — a store whose early groups differ from the
    rest (warm page cache, different files) must not skew the ceilings."""
    pieces = list(pieces)
    n = len(pieces)
    k = max(1, min(sample_row_groups, n))
    if k == n:
        return pieces
    step = (n - 1) / (k - 1) if k > 1 else 0
    return [pieces[int(round(i * step))] for i in range(k)]


#: Repetitions per timed probe section; the BEST (minimum-time) rep is the
#: ceiling. Scheduler interference only ever slows a measurement down, so
#: min-of-N is the honest estimator for "what this host can do" — a single
#: timing of a sub-millisecond read under a loaded host reads 2-5x slow,
#: enough to mis-rank io vs decode on small stores.
PROBE_REPS = 5


def _probe_storage(filesystem, sampled) -> dict:
    """Sequential-read bandwidth of the dataset's own files, plus the parquet
    row-group read rate under the three open modes the workers choose between
    (plain for local filesystems, ``pre_buffer=True`` for remote — see
    ``piece_worker._LOCAL_PROTOCOLS`` — and the coalesced parallel-range
    plan of ``objectstore.ParallelRangeReader``, the ranged-ingest
    ceiling). Page-cache state is whatever the
    host has (recorded as ``page_cache: 'ambient'``): these are sustained
    re-read ceilings, the regime epochs 2+ run in."""
    import pyarrow.parquet as pq
    total_bytes = 0
    seq_s = 0.0
    paths = []
    for piece in sampled:
        if piece.path not in paths:
            paths.append(piece.path)
    for path in paths:
        start = time.perf_counter()
        with filesystem.open(path, 'rb') as f:
            while True:
                chunk = f.read(4 * 1024 * 1024)
                if not chunk:
                    break
                total_bytes += len(chunk)
        seq_s += time.perf_counter() - start

    def timed_read(pre_buffer: bool) -> Tuple[float, int]:
        read_s, rows = 0.0, 0
        for piece in sampled:
            handle = filesystem.open(piece.path, 'rb')
            try:
                if pre_buffer:
                    try:
                        pf = pq.ParquetFile(handle, pre_buffer=True)
                    except TypeError:     # pyarrow predating the kwarg
                        pf = pq.ParquetFile(handle)
                else:
                    pf = pq.ParquetFile(handle)
                start = time.perf_counter()
                table = pf.read_row_group(piece.row_group)
                read_s += time.perf_counter() - start
                rows += table.num_rows
            finally:
                handle.close()
        return read_s, rows

    def timed_ranged_read() -> Tuple[float, int]:
        from petastorm_tpu.objectstore import ParallelRangeReader
        reader = ParallelRangeReader(filesystem)
        read_s, rows = 0.0, 0
        for piece in sampled:
            start = time.perf_counter()
            table = reader.read_row_group(piece.path, piece.row_group)
            read_s += time.perf_counter() - start
            rows += table.num_rows
        return read_s, rows

    plain_s, rows = min(timed_read(pre_buffer=False)
                        for _ in range(PROBE_REPS))
    pre_s, _ = min(timed_read(pre_buffer=True) for _ in range(PROBE_REPS))
    ranged_s, _ = min(timed_ranged_read() for _ in range(PROBE_REPS))
    return {
        'page_cache': 'ambient',
        'bytes': total_bytes,
        'seq_read_mb_per_s': round(total_bytes / _MB / seq_s, 2)
        if seq_s else None,
        'parquet_rows_per_s': round(rows / plain_s, 1) if plain_s else None,
        'parquet_pre_buffer_rows_per_s': round(rows / pre_s, 1)
        if pre_s else None,
        'parquet_ranged_rows_per_s': round(rows / ranged_s, 1)
        if ranged_s else None,
        'parquet_read_s': round(plain_s, 4),
        'rows': rows,
    }


def _probe_decode(filesystem, sampled, schema) -> dict:
    """Per-codec decode throughput through the REAL decode path
    (``columnar_worker._column_to_numpy``, honoring each field's codec and
    the same batched/per-cell routing the workers use — the ceiling must
    measure the path the pipeline runs) over the sampled row groups. One
    untimed pass warms codec imports and the column buffers; the timed
    pass is the single-core decode ceiling. Each per-codec entry records
    the cells decoded by the vectorized path (``batched_rows``) so the
    calibration artifact shows which ceilings are batched-path numbers."""
    import pyarrow.parquet as pq

    from petastorm_tpu.codecs import batched_decode_enabled
    from petastorm_tpu.readers.columnar_worker import _column_to_numpy
    batched = batched_decode_enabled()
    names = [name for name, field in schema.fields.items()]
    per_codec: Dict[str, dict] = {}
    rows = 0
    total_s = 0.0
    decoded_bytes = 0
    for piece in sampled:
        handle = filesystem.open(piece.path, 'rb')
        try:
            table = pq.ParquetFile(handle).read_row_group(piece.row_group)
        finally:
            handle.close()
        present = [n for n in names if n in table.column_names]
        # warm pass: codec imports, lazy cv2 init, chunk materialization
        for name in present:
            _column_to_numpy(table.column(name), schema.fields[name], None,
                             batched=batched)
        n = table.num_rows
        rows += n
        for name in present:
            field = schema.fields[name]
            elapsed, out = None, None
            path_counts = {'batched': 0, 'percell': 0}
            for _ in range(PROBE_REPS):
                path_counts = {'batched': 0, 'percell': 0}
                start = time.perf_counter()
                out = _column_to_numpy(table.column(name), field, None,
                                       batched=batched,
                                       path_counts=path_counts)
                took = time.perf_counter() - start
                elapsed = took if elapsed is None else min(elapsed, took)
            total_s += elapsed
            codec = field.codec
            label = type(codec).__name__ if codec is not None else 'none'
            image_format = getattr(codec, '_image_codec', None)
            if image_format:
                label = '{}({})'.format(label, str(image_format).lstrip('.'))
            entry = per_codec.setdefault(label, {'rows': 0, 'seconds': 0.0,
                                                 'decoded_bytes': 0,
                                                 'batched_rows': 0,
                                                 'percell_rows': 0})
            entry['rows'] += n
            entry['seconds'] += elapsed
            entry['batched_rows'] += path_counts['batched']
            entry['percell_rows'] += path_counts['percell']
            nbytes = getattr(out, 'nbytes', 0)
            entry['decoded_bytes'] += int(nbytes)
            decoded_bytes += int(nbytes)
    for entry in per_codec.values():
        entry['rows_per_s'] = (round(entry['rows'] / entry['seconds'], 1)
                               if entry['seconds'] else None)
        entry['mb_per_s'] = (round(entry['decoded_bytes'] / _MB
                                   / entry['seconds'], 1)
                             if entry['seconds'] else None)
        entry['seconds'] = round(entry['seconds'], 4)
        # which path produced this ceiling (probe_version 3): a device
        # measurement judged against a per-cell ceiling — or vice versa —
        # would mis-grade by the whole path speedup
        if entry['batched_rows'] >= entry['percell_rows'] \
                and entry['batched_rows']:
            entry['path'] = 'host-batched'
        elif entry['percell_rows']:
            entry['path'] = 'per-cell'
        else:
            entry['path'] = 'host-native'
    return {
        'rows': rows,
        'seconds': round(total_s, 4),
        'rows_per_s': round(rows / total_s, 1) if total_s else None,
        'decoded_mb_per_s': round(decoded_bytes / _MB / total_s, 1)
        if total_s else None,
        'per_codec': per_codec,
        'decoded_bytes': decoded_bytes,
    }


def _decode_sample_columns(filesystem, sampled, schema) -> Tuple[dict, int]:
    """One decoded row group's columns (numpy dict) for the transport and
    staging probes — the actual payload shape the pipeline ships."""
    import pyarrow.parquet as pq

    from petastorm_tpu.readers.columnar_worker import _column_to_numpy
    piece = sampled[0]
    handle = filesystem.open(piece.path, 'rb')
    try:
        table = pq.ParquetFile(handle).read_row_group(piece.row_group)
    finally:
        handle.close()
    columns = {}
    for name, field in schema.fields.items():
        if name in table.column_names:
            columns[name] = _column_to_numpy(table.column(name), field, None)
    return columns, table.num_rows


def _probe_serialize(columns: dict, rows: int) -> dict:
    """``ZeroCopySerializer`` roundtrip bandwidth on a real decoded payload —
    the worker→consumer transport ceiling for process pools (in-process pools
    skip this stage entirely; their ceiling is effectively infinite)."""
    from petastorm_tpu.workers.serializers import ZeroCopySerializer
    serializer = ZeroCopySerializer()
    frames = serializer.serialize_multipart(columns)     # warm
    serializer.deserialize_multipart(frames)
    payload_bytes = sum(getattr(v, 'nbytes', 0) for v in columns.values())
    elapsed = None
    for _ in range(PROBE_REPS):
        start = time.perf_counter()
        frames = serializer.serialize_multipart(columns)
        serializer.deserialize_multipart(frames)
        took = time.perf_counter() - start
        elapsed = took if elapsed is None else min(elapsed, took)
    return {
        'rows': rows,
        'payload_bytes': int(payload_bytes),
        'seconds': round(elapsed, 6),
        'rows_per_s': round(rows / elapsed, 1) if elapsed else None,
        'mb_per_s': round(payload_bytes / _MB / elapsed, 1)
        if elapsed else None,
    }


def _probe_device_stage(columns: dict, rows: int) -> Optional[dict]:
    """Host→device staging bandwidth through the production
    :func:`~petastorm_tpu.jax_utils.stage_to_global` on a replicated
    single-device sharding. ``None`` when no jax backend initializes (the
    profiler must work on a read-only host with no accelerator runtime)."""
    try:
        import jax
        import numpy as np

        from petastorm_tpu.jax_utils import stage_to_global
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:1]), ('data',))
        sharding = jax.sharding.NamedSharding(mesh,
                                              jax.sharding.PartitionSpec())
        staged = stage_to_global(columns, sharding)          # warm + compile
        jax.block_until_ready({k: v for k, v in staged.items()
                               if k != '_host'})
        payload_bytes = sum(getattr(v, 'nbytes', 0)
                            for v in columns.values())
        elapsed = None
        for _ in range(PROBE_REPS):
            start = time.perf_counter()
            staged = stage_to_global(columns, sharding)
            jax.block_until_ready({k: v for k, v in staged.items()
                                   if k != '_host'})
            took = time.perf_counter() - start
            elapsed = took if elapsed is None else min(elapsed, took)
    except Exception as e:  # noqa: BLE001 - probe must degrade, not raise
        logger.debug('device-stage probe unavailable: %r', e)
        return None
    return {
        'rows': rows,
        'payload_bytes': int(payload_bytes),
        'seconds': round(elapsed, 6),
        'rows_per_s': round(rows / elapsed, 1) if elapsed else None,
        'mb_per_s': round(payload_bytes / _MB / elapsed, 1)
        if elapsed else None,
    }


def _raw_sample_columns(filesystem, sampled, schema) -> Optional[Tuple]:
    """``(plans, raw_columns, rows)`` for the bytes-through probes: the
    device-decode plans of this view plus one sampled row group's raw
    ``(n, stride)`` uint8 grids, or ``None`` when nothing plans (host-matrix
    store, kill switch off, no jax backend)."""
    import pyarrow.parquet as pq

    from petastorm_tpu.ops.decode import (plan_device_decode, raw_column_view,
                                          repack_to_raw)
    from petastorm_tpu.readers.columnar_worker import _column_to_numpy
    plans, _ = plan_device_decode(schema)
    if not plans:
        return None
    piece = sampled[0]
    handle = filesystem.open(piece.path, 'rb')
    try:
        table = pq.ParquetFile(handle).read_row_group(piece.row_group)
    finally:
        handle.close()
    raw_columns = {}
    for name, plan in plans.items():
        if name not in table.column_names:
            continue
        raw = raw_column_view(table.column(name), plan)
        if raw is None:
            decoded = _column_to_numpy(table.column(name),
                                       schema.fields[name], None)
            raw = repack_to_raw(plan, decoded)
        raw_columns[name] = raw
    if not raw_columns:
        return None
    return plans, raw_columns, table.num_rows


def _probe_device_decode(plans, raw_columns, rows) -> Optional[dict]:
    """The jitted bytes-through decode ceiling (docs/decode.md): header-strip
    + bitcast + reshape under ``jax.jit`` over resident raw grids — compute
    only, no transfer (the :func:`_probe_ingest` twin measures that). The
    pair answers BENCH_r13's open question quantitatively: once decode moves
    off the host, which wall is next — device decode FLOPs or the PCIe/ICI
    ingest link. ``None`` when no jax backend initializes."""
    try:
        import jax

        from petastorm_tpu.ops.decode import build_fused_infeed
        fused = build_fused_infeed(plans)
        staged = {name: jax.device_put(col)
                  for name, col in raw_columns.items()}
        jax.block_until_ready(fused(staged))          # warm + compile
        decoded_bytes = sum(rows * plans[name].cell_nbytes
                            for name in raw_columns)
        elapsed = None
        for _ in range(PROBE_REPS):
            start = time.perf_counter()
            jax.block_until_ready(fused(staged))
            took = time.perf_counter() - start
            elapsed = took if elapsed is None else min(elapsed, took)
    except Exception as e:  # noqa: BLE001 - probe must degrade, not raise
        logger.debug('device-decode probe unavailable: %r', e)
        return None
    return {
        'rows': rows,
        'columns': sorted(raw_columns),
        'path': 'device',
        'decoded_bytes': int(decoded_bytes),
        'seconds': round(elapsed, 6),
        'rows_per_s': round(rows / elapsed, 1) if elapsed else None,
        'mb_per_s': round(decoded_bytes / _MB / elapsed, 1)
        if elapsed else None,
    }


def _probe_ingest(raw_columns, rows) -> Optional[dict]:
    """Raw-payload host→device transfer ceiling: ``jax.device_put`` of the
    exact ``(n, stride)`` uint8 grids a bytes-through reader ships — the
    PCIe/ICI ingest bandwidth PAPER §5.8 names as the intended pipeline
    ceiling. ``None`` when no jax backend initializes."""
    try:
        import jax
        payload_bytes = sum(col.nbytes for col in raw_columns.values())
        jax.block_until_ready(
            {k: jax.device_put(v) for k, v in raw_columns.items()})  # warm
        elapsed = None
        for _ in range(PROBE_REPS):
            start = time.perf_counter()
            jax.block_until_ready(
                {k: jax.device_put(v) for k, v in raw_columns.items()})
            took = time.perf_counter() - start
            elapsed = took if elapsed is None else min(elapsed, took)
    except Exception as e:  # noqa: BLE001 - probe must degrade, not raise
        logger.debug('ingest probe unavailable: %r', e)
        return None
    return {
        'rows': rows,
        'payload_bytes': int(payload_bytes),
        'seconds': round(elapsed, 6),
        'rows_per_s': round(rows / elapsed, 1) if elapsed else None,
        'mb_per_s': round(payload_bytes / _MB / elapsed, 1)
        if elapsed else None,
    }


def calibrate(filesystem, dataset_path, pieces, schema,
              sample_row_groups: int = 3,
              cache_dir: Optional[str] = None,
              save: bool = True) -> dict:
    """Run every micro-probe against ``sample_row_groups`` row groups of the
    actual dataset and return (and, with ``save``, cache) the calibration
    artifact. All ceilings are rows/sec for THIS dataset's rows on THIS
    host — per-stage, single-stream (the advisor's model scales them)."""
    digest = dataset_digest(pieces, schema)
    sampled = _sample_pieces(pieces, sample_row_groups)
    storage = _probe_storage(filesystem, sampled)
    decode = _probe_decode(filesystem, sampled, schema)
    columns, sample_rows = _decode_sample_columns(filesystem, sampled, schema)
    serialize = _probe_serialize(columns, sample_rows)
    device = _probe_device_stage(columns, sample_rows)
    # bytes-through probe family (docs/decode.md "Device-side decode"):
    # measured only when this view actually plans device columns
    raw_sample = _raw_sample_columns(filesystem, sampled, schema)
    device_decode = ingest = None
    if raw_sample is not None:
        plans, raw_columns, raw_rows = raw_sample
        device_decode = _probe_device_decode(plans, raw_columns, raw_rows)
        ingest = _probe_ingest(raw_columns, raw_rows)
    total_rows = sum(max(0, p.num_rows) for p in pieces)
    # the fastest of the open modes is the storage ceiling: the workers
    # pick per filesystem (and ``remote_read='ranged'`` by request), and
    # the roofline should not punish a dataset for the mode it does not use
    io_rates = [r for r in (storage.get('parquet_rows_per_s'),
                            storage.get('parquet_pre_buffer_rows_per_s'),
                            storage.get('parquet_ranged_rows_per_s'))
                if r]
    ceilings = {
        'io': max(io_rates) if io_rates else None,
        'decode': decode.get('rows_per_s'),
        'serialize': serialize.get('rows_per_s'),
        'device_stage': device.get('rows_per_s') if device else None,
        'device_decode': (device_decode.get('rows_per_s')
                          if device_decode else None),
        'ingest': ingest.get('rows_per_s') if ingest else None,
    }
    calibration = {
        'kind': 'petastorm_tpu_roofline_calibration',
        'probe_version': PROBE_SCHEMA_VERSION,
        'host': socket.gethostname(),
        'cpu_count': os.cpu_count() or 1,
        'dataset_path': str(dataset_path),
        'dataset_digest': digest,
        # deliberate wall clock: artifact timestamp for humans, never
        # compared against monotonic readings
        'written_at': time.time(),  # petalint: disable=monotonic-clock
        'sampled_row_groups': len(sampled),
        'sampled_rows': decode['rows'],
        'total_rows': total_rows,
        'rows_per_group': (decode['rows'] / len(sampled)) if sampled else 0,
        'probes': {
            'storage': storage,
            'decode': decode,
            'serialize': serialize,
            'device_stage': device,
            'device_decode': device_decode,
            'ingest': ingest,
        },
        'ceilings': ceilings,
    }
    if save:
        try:
            save_calibration(calibration, cache_dir)
        except OSError:
            logger.warning('could not cache calibration artifact',
                           exc_info=True)
    return calibration


def get_calibration(filesystem, dataset_path, pieces, schema,
                    mode: str = 'auto',
                    sample_row_groups: int = 3,
                    cache_dir: Optional[str] = None) -> Optional[dict]:
    """Resolve a calibration per ``mode``: ``'cached'`` loads the artifact
    or returns ``None`` (never probes — safe for hot paths and HTTP
    handlers that must stay cheap); ``'auto'`` loads the artifact and
    probes on a miss; ``'force'`` always re-probes."""
    if mode not in ('cached', 'auto', 'force'):
        raise ValueError("calibration mode must be 'cached', 'auto' or "
                         "'force'; got {!r}".format(mode))
    digest = dataset_digest(pieces, schema)
    if mode in ('cached', 'auto'):
        cal = load_calibration(digest, cache_dir)
        if cal is not None or mode == 'cached':
            return cal
    return calibrate(filesystem, dataset_path, pieces, schema,
                     sample_row_groups=sample_row_groups,
                     cache_dir=cache_dir)


# ---------------------------------------------------------------------------
# overlap-aware attribution
# ---------------------------------------------------------------------------

def interval_union(intervals: Iterable[Tuple[float, float]]) -> float:
    """Total length of the union of ``(start, end)`` intervals. THE
    attribution primitive: two overlapped 1s decode spans are 1s of decode
    wall, not 2 — summing stage durations double-counts exactly the overlap
    the pipeline exists to create."""
    merged = 0.0
    current_start = current_end = None
    # normalize BEFORE sorting: a reversed (end, start) tuple sorted raw
    # breaks the merge invariant (its true start can precede tuples already
    # consumed)
    for start, end in sorted((e, s) if e < s else (s, e)
                             for s, e in intervals):
        if current_end is None:
            current_start, current_end = start, end
        elif start > current_end:
            merged += current_end - current_start
            current_start, current_end = start, end
        elif end > current_end:
            current_end = end
    if current_end is not None:
        merged += current_end - current_start
    return merged


def attribute(spans: Sequence, wall_s: Optional[float] = None,
              snapshot: Optional[dict] = None) -> dict:
    """Per-stage busy/idle attribution from recorded span tuples
    (``Tracer.spans()``: ``(name, cat, start_s, dur_s, pid, tid, args)``).

    Per stage, the busy time is the **interval union** of that stage's
    spans across every track; ``busy_fraction`` divides by the observed
    wall (max span end − min span start unless ``wall_s`` is given). The
    ``critical`` stage is the busiest non-idle stage — with overlap, stage
    fractions do not sum to 1, and the binding constraint is whichever
    stage the wall clock cannot escape. ``overlap_s`` quantifies the win:
    sum of stage busy times minus their global union (0 = fully serial).

    With no spans (tracing off) and a ``snapshot``, falls back to the
    aggregate ``ReaderStats`` stage times — flagged ``'source':
    'snapshot'``, since aggregate sums cannot see overlap across workers.
    """
    spans = list(spans or ())
    if not spans:
        out = {'source': 'snapshot', 'wall_s': None, 'stages': {},
               'critical_stage': None, 'overlap_s': None}
        if snapshot:
            wall = snapshot.get('window_s') or wall_s
            out['wall_s'] = wall
            from petastorm_tpu.workers.stats import effective_io_s
            # same canonical stage names as the spans path, so consumers
            # can join stages[critical_stage] regardless of trace mode;
            # these sum ACROSS workers, so fractions can exceed 1 (flagged
            # by source='snapshot' — only spans see overlap)
            named = {
                'io': effective_io_s(snapshot),
                'decode': snapshot.get('worker_decode_s', 0.0),
                'serialize': snapshot.get('serialize_s', 0.0),
                'deserialize': snapshot.get('deserialize_s', 0.0),
                'device_stage': snapshot.get('device_stage_s', 0.0),
                'consumer_wait': (snapshot.get('queue_wait_s', 0.0)
                                  + snapshot.get('worker_publish_wait_s',
                                                 0.0)),
            }
            for stage, busy in named.items():
                if busy:
                    out['stages'][stage] = {
                        'busy_s': round(busy, 4),
                        'busy_fraction': round(busy / wall, 4)
                        if wall else None,
                    }
            active = {stage: busy for stage, busy in named.items()
                      if busy and stage not in IDLE_ATTRIBUTION_STAGES}
            if active:
                out['critical_stage'] = max(active, key=active.get)
        return out

    by_stage: Dict[str, List[Tuple[float, float]]] = {}
    starts, ends = [], []
    everything = []
    for name, cat, start_s, dur_s, _pid, _tid, _args in spans:
        stage = SPAN_STAGE.get(name, cat or 'other')
        end = start_s + max(0.0, dur_s)
        by_stage.setdefault(stage, []).append((start_s, end))
        everything.append((start_s, end))
        starts.append(start_s)
        ends.append(end)
    wall = wall_s if wall_s else (max(ends) - min(starts))
    stages = {}
    busy_sum = 0.0
    for stage, intervals in sorted(by_stage.items()):
        busy = interval_union(intervals)
        stages[stage] = {
            'spans': len(intervals),
            'busy_s': round(busy, 4),
            'busy_fraction': round(busy / wall, 4) if wall else None,
        }
        if stage not in IDLE_ATTRIBUTION_STAGES:
            busy_sum += busy
    active = {stage: info['busy_s'] for stage, info in stages.items()
              if stage not in IDLE_ATTRIBUTION_STAGES}
    critical = max(active, key=active.get) if active else None
    return {
        'source': 'spans',
        'wall_s': round(wall, 4),
        'stages': stages,
        'critical_stage': critical,
        # how much stage work ran concurrently: serial sum minus the union
        'overlap_s': round(max(0.0, busy_sum - interval_union(everything)), 4),
    }


# ---------------------------------------------------------------------------
# throughput model + roofline profile
# ---------------------------------------------------------------------------

def predict_throughput(ceilings: dict, workers: int = 1,
                       cpu_count: Optional[int] = None,
                       io_overlap: bool = False,
                       in_process: bool = True,
                       cached: bool = False,
                       worker_efficiency: float = 1.0) -> Optional[float]:
    """Predicted samples/s from calibrated single-stream ceilings.

    The model (docs/profiling.md "Attribution math"):

    - decode scales with effective parallel workers ``min(workers,
      cpu_count)`` (per BENCH_scaling.json: workers beyond cores
      time-slice, they do not add decode), damped by ``worker_efficiency``
      — the *measured* marginal value of each extra worker. ``1.0`` is
      ideal scaling (the default and the old behavior); ``0.0`` means
      extra workers add nothing; **negative** values model the GIL-convoy
      regime BENCH_r13 measured (2 thread workers 2.6x *slower* than 1 on
      ~10µs decode calls: sub-GIL-quantum work makes workers serialize on
      the lock instead of the codecs). Effective parallelism is
      ``1 + worker_efficiency * (eff_workers - 1)``, floored at 0.05 so a
      pathological factor predicts "much slower", never zero;
    - storage is a shared resource (no worker scaling);
    - without readahead a worker serializes read→decode, so the combined
      rate is harmonic (``1/(1/io + 1/decode)``); with ``io_overlap``
      (readahead) it is ``min(io, decode)``;
    - process pools additionally cap at the serializer ceiling,
      in-process pools skip that stage;
    - device staging caps everything (it is downstream of any cache);
    - ``cached`` (warm shared/local tier) skips io+decode entirely.

    Monotone in ``workers`` by construction **for non-negative
    worker_efficiency** — every term is then nondecreasing in the
    effective worker count (the advisor's monotonicity contract, asserted
    in tests). A negative measured factor deliberately breaks monotonicity:
    that is the point (the model must be able to predict that removing a
    worker is the winning move).
    """
    io = ceilings.get('io')
    decode = ceilings.get('decode')
    caps = []
    if not cached:
        eff = max(1, min(workers, cpu_count or workers))
        parallel = max(0.05, 1.0 + worker_efficiency * (eff - 1))
        scaled_decode = decode * parallel if decode else None
        if io and scaled_decode:
            if io_overlap:
                caps.append(min(io, scaled_decode))
            else:
                caps.append(1.0 / (1.0 / io + 1.0 / scaled_decode))
        elif scaled_decode:
            caps.append(scaled_decode)
        elif io:
            caps.append(io)
    if not in_process and ceilings.get('serialize'):
        caps.append(ceilings['serialize'])
    if ceilings.get('device_stage'):
        caps.append(ceilings['device_stage'])
    if not caps and cached:
        # no post-cache stage was calibrated (in-process pool, no jax
        # backend for the staging probe): the measurable FLOOR is the best
        # uncached configuration — a warm cache can only beat it, so the
        # model must not predict nothing at all
        return predict_throughput(ceilings, workers=cpu_count or workers,
                                  cpu_count=cpu_count, io_overlap=True,
                                  in_process=in_process, cached=False)
    if not caps:
        return None
    return min(caps)


def measured_worker_efficiency(measured_samples_per_s,
                               decode_ceiling,
                               workers: int) -> Optional[float]:
    """The per-worker efficiency factor implied by a *measured* rate on a
    decode-bound pipeline: solve ``measured = ceiling * (1 + e*(w-1))`` for
    ``e``, clamped to ``[-1, 1]``. ``None`` when underdetermined (one
    worker, or no decode ceiling) — with one worker the marginal value of a
    second is unknowable until tried, which is exactly why the autotune
    controller pairs this model with revert-on-regression.

    This is how BENCH_r13's GIL-convoy evidence (w2 at 25% of the decode
    ceiling vs w1 at 66%) becomes representable: the implied ``e`` is
    strongly negative, and the model then predicts the *removal* of a
    worker as a gain (see :func:`replay_against_artifacts`)."""
    if workers is None or workers <= 1:
        return None
    if not decode_ceiling or not measured_samples_per_s:
        return None
    e = (measured_samples_per_s / decode_ceiling - 1.0) / (workers - 1)
    return max(-1.0, min(1.0, e))


def build_profile(snapshot: dict, calibration: Optional[dict] = None,
                  spans: Optional[Sequence] = None,
                  samples_per_sec: Optional[float] = None,
                  workers_count: Optional[int] = None,
                  io_readahead=0, pool_type: str = 'thread',
                  cache_type: str = 'null') -> dict:
    """Assemble the roofline profile: measured rate, calibrated ceilings,
    the binding stage, the %-of-ceiling verdict, overlap-aware attribution,
    and the advisor's ranked recommendations. Everything JSON-able."""
    measured = samples_per_sec
    estimated = False
    if measured is None:
        items_per_s = snapshot.get('items_per_s') or 0.0
        rows_per_group = (calibration or {}).get('rows_per_group') or 0
        if items_per_s and rows_per_group:
            # the stats layer counts published items (row groups for
            # columnar/batch readers); scale by the calibrated mean rows
            # per group to talk samples/s like the benchmarks do
            measured = items_per_s * rows_per_group
            estimated = True
        else:
            measured = items_per_s
    profile = {
        'kind': 'petastorm_tpu_roofline_profile',
        'measured_samples_per_s': round(measured, 2) if measured else 0.0,
        'measured_is_estimated_from_items': estimated,
        'attribution': attribute(spans, snapshot=snapshot),
        'config': {'workers_count': workers_count,
                   'io_readahead': io_readahead,
                   'pool_type': pool_type,
                   'cache_type': cache_type},
    }
    if calibration is None:
        profile['calibrated'] = False
        profile['ceilings'] = {}
        profile['binding_stage'] = None
        profile['roofline_fraction'] = None
        return profile
    ceilings = dict(calibration.get('ceilings') or {})
    in_process = pool_type != 'process'
    workers = max(1, workers_count or 1)
    cpu_count = calibration.get('cpu_count') or 1
    io_overlap = bool(io_readahead) \
        or snapshot.get('io_overlap_fraction', 0.0) > 0.5
    # A warm cache legitimately skips the io+decode the ceilings measure
    # (BENCH_r11: 13.4x the roofline): when the snapshot proves the reads
    # were mostly cache hits, judge against the post-cache stages instead.
    hits = snapshot.get('shared_hits', 0)
    misses = snapshot.get('shared_misses', 0)
    cache_warm = (cache_type == 'shared' and hits + misses > 0
                  and hits / (hits + misses) > 0.5)
    # effective per-stage ceilings for THIS configuration: decode scaled by
    # usable workers, serializer dropped for in-process pools, io+decode
    # dropped for a proven-warm cache
    effective = {}
    if not cache_warm:
        if ceilings.get('io'):
            effective['io'] = ceilings['io']
        if ceilings.get('decode'):
            effective['decode'] = \
                ceilings['decode'] * min(workers, cpu_count)
    if not in_process and ceilings.get('serialize'):
        effective['serialize'] = ceilings['serialize']
    if ceilings.get('device_stage'):
        effective['device_stage'] = ceilings['device_stage']
    if cache_warm and not effective:
        # no post-cache stage was calibrated (in-process pool, no jax
        # backend): fall back to the uncached ceilings so the verdict
        # stays defined — a warm cache legitimately exceeding them gets
        # the benign cache-replay warning below, not a None binding stage
        if ceilings.get('io'):
            effective['io'] = ceilings['io']
        if ceilings.get('decode'):
            effective['decode'] = \
                ceilings['decode'] * min(workers, cpu_count)
    binding = min(effective, key=effective.get) if effective else None
    fraction = None
    if binding and effective[binding]:
        fraction = measured / effective[binding] if measured else 0.0
    predicted = predict_throughput(
        ceilings, workers=workers, cpu_count=cpu_count,
        io_overlap=io_overlap, in_process=in_process, cached=cache_warm)
    profile.update({
        'calibrated': True,
        'cache_warm': cache_warm,
        'calibration_host': calibration.get('host'),
        'dataset_digest': calibration.get('dataset_digest'),
        'cpu_count': cpu_count,
        'ceilings': {k: round(v, 2) for k, v in ceilings.items()
                     if v is not None},
        'effective_ceilings': {k: round(v, 2)
                               for k, v in effective.items()},
        'binding_stage': binding,
        'binding_ceiling_samples_per_s': round(effective[binding], 2)
        if binding else None,
        'roofline_fraction': round(fraction, 4)
        if fraction is not None else None,
        'predicted_samples_per_s': round(predicted, 2)
        if predicted else None,
    })
    if fraction is not None and fraction > SANE_FRACTION_LIMIT:
        if cache_type != 'null':
            # a replaying cache (proven warm, or local-disk whose hits no
            # counter records) is the benign explanation — name it
            # instead of crying broken measurement
            profile['warning'] = (
                'measured rate is {:.1f}x the calibrated {} ceiling; with '
                "cache_type={!r} a cache-replay epoch legitimately beats "
                'the io+decode ceilings — judge cached epochs against the '
                'post-cache stages, not this one'.format(
                    fraction, binding, cache_type))
        else:
            profile['warning'] = (
                'measured rate is {:.1f}x the calibrated {} ceiling — a '
                'sustained pipeline cannot beat its binding stage, so '
                'either the measured window drained pre-decoded buffers '
                '(lengthen it past steady state) or the calibration is '
                "stale (profile(calibrate='force'))".format(
                    fraction, binding))
    profile['advisor'] = advise(profile)
    return profile


def explain(profile: dict) -> str:
    """One human sentence per roofline verdict — what ``reader
    .explain_throughput()`` and the CLI's ``--profile`` print."""
    measured = profile.get('measured_samples_per_s') or 0.0
    if not profile.get('calibrated'):
        return ('measured {:.1f} samples/s; no calibration for this '
                'dataset yet — run reader.profile() (or benchmark/'
                'roofline.py) to measure the per-stage ceilings'
                .format(measured))
    binding = profile.get('binding_stage')
    ceiling = profile.get('binding_ceiling_samples_per_s') or 0.0
    fraction = profile.get('roofline_fraction') or 0.0
    lines = ['measured {:.1f} samples/s = {:.1f}% of the binding stage '
             "({}) ceiling of {:.1f} samples/s".format(
                 measured, 100.0 * fraction, binding, ceiling)]
    if profile.get('warning'):
        lines.append('WARNING: ' + profile['warning'])
    for rec in (profile.get('advisor') or [])[:2]:
        lines.append('try {}: {}'.format(rec['knob'], rec['reason']))
    return '; '.join(lines)


def roofline_gauges(profile: dict) -> dict:
    """The profile as flat metric gauges merged into stats snapshots —
    ``stage_ceiling_<stage>``, ``roofline_fraction`` and the (string-
    valued, label-exported) ``binding_stage`` — so Prometheus scrapes show
    %-of-ceiling next to raw samples/s."""
    gauges = {}
    for stage, value in (profile.get('effective_ceilings') or {}).items():
        gauges['stage_ceiling_{}'.format(stage)] = value
    if profile.get('roofline_fraction') is not None:
        gauges['roofline_fraction'] = profile['roofline_fraction']
    if profile.get('binding_stage'):
        gauges['binding_stage'] = profile['binding_stage']
    if profile.get('measured_samples_per_s') is not None:
        gauges['roofline_samples_per_s'] = profile['measured_samples_per_s']
    return gauges


def roofline_summary(profile: dict) -> dict:
    """The compact roofline section embedded in flight records and
    ``infeed_diagnosis`` output."""
    return {
        'measured_samples_per_s': profile.get('measured_samples_per_s'),
        'binding_stage': profile.get('binding_stage'),
        'binding_ceiling_samples_per_s':
            profile.get('binding_ceiling_samples_per_s'),
        'roofline_fraction': profile.get('roofline_fraction'),
        'critical_stage': (profile.get('attribution') or {})
            .get('critical_stage'),
    }


# ---------------------------------------------------------------------------
# what-if advisor
# ---------------------------------------------------------------------------

def advise(profile: dict, max_workers: Optional[int] = None) -> List[dict]:
    """Ranked knob recommendations with predicted samples/s deltas.

    Each entry: ``{'knob', 'from', 'to', 'predicted_samples_per_s',
    'predicted_delta_pct', 'reason'}``, sorted by predicted delta
    descending; only positive-delta recommendations are emitted. The
    predictions replay :func:`predict_throughput` — the same model the
    roofline verdict uses — so a recommendation can never promise more than
    the calibrated ceilings admit."""
    if not profile.get('calibrated'):
        return []
    ceilings = {k: v for k, v in (profile.get('ceilings') or {}).items()}
    config = profile.get('config') or {}
    workers = max(1, config.get('workers_count') or 1)
    cpu_count = profile.get('cpu_count') or 1
    in_process = config.get('pool_type') != 'process'
    io_overlap = bool(config.get('io_readahead'))
    base = predict_throughput(ceilings, workers=workers, cpu_count=cpu_count,
                              io_overlap=io_overlap, in_process=in_process)
    if not base:
        return []
    recommendations = []

    def consider(knob, from_value, to_value, predicted, reason):
        if predicted is None:
            return
        delta = 100.0 * (predicted - base) / base
        if delta < 1.0:       # sub-percent predictions are noise, not advice
            return
        recommendations.append({
            'knob': knob, 'from': from_value, 'to': to_value,
            'predicted_samples_per_s': round(predicted, 1),
            'predicted_delta_pct': round(delta, 1),
            'reason': reason,
        })

    target_workers = max_workers or cpu_count
    if target_workers > workers:
        predicted = predict_throughput(
            ceilings, workers=target_workers, cpu_count=cpu_count,
            io_overlap=io_overlap, in_process=in_process)
        consider('workers_count', workers, target_workers, predicted,
                 'decode is parallel up to the {} host cores'
                 .format(cpu_count))
    if not io_overlap and ceilings.get('io') and ceilings.get('decode'):
        predicted = predict_throughput(
            ceilings, workers=workers, cpu_count=cpu_count,
            io_overlap=True, in_process=in_process)
        consider('io_readahead', 0, 'auto', predicted,
                 'overlap storage reads with decode instead of serializing '
                 'them per row group')
    if config.get('cache_type') in (None, 'null', 'local-disk'):
        cached = predict_throughput(ceilings, workers=workers,
                                    cpu_count=cpu_count, io_overlap=True,
                                    in_process=in_process, cached=True)
        consider("cache_type='shared'", config.get('cache_type') or 'null',
                 'shared', cached,
                 'epochs 2+ (and every concurrent reader on this host) '
                 'skip io+decode entirely via the host-wide decoded tier')
    if not in_process:
        # the inverse direction: a process pool whose serializer ceiling
        # binds should drop to threads when decode would not regress
        without = predict_throughput(ceilings, workers=workers,
                                     cpu_count=cpu_count,
                                     io_overlap=io_overlap, in_process=True)
        consider("reader_pool_type='thread'", 'process', 'thread', without,
                 'the zero-copy transport ceiling binds before decode does')
    recommendations.sort(key=lambda r: -r['predicted_delta_pct'])
    return recommendations


# ---------------------------------------------------------------------------
# model validation against the committed BENCH artifacts
# ---------------------------------------------------------------------------

def replay_against_artifacts(root: Optional[str] = None) -> List[dict]:
    """Directional validation of the advisor's model against committed BENCH
    artifacts: each check replays the model on a measured configuration pair
    and verifies the model predicts the direction the measurement showed.
    Returns ``[{'check', 'artifact', 'ok', 'detail'}, ...]`` (artifacts
    absent from ``root`` are skipped, not failed — the profiler must work
    outside the repo checkout)."""
    root = root or os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    checks = []

    def load(name):
        try:
            with open(os.path.join(root, name)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    # BENCH_r07: readahead overlapped a ~1:1 io:decode pipeline for 1.81x.
    # Model: min(io, dec) / harmonic(io, dec) = 2.0 at 1:1 — direction up,
    # bounded by 2 (the model must predict a gain, and not a fantasy one).
    r07 = load('BENCH_r07.json')
    if r07 is not None:
        parsed = r07.get('parsed') or r07
        speedup = (parsed.get('speedup_items_per_s')
                   if isinstance(parsed, dict) else None)
        ceilings = {'io': 100.0, 'decode': 100.0}
        serial = predict_throughput(ceilings, io_overlap=False, cpu_count=1)
        overlapped = predict_throughput(ceilings, io_overlap=True,
                                        cpu_count=1)
        model_gain = overlapped / serial
        ok = 1.0 < model_gain <= 2.0 and (speedup is None or speedup > 1.0)
        checks.append({'check': 'readahead_overlap_direction',
                       'artifact': 'BENCH_r07.json', 'ok': ok,
                       'detail': 'model {:.2f}x vs measured {}x'.format(
                           model_gain, speedup)})
    # BENCH_scaling: flat samples/s curve on a 1-core host. Model with
    # cpu_count=1 must predict zero gain from extra workers.
    scaling = load('BENCH_scaling.json')
    if scaling is not None:
        cpus = scaling.get('host_cpu_count') or 1
        ceilings = {'io': 1e6, 'decode': 100.0}
        one = predict_throughput(ceilings, workers=1, cpu_count=cpus,
                                 io_overlap=True)
        eight = predict_throughput(ceilings, workers=8, cpu_count=cpus,
                                   io_overlap=True)
        ok = (eight <= one * max(1, cpus) + 1e-9) and \
            (cpus != 1 or abs(eight - one) < 1e-9)
        checks.append({'check': 'worker_scaling_bounded_by_cores',
                       'artifact': 'BENCH_scaling.json', 'ok': ok,
                       'detail': 'model predicts {:.1f} -> {:.1f} on a '
                                 '{}-core host'.format(one, eight, cpus)})
    # BENCH_r11: a warm shared-cache pass beat the serial io+decode
    # roofline. Model: cached throughput must be >= the uncached ceiling.
    r11 = load('BENCH_r11.json')
    if r11 is not None:
        roof = (r11.get('roofline') or {}).get('samples_per_sec')
        warm = (r11.get('warm') or {}).get('samples_per_sec')
        ceilings = {'io': 1000.0, 'decode': 500.0, 'device_stage': 50000.0}
        uncached = predict_throughput(ceilings, io_overlap=True, cpu_count=1)
        cached = predict_throughput(ceilings, io_overlap=True, cpu_count=1,
                                    cached=True)
        ok = cached >= uncached and (not roof or not warm or warm >= roof)
        checks.append({'check': 'warm_cache_exceeds_io_decode_roofline',
                       'artifact': 'BENCH_r11.json', 'ok': ok,
                       'detail': 'model cached {:.0f} >= uncached {:.0f}; '
                                 'measured warm {} vs roofline {}'.format(
                                     cached, uncached, warm, roof)})
    # BENCH_r13: 2 thread workers measured ~2.6x SLOWER than 1 on the
    # small-png mnist line (GIL convoy on ~10µs decode calls). With the
    # measured per-worker efficiency factor the model must predict the w2
    # direction DOWN — the honest-measurement note the default ideal-scaling
    # model could not represent (and the regression the autotune
    # controller's revert path exists to undo when it walks into it blind).
    r13 = load('BENCH_r13.json')
    if r13 is not None:
        lines = r13.get('lines') or {}
        w1 = (lines.get('mnist_w1_batched') or {}).get('samples_per_sec')
        w2_line = lines.get('mnist_w2_batched') or {}
        w2 = w2_line.get('samples_per_sec')
        decode_ceiling = ((w2_line.get('roofline') or {})
                          .get('ceilings') or {}).get('decode')
        if w1 and w2 and decode_ceiling:
            efficiency = measured_worker_efficiency(w2, decode_ceiling, 2)
            ceilings = {'io': 10.0 * decode_ceiling,
                        'decode': decode_ceiling}
            base = predict_throughput(ceilings, workers=1, cpu_count=2,
                                      io_overlap=True)
            measured_model = predict_throughput(
                ceilings, workers=2, cpu_count=2, io_overlap=True,
                worker_efficiency=efficiency)
            ideal_model = predict_throughput(ceilings, workers=2,
                                             cpu_count=2, io_overlap=True)
            # the measured factor must flip the predicted direction to
            # match the measurement (down), while the ideal factor still
            # predicts up — proving the knob adds representational power
            # rather than just re-deriving the ideal curve
            ok = (w2 < w1 and measured_model < base
                  and ideal_model > base and efficiency is not None
                  and efficiency < 0)
            checks.append({
                'check': 'gil_convoy_negative_scaling_direction',
                'artifact': 'BENCH_r13.json', 'ok': ok,
                'detail': 'measured w1 {:.0f} -> w2 {:.0f}; implied '
                          'efficiency {:.2f}; model w2 {:.0f} vs w1 {:.0f} '
                          '(ideal-scaling model said {:.0f})'.format(
                              w1, w2, efficiency or 0.0,
                              measured_model or 0.0, base or 0.0,
                              ideal_model or 0.0)})
    return checks
