"""Mixes several readers with given sampling probabilities.

Reference parity: ``petastorm/weighted_sampling_reader.py`` — cumulative
probability draw per ``__next__`` (:90-95), schema/batched/ngram compatibility
validation (:64-82). Ours draws from a seedable generator.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


def normalize_cumulative(probabilities) -> np.ndarray:
    """Validated, normalized cumulative probability edges — shared by the
    streaming mixture and the indexed mixture so the draw semantics cannot
    diverge."""
    probabilities = list(probabilities)
    if not probabilities:
        raise ValueError('At least one probability is required')
    if any(p < 0 for p in probabilities):
        raise ValueError('probabilities must be non-negative, got {!r}'
                         .format(probabilities))
    total = float(sum(probabilities))
    if total <= 0:
        raise ValueError('probabilities must sum to a positive value')
    return np.cumsum([p / total for p in probabilities])


def draw_index(cumulative: np.ndarray, unit_sample: float) -> int:
    """Map one uniform [0,1) draw onto the cumulative edges."""
    idx = int(np.searchsorted(cumulative, unit_sample, side='right'))
    return min(idx, len(cumulative) - 1)


class WeightedSamplingReader:
    """On every ``next()``, picks reader ``i`` with probability ``probabilities[i]``.

    Iteration stops when any underlying reader is exhausted (matching the
    reference semantics).
    """

    def __init__(self, readers: List, probabilities: List[float],
                 seed: Optional[int] = None):
        if len(readers) != len(probabilities):
            raise ValueError('readers and probabilities must have equal length')
        if not readers:
            raise ValueError('At least one reader is required')
        self._readers = readers
        self._cumulative = normalize_cumulative(probabilities)
        self._rng = np.random.default_rng(seed)

        first = readers[0]
        for other in readers[1:]:
            if set(other.schema.fields.keys()) != set(first.schema.fields.keys()):
                raise ValueError('All readers must share the same schema fields')
            if other.batched_output != first.batched_output:
                raise ValueError('All readers must have the same batched_output mode')
            if (getattr(other, 'ngram', None) is None) != (getattr(first, 'ngram', None)
                                                           is None):
                raise ValueError('Cannot mix ngram and non-ngram readers')
        self.schema = first.schema
        self.batched_output = first.batched_output
        self.ngram = getattr(first, 'ngram', None)
        self.last_row_consumed = False

    def __iter__(self):
        return self

    def __next__(self):
        choice = draw_index(self._cumulative, self._rng.random())
        try:
            return next(self._readers[choice])
        except StopIteration:
            self.last_row_consumed = True
            raise

    def next(self):
        return self.__next__()

    def stop(self):
        for r in self._readers:
            r.stop()

    def join(self):
        for r in self._readers:
            r.join()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.stop()
        self.join()
