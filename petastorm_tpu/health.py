"""Live pipeline health: heartbeats, stall watchdog, flight recorder, and an
HTTP debug endpoint.

The reader is a multi-stage pipeline (ventilator → worker pool → transport →
loader → device staging) whose dominant production failure mode is not a
crash but a **silent stall** — a wedged worker, a full result queue, a
starving infeed that only shows up as a slow train step. The post-hoc layers
(``ReaderStats``, spans, the metrics emitter — see ``docs/tracing.md``) tell
you what happened after you attach to the job; this module is the *live*
layer: the pipeline reports its own health while running, detects that it is
stuck, and dumps a diagnosis automatically.

Three pieces:

- **Heartbeats.** Every long-lived pipeline entity — each worker (thread and
  process pools), the ventilator thread, each worker's background readahead
  reader thread, the loader's prefetch thread — publishes a per-entity
  record: current stage (``idle``/``io``/``decode``/...), a monotonic
  last-progress timestamp, and items completed. In-process entities publish
  through :class:`~petastorm_tpu.workers.worker_base.WorkerBase` (the pool
  reads their records directly); process workers piggyback their records on
  the existing per-item accounting control message *plus* a low-frequency
  ZMQ heartbeat frame, so an item that legitimately takes minutes still
  beats. Timestamps are ``time.perf_counter()`` readings — CLOCK_MONOTONIC
  on Linux, comparable across local processes (the same clock contract as
  the span tracer).
- **Watchdog.** :class:`PipelineWatchdog` evaluates the heartbeat records
  against a stall threshold and classifies the pipeline as ``healthy`` /
  ``degraded`` / ``stalled`` / ``starving``, using the same bottleneck
  signals as ``jax_utils.infeed_diagnosis`` (one classification, two
  consumers). On a transition into ``stalled`` it fires its ``on_stall``
  callback once per episode — the ``Reader`` wires that to a
  **flight-recorder dump**: one JSON artifact with per-entity heartbeats,
  the stats snapshot, queue occupancy, faulthandler-style stacks of every
  in-process thread, and the tail of the tracer's span ring when tracing is
  on.
- **HTTP debug endpoint.** :class:`DebugServer` is an opt-in stdlib
  ``http.server`` thread (``debug_port=`` on the reader factories, the
  ``PETASTORM_TPU_DEBUG_PORT`` env var, or ``--debug-port`` on the CLI)
  serving ``GET /healthz`` (200/503 from the watchdog verdict), ``/metrics``
  (Prometheus text, same formatter as the metrics emitter),
  ``/diagnostics`` (stats + heartbeats + verdict as JSON) and ``/stacks``.

Heartbeat publishing is on by default and costs a few attribute assignments
per item (measured ~0 on the throughput bench, ``BENCH_r09.json``); set
``PETASTORM_TPU_HEALTH=0`` to compile it out of the workers entirely. The
watchdog thread and HTTP server only exist when requested
(``stall_timeout=`` / ``debug_port=``). See ``docs/health.md``.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
import traceback
from typing import Callable, Dict, List, Optional

logger = logging.getLogger(__name__)

#: Environment variable gating heartbeat publication (default on).
#: ``0``/``false``/``off`` disable every beat call site.
HEALTH_ENV_VAR = 'PETASTORM_TPU_HEALTH'

#: Environment variable naming the debug-endpoint port when the
#: ``debug_port=`` kwarg is left at its default. ``0`` binds an ephemeral
#: port (read it back from ``reader.debug_port``).
DEBUG_PORT_ENV_VAR = 'PETASTORM_TPU_DEBUG_PORT'

#: Default stall threshold (seconds an entity may sit in an active stage
#: without progress before the pipeline is classified ``stalled``). Used for
#: on-demand verdicts (``/healthz`` with no ``stall_timeout=``); row-group
#: decode on cold object stores can legitimately take tens of seconds.
DEFAULT_STALL_AFTER_S = 120.0

#: Pipeline states, from best to worst.
HEALTHY, DEGRADED, STARVING, STALLED = ('healthy', 'degraded', 'starving',
                                        'stalled')

#: Stages that mean "waiting for work, not doing it" — age in these stages
#: is never a stall. ``backpressured`` is the ventilator blocked on its
#: in-flight bound (the stall, if any, is downstream); ``starting`` covers
#: the gap between entity construction and its first work item.
IDLE_STAGES = frozenset({'idle', 'done', 'stopped', 'backpressured',
                         'starting'})

#: Read-plane tail thresholds for NAMING the slow side (not inferring it):
#: a planned object-store range fetch whose p99 exceeds this is a slow
#: store; a shared-cache peer fetch (one LAN HTTP round trip + a segment
#: read) whose p99 exceeds this is a slow peer. Both feed
#: :func:`bottleneck_signals` from the ``io_range_p99_s`` /
#: ``peer_fetch_p99_s`` snapshot keys (docs/pod_observability.md).
SLOW_RANGE_FETCH_P99_S = 1.0
SLOW_PEER_FETCH_P99_S = 0.25


def heartbeats_enabled() -> bool:
    """The :data:`HEALTH_ENV_VAR` gate (default on)."""
    value = os.environ.get(HEALTH_ENV_VAR, '').strip().lower()
    return value not in ('0', 'false', 'off')


def resolve_debug_port(debug_port) -> Optional[int]:
    """Resolve the ``debug_port=`` kwarg against :data:`DEBUG_PORT_ENV_VAR`.

    ``None`` defers to the env var (unset/empty → no server); an int is the
    port to bind (``0`` = ephemeral). Returns ``None`` when no server should
    run. A malformed env value disables the endpoint with a warning instead
    of raising: a job-wide observability env var must never kill the
    pipeline it observes (an explicit bad ``debug_port=`` kwarg still
    raises — that is a programming error at the call site)."""
    if debug_port is None:
        value = os.environ.get(DEBUG_PORT_ENV_VAR, '').strip()
        if not value:
            return None
        try:
            port = int(value)
            if not 0 <= port <= 65535:
                raise ValueError(port)
        except ValueError:
            logger.warning('debug endpoint disabled: %s=%r is not a port '
                           'number', DEBUG_PORT_ENV_VAR, value)
            return None
        return port
    return int(debug_port)


class HeartbeatRegistry:
    """Thread-safe store of per-entity heartbeat records.

    A record is ``{'stage': str, 'ts': float, 'items': int, 'pid': int}``
    with ``ts`` a ``time.perf_counter()`` reading; :meth:`snapshot` adds the
    derived ``age_s``."""

    __slots__ = ('_lock', '_records')

    def __init__(self):
        self._lock = threading.Lock()
        self._records: Dict[str, dict] = {}

    def beat(self, entity: str, stage: str, items: Optional[int] = None,
             pid: Optional[int] = None) -> None:
        """Record progress for ``entity``: it is now in ``stage`` and (when
        given) has completed ``items`` work items."""
        record = {'stage': stage, 'ts': time.perf_counter(),
                  'pid': os.getpid() if pid is None else pid}
        with self._lock:
            prev = self._records.get(entity)
            record['items'] = (items if items is not None
                               else (prev or {}).get('items', 0))
            self._records[entity] = record

    def update(self, records: Dict[str, dict]) -> None:
        """Replace entity records wholesale (records shipped back from a
        process worker already carry their own ``ts``/``pid``)."""
        if not records:
            return
        with self._lock:
            self._records.update(records)

    def snapshot(self, now: Optional[float] = None) -> Dict[str, dict]:
        """Point-in-time copy of every record with ``age_s`` derived."""
        now = time.perf_counter() if now is None else now
        with self._lock:
            records = {entity: dict(record)
                       for entity, record in self._records.items()}
        for record in records.values():
            record['age_s'] = max(0.0, now - record['ts'])
        return records


class HealthMonitor:
    """Aggregates the heartbeat sources of one reader pipeline.

    Non-pool entities (ventilator, loader prefetch thread) :meth:`beat`
    directly into the monitor's own registry; the pool contributes a live
    source callable (``pool.heartbeats``) merged at :meth:`heartbeats` time,
    so in-process worker records are read fresh rather than forwarded."""

    def __init__(self):
        self._registry = HeartbeatRegistry()
        self._sources: List[Callable[[], Dict[str, dict]]] = []
        self.enabled = heartbeats_enabled()

    def beat(self, entity: str, stage: str, items: Optional[int] = None) -> None:
        if self.enabled:
            self._registry.beat(entity, stage, items=items)

    def add_source(self, source: Callable[[], Dict[str, dict]]) -> None:
        """Register a callable returning ``{entity: record}`` (records carry
        their own ``ts``; ``age_s`` is derived here)."""
        self._sources.append(source)

    def heartbeats(self) -> Dict[str, dict]:
        """Merged per-entity records across the registry and every source,
        each with derived ``age_s``."""
        now = time.perf_counter()
        merged = self._registry.snapshot(now)
        for source in self._sources:
            try:
                records = source()
            except Exception:  # a dying pool must not break health reporting
                logger.debug('heartbeat source %r failed', source,
                             exc_info=True)
                continue
            for entity, record in (records or {}).items():
                record = dict(record)
                record['age_s'] = max(0.0, now - record.get('ts', now))
                merged[entity] = record
        return merged


def bottleneck_signals(snapshot: dict) -> dict:
    """Classify the io/decode/consumer bottleneck from a ``ReaderStats``
    snapshot — the one definition shared by ``jax_utils.infeed_diagnosis``
    and :func:`classify_pipeline` (the watchdog), so the CLI's ``-d`` output
    and ``/healthz`` can never disagree.

    Returns ``{'bottleneck', 'hint', 'io_s', 'decode_s'}`` plus the
    queue-wait tail keys; thresholds and wording match
    ``docs/troubleshooting.md``.

    The consumer-wait **distribution** (not its mean) separates two regimes
    the sums cannot: steady backpressure (p50 ≈ p99 — the reader is simply
    slower than the consumer) vs **tail stalls** (p50 near zero but p99
    large: most batches are ready instantly, yet every Nth delivery stalls
    the device — the contention signature of a worker-pool + bounded-queue
    pipeline). A tail-stall verdict rides out as ``tail_stall: True`` with
    its own hint; see ``docs/latency.md``."""
    from petastorm_tpu.workers.stats import effective_io_s
    io_s = effective_io_s(snapshot)
    decode_s = snapshot.get('worker_decode_s', 0.0)
    publish_wait_s = snapshot.get('worker_publish_wait_s', 0.0)
    qw_p50 = snapshot.get('queue_wait_p50_s', 0.0)
    qw_p99 = snapshot.get('queue_wait_p99_s', 0.0)
    # tail stall: the p99 consumer wait dwarfs the median AND is large
    # enough to matter (>= 50ms) — mean-based signals read this as healthy
    tail_stall = bool(qw_p99 >= 0.05 and qw_p99 > 10.0 * max(qw_p50, 1e-4))
    busy = io_s + decode_s
    if tail_stall:
        bottleneck = 'tail-stall'
        hint = ('queue-wait p99 ({:.3f}s) dwarfs p50 ({:.4f}s): most '
                'batches arrive instantly but every Nth delivery stalls '
                'the consumer — look at the /slo burn, the flight-record '
                'p99 trend and per-stage histograms, not the means '
                '(docs/latency.md)'.format(qw_p99, qw_p50))
    elif publish_wait_s > busy:
        bottleneck = 'consumer'
        hint = ('workers outrun the consumer (publish_wait > io+decode): '
                'the training step / consumer loop is the ceiling')
    elif io_s > decode_s * 1.5:
        bottleneck = 'io'
        hint = ('storage stall dominates: raise io_readahead (or pass '
                "io_readahead='auto') before raising workers_count")
    elif decode_s > io_s * 1.5:
        bottleneck = 'decode'
        hint = ('decode dominates and reads are hidden: raise workers_count '
                'or cut decode work (decode_hints, lighter transforms)')
    else:
        bottleneck = 'balanced'
        hint = ('io and decode are comparable: io_readahead overlaps them '
                'for up to 2x; workers_count scales both')
    # name the slow side of the read plane when its own latency stage says
    # so — "io-bound" alone cannot distinguish a slow object store from a
    # slow peer cache, but the io_range/peer_fetch histograms can
    io_range_p99 = snapshot.get('io_range_p99_s') or 0.0
    peer_fetch_p99 = snapshot.get('peer_fetch_p99_s') or 0.0
    slow_object_store = bool(io_range_p99 >= SLOW_RANGE_FETCH_P99_S)
    slow_peer_cache = bool(peer_fetch_p99 >= SLOW_PEER_FETCH_P99_S)
    if slow_object_store and bottleneck in ('io', 'balanced'):
        hint = ('the OBJECT STORE is the slow side: range-fetch p99 is '
                '{:.3f}s (>= {:.2f}s) — check the store/network before '
                'touching pipeline knobs; hedging (hedge_ms) clips this '
                'tail (docs/object_store.md)'.format(
                    io_range_p99, SLOW_RANGE_FETCH_P99_S))
    if slow_peer_cache:
        hint += ('; a PEER CACHE host is slow: peer-fetch p99 is {:.3f}s '
                 '(>= {:.2f}s) — use /podmetrics to see which host, and '
                 'peer_hedge_s to route around it '
                 '(docs/pod_observability.md)'.format(
                     peer_fetch_p99, SLOW_PEER_FETCH_P99_S))
    return {'bottleneck': bottleneck, 'hint': hint, 'io_s': io_s,
            'decode_s': decode_s, 'queue_wait_p50_s': qw_p50,
            'queue_wait_p99_s': qw_p99, 'tail_stall': tail_stall,
            'io_range_p99_s': io_range_p99,
            'peer_fetch_p99_s': peer_fetch_p99,
            'slow_object_store': slow_object_store,
            'slow_peer_cache': slow_peer_cache}


def degradation_causes(snapshot: dict) -> List[str]:
    """Named fault-plane degradations evident in a stats snapshot — the
    pipeline is delivering correct data, but something it normally relies
    on has failed and been routed around (``docs/robustness.md``). Plain
    retries/hedges are NOT causes: they are the fault plane doing its job
    within budget."""
    causes = []
    n = snapshot.get('shared_put_failures', 0)
    if n:
        causes.append('cache-degraded: {} shared-cache segment '
                      'publication(s) failed (ENOSPC/serialization); '
                      'serving direct decode'.format(n))
    n = snapshot.get('worker_respawns', 0)
    if n:
        causes.append('worker-respawns: {} crashed worker(s) replaced; '
                      'in-flight items re-ventilated exactly once'.format(n))
    n = snapshot.get('poison_items_quarantined', 0)
    if n:
        causes.append('poison-items: {} item(s) quarantined after '
                      'repeatedly killing workers'.format(n))
    n = snapshot.get('io_permanent_failures', 0)
    if n:
        causes.append('io-permanent-failures: {} read(s) failed with '
                      'non-retryable errors'.format(n))
    n = snapshot.get('hosts_died', 0)
    if n:
        dead = snapshot.get('dead_hosts') or ()
        who = ' ({})'.format(', '.join(dead)) if dead else ''
        causes.append('host-death: {} pod member(s) died{}; their shard '
                      'leases were rebalanced onto survivors '
                      '(docs/robustness.md)'.format(n, who))
    n = snapshot.get('leases_rebalanced', 0)
    if n and not snapshot.get('hosts_died', 0):
        causes.append('lease-rebalance: {} shard lease(s) moved after a '
                      'pod membership change (host join)'.format(n))
    return causes


def classify_pipeline(heartbeats: Dict[str, dict],
                      snapshot: Optional[dict] = None,
                      stall_after_s: float = DEFAULT_STALL_AFTER_S) -> dict:
    """Classify a pipeline from its heartbeat records (as returned by
    ``HealthMonitor.heartbeats()``) and an optional stats snapshot.

    - ``stalled`` — some entity has sat in an **active** (non-idle) stage
      for longer than ``stall_after_s`` without progress; the verdict names
      every such entity and its stage.
    - ``degraded`` — no entity over the threshold, but at least one active
      entity is past half of it (the early warning the watchdog logs) — OR
      the fault plane routed around a failure (:func:`degradation_causes`:
      cache ENOSPC fell through to direct decode, a crashed worker was
      respawned, a poison item was quarantined, reads hit permanent
      errors); the named causes ride out as ``degraded_causes``.
    - ``starving`` — entities are healthy but the io bottleneck signal fires
      with an empty result queue: storage cannot feed the consumer (the
      device is starving, not the pipeline wedged).
    - ``healthy`` — everything else, including a fully idle pipeline.
    """
    now = time.perf_counter()
    stalled, slow = [], []
    for entity, record in sorted(heartbeats.items()):
        stage = record.get('stage', 'idle')
        if stage in IDLE_STAGES:
            continue
        age = record.get('age_s')
        if age is None:
            # raw records (straight off a pool or registry) carry only the
            # beat timestamp; derive the age here so classification works on
            # any heartbeat source
            age = max(0.0, now - record.get('ts', now))
        brief = {'entity': entity, 'stage': stage, 'age_s': round(age, 3)}
        if age > stall_after_s:
            stalled.append(brief)
        elif age > stall_after_s / 2.0:
            slow.append(brief)
    verdict = {
        'state': HEALTHY,
        'stall_after_s': stall_after_s,
        'entities': len(heartbeats),
        'stalled_entities': stalled,
        'slow_entities': slow,
    }
    if stalled:
        verdict['state'] = STALLED
        verdict['hint'] = ('no progress from {} for > {:.0f}s: dump stacks '
                           '(/stacks or the flight record) to see where it '
                           'is wedged'.format(
                               ', '.join(e['entity'] for e in stalled),
                               stall_after_s))
        return verdict
    if slow:
        verdict['state'] = DEGRADED
        verdict['hint'] = ('{} past half the stall threshold: a stall dump '
                           'fires at {:.0f}s'.format(
                               ', '.join(e['entity'] for e in slow),
                               stall_after_s))
        return verdict
    if snapshot:
        signals = bottleneck_signals(snapshot)
        verdict['bottleneck'] = signals['bottleneck']
        if (signals['bottleneck'] == 'io'
                and snapshot.get('queue_depth', 0) == 0
                and snapshot.get('items_out', 0) > 0):
            verdict['state'] = STARVING
            verdict['hint'] = ('storage cannot feed the consumer (io-bound, '
                               'result queue empty): ' + signals['hint'])
        else:
            verdict['hint'] = signals['hint']
        causes = degradation_causes(snapshot)
        if causes:
            verdict['degraded_causes'] = causes
            if verdict['state'] == HEALTHY:
                verdict['state'] = DEGRADED
                verdict['hint'] = ('fault plane routed around a failure: '
                                   + '; '.join(causes))
    return verdict


def thread_stacks() -> Dict[str, str]:
    """Faulthandler-style stack dumps of every thread in this process,
    keyed ``'<thread name> (tid)'`` — what the flight recorder and the
    ``/stacks`` endpoint serve. Pure stdlib (``sys._current_frames``), no
    signal handling, safe to call from any thread."""
    names = {t.ident: t.name for t in threading.enumerate()}
    stacks = {}
    for tid, frame in sys._current_frames().items():
        label = '{} ({})'.format(names.get(tid, '<unknown>'), tid)
        stacks[label] = ''.join(traceback.format_stack(frame))
    return stacks


def build_flight_record(verdict: dict, heartbeats: Dict[str, dict],
                        snapshot: Optional[dict] = None,
                        queues: Optional[dict] = None,
                        tracer=None, span_tail: int = 500,
                        lineage: Optional[dict] = None,
                        roofline: Optional[dict] = None,
                        latency: Optional[dict] = None,
                        slo: Optional[dict] = None,
                        autotune: Optional[dict] = None,
                        elastic: Optional[dict] = None,
                        goodput: Optional[dict] = None) -> dict:
    """Assemble the flight-recorder artifact: everything needed to diagnose
    a stall *after* the process is gone. JSON-able by construction.
    ``lineage`` (a tracker's ``flight_summary()``) adds the coverage audit
    and recent quarantine records, so a stall dump also answers "what data
    had the model seen, and what was dropped" (see ``docs/lineage.md``).
    ``roofline`` (a profiler ``roofline_summary()``) records how far below
    its calibrated ceiling the pipeline was running when it died — a stall
    that follows a long degradation reads differently from one out of the
    blue (see ``docs/profiling.md``). ``latency`` (a
    ``PipelineLatency.flight_summary()``) embeds per-stage percentiles plus
    the recent per-interval p99 trend — whether the episode was a cliff or
    a creep; ``slo`` (an ``SLOMonitor.evaluate()`` verdict) records the
    burn state at the moment of death (see ``docs/latency.md``);
    ``autotune`` (a ``PipelineController.flight_summary()``) records the
    controller's recent knob moves and prediction grades — a stall that
    follows a controller action must be attributable to it
    (``docs/autotune.md``); ``elastic`` (an ``ElasticHost.elastic_snapshot()``)
    records this host's pod-membership view — held leases, hosts joined/died,
    leases rebalanced — so a stall after a membership change is attributable
    to the rebalance (``docs/robustness.md``); ``goodput`` (a
    ``GoodputMonitor.flight_summary()``) records the per-step goodput
    decomposition and the last few step rings — whether the accelerator was
    fed when the pipeline died (``docs/goodput.md``)."""
    record = {
        'kind': 'petastorm_tpu_flight_record',
        # deliberate wall clock: a human-facing artifact timestamp, never
        # compared against monotonic readings
        'written_at': time.time(),  # petalint: disable=monotonic-clock
        'pid': os.getpid(),
        'verdict': verdict,
        'heartbeats': heartbeats,
        'stats': snapshot or {},
        'queues': queues or {},
        'stacks': thread_stacks(),
    }
    if tracer is not None:
        record['span_tail'] = tracer.tail(span_tail)
        record['spans_dropped'] = tracer.dropped
    if lineage is not None:
        record['lineage'] = lineage
    if roofline is not None:
        record['roofline'] = roofline
    if latency is not None:
        record['latency'] = latency
    if slo is not None:
        record['slo'] = slo
    if autotune is not None:
        record['autotune'] = autotune
    if elastic is not None:
        record['elastic'] = elastic
    if goodput is not None:
        record['goodput'] = goodput
    return record


def write_flight_record(path: str, record: dict) -> str:
    """Write one flight record as JSON; returns ``path``. Atomic (tmp file +
    ``os.replace``, shared :func:`petastorm_tpu.utils.atomic_write`): a crash
    mid-dump cannot leave truncated JSON that tooling rejects."""
    from petastorm_tpu.utils import atomic_write
    return atomic_write(path, lambda f: json.dump(
        record, f, indent=2, sort_keys=True, default=str))


class PipelineWatchdog:
    """Background stall detector over a pipeline's heartbeats.

    :meth:`evaluate` is cheap and callable on demand (the ``/healthz``
    endpoint does); :meth:`start` adds a daemon thread re-evaluating every
    ``interval_s`` that fires ``on_stall(verdict)`` once per stall episode
    (edge-triggered: it re-arms when the pipeline recovers). Lifecycle
    mirrors ``MetricsEmitter``: ``stop(join=True)`` joins with a timeout and
    is idempotent, so ``Reader.stop()/join()`` can always call it — even
    when the pool died uncleanly.
    """

    def __init__(self, heartbeats_fn: Callable[[], Dict[str, dict]],
                 snapshot_fn: Optional[Callable[[], dict]] = None,
                 stall_after_s: float = DEFAULT_STALL_AFTER_S,
                 interval_s: Optional[float] = None,
                 on_stall: Optional[Callable[[dict], None]] = None,
                 slo_monitor=None):
        if stall_after_s <= 0:
            raise ValueError('stall_after_s must be positive, got '
                             '{!r}'.format(stall_after_s))
        self._heartbeats_fn = heartbeats_fn
        self._snapshot_fn = snapshot_fn
        self._stall_after_s = stall_after_s
        #: Optional :class:`petastorm_tpu.latency.SLOMonitor`: the watchdog
        #: thread drives its periodic evaluations (burn accounting needs a
        #: steady cadence, not just on-demand ``/slo`` probes).
        self._slo_monitor = slo_monitor
        # default tick: a quarter of the threshold, clamped so tiny test
        # thresholds do not busy-spin and huge ones still tick regularly
        self._interval = (interval_s if interval_s is not None
                          else min(5.0, max(0.05, stall_after_s / 4.0)))
        self._on_stall = on_stall
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._stall_fired = False
        self._last_items_out = 0
        #: The most recent verdict (from the thread or an explicit
        #: :meth:`evaluate` call); ``None`` until the first evaluation.
        self.last_verdict: Optional[dict] = None

    @property
    def stall_after_s(self) -> float:
        return self._stall_after_s

    def evaluate(self, _advance_progress_window: bool = False) -> dict:
        """Classify the pipeline right now; updates :attr:`last_verdict`.

        ``items_out_delta`` is progress since the watchdog thread's previous
        tick. Only the thread advances that baseline
        (``_advance_progress_window``): on-demand callers (``/healthz``, a
        k8s probe every few seconds) must not reset it, or the delta in a
        stall's flight record would cover whatever arbitrary window the last
        probe left behind — and concurrent probes would race the counter."""
        snapshot = self._snapshot_fn() if self._snapshot_fn is not None else None
        verdict = classify_pipeline(self._heartbeats_fn(), snapshot,
                                    self._stall_after_s)
        if snapshot is not None:
            from petastorm_tpu.workers.stats import progress_marker
            items_out, _ = progress_marker(snapshot)
            verdict['items_out'] = items_out
            verdict['items_out_delta'] = items_out - self._last_items_out
            if _advance_progress_window:
                self._last_items_out = items_out
        self.last_verdict = verdict
        return verdict

    # -- background thread -----------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name='petastorm-tpu-watchdog')
        self._thread.start()

    def _run(self) -> None:
        while not self._stop_event.wait(self._interval):
            try:
                verdict = self.evaluate(_advance_progress_window=True)
            except Exception:
                logger.exception('watchdog evaluation failed')
                continue
            if self._slo_monitor is not None:
                try:
                    self._slo_monitor.evaluate()
                except Exception:
                    logger.exception('SLO evaluation failed')
            if verdict['state'] == STALLED:
                if not self._stall_fired:
                    self._stall_fired = True
                    logger.error('pipeline stalled: %s',
                                 verdict.get('hint', verdict))
                    if self._on_stall is not None:
                        try:
                            self._on_stall(verdict)
                        except Exception:
                            logger.exception('on_stall callback failed')
            else:
                self._stall_fired = False

    def stop(self, join: bool = True) -> None:
        """Signal the thread to stop; with ``join`` also wait for it.
        Idempotent."""
        self._stop_event.set()
        if not join:
            return
        thread = self._thread
        if thread is not None:
            thread.join(timeout=10)
            self._thread = None


class DebugServer:
    """Opt-in HTTP debug endpoint over one pipeline's health surfaces.

    Binds ``127.0.0.1:<port>`` (``port=0`` = ephemeral; read :attr:`port`
    after :meth:`start`) and serves:

    - ``GET /healthz`` — the watchdog verdict as JSON; status 200, or 503
      when the pipeline is classified ``stalled`` (point a k8s liveness
      probe at it). When an SLO monitor with the ``fail_healthz`` target is
      wired and its error budget is spent (``hard_breach``), ``/healthz``
      also flips to 503 with the SLO verdict embedded — the recycle signal
      for an infeed that is up but violating its latency contract.
    - ``GET /slo`` — the SLO monitor's verdict
      (:meth:`petastorm_tpu.latency.SLOMonitor.evaluate`): per-target
      checks, breach list, error-budget burn rate. 404 when the reader was
      built without ``slo=`` targets.
    - ``GET /metrics`` — the stats snapshot in Prometheus text-exposition
      format (the metrics emitter's formatter).
    - ``GET /diagnostics`` — ``{stats, heartbeats, verdict}`` (plus the
      lineage coverage audit when wired) as JSON.
    - ``GET /coverage`` — the sample-lineage coverage audit
      (:meth:`petastorm_tpu.lineage.LineageTracker.coverage_report`):
      per-epoch exactly-once verdicts, dup/drop row groups, shuffle quality,
      quarantine totals. 404 when the reader runs with lineage disabled.
    - ``GET /profile`` — the roofline profile
      (:meth:`petastorm_tpu.reader.Reader.profile`): measured samples/s vs
      the calibrated per-stage ceilings, binding stage, overlap-aware
      attribution, advisor recommendations. 404 when the profiler is
      disabled (``PETASTORM_TPU_PROFILER=0``) or not wired.
    - ``GET /autotune`` — the autotune controller's self-grading report
      (:meth:`petastorm_tpu.autotune.PipelineController.report`): every
      ringed action with its sensor evidence and predicted-vs-measured
      delta, the aggregate model error, quarantines, and the current knob
      state. 404 when the reader runs without a controller (autotune off or
      kill-switched).
    - ``GET /observe/snapshot`` — the per-host pod-observability surface
      (:func:`petastorm_tpu.podobs.make_observe_fn`): stats counters, raw
      latency-histogram bucket states, health verdict + degraded causes,
      SLO burn, coverage digest, shared-cache counters, span tail, and the
      host's monotonic clock reading. The response carries the
      ``X-Petastorm-Trace`` echo and ``X-Petastorm-Clock-S`` headers so an
      aggregator can estimate this host's clock offset. 404 when the pod
      plane is off (``PETASTORM_TPU_PODOBS=0``) or unwired.
    - ``GET /podmetrics`` — the merged pod report
      (:meth:`petastorm_tpu.podobs.PodObserver.report`) when this host
      acts as the aggregator (``PETASTORM_TPU_PODOBS_PEERS``); 404
      otherwise.
    - ``GET /goodput`` — the per-step goodput summary
      (:meth:`petastorm_tpu.goodput.GoodputMonitor.summary`): cumulative +
      rolling-window goodput/data-stall fractions and the mergeable
      summed-seconds state. 404 when the plane is off
      (``PETASTORM_TPU_GOODPUT=0``); ``{'attached': False}`` until a loader
      iterates.
    - ``GET /stacks`` — plain-text stack dump of every in-process thread.

    Requests are served on daemon threads (``ThreadingHTTPServer``);
    :meth:`stop` shuts the accept loop down, closes the socket and joins the
    server thread. Idempotent.
    """

    def __init__(self, evaluate_fn: Callable[[], dict],
                 snapshot_fn: Optional[Callable[[], dict]] = None,
                 heartbeats_fn: Optional[Callable[[], Dict[str, dict]]] = None,
                 port: int = 0, prefix: str = 'petastorm_tpu',
                 coverage_fn: Optional[Callable[[], dict]] = None,
                 profile_fn: Optional[Callable[[], dict]] = None,
                 slo_fn: Optional[Callable[[], dict]] = None,
                 autotune_fn: Optional[Callable[[], dict]] = None,
                 observe_fn: Optional[Callable[[], dict]] = None,
                 podmetrics_fn: Optional[Callable[[], dict]] = None,
                 goodput_fn: Optional[Callable[[], dict]] = None):
        self._evaluate_fn = evaluate_fn
        self._snapshot_fn = snapshot_fn or (lambda: {})
        self._heartbeats_fn = heartbeats_fn or (lambda: {})
        self._coverage_fn = coverage_fn
        self._profile_fn = profile_fn
        self._slo_fn = slo_fn
        self._autotune_fn = autotune_fn
        self._observe_fn = observe_fn
        self._podmetrics_fn = podmetrics_fn
        self._goodput_fn = goodput_fn
        self._requested_port = port
        self._prefix = prefix
        self._server = None
        self._thread: Optional[threading.Thread] = None
        #: The bound port (differs from the requested one when it was 0).
        self.port: Optional[int] = None

    def start(self) -> 'DebugServer':
        if self._server is not None:
            return self
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet by default
                logger.debug('debug endpoint: ' + fmt, *args)

            def _reply(self, status: int, content_type: str, body: str,
                       extra_headers: Optional[Dict[str, str]] = None):
                payload = body.encode('utf-8')
                self.send_response(status)
                self.send_header('Content-Type', content_type)
                self.send_header('Content-Length', str(len(payload)))
                for name, value in (extra_headers or {}).items():
                    self.send_header(name, value)
                self.end_headers()
                self.wfile.write(payload)

            def _pod_headers(self) -> Dict[str, str]:
                """The pod-plane response headers: echo the caller's trace
                id and stamp this host's monotonic clock at reply time —
                the aggregator's clock-offset anchor."""
                from petastorm_tpu.podobs import CLOCK_HEADER, TRACE_HEADER
                headers = {CLOCK_HEADER: repr(time.perf_counter())}
                trace_id = self.headers.get(TRACE_HEADER)
                if trace_id:
                    headers[TRACE_HEADER] = trace_id
                return headers

            def do_GET(self):  # noqa: N802 - http.server API
                try:
                    route = self.path.split('?', 1)[0].rstrip('/') or '/'
                    if route == '/healthz':
                        verdict = outer._evaluate_fn()
                        status = 503 if verdict.get('state') == STALLED else 200
                        if outer._slo_fn is not None:
                            # a spent error budget is a liveness failure only
                            # when the operator opted in (fail_healthz): an
                            # SLO is a contract, 503 is a recycle signal
                            slo_verdict = outer._slo_fn()
                            verdict = dict(verdict, slo=slo_verdict)
                            if (slo_verdict.get('fail_healthz')
                                    and slo_verdict.get('hard_breach')):
                                status = 503
                        self._reply(status, 'application/json',
                                    json.dumps(verdict, default=str))
                    elif route == '/slo':
                        if outer._slo_fn is None:
                            self._reply(404, 'text/plain',
                                        'no SLO targets configured for this '
                                        'reader (pass slo=dict(...) to the '
                                        'factory)\n')
                        else:
                            self._reply(200, 'application/json',
                                        json.dumps(outer._slo_fn(),
                                                   default=str))
                    elif route == '/metrics':
                        from petastorm_tpu.tracing import prometheus_text
                        self._reply(200, 'text/plain; version=0.0.4',
                                    prometheus_text(outer._snapshot_fn(),
                                                    prefix=outer._prefix))
                    elif route == '/diagnostics':
                        blob = {'verdict': outer._evaluate_fn(),
                                'stats': outer._snapshot_fn(),
                                'heartbeats': outer._heartbeats_fn()}
                        if outer._coverage_fn is not None:
                            blob['coverage'] = outer._coverage_fn()
                        if outer._slo_fn is not None:
                            blob['slo'] = outer._slo_fn()
                        if outer._goodput_fn is not None:
                            blob['goodput'] = outer._goodput_fn()
                        self._reply(200, 'application/json',
                                    json.dumps(blob, default=str))
                    elif route == '/coverage':
                        if outer._coverage_fn is None:
                            self._reply(404, 'text/plain',
                                        'lineage is disabled for this '
                                        'reader (PETASTORM_TPU_LINEAGE=0)\n')
                        else:
                            self._reply(200, 'application/json',
                                        json.dumps(outer._coverage_fn(),
                                                   default=str))
                    elif route == '/profile':
                        if outer._profile_fn is None:
                            self._reply(404, 'text/plain',
                                        'the roofline profiler is disabled '
                                        'for this reader '
                                        '(PETASTORM_TPU_PROFILER=0 or no '
                                        'profile source wired)\n')
                        else:
                            self._reply(200, 'application/json',
                                        json.dumps(outer._profile_fn(),
                                                   default=str))
                    elif route == '/autotune':
                        if outer._autotune_fn is None:
                            self._reply(404, 'text/plain',
                                        'no autotune controller runs for '
                                        'this reader (pass autotune=True to '
                                        'the factory, or set '
                                        'PETASTORM_TPU_AUTOTUNE=1)\n')
                        else:
                            self._reply(200, 'application/json',
                                        json.dumps(outer._autotune_fn(),
                                                   default=str))
                    elif route == '/observe/snapshot':
                        if outer._observe_fn is None:
                            self._reply(404, 'text/plain',
                                        'the pod observability plane is off '
                                        'or unwired for this reader '
                                        '(PETASTORM_TPU_PODOBS=0)\n')
                        else:
                            self._reply(200, 'application/json',
                                        json.dumps(outer._observe_fn(),
                                                   default=str),
                                        extra_headers=self._pod_headers())
                    elif route == '/podmetrics':
                        if outer._podmetrics_fn is None:
                            self._reply(404, 'text/plain',
                                        'this host is not a pod aggregator '
                                        '(set PETASTORM_TPU_PODOBS_PEERS to '
                                        'a host:port list, or run '
                                        'petastorm-tpu-podstat)\n')
                        else:
                            self._reply(200, 'application/json',
                                        json.dumps(outer._podmetrics_fn(),
                                                   default=str),
                                        extra_headers=self._pod_headers())
                    elif route == '/goodput':
                        if outer._goodput_fn is None:
                            self._reply(404, 'text/plain',
                                        'the goodput plane is off for this '
                                        'reader (PETASTORM_TPU_GOODPUT=0)\n')
                        else:
                            self._reply(200, 'application/json',
                                        json.dumps(outer._goodput_fn(),
                                                   default=str))
                    elif route == '/stacks':
                        stacks = thread_stacks()
                        body = '\n'.join('== {} ==\n{}'.format(name, stack)
                                         for name, stack in sorted(
                                             stacks.items()))
                        self._reply(200, 'text/plain', body)
                    else:
                        self._reply(404, 'text/plain',
                                    'unknown route {}; try /healthz /metrics '
                                    '/diagnostics /coverage /profile /slo '
                                    '/autotune /observe/snapshot /podmetrics '
                                    '/goodput /stacks\n'.format(route))
                except Exception as e:  # report, never kill the serve loop
                    logger.exception('debug endpoint request failed')
                    try:
                        self._reply(500, 'text/plain', 'error: {}\n'.format(e))
                    except OSError:
                        pass

        self._server = ThreadingHTTPServer(('127.0.0.1', self._requested_port),
                                           Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        kwargs={'poll_interval': 0.1},
                                        daemon=True,
                                        name='petastorm-tpu-debug-http')
        self._thread.start()
        logger.info('petastorm_tpu debug endpoint on http://127.0.0.1:%d '
                    '(/healthz /metrics /diagnostics /profile /slo /stacks)',
                    self.port)
        return self

    def stop(self) -> None:
        server, self._server = self._server, None
        if server is None:
            return
        server.shutdown()
        server.server_close()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=10)
