"""Row predicates with worker-side pushdown.

Reference parity: ``petastorm/predicates.py`` — ``PredicateBase`` (:26-36),
``in_set``/``in_intersection``/``in_lambda``/``in_negate``/``in_reduce``
(:39-141), ``in_pseudorandom_split`` (:144-182).
"""

from __future__ import annotations

import hashlib
from abc import ABC, abstractmethod
from typing import Callable, Iterable, List, Optional

import numpy as np


class PredicateBase(ABC):
    """A predicate pushed down to reader workers: rows failing
    ``do_include`` never leave the worker."""

    @abstractmethod
    def get_fields(self) -> List[str]:
        """Field names the predicate needs to evaluate."""

    @abstractmethod
    def do_include(self, values: dict) -> bool:
        """Decide inclusion given a dict of the requested field values."""


class in_set(PredicateBase):
    """True if the field value is in the given set."""

    def __init__(self, inclusion_values: Iterable, predicate_field: str):
        self._inclusion_values = set(inclusion_values)
        self._predicate_field = predicate_field

    def get_fields(self):
        return [self._predicate_field]

    def do_include(self, values):
        return values[self._predicate_field] in self._inclusion_values

    def column_mask(self, columns) -> Optional[np.ndarray]:
        """Vectorized membership over a decoded numpy column (the columnar
        readers' ``predicate_row_mask`` hook): one ``np.isin`` instead of a
        per-row dict build + set probe. Returns ``None`` — caller falls
        back to per-row ``do_include`` — whenever numpy elementwise
        equality could disagree with Python's ``in``: object columns,
        mixed-kind value sets, NaN members (set membership is
        identity-based), and any int/float pairing whose float64
        promotion would round exact integers (int64 x uint64, members or
        64-bit columns beyond 2**53)."""
        column = columns.get(self._predicate_field)
        dtype = getattr(column, 'dtype', None)
        if dtype is None or dtype.kind not in 'biufUS':
            return None
        if getattr(column, 'ndim', 0) != 1:
            # a dense (n, *shape) array column would yield an elementwise
            # N-D mask ("any element matches" rows, duplicated indices at
            # the nonzero() callers) where the per-row path raises loudly
            # on the unhashable cell — keep that loud failure
            return None
        try:
            values = np.asarray(list(self._inclusion_values))
        except (ValueError, TypeError, OverflowError):
            return None
        ck, vk = dtype.kind, values.dtype.kind
        if ck in 'US':
            if vk != ck:
                return None
        elif ck in 'bui' and vk in 'bui':
            # int64 x uint64 promotes to float64 inside np.isin — 2**63
            # neighbors collide after rounding where Python's exact int
            # compare would not
            if np.result_type(dtype, values.dtype).kind not in 'bui':
                return None
        elif ck == 'f' and vk == 'f':
            if np.isnan(values).any():
                return None  # nan in {nan} is True (identity); ==nan isn't
        elif ck == 'f' and vk in 'bui':
            # integer members compare exactly against a float column only
            # when float64 represents each one exactly — proven by the
            # round trip (a magnitude test would itself round 2**53 + 1)
            promoted = values.astype(np.float64)
            if not bool(np.array_equal(promoted.astype(values.dtype),
                                       values)):
                return None
            values = promoted
        elif ck in 'ui' and vk == 'f':
            # np.isin promotes the COLUMN to float64: exact only for
            # <=32-bit integer columns (int64 values beyond 2**53 round)
            if dtype.itemsize > 4:
                return None
        else:
            return None
        return np.isin(column, values)


class in_intersection(PredicateBase):
    """True if a list-valued field intersects the given set."""

    def __init__(self, inclusion_values: Iterable, predicate_field: str):
        self._inclusion_values = set(inclusion_values)
        self._predicate_field = predicate_field

    def get_fields(self):
        return [self._predicate_field]

    def do_include(self, values):
        return not self._inclusion_values.isdisjoint(values[self._predicate_field])


class in_lambda(PredicateBase):
    """Custom predicate function, with optional mutable state
    (reference ``predicates.py:95-121``)."""

    def __init__(self, predicate_fields: List[str], predicate_func: Callable,
                 state=None):
        self._predicate_fields = list(predicate_fields)
        self._predicate_func = predicate_func
        self._state = state

    def get_fields(self):
        return self._predicate_fields

    def do_include(self, values):
        if self._state is not None:
            return self._predicate_func(values, self._state)
        return self._predicate_func(values)


class in_negate(PredicateBase):
    """Logical NOT of another predicate."""

    def __init__(self, predicate: PredicateBase):
        self._predicate = predicate

    def get_fields(self):
        return self._predicate.get_fields()

    def do_include(self, values):
        return not self._predicate.do_include(values)


class in_reduce(PredicateBase):
    """Composition of predicates with a reduce function, e.g. ``all``/``any``."""

    def __init__(self, predicate_list: List[PredicateBase], reduce_func: Callable):
        self._predicate_list = list(predicate_list)
        self._reduce_func = reduce_func

    def get_fields(self):
        fields = []
        for p in self._predicate_list:
            fields.extend(p.get_fields())
        return sorted(set(fields))

    def do_include(self, values):
        return self._reduce_func([p.do_include(values) for p in self._predicate_list])


class in_pseudorandom_split(PredicateBase):
    """Deterministic hash-based train/val/test split
    (reference ``predicates.py:144-182``).

    ``fraction_list`` partitions [0,1); a row is included when the md5-hash
    bucket of its ``predicate_field`` value falls into partition
    ``subset_index``. The same value always lands in the same subset, across
    processes and runs.
    """

    def __init__(self, fraction_list: List[float], subset_index: int, predicate_field: str):
        if not 0 <= subset_index < len(fraction_list):
            raise ValueError('subset_index {} out of range for {} fractions'.format(
                subset_index, len(fraction_list)))
        if sum(fraction_list) > 1.0 + 1e-9:
            raise ValueError('fractions must sum to <= 1.0')
        self._boundaries = np.cumsum([0.0] + list(fraction_list))
        self._subset_index = subset_index
        self._predicate_field = predicate_field

    def get_fields(self):
        return [self._predicate_field]

    def do_include(self, values):
        value = values[self._predicate_field]
        if isinstance(value, bytes):
            payload = value
        else:
            payload = str(value).encode('utf-8')
        bucket = int(hashlib.md5(payload).hexdigest(), 16) / float(1 << 128)
        return (self._boundaries[self._subset_index] <= bucket
                < self._boundaries[self._subset_index + 1])
