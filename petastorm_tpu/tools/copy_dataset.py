"""Copy a petastorm_tpu dataset, optionally subsetting columns and dropping
null rows.

Reference parity: ``petastorm/tools/copy_dataset.py:35-93`` — the reference
runs a Spark job; here the copy streams row-group tables through pyarrow with
the same options: ``field_regex`` column subsetting, ``not_null_fields``
filtering, output partitioning control.

Usage::

    python -m petastorm_tpu.tools.copy_dataset file:///src file:///dst \
        --field-regex 'id.*' --not-null-fields other_field --rows-per-file 10000
"""

from __future__ import annotations

import argparse
import logging
from typing import List, Optional

from petastorm_tpu.etl.dataset_metadata import (get_schema, load_row_groups,
                                                materialize_dataset)
from petastorm_tpu.fs import get_filesystem_and_path_or_paths, normalize_dir_url
from petastorm_tpu.unischema import decode_row, match_unischema_fields

logger = logging.getLogger(__name__)


def copy_dataset(source_url: str, target_url: str,
                 field_regex: Optional[List[str]] = None,
                 not_null_fields: Optional[List[str]] = None,
                 overwrite_output: bool = False,
                 rows_per_file: int = 0,
                 row_group_size_mb: Optional[float] = None,
                 storage_options=None) -> int:
    """Copy ``source_url`` to ``target_url``; returns rows copied."""
    source_url = normalize_dir_url(source_url)
    target_url = normalize_dir_url(target_url)
    fs, path, _ = get_filesystem_and_path_or_paths(source_url, storage_options)
    schema = get_schema(fs, path)

    if field_regex:
        fields = match_unischema_fields(schema, field_regex)
        if not fields:
            raise ValueError('field_regex {} matched no fields'.format(field_regex))
        schema = schema.create_schema_view(fields)
    if not_null_fields:
        unknown = set(not_null_fields) - set(schema.fields)
        if unknown:
            raise ValueError('not_null_fields not in schema: {}'.format(sorted(unknown)))

    pieces = load_row_groups(fs, path)
    copied = 0
    kwargs = {'rows_per_file': rows_per_file} if rows_per_file else {}
    if row_group_size_mb:
        kwargs['row_group_size_mb'] = row_group_size_mb
    with materialize_dataset(target_url, schema, overwrite=overwrite_output,
                             **kwargs) as writer:
        import pyarrow.parquet as pq
        for piece in pieces:
            with fs.open(piece.path, 'rb') as f:
                table = pq.ParquetFile(f).read_row_group(
                    piece.row_group,
                    columns=[n for n in schema.fields
                             if n not in piece.partition_dict])
            rows = table.to_pylist()
            for key, value in piece.partition_dict.items():
                if key in schema.fields:
                    for r in rows:
                        r[key] = value
            decoded = [decode_row(r, schema) for r in rows]
            if not_null_fields:
                decoded = [r for r in decoded
                           if all(r[f] is not None for f in not_null_fields)]
            writer.write_rows(decoded)
            copied += len(decoded)
    logger.info('Copied %d rows from %s to %s', copied, source_url, target_url)
    return copied


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description=__doc__.split('\n')[0])
    parser.add_argument('source_url')
    parser.add_argument('target_url')
    parser.add_argument('--field-regex', nargs='+', default=None)
    parser.add_argument('--not-null-fields', nargs='+', default=None)
    parser.add_argument('--overwrite-output', action='store_true')
    parser.add_argument('--rows-per-file', type=int, default=0)
    parser.add_argument('--row-group-size-mb', type=float, default=None)
    parser.add_argument('-v', action='store_true')
    return parser


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.v:
        logging.basicConfig(level=logging.INFO)
    copy_dataset(args.source_url, args.target_url,
                 field_regex=args.field_regex,
                 not_null_fields=args.not_null_fields,
                 overwrite_output=args.overwrite_output,
                 rows_per_file=args.rows_per_file,
                 row_group_size_mb=args.row_group_size_mb)
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
