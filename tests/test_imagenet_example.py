"""End-to-end test of the ImageNet-style pipeline: ETL -> variable-shape
png decode -> worker-side resize TransformSpec -> CNN train step."""

import numpy as np
import pytest

import examples.imagenet.generate_imagenet as gen
from examples.imagenet.main import make_resize_transform, train
from petastorm_tpu import make_columnar_reader, make_reader

pytestmark = pytest.mark.slow    # kernels / model training: minutes-scale (fast lane skips)


@pytest.fixture(scope='module')
def imagenet_dataset(tmp_path_factory):
    path = tmp_path_factory.mktemp('imagenet') / 'ds'
    url = 'file://' + str(path)
    n = gen.generate(url, gen.synthetic_rows(24, classes=4, base_hw=(48, 64)),
                     row_group_size_mb=0.5)
    assert n == 24
    return url


class TestImagenetETL:
    def test_variable_shape_roundtrip(self, imagenet_dataset):
        with make_reader(imagenet_dataset, num_epochs=1) as r:
            rows = list(r)
        assert len(rows) == 24
        shapes = {row.image.shape for row in rows}
        assert len(shapes) > 1                       # jittered sizes survive
        assert all(s[2] == 3 for s in shapes)
        assert all(row.noun_id.startswith('n') for row in rows)
        assert all(0 <= int(row.label) < 4 for row in rows)

    def test_directory_etl(self, tmp_path):
        cv2 = pytest.importorskip('cv2')
        rng = np.random.default_rng(0)
        for noun, cls in [('n01440764', 0), ('n01443537', 1)]:
            d = tmp_path / 'tree' / noun
            d.mkdir(parents=True)
            for i in range(3):
                img = rng.integers(0, 255, (40, 50, 3), dtype=np.uint8)
                cv2.imwrite(str(d / '{}.JPEG'.format(i)), img)
        url = 'file://' + str(tmp_path / 'out')
        n = gen.generate(url, gen.rows_from_directory(str(tmp_path / 'tree')),
                         row_group_size_mb=0.5)
        assert n == 6
        with make_reader(url, num_epochs=1) as r:
            rows = list(r)
        assert sorted({row.noun_id for row in rows}) == ['n01440764', 'n01443537']
        assert sorted({int(row.label) for row in rows}) == [0, 1]

    def test_resize_transform_columnar(self, imagenet_dataset):
        with make_columnar_reader(imagenet_dataset, num_epochs=1,
                                  transform_spec=make_resize_transform(32)) as r:
            batch = next(iter(r))
        assert batch.image.shape[1:] == (32, 32, 3)
        assert batch.image.dtype == np.uint8
        assert set(batch._fields) == {'image', 'label'}


class TestImagenetTrain:
    def test_train_steps_run(self, imagenet_dataset):
        params = train(imagenet_dataset, batch_size=8, steps=2,
                       workers_count=2, num_classes=4, image_size=32)
        assert 'head_w' in params


class TestImageCnn:
    def test_forward_shapes_and_grad(self):
        import jax
        import jax.numpy as jnp

        from petastorm_tpu.models import image_cnn
        params = image_cnn.init(jax.random.PRNGKey(0), num_classes=10,
                                widths=(8, 16), blocks_per_stage=1)
        images = jnp.zeros((2, 32, 32, 3), jnp.float32)
        logits = image_cnn.forward(params, images)
        assert logits.shape == (2, 10)
        assert logits.dtype == jnp.float32
        step = image_cnn.make_train_step(lr=1e-2)
        labels = jnp.zeros((2,), jnp.int32)
        u8 = jnp.zeros((2, 32, 32, 3), jnp.uint8)
        params2, loss = step(params, u8, labels)
        assert np.isfinite(float(loss))
        # params actually moved
        delta = float(jnp.abs(params2['head_b'] - params['head_b']).max())
        assert delta > 0
