"""HDFS namenode resolution/failover (config-driven, no cluster) and
BatchingTableQueue tests (reference ``tests/test_namenode_resolution.py``,
``tests/test_batching_table_queue.py``)."""

import numpy as np
import pyarrow as pa
import pytest

from petastorm_tpu.hdfs.namenode import (HAHdfsClient, HdfsConnectError,
                                         HdfsNamenodeResolver,
                                         MaxFailoversExceeded)
from petastorm_tpu.pyarrow_helpers import BatchingTableQueue

HA_CONFIG = {
    'fs.defaultFS': 'hdfs://nameservice1',
    'dfs.ha.namenodes.nameservice1': 'nn1,nn2',
    'dfs.namenode.rpc-address.nameservice1.nn1': 'host1:8020',
    'dfs.namenode.rpc-address.nameservice1.nn2': 'host2:8020',
}


class TestNamenodeResolver:
    def test_resolves_ha_service(self):
        r = HdfsNamenodeResolver(HA_CONFIG)
        assert r.resolve_hdfs_name_service('nameservice1') == \
            ['host1:8020', 'host2:8020']

    def test_default_service(self):
        r = HdfsNamenodeResolver(HA_CONFIG)
        service, namenodes = r.resolve_default_hdfs_service()
        assert service == 'nameservice1'
        assert namenodes == ['host1:8020', 'host2:8020']

    def test_non_ha_defaultfs(self):
        r = HdfsNamenodeResolver({'fs.defaultFS': 'hdfs://single:8020'})
        service, namenodes = r.resolve_default_hdfs_service()
        assert namenodes == ['single:8020']

    def test_unknown_service_returns_none(self):
        r = HdfsNamenodeResolver(HA_CONFIG)
        assert r.resolve_hdfs_name_service('other') is None

    def test_missing_defaultfs_raises(self):
        with pytest.raises(HdfsConnectError):
            HdfsNamenodeResolver({}).resolve_default_hdfs_service()

    def test_hadoop_xml_parsing(self, tmp_path, monkeypatch):
        conf_dir = tmp_path / 'etc' / 'hadoop'
        conf_dir.mkdir(parents=True)
        (conf_dir / 'core-site.xml').write_text(
            '<configuration><property><name>fs.defaultFS</name>'
            '<value>hdfs://ns</value></property></configuration>')
        (conf_dir / 'hdfs-site.xml').write_text(
            '<configuration>'
            '<property><name>dfs.ha.namenodes.ns</name><value>a,b</value></property>'
            '<property><name>dfs.namenode.rpc-address.ns.a</name><value>h1:8020</value></property>'
            '<property><name>dfs.namenode.rpc-address.ns.b</name><value>h2:8020</value></property>'
            '</configuration>')
        monkeypatch.setenv('HADOOP_HOME', str(tmp_path))
        r = HdfsNamenodeResolver()
        assert r.resolve_default_hdfs_service() == ['ns', ['h1:8020', 'h2:8020']]


class _FlakyFs(object):
    """Fails N times then succeeds; records which 'namenode' served."""
    def __init__(self, host, fail_first):
        self.host = host
        self._fail_first = fail_first

    def ls(self, path):
        if self._fail_first['remaining'] > 0:
            self._fail_first['remaining'] -= 1
            raise IOError('connection refused')
        return ['{}:{}'.format(self.host, path)]


class TestHAFailover:
    def _client(self, fail_count):
        state = {'remaining': fail_count}
        return HAHdfsClient(lambda host: _FlakyFs(host, state),
                            ['nn1:8020', 'nn2:8020'])

    def test_failover_retries_next_namenode(self):
        client = self._client(fail_count=1)
        assert client.ls('/x') == ['nn2:8020:/x']

    def test_exhausted_failovers_raise(self):
        client = self._client(fail_count=10)
        with pytest.raises(MaxFailoversExceeded):
            client.ls('/x')

    def test_request_errors_do_not_fail_over(self):
        # FileNotFoundError/PermissionError describe the request, not the
        # connection: they must surface immediately instead of burning
        # namenode failovers (advisor finding; reference namenode.py:181
        # only retries connection-type errors).
        class _MissingFileFs(object):
            connects = 0

            def __init__(self, host):
                _MissingFileFs.connects += 1

            def ls(self, path):
                raise FileNotFoundError(path)

        client = HAHdfsClient(_MissingFileFs, ['nn1:8020', 'nn2:8020'])
        connects_after_init = _MissingFileFs.connects
        with pytest.raises(FileNotFoundError):
            client.ls('/missing')
        assert _MissingFileFs.connects == connects_after_init  # no reconnects


class TestBatchingTableQueue:
    def test_rechunks(self):
        q = BatchingTableQueue(batch_size=4)
        q.put(pa.table({'x': np.arange(3)}))
        assert q.empty()
        q.put(pa.table({'x': np.arange(3, 10)}))
        assert not q.empty()
        out = q.get()
        np.testing.assert_array_equal(out.column('x').to_numpy(), [0, 1, 2, 3])
        out2 = q.get()
        np.testing.assert_array_equal(out2.column('x').to_numpy(), [4, 5, 6, 7])
        assert q.empty()   # 2 rows left < 4

    def test_record_batch_input(self):
        q = BatchingTableQueue(batch_size=2)
        q.put(pa.RecordBatch.from_pydict({'x': [1, 2, 3]}))
        assert q.get().num_rows == 2

    def test_get_on_empty_raises(self):
        q = BatchingTableQueue(batch_size=2)
        with pytest.raises(IndexError):
            q.get()


class TestFsIntegration:
    def test_ha_nameservice_routes_through_ha_client(self, tmp_path, monkeypatch):
        conf_dir = tmp_path / 'etc' / 'hadoop'
        conf_dir.mkdir(parents=True)
        (conf_dir / 'hdfs-site.xml').write_text(
            '<configuration>'
            '<property><name>dfs.ha.namenodes.ns1</name><value>a,b</value></property>'
            '<property><name>dfs.namenode.rpc-address.ns1.a</name><value>h1:8020</value></property>'
            '<property><name>dfs.namenode.rpc-address.ns1.b</name><value>h2:8020</value></property>'
            '</configuration>')
        monkeypatch.setenv('HADOOP_HOME', str(tmp_path))

        from petastorm_tpu import fs as fs_mod
        from petastorm_tpu.hdfs import namenode as nn_mod
        assert fs_mod._resolve_hdfs_namenodes('hdfs://ns1/data') == \
            ['h1:8020', 'h2:8020']
        assert fs_mod._resolve_hdfs_namenodes('hdfs://host:8020/data') is None

        sentinel = object()
        captured = {}

        def fake_connect(namenodes):
            captured['namenodes'] = namenodes
            return sentinel

        monkeypatch.setattr(nn_mod.HdfsConnector, 'connect_to_either_namenode',
                            staticmethod(fake_connect))
        fs, path, factory = fs_mod.get_filesystem_and_path_or_paths('hdfs://ns1/data')
        assert fs is sentinel
        assert captured['namenodes'] == ['h1:8020', 'h2:8020']
        assert path == '/data'
        assert factory() is sentinel
