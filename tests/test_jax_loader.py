"""JAX adapter tests: host loader, sharded loader over a virtual 8-device CPU
mesh, device prefetch, dtype sanitization, in-memory epoch caching.

Reference analogues: ``petastorm/tests/test_pytorch_dataloader.py`` and
``test_tf_dataset.py`` — re-targeted at the JAX adapter this framework ships
instead of TF/torch adapters.
"""

import numpy as np
import pytest

from petastorm_tpu.jax_utils import (JaxDataLoader, make_jax_loader,
                                     prefetch_to_device, sanitize_jax_types)
from petastorm_tpu.reader import make_batch_reader, make_reader


def _all_ids(batches, key='id'):
    out = []
    for b in batches:
        out.extend(np.asarray(b[key]).ravel().tolist())
    return out


class TestHostLoader:
    def test_row_reader_batches(self, synthetic_dataset):
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                         num_epochs=1, shuffle_row_groups=False) as reader:
            loader = JaxDataLoader(reader, batch_size=10)
            batches = list(loader)
        expected = sorted(r['id'] for r in synthetic_dataset.data)
        assert sorted(_all_ids(batches)) == expected
        # full batches except possibly the last
        for b in batches[:-1]:
            assert len(b['id']) == 10

    def test_row_reader_drop_last(self, synthetic_dataset):
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                         num_epochs=1) as reader:
            loader = JaxDataLoader(reader, batch_size=32, drop_last=True)
            batches = list(loader)
        assert all(len(b['id']) == 32 for b in batches)
        assert len(batches) == len(synthetic_dataset.data) // 32

    def test_batch_reader_vectorized_path(self, scalar_dataset):
        with make_batch_reader(scalar_dataset.url, reader_pool_type='dummy',
                               num_epochs=1) as reader:
            loader = JaxDataLoader(reader, batch_size=16)
            batches = list(loader)
        assert sorted(_all_ids(batches)) == sorted(r['id'] for r in scalar_dataset.data)
        for b in batches[:-1]:
            assert len(b['id']) == 16

    def test_shuffling_changes_order(self, synthetic_dataset):
        def read(shuffle_capacity, seed):
            with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                             num_epochs=1, shuffle_row_groups=False) as reader:
                loader = JaxDataLoader(reader, batch_size=10,
                                       shuffling_queue_capacity=shuffle_capacity,
                                       seed=seed)
                return _all_ids(list(loader))

        plain = read(0, None)
        shuffled = read(50, 42)
        assert sorted(plain) == sorted(shuffled)
        assert plain != shuffled

    def test_batched_shuffling(self, scalar_dataset):
        with make_batch_reader(scalar_dataset.url, reader_pool_type='dummy',
                               num_epochs=1, shuffle_row_groups=False) as reader:
            loader = JaxDataLoader(reader, batch_size=10,
                                   shuffling_queue_capacity=40, seed=0)
            ids = _all_ids(list(loader))
        assert sorted(ids) == sorted(r['id'] for r in scalar_dataset.data)

    def test_multidim_fields_stacked(self, synthetic_dataset):
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                         num_epochs=1,
                         schema_fields=['id', 'matrix']) as reader:
            loader = JaxDataLoader(reader, batch_size=5)
            batch = next(iter(loader))
        assert batch['matrix'].shape == (5, 8, 4, 3)
        by_id = {r['id']: r['matrix'] for r in synthetic_dataset.data}
        for i, row_id in enumerate(batch['id']):
            np.testing.assert_array_equal(batch['matrix'][i], by_id[row_id])

    def test_transform_fn(self, scalar_dataset):
        with make_batch_reader(scalar_dataset.url, reader_pool_type='dummy',
                               num_epochs=1) as reader:
            loader = JaxDataLoader(
                reader, batch_size=8,
                transform_fn=lambda b: {'twice': b['id'] * 2})
            batch = next(iter(loader))
        # '_provenance' is the loader's reserved lineage annotation (see
        # docs/lineage.md); the transform itself only ever sees its own keys
        assert set(batch.keys()) - {'_provenance'} == {'twice'}

    def test_inmemory_cache_replays_epochs(self, scalar_dataset):
        with make_batch_reader(scalar_dataset.url, reader_pool_type='dummy',
                               num_epochs=1) as reader:
            loader = JaxDataLoader(reader, batch_size=16, inmemory_cache_all=True)
            first = _all_ids(list(loader))
            second = _all_ids(list(loader))   # reader is exhausted; replay from cache
        assert first == second
        assert sorted(first) == sorted(r['id'] for r in scalar_dataset.data)

    def test_double_iteration_resets_reader(self, scalar_dataset):
        with make_batch_reader(scalar_dataset.url, reader_pool_type='dummy',
                               num_epochs=1) as reader:
            loader = JaxDataLoader(reader, batch_size=16)
            first = sorted(_all_ids(list(loader)))
            second = sorted(_all_ids(list(loader)))
        assert first == second

    def test_concurrent_iteration_rejected(self, scalar_dataset):
        with make_batch_reader(scalar_dataset.url, reader_pool_type='dummy') as reader:
            loader = JaxDataLoader(reader, batch_size=4)
            it = iter(loader)
            next(it)
            with pytest.raises(RuntimeError, match='already being iterated'):
                next(iter(loader))


class TestSanitize:
    def test_decimal_and_datetime(self):
        from decimal import Decimal
        row = {'d': Decimal('1.5'),
               'ts': np.array(['2020-01-01'], dtype='datetime64[D]'),
               'x': np.int32(3)}
        out = sanitize_jax_types(row)
        assert out['d'].dtype == np.float64 and out['d'] == 1.5
        assert out['ts'].dtype == np.int64
        assert out['x'] == 3

    def test_decimal_array(self):
        from decimal import Decimal
        row = {'d': np.array([Decimal('1.5'), Decimal('2.5')], dtype=object)}
        out = sanitize_jax_types(row)
        assert out['d'].dtype == np.float64
        np.testing.assert_array_equal(out['d'], [1.5, 2.5])


class TestShardedLoader:
    @pytest.fixture()
    def mesh(self):
        import jax
        from jax.sharding import Mesh
        devices = np.array(jax.devices('cpu')[:8]).reshape(8)
        return Mesh(devices, ('data',))

    def test_global_arrays_over_mesh(self, scalar_dataset, mesh):
        import jax
        with make_batch_reader(scalar_dataset.url, reader_pool_type='dummy',
                               num_epochs=1) as reader:
            loader = make_jax_loader(reader, batch_size=16, mesh=mesh)
            batches = list(loader)
        for b in batches:
            arr = b['id']
            assert isinstance(arr, jax.Array)
            assert arr.shape[0] == 16
            assert len(arr.sharding.device_set) == 8
        # all ids present (drop_last may drop a ragged tail)
        ids = np.concatenate([np.asarray(b['id']) for b in batches])
        assert len(set(ids.tolist())) == len(ids)

    def test_string_columns_stay_on_host(self, synthetic_dataset, mesh):
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                         num_epochs=1,
                         schema_fields=['id', 'partition_key']) as reader:
            loader = make_jax_loader(reader, batch_size=8, mesh=mesh)
            batch = next(iter(loader))
        assert '_host' in batch and 'partition_key' in batch['_host']
        assert len(batch['_host']['partition_key']) == 8

    def test_jit_consumes_sharded_batch(self, scalar_dataset, mesh):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec

        @jax.jit
        def step(x):
            return jnp.sum(x * 2)

        with make_batch_reader(scalar_dataset.url, reader_pool_type='dummy',
                               num_epochs=1) as reader:
            loader = make_jax_loader(reader, batch_size=16, mesh=mesh)
            total = 0.0
            plain = 0
            for b in loader:
                total += float(step(b['id']))
                plain += int(np.sum(np.asarray(b['id']))) * 2
        assert total == plain


class TestPrefetch:
    def test_prefetch_preserves_stream(self, scalar_dataset):
        with make_batch_reader(scalar_dataset.url, reader_pool_type='dummy',
                               num_epochs=1, shuffle_row_groups=False) as reader:
            loader = JaxDataLoader(reader, batch_size=16)
            direct = _all_ids(list(loader))
            prefetched = _all_ids(list(prefetch_to_device(iter(loader), size=2)))
        assert direct == prefetched

    def test_prefetch_propagates_errors(self):
        def boom():
            yield {'x': np.arange(3)}
            raise ValueError('downstream failure')

        it = prefetch_to_device(boom(), size=2)
        next(it)
        with pytest.raises(ValueError, match='downstream failure'):
            list(it)


class TestRaggedPadding:
    """pad_spec: variable-length fields become dense bucketed device arrays
    (SURVEY §7 'hard parts': pad-to-bucket vs XLA's static-shape world)."""

    @pytest.fixture(scope='class')
    def ragged_url(self, tmp_path_factory):
        from petastorm_tpu import materialize_dataset
        from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
        from petastorm_tpu.unischema import Unischema, UnischemaField
        schema = Unischema('Ragged', [
            UnischemaField('id', np.int64, (), ScalarCodec(), False),
            UnischemaField('tokens', np.int32, (None,), NdarrayCodec(), False)])
        url = 'file://' + str(tmp_path_factory.mktemp('ragged') / 'ds')
        rng = np.random.default_rng(0)
        with materialize_dataset(url, schema) as w:
            w.write_rows({'id': np.int64(i),
                          'tokens': rng.integers(1, 100, 3 + i % 20).astype(np.int32)}
                         for i in range(40))
        return url

    def test_unit_pad_and_lengths(self):
        from petastorm_tpu.jax_utils import pad_ragged_batch, validate_pad_spec
        col = np.empty(3, dtype=object)
        col[0] = np.array([1, 2], np.int32)
        col[1] = np.array([3], np.int32)
        col[2] = np.array([4, 5, 6], np.int32)
        spec = validate_pad_spec({'tokens': {'buckets': [2, 4, 8],
                                             'pad_value': -1}})
        out = pad_ragged_batch({'tokens': col}, spec)
        assert out['tokens'].shape == (3, 4)        # bucket 4 covers max len 3
        np.testing.assert_array_equal(out['tokens_len'], [2, 1, 3])
        np.testing.assert_array_equal(out['tokens'][1], [3, -1, -1, -1])

    def test_bucket_overflow_raises(self):
        from petastorm_tpu.jax_utils import pad_ragged_batch, validate_pad_spec
        col = np.empty(1, dtype=object)
        col[0] = np.arange(10, dtype=np.int32)
        spec = validate_pad_spec({'t': {'max_len': 4}})
        with pytest.raises(ValueError, match='exceeds largest bucket'):
            pad_ragged_batch({'t': col}, spec)

    def test_spec_validation(self):
        from petastorm_tpu.jax_utils import validate_pad_spec
        with pytest.raises(ValueError, match='exactly one of'):
            validate_pad_spec({'t': {}})
        with pytest.raises(ValueError, match='unknown keys'):
            validate_pad_spec({'t': {'max_len': 4, 'bukets': [2]}})
        with pytest.raises(ValueError, match='positive'):
            validate_pad_spec({'t': {'buckets': [0, 4]}})

    def test_loader_pads_and_jit_consumes(self, ragged_url):
        import jax
        import jax.numpy as jnp
        with make_reader(ragged_url, reader_pool_type='dummy', num_epochs=1,
                         shuffle_row_groups=False) as reader:
            loader = JaxDataLoader(reader, batch_size=8, drop_last=True,
                                   pad_spec={'tokens': {'buckets': [8, 16, 32],
                                                        'pad_value': 0}})
            batches = list(loader)
        assert batches
        for b in batches:
            assert b['tokens'].dtype == np.int32
            assert b['tokens'].shape[1] in (8, 16, 32)
            assert b['tokens_len'].dtype == np.int32

            @jax.jit
            def masked_sum(tokens, lengths):
                mask = jnp.arange(tokens.shape[1])[None, :] < lengths[:, None]
                return jnp.sum(tokens * mask, axis=1)

            dev = masked_sum(jnp.asarray(b['tokens']), jnp.asarray(b['tokens_len']))
            # padded positions (pad_value 0 here, but mask regardless) excluded
            expected = [int(row[:n].sum()) for row, n in
                        zip(b['tokens'], b['tokens_len'])]
            np.testing.assert_array_equal(np.asarray(dev), expected)

    def test_batch_size_one_still_buckets(self, ragged_url):
        # a single-row batch arrives DENSE from _collate; it must still pad
        # to a bucket or every distinct length is a fresh XLA compile
        with make_reader(ragged_url, reader_pool_type='dummy', num_epochs=1,
                         shuffle_row_groups=False) as reader:
            loader = JaxDataLoader(reader, batch_size=1,
                                   pad_spec={'tokens': {'buckets': [32]}})
            widths = {b['tokens'].shape[1] for b in loader}
        assert widths == {32}

    def test_unknown_pad_field_fails_fast(self, ragged_url):
        with make_reader(ragged_url, reader_pool_type='dummy') as reader:
            with pytest.raises(ValueError, match='unknown fields'):
                JaxDataLoader(reader, batch_size=4,
                              pad_spec={'token': {'max_len': 8}})

    def test_sharded_loader_rejects_multi_bucket(self, ragged_url):
        import jax
        from jax.sharding import Mesh
        from petastorm_tpu.jax_utils import ShardedJaxLoader
        devices = jax.devices('cpu')
        if len(devices) < 8:
            pytest.skip('needs 8 CPU devices')
        mesh = Mesh(np.array(devices[:8]), ('data',))
        with make_reader(ragged_url, reader_pool_type='dummy') as reader:
            with pytest.raises(ValueError, match='single-bucket'):
                ShardedJaxLoader(reader, mesh, 8,
                                 pad_spec={'tokens': {'buckets': [8, 16]}})
            loader = ShardedJaxLoader(reader, mesh, 8,
                                      pad_spec={'tokens': {'max_len': 32}})
            batch = next(iter(loader))
            assert batch['tokens'].shape[1] == 32    # global, fixed width
