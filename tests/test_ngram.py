"""NGram unit + end-to-end tests (reference ``tests/test_ngram.py``,
``tests/test_ngram_end_to_end.py``)."""

import numpy as np
import pytest

from petastorm_tpu import make_reader
from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
from petastorm_tpu.etl.dataset_metadata import materialize_dataset
from petastorm_tpu.ngram import NGram
from petastorm_tpu.unischema import Unischema, UnischemaField

SeqSchema = Unischema('SeqSchema', [
    UnischemaField('ts', np.int64, (), ScalarCodec(), False),
    UnischemaField('value', np.float32, (3,), NdarrayCodec(), False),
    UnischemaField('label', np.int32, (), ScalarCodec(), False),
])


@pytest.fixture(scope='module')
def seq_dataset(tmp_path_factory):
    """Rows with timestamps 0..49 plus a gap: 60..79; single file, two row groups."""
    path = tmp_path_factory.mktemp('seq') / 'ds'
    url = 'file://' + str(path)
    timestamps = list(range(50)) + list(range(60, 80))
    rows = [{'ts': np.int64(t),
             'value': np.full(3, t, dtype=np.float32),
             'label': np.int32(t % 7)} for t in timestamps]
    with materialize_dataset(url, SeqSchema, row_group_size_mb=100,
                             rows_per_file=1000) as w:
        w.write_rows(rows)
    return url, rows


def _make_ngram(length=3, delta_threshold=1, timestamp_overlap=True):
    fields = {i: ['ts', 'value', 'label'] for i in range(length)}
    return NGram(fields, delta_threshold=delta_threshold, timestamp_field='ts',
                 timestamp_overlap=timestamp_overlap)


def test_ngram_form_windows_unit():
    ngram = _make_ngram(length=2, delta_threshold=1)
    ngram.resolve_regex_field_names(SeqSchema)
    rows = [{'ts': t, 'value': np.zeros(3, np.float32), 'label': np.int32(0)}
            for t in [0, 1, 2, 10, 11]]
    grams = ngram.form_ngram(rows, SeqSchema)
    # (0,1),(1,2),(10,11) — the 2->10 gap exceeds the threshold
    assert len(grams) == 3
    assert [g[0].ts for g in grams] == [0, 1, 10]


def test_ngram_gapped_offsets_span_rows():
    # gaps are legal (reference test_non_consecutive_ngram): the window spans
    # max-min+1 rows and emits only the declared offsets
    ngram = NGram({0: ['a'], 2: ['b']}, delta_threshold=1, timestamp_field='ts')
    assert ngram.length == 3


def test_ngram_rejects_bad_construction():
    with pytest.raises(ValueError, match='at least one'):
        NGram({}, delta_threshold=1, timestamp_field='ts')
    with pytest.raises(TypeError, match='integers'):
        NGram({'x': ['a']}, delta_threshold=1, timestamp_field='ts')
    with pytest.raises(TypeError, match='lists'):
        NGram({0: 'a'}, delta_threshold=1, timestamp_field='ts')
    with pytest.raises(TypeError, match='numeric'):
        NGram({0: ['a']}, delta_threshold='big', timestamp_field='ts')


def test_ngram_non_overlap():
    ngram = _make_ngram(length=2, delta_threshold=1, timestamp_overlap=False)
    ngram.resolve_regex_field_names(SeqSchema)
    rows = [{'ts': t, 'value': np.zeros(3, np.float32), 'label': np.int32(0)}
            for t in range(6)]
    grams = ngram.form_ngram(rows, SeqSchema)
    assert [g[0].ts for g in grams] == [0, 2, 4]


def test_ngram_per_timestep_fields():
    ngram = NGram({0: ['ts', 'value'], 1: ['ts', 'label']}, delta_threshold=1,
                  timestamp_field='ts')
    ngram.resolve_regex_field_names(SeqSchema)
    rows = [{'ts': t, 'value': np.zeros(3, np.float32), 'label': np.int32(t)}
            for t in range(3)]
    grams = ngram.form_ngram(rows, SeqSchema)
    assert set(grams[0][0]._fields) == {'ts', 'value'}
    assert set(grams[0][1]._fields) == {'ts', 'label'}


@pytest.mark.parametrize('pool_type', ['dummy', 'thread'])
def test_ngram_end_to_end(seq_dataset, pool_type):
    url, rows = seq_dataset
    ngram = _make_ngram(length=3, delta_threshold=1)
    with make_reader(url, schema_fields=ngram, shuffle_row_groups=False,
                     reader_pool_type=pool_type, workers_count=2) as reader:
        grams = list(reader)
    # Validate window contents
    for g in grams:
        ts0 = g[0].ts
        assert g[1].ts == ts0 + 1 and g[2].ts == ts0 + 2
        np.testing.assert_array_equal(g[1].value, np.full(3, ts0 + 1, np.float32))
    starts = sorted(g[0].ts for g in grams)
    # contiguous runs 0..49 and 60..79 yield (50-2)+(20-2) windows
    assert len(starts) == 48 + 18


def test_ngram_regex_resolution(seq_dataset):
    url, _ = seq_dataset
    ngram = NGram({0: ['.*'], 1: ['ts']}, delta_threshold=1, timestamp_field='ts')
    with make_reader(url, schema_fields=ngram, shuffle_row_groups=False,
                     reader_pool_type='dummy') as reader:
        g = next(reader)
        assert set(g[0]._fields) == {'ts', 'value', 'label'}
        assert set(g[1]._fields) == {'ts'}
