"""Tests for the deterministic indexed loader and its O(1) exact resume
(closes SURVEY §5.4 properly — the reference cannot resume mid-epoch at all,
``reference reader.py:468-492``)."""

import numpy as np
import pytest

from petastorm_tpu import make_indexed_loader
from petastorm_tpu.codecs import ArrowListCodec, ScalarCodec
from petastorm_tpu.etl.dataset_metadata import materialize_dataset
from petastorm_tpu.indexed import IndexedDatasetReader, epoch_permutation
from petastorm_tpu.unischema import Unischema, UnischemaField

ROWS = 230

IndexedSchema = Unischema('IndexedSchema', [
    UnischemaField('idx', np.int64, (), ScalarCodec(), False),
    UnischemaField('vec', np.float32, (5,), ArrowListCodec(), False),
])


@pytest.fixture(scope='module')
def indexed_dataset(tmp_path_factory):
    path = tmp_path_factory.mktemp('indexed') / 'ds'
    url = 'file://' + str(path)
    rng = np.random.default_rng(0)
    rows = [{'idx': np.int64(i),
             'vec': rng.standard_normal(5).astype(np.float32)}
            for i in range(ROWS)]
    with materialize_dataset(url, IndexedSchema, row_group_size_mb=0.001) as w:
        w.write_rows(rows)
    return url, rows


def _stream(loader, limit=None):
    out = []
    for i, batch in enumerate(loader):
        out.append(batch)
        if limit is not None and i + 1 >= limit:
            break
    return out


class TestIndexedDataset:
    def test_random_access_gather(self, indexed_dataset):
        url, rows = indexed_dataset
        ds = IndexedDatasetReader(url)
        assert ds.total_rows == ROWS
        assert len(ds.pieces) >= 4            # enough row groups to matter
        want = np.asarray([7, 199, 0, 64, 7], np.int64)
        got = ds.gather(want)
        np.testing.assert_array_equal(got['idx'], want)
        for j, i in enumerate(want):
            np.testing.assert_array_equal(got['vec'][j], rows[i]['vec'])

    def test_permutation_properties(self, indexed_dataset):
        url, _ = indexed_dataset
        ds = IndexedDatasetReader(url)
        p1 = epoch_permutation(ds.total_rows, ds.row_offsets, seed=5, epoch=0)
        p2 = epoch_permutation(ds.total_rows, ds.row_offsets, seed=5, epoch=0)
        p3 = epoch_permutation(ds.total_rows, ds.row_offsets, seed=5, epoch=1)
        np.testing.assert_array_equal(p1, p2)          # deterministic
        assert not np.array_equal(p1, p3)              # varies by epoch
        np.testing.assert_array_equal(np.sort(p1), np.arange(ROWS))  # bijection


class TestIndexedLoader:
    def test_epoch_covers_all_batched_rows_exactly_once(self, indexed_dataset):
        url, _ = indexed_dataset
        loader = make_indexed_loader(url, batch_size=32, num_epochs=1, seed=1)
        batches = _stream(loader)
        assert len(batches) == ROWS // 32
        ids = np.concatenate([b['idx'] for b in batches])
        assert len(np.unique(ids)) == len(ids)          # no duplicates

    def test_stream_is_scheduling_independent(self, indexed_dataset):
        url, _ = indexed_dataset
        a = make_indexed_loader(url, batch_size=16, num_epochs=2, seed=3,
                                workers_count=1)
        b = make_indexed_loader(url, batch_size=16, num_epochs=2, seed=3,
                                workers_count=4)
        for ba, bb in zip(_stream(a), _stream(b)):
            np.testing.assert_array_equal(ba['idx'], bb['idx'])
            np.testing.assert_array_equal(ba['vec'], bb['vec'])

    def test_kill_midepoch_restore_byte_identical(self, indexed_dataset):
        """The VERDICT 'done' criterion: kill a thread-pool loader mid-epoch,
        restore from the cursor, get the byte-identical remaining stream."""
        url, _ = indexed_dataset
        make = lambda: make_indexed_loader(url, batch_size=16, num_epochs=3,  # noqa: E731
                                           seed=9, workers_count=4)

        reference = _stream(make())                     # the full stream
        victim = make()
        consumed = 0
        it = iter(victim)
        for _ in range(10):                             # mid-epoch-2 (14/epoch)
            next(it)
            consumed += 1
        state = victim.state_dict()
        it.close()                                      # "kill" the loader

        restored = make()
        restored.load_state_dict(state)
        rest = _stream(restored)
        assert len(rest) == len(reference) - consumed
        for got, want in zip(rest, reference[consumed:]):
            np.testing.assert_array_equal(got['idx'], want['idx'])
            np.testing.assert_array_equal(got['vec'], want['vec'])

    def test_resume_across_epoch_boundary(self, indexed_dataset):
        url, _ = indexed_dataset
        make = lambda: make_indexed_loader(url, batch_size=16, num_epochs=2,  # noqa: E731
                                           seed=4, workers_count=2)
        reference = _stream(make())
        per_epoch = ROWS // 16
        victim = make()
        it = iter(victim)
        for _ in range(per_epoch):                      # exactly one epoch
            next(it)
        state = victim.state_dict()
        it.close()
        assert state == {'epoch': 1, 'batch': 0, 'version': 1}
        restored = make()
        restored.load_state_dict(state)
        rest = _stream(restored)
        assert len(rest) == per_epoch
        for got, want in zip(rest, reference[per_epoch:]):
            np.testing.assert_array_equal(got['idx'], want['idx'])

    def test_no_shuffle_is_sequential(self, indexed_dataset):
        url, _ = indexed_dataset
        loader = make_indexed_loader(url, batch_size=32, num_epochs=1,
                                     shuffle=False)
        ids = np.concatenate([b['idx'] for b in _stream(loader)])
        np.testing.assert_array_equal(ids, np.arange(len(ids)))

    def test_state_roundtrips_through_json(self, indexed_dataset):
        import json
        url, _ = indexed_dataset
        loader = make_indexed_loader(url, batch_size=32, num_epochs=1)
        state = json.loads(json.dumps(loader.state_dict()))
        loader.load_state_dict(state)
        assert loader.state_dict() == state


class TestShardedIndexedLoader:
    """Global jax.Array batches addressed by (seed, epoch, batch): the
    composition of O(1) exact resume with the GSPMD mesh adapter."""

    @pytest.fixture()
    def mesh(self):
        import jax
        from jax.sharding import Mesh
        devices = jax.devices('cpu')
        if len(devices) < 8:
            pytest.skip('needs 8 CPU devices')
        return Mesh(np.array(devices[:8]), ('data',))

    def test_global_arrays_and_exact_resume(self, indexed_dataset, mesh):
        import jax
        from petastorm_tpu.indexed import make_indexed_loader
        url, _ = indexed_dataset
        kwargs = dict(batch_size=16, num_epochs=2, seed=5, mesh=mesh,
                      schema_fields=['idx', 'vec'])
        loader = make_indexed_loader(url, **kwargs)
        it = iter(loader)
        first = [next(it) for _ in range(3)]
        for b in first:
            assert isinstance(b['idx'], jax.Array)
            assert b['idx'].shape == (16,)
            assert b['idx'].sharding.spec == jax.sharding.PartitionSpec('data')
        state = loader.state_dict()
        rest_a = [np.asarray(b['idx']) for b in it]

        restored = make_indexed_loader(url, **kwargs)
        restored.load_state_dict(state)
        rest_b = [np.asarray(b['idx']) for b in restored]
        assert len(rest_a) == len(rest_b) > 0
        for x, y in zip(rest_a, rest_b):
            np.testing.assert_array_equal(x, y)

    def test_jit_consumes_global_batch(self, indexed_dataset, mesh):
        import jax
        import jax.numpy as jnp
        from petastorm_tpu.indexed import make_indexed_loader
        url, _ = indexed_dataset
        loader = make_indexed_loader(url, batch_size=16, num_epochs=1,
                                     mesh=mesh, schema_fields=['vec'])

        @jax.jit
        def f(v):
            return jnp.sum(v)

        total = sum(float(f(b['vec'])) for b in loader)
        assert np.isfinite(total)

    def test_global_batch_must_divide_processes(self, indexed_dataset, mesh,
                                                monkeypatch):
        import jax
        from petastorm_tpu.indexed import ShardedIndexedLoader
        import petastorm_tpu.indexed as idx
        url, _ = indexed_dataset
        monkeypatch.setattr(jax, 'process_count', lambda: 3)
        with idx.IndexedDatasetReader(url, schema_fields=['idx']) as reader:
            with pytest.raises(ValueError, match='divide evenly'):
                ShardedIndexedLoader(reader, 16, mesh=mesh, num_epochs=1)

    def test_permuted_mesh_keeps_global_order(self, indexed_dataset, mesh):
        """Local row slices derive from the sharding's device→index map, not
        process_index blocks: a topology-permuted device order must produce
        byte-identical global batches."""
        import jax
        from jax.sharding import Mesh
        from petastorm_tpu.indexed import make_indexed_loader
        url, _ = indexed_dataset
        devices = jax.devices('cpu')[:8]
        mesh_rev = Mesh(np.array(devices[::-1]), ('data',))
        kw = dict(batch_size=16, num_epochs=1, seed=3, schema_fields=['idx'])
        a = [np.asarray(b['idx'])
             for b in make_indexed_loader(url, mesh=mesh, **kw)]
        b = [np.asarray(x['idx'])
             for x in make_indexed_loader(url, mesh=mesh_rev, **kw)]
        assert len(a) == len(b) > 0
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)

    def test_indivisible_global_batch_fails_fast(self, indexed_dataset, mesh):
        from petastorm_tpu.indexed import make_indexed_loader
        url, _ = indexed_dataset
        with pytest.raises(ValueError, match='devices of mesh axis'):
            make_indexed_loader(url, batch_size=12, mesh=mesh, num_epochs=1)


class TestForeignStore:
    """Indexed loading over plain parquet with NO petastorm metadata."""

    @pytest.fixture(scope='class')
    def foreign_url(self, tmp_path_factory):
        import pyarrow as pa
        import pyarrow.parquet as pq
        path = tmp_path_factory.mktemp('foreign') / 'plain'
        path.mkdir()
        n = 120
        table = pa.table({'id': np.arange(n, dtype=np.int64),
                          'value': np.arange(n, dtype=np.float64) * 0.5})
        pq.write_table(table, str(path / 'part0.parquet'), row_group_size=16)
        return 'file://' + str(path), n

    def test_schema_inferred_and_values_exact(self, foreign_url):
        url, n = foreign_url
        loader = make_indexed_loader(url, batch_size=10, num_epochs=1, seed=1)
        assert set(loader.schema.fields) == {'id', 'value'}
        seen = np.sort(np.concatenate([b['id'] for b in loader]))
        np.testing.assert_array_equal(seen, np.arange(n, dtype=np.int64))
        loader.close()

    def test_resume_on_foreign_store(self, foreign_url):
        url, _ = foreign_url
        full = _stream(make_indexed_loader(url, batch_size=10, num_epochs=2,
                                           seed=3))
        probe = make_indexed_loader(url, batch_size=10, num_epochs=2, seed=3)
        _stream(probe, limit=7)
        state = probe.state_dict()
        probe.close()
        restored = make_indexed_loader(url, batch_size=10, num_epochs=2, seed=3)
        restored.load_state_dict(state)
        rest = _stream(restored)
        assert len(rest) == len(full) - 7
        for a, b in zip(rest, full[7:]):
            np.testing.assert_array_equal(a['id'], b['id'])
            np.testing.assert_array_equal(a['value'], b['value'])


class TestIndexedPredicate:
    def test_predicate_fixes_surviving_rows(self, indexed_dataset):
        from petastorm_tpu.predicates import in_lambda
        url, _ = indexed_dataset
        pred = in_lambda(['idx'], lambda v: v['idx'] % 3 == 0)
        loader = make_indexed_loader(url, batch_size=8, num_epochs=1, seed=0,
                                     predicate=pred)
        expected = np.arange(0, ROWS, 3, dtype=np.int64)
        assert loader.total_rows == len(expected)
        seen = np.concatenate([b['idx'] for b in loader])
        # drop_last may trim a tail; every seen row satisfies the predicate
        # and no row repeats within the epoch
        assert np.all(seen % 3 == 0)
        assert len(np.unique(seen)) == len(seen)
        assert set(seen).issubset(set(expected))
        loader.close()

    def test_predicate_stream_deterministic_and_resumable(self, indexed_dataset):
        from petastorm_tpu.predicates import in_lambda
        url, _ = indexed_dataset
        pred = in_lambda(['idx'], lambda v: v['idx'] % 2 == 0)
        kwargs = dict(batch_size=8, num_epochs=2, seed=11, predicate=pred)
        full = _stream(make_indexed_loader(url, **kwargs))
        probe = make_indexed_loader(url, **kwargs)
        _stream(probe, limit=5)
        state = probe.state_dict()
        probe.close()
        restored = make_indexed_loader(url, workers_count=1, **kwargs)
        restored.load_state_dict(state)
        rest = _stream(restored)
        for a, b in zip(rest, full[5:]):
            np.testing.assert_array_equal(a['idx'], b['idx'])
            np.testing.assert_array_equal(a['vec'], b['vec'])
        assert len(rest) == len(full) - 5

    def test_unknown_predicate_field_fails_fast(self, indexed_dataset):
        from petastorm_tpu.predicates import in_lambda
        url, _ = indexed_dataset
        with pytest.raises(ValueError, match='unknown fields'):
            make_indexed_loader(url, batch_size=8,
                                predicate=in_lambda(['nope'], lambda v: True))

    def test_predicate_rejecting_everything_raises(self, indexed_dataset):
        from petastorm_tpu.errors import NoDataAvailableError
        from petastorm_tpu.predicates import in_lambda
        url, _ = indexed_dataset
        with pytest.raises(NoDataAvailableError, match='after predicate'):
            make_indexed_loader(url, batch_size=8,
                                predicate=in_lambda(['idx'], lambda v: False))


class TestIndexedTransform:
    def _resize_spec(self):
        """ImageNet-style deterministic worker transform: vec (5,) -> first
        three components scaled (stands in for decode+resize)."""
        from petastorm_tpu.transform import TransformSpec

        def shrink(columns):
            columns['vec'] = (columns['vec'][:, :3] * 2.0).astype(np.float32)
            return columns

        return TransformSpec(shrink,
                             edit_fields=[('vec', np.float32, (3,), False)],
                             selected_fields=['idx', 'vec'])

    def test_transform_applied_and_schema_updated(self, indexed_dataset):
        url, rows = indexed_dataset
        loader = make_indexed_loader(url, batch_size=8, num_epochs=1, seed=0,
                                     shuffle=False,
                                     transform_spec=self._resize_spec())
        assert loader.schema.fields['vec'].shape == (3,)
        batch = next(iter(loader))
        assert batch['vec'].shape == (8, 3)
        for i, idx in enumerate(batch['idx']):
            np.testing.assert_allclose(batch['vec'][i],
                                       rows[int(idx)]['vec'][:3] * 2.0,
                                       rtol=1e-6)
        loader.close()

    def test_transform_resume_value_exact(self, indexed_dataset):
        url, _ = indexed_dataset
        kwargs = dict(batch_size=8, num_epochs=2, seed=9,
                      transform_spec=self._resize_spec())
        full = _stream(make_indexed_loader(url, **kwargs))
        probe = make_indexed_loader(url, **kwargs)
        _stream(probe, limit=11)
        state = probe.state_dict()
        probe.close()
        restored = make_indexed_loader(url, workers_count=2, **kwargs)
        restored.load_state_dict(state)
        rest = _stream(restored)
        for a, b in zip(rest, full[11:]):
            np.testing.assert_array_equal(a['idx'], b['idx'])
            np.testing.assert_array_equal(a['vec'], b['vec'])
        assert len(rest) == len(full) - 11

    def test_predicate_and_transform_compose(self, indexed_dataset):
        from petastorm_tpu.predicates import in_lambda
        url, rows = indexed_dataset
        pred = in_lambda(['idx'], lambda v: v['idx'] < 100)
        loader = make_indexed_loader(url, batch_size=8, num_epochs=1, seed=4,
                                     predicate=pred,
                                     transform_spec=self._resize_spec())
        for batch in loader:
            assert np.all(batch['idx'] < 100)
            assert batch['vec'].shape == (8, 3)
            for i, idx in enumerate(batch['idx']):
                np.testing.assert_allclose(batch['vec'][i],
                                           rows[int(idx)]['vec'][:3] * 2.0,
                                           rtol=1e-6)
        loader.close()

    def test_sharded_loader_applies_transform(self, indexed_dataset):
        import jax
        from petastorm_tpu.parallel import make_mesh
        url, rows = indexed_dataset
        mesh = make_mesh({'data': len(jax.devices())})
        loader = make_indexed_loader(url, batch_size=16, num_epochs=1, seed=2,
                                     mesh=mesh,
                                     transform_spec=self._resize_spec())
        batch = next(iter(loader))
        assert batch['vec'].shape == (16, 3)
        vec = np.asarray(batch['vec'])
        for i, idx in enumerate(np.asarray(batch['idx'])):
            np.testing.assert_allclose(vec[i], rows[int(idx)]['vec'][:3] * 2.0,
                                       rtol=1e-6)
        loader.close()

    def test_predicate_may_use_fields_outside_view(self, indexed_dataset):
        # matches the streaming readers: predicate fields need not be in the
        # schema_fields output view
        from petastorm_tpu.predicates import in_lambda
        url, _ = indexed_dataset
        pred = in_lambda(['idx'], lambda v: v['idx'] % 5 == 0)
        loader = make_indexed_loader(url, batch_size=4, num_epochs=1, seed=0,
                                     schema_fields=['vec'], predicate=pred)
        batch = next(iter(loader))
        assert set(batch.keys()) == {'vec'}
        loader.close()


class TestRaggedFieldsExactResume:
    """Ragged (wildcard-shape) fields compose with the indexed loader +
    pad_ragged_batch: exact O(1) resume is NOT limited to fixed-shape
    pipelines (round-3 weak item: ragged pipelines fell back to replay)."""

    @pytest.fixture(scope='class')
    def ragged_url(self, tmp_path_factory):
        from petastorm_tpu.codecs import NdarrayCodec
        schema = Unischema('Ragged', [
            UnischemaField('idx', np.int64, (), ScalarCodec(), False),
            UnischemaField('seq', np.int32, (None,), NdarrayCodec(), False),
        ])
        url = 'file://' + str(tmp_path_factory.mktemp('ragged_idx') / 'ds')
        rng = np.random.default_rng(1)
        rows = [{'idx': np.int64(i),
                 'seq': rng.integers(0, 100, rng.integers(1, 9),
                                     dtype='int64').astype(np.int32)}
                for i in range(96)]
        with materialize_dataset(url, schema, row_group_size_mb=0.001) as w:
            w.write_rows(rows)
        return url, rows

    def _make(self, url, pad_spec, **kw):
        return make_indexed_loader(url, batch_size=16, num_epochs=2, seed=5,
                                   workers_count=2, pad_spec=pad_spec, **kw)

    def test_padded_batches_dense_and_resumable(self, ragged_url):
        url, rows = ragged_url
        pad_spec = {'seq': {'max_len': 8, 'pad_value': -1}}
        full = []
        for batch in self._make(url, pad_spec):
            assert batch['seq'].dtype == np.int32
            assert batch['seq'].shape == (16, 8)        # dense, bucketed
            assert batch['seq_len'].dtype == np.int32
            # padding slots carry pad_value; real slots match the source rows
            for r in range(16):
                n = int(batch['seq_len'][r])
                src = next(x for x in rows if x['idx'] == batch['idx'][r])
                np.testing.assert_array_equal(batch['seq'][r, :n], src['seq'])
                assert (batch['seq'][r, n:] == -1).all()
            full.append((batch['idx'].tobytes(), batch['seq'].tobytes()))

        # byte-exact mid-epoch resume of the PADDED stream
        first = self._make(url, pad_spec)
        it = iter(first)
        for _ in range(3):
            next(it)
        state = first.state_dict()
        it.close()
        first.close()
        resumed = self._make(url, pad_spec)
        resumed.load_state_dict(state)
        rest = [(b['idx'].tobytes(), b['seq'].tobytes()) for b in resumed]
        assert rest == full[3:]

    def test_unknown_pad_field_rejected(self, ragged_url):
        url, _ = ragged_url
        with pytest.raises(ValueError, match='unknown fields'):
            self._make(url, {'nope': {'max_len': 8}})

    def test_length_field_collision_rejected(self, ragged_url):
        """The synthesized length column must not silently overwrite a real
        schema column."""
        url, _ = ragged_url
        with pytest.raises(ValueError, match='collides'):
            self._make(url, {'seq': {'max_len': 8, 'length_field': 'idx'}})

    def test_sharded_multi_bucket_rejected(self, ragged_url):
        import jax
        from petastorm_tpu.parallel import make_mesh
        url, _ = ragged_url
        devices = jax.devices('cpu')
        if len(devices) < 8:
            pytest.skip('needs 8 CPU devices')
        mesh = make_mesh({'data': 8}, devices=devices)
        with pytest.raises(ValueError, match='single-bucket'):
            self._make(url, {'seq': {'buckets': [4, 8]}}, mesh=mesh)

    def test_sharded_single_bucket_pads_globally(self, ragged_url):
        import jax
        from petastorm_tpu.parallel import make_mesh
        url, rows = ragged_url
        devices = jax.devices('cpu')
        if len(devices) < 8:
            pytest.skip('needs 8 CPU devices')
        mesh = make_mesh({'data': 8}, devices=devices)
        loader = self._make(url, {'seq': {'max_len': 8, 'pad_value': -1}},
                            mesh=mesh)
        batch = next(iter(loader))
        assert isinstance(batch['seq'], jax.Array)
        assert batch['seq'].shape == (16, 8)
        assert batch['seq_len'].shape == (16,)
        loader.close()


def test_gather_promotes_dtype_across_mixed_null_pieces(tmp_path):
    """A nullable int column decodes int64 in null-free groups but NaN-holed
    float in null-bearing ones; gather must promote the output dtype instead
    of casting NaN into garbage ints (r05 review finding)."""
    import numpy as np

    from petastorm_tpu.codecs import ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import materialize_dataset
    from petastorm_tpu.indexed import make_indexed_loader
    from petastorm_tpu.unischema import Unischema, UnischemaField

    schema = Unischema('N', [
        UnischemaField('id', np.int64, (), ScalarCodec(), False),
        UnischemaField('m', np.int64, (), ScalarCodec(), True)])
    url = 'file://' + str(tmp_path / 'mixed_nulls')
    vals = list(range(10)) + [None if i % 2 else i for i in range(10, 20)]
    with materialize_dataset(url, schema, rows_per_file=10) as w:
        w.write_rows({'id': np.int64(i), 'm': vals[i]} for i in range(20))
    with make_indexed_loader(url, batch_size=20, num_epochs=1,
                             shuffle=False) as loader:
        batch = next(iter(loader))
    m = batch['m']
    assert m.dtype.kind == 'f'
    assert float(m[0]) == 0.0 and float(m[10]) == 10.0
    assert np.isnan(m[11]) and np.isnan(m[19])
