"""Tail-latency plane tests: streaming-histogram math (merge associativity,
quantile error bounds, rolling windows), worker-side delta shipping across the
process boundary (including a killed worker), the end-to-end SLO breach →
error-budget burn → ``/slo``/``/healthz`` path, and the
``PETASTORM_TPU_LATENCY=0`` kill switch's no-histogram-state contract."""

import json
import os
import time
import urllib.request

import numpy as np
import pytest

from petastorm_tpu.latency import (BUCKET_BOUNDS_S, LATENCY_ENV_VAR,
                                   NUM_BUCKETS, QUANTILE_REL_ERROR_BOUND,
                                   STAGES, LatencyDeltas, LatencyHistogram,
                                   PipelineLatency, SLOMonitor, bucket_index,
                                   latency_enabled,
                                   prometheus_histogram_lines,
                                   validate_slo_targets)
from petastorm_tpu.reader import make_reader
from petastorm_tpu.test_util.dataset_gen import create_test_dataset
from petastorm_tpu.workers.stats import LATENCY_HISTOGRAMS_KEY, ReaderStats


class _FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


class TestBucketScheme:
    def test_boundaries_are_fixed_and_geometric(self):
        bounds = np.asarray(BUCKET_BOUNDS_S)
        ratios = bounds[1:] / bounds[:-1]
        assert np.allclose(ratios, ratios[0])
        # mergeability rests on every instance sharing these: they are
        # module constants, never per-instance configuration
        assert len(bounds) == NUM_BUCKETS

    def test_bucket_index_boundary_exact(self):
        # v == bound must land IN that bucket (le semantics), v just above
        # in the next — across the whole range, despite float log noise
        for i, bound in enumerate(BUCKET_BOUNDS_S):
            assert bucket_index(bound) == i
            assert bucket_index(bound * 1.0000001) == i + 1
        assert bucket_index(0.0) == 0
        assert bucket_index(-1.0) == 0
        assert bucket_index(1e9) == NUM_BUCKETS   # overflow bucket

    @pytest.mark.parametrize('dist', ['lognormal', 'uniform', 'bimodal'])
    def test_quantile_error_bound_vs_numpy(self, dist):
        rng = np.random.default_rng(7)
        if dist == 'lognormal':
            vals = rng.lognormal(-5.0, 1.5, 20000)
        elif dist == 'uniform':
            vals = rng.uniform(1e-4, 0.5, 20000)
        else:
            vals = np.concatenate([rng.normal(0.001, 1e-4, 10000),
                                   rng.normal(0.2, 0.01, 200)])
            vals = np.clip(vals, 1e-6, None)
        histogram = LatencyHistogram()
        for v in vals:
            histogram.record(float(v))
        for q in (0.5, 0.9, 0.99, 0.999):
            estimated = histogram.quantile(q)
            exact = float(np.percentile(vals, q * 100))
            assert abs(estimated - exact) / exact <= QUANTILE_REL_ERROR_BOUND, \
                (dist, q, estimated, exact)

    def test_empty_histogram_quantile_none(self):
        assert LatencyHistogram().quantile(0.99) is None
        assert LatencyHistogram().percentiles()['p50'] is None


class TestMerge:
    def test_merge_associative_and_equals_direct_recording(self):
        rng = np.random.default_rng(3)
        vals = rng.lognormal(-6.0, 2.0, 3000)
        direct = LatencyHistogram()
        parts = [LatencyHistogram() for _ in range(3)]
        for i, v in enumerate(vals):
            direct.record(float(v))
            parts[i % 3].record(float(v))
        merged_fwd = LatencyHistogram()
        for part in parts:
            merged_fwd.merge(part)
        merged_rev = LatencyHistogram()
        for part in reversed(parts):
            merged_rev.merge(part)
        # bucket-count addition is commutative/associative; both orders
        # equal recording everything into one instance
        assert np.array_equal(merged_fwd.counts(), merged_rev.counts())
        assert np.array_equal(merged_fwd.counts(), direct.counts())
        assert merged_fwd.count == direct.count == len(vals)
        assert merged_fwd.sum_s == pytest.approx(direct.sum_s)

    def test_merge_delta_equals_merge(self):
        vals = [1e-5, 3e-4, 0.02, 0.02, 1.5]
        deltas = LatencyDeltas()
        direct = LatencyHistogram()
        for v in vals:
            deltas.record('io', v)
            direct.record(v)
        drained = deltas.drain()
        via_delta = LatencyHistogram()
        via_delta.merge_delta(drained['io'])
        assert np.array_equal(via_delta.counts(), direct.counts())
        assert via_delta.count == direct.count
        assert via_delta.sum_s == pytest.approx(direct.sum_s)
        # drain resets; empty drain is None (nothing ships on idle items)
        assert deltas.drain() is None

    def test_deltas_map_time_stage_names(self):
        deltas = LatencyDeltas()
        deltas.record_time_stage('worker_io_s', 0.01)
        deltas.record_time_stage('worker_decode_s', 0.02)
        deltas.record_time_stage('serialize_s', 0.03)   # not a latency stage
        drained = deltas.drain()
        assert set(drained) == {'io', 'decode'}


class TestRollingWindow:
    def test_old_observations_age_out(self):
        clock = _FakeClock()
        histogram = LatencyHistogram(interval_s=1.0, window_intervals=3,
                                     clock=clock)
        histogram.record(0.001)
        clock.t = 1.5
        histogram.record(0.002)
        # both still inside the 3-interval window
        assert histogram.window_counts().sum() == 2
        clock.t = 10.0   # far beyond the window: silent intervals roll in
        assert histogram.window_counts().sum() == 0
        # lifetime counts never age
        assert histogram.count == 2
        assert histogram.quantile(0.5) is not None
        assert histogram.quantile(0.5, window=True) is None

    def test_window_quantile_covers_recent_only(self):
        clock = _FakeClock()
        histogram = LatencyHistogram(interval_s=1.0, window_intervals=2,
                                     clock=clock)
        for _ in range(100):
            histogram.record(0.001)   # old regime
        clock.t = 5.0
        for _ in range(10):
            histogram.record(1.0)     # recent regime
        window_p50 = histogram.quantile(0.5, window=True)
        lifetime_p50 = histogram.quantile(0.5)
        assert window_p50 == pytest.approx(1.0, rel=0.25)
        assert lifetime_p50 == pytest.approx(0.001, rel=0.25)

    def test_recent_interval_p99_trend(self):
        clock = _FakeClock()
        histogram = LatencyHistogram(interval_s=1.0, window_intervals=4,
                                     clock=clock)
        for step, value in enumerate([0.001, 0.01, 0.1]):
            clock.t = float(step)
            histogram.record(value)
        clock.t = 3.0
        histogram.record(0.5)   # open interval: not in the closed trend yet
        trend = histogram.recent_interval_p99s()
        assert len(trend) == 3
        # the creep is visible interval over interval
        assert trend[0] < trend[1] < trend[2]


class TestPipelineLatencyPlane:
    def test_fixed_stage_set_and_export(self):
        plane = PipelineLatency()
        assert set(plane.histograms) == set(STAGES)
        plane.record('io', 0.01)
        plane.record('nonexistent-stage', 0.01)   # ignored, never raises
        state = plane.export_state()
        assert set(state) == {'io'}
        assert state['io']['count'] == 1

    def test_flight_summary_has_trend(self):
        clock = _FakeClock()
        plane = PipelineLatency(interval_s=1.0, window_intervals=4,
                                clock=clock)
        for step in range(3):
            clock.t = float(step)
            plane.record('e2e_batch', 0.01 * (step + 1))
        clock.t = 3.0
        summary = plane.flight_summary()
        assert 'e2e_batch' in summary['stages']
        assert summary['stages']['e2e_batch']['p99_s'] > 0
        assert len(summary['p99_trend']['e2e_batch']) == 3


class TestPrometheusHistogramLines:
    def test_cumulative_buckets_and_terminals(self):
        histogram = LatencyHistogram()
        for v in (1e-5, 1e-5, 3e-3, 0.2, 9999.0):
            histogram.record(v)
        lines = prometheus_histogram_lines('x_seconds', histogram.state())
        assert lines[0] == '# TYPE x_seconds histogram'
        bucket_lines = [ln for ln in lines if '_bucket{' in ln]
        counts = [int(ln.rsplit(' ', 1)[1]) for ln in bucket_lines]
        assert counts == sorted(counts), 'bucket samples must be cumulative'
        assert bucket_lines[-1].startswith('x_seconds_bucket{le="+Inf"}')
        assert counts[-1] == 5
        assert any(ln.startswith('x_seconds_sum ') for ln in lines)
        assert lines[-1] == 'x_seconds_count 5'


class TestSLOMonitor:
    def test_unknown_target_rejected(self):
        with pytest.raises(ValueError, match='unknown slo target'):
            validate_slo_targets({'p99_e2e_msec': 5})
        with pytest.raises(ValueError, match='error_budget'):
            validate_slo_targets({'error_budget': 0.0})
        with pytest.raises(ValueError, match='budget_window'):
            validate_slo_targets({'budget_window': 0})

    def test_latency_target_skips_without_data(self):
        monitor = SLOMonitor({'p99_e2e_ms': 5.0}, latency=PipelineLatency())
        verdict = monitor.evaluate({})
        assert verdict['skipped_checks'] == ['p99_e2e_ms']
        assert not verdict['breached']
        # kill switch: no latency plane at all also skips, loudly
        monitor = SLOMonitor({'p99_e2e_ms': 5.0}, latency=None)
        assert monitor.evaluate({})['skipped_checks'] == ['p99_e2e_ms']

    def test_breach_and_burn_accounting(self):
        plane = PipelineLatency()
        for _ in range(50):
            plane.record('e2e_batch', 0.5)   # 500ms p99
        monitor = SLOMonitor({'p99_e2e_ms': 10.0, 'error_budget': 0.5,
                              'budget_window': 4, 'eval_interval_s': 0,
                              'min_evaluations': 1}, latency=plane)
        first = monitor.evaluate({})
        assert first['breached']
        assert first['breached_checks'] == ['p99_e2e_ms']
        assert first['checks']['p99_e2e_ms']['measured_ms'] > 10.0
        # 1/1 breaching over budget 0.5 → burn 2.0: hard breach
        assert first['burn_rate'] == pytest.approx(2.0)
        assert first['hard_breach']
        # the ring is bounded by budget_window
        for _ in range(10):
            last = monitor.evaluate({})
        assert last['evaluations'] == 4

    def test_burn_recording_is_probe_rate_independent(self):
        """Read-style observers (/healthz probes, /slo scrapes) evaluate
        freely, but at most one burn sample per eval_interval_s is RECORDED
        — a fast prober can neither flush breach samples out of the ring
        nor multiply them."""
        monitor = SLOMonitor({'min_samples_per_s': 100.0,
                              'eval_interval_s': 3600.0,
                              'min_evaluations': 1})
        first = monitor.evaluate({'items_per_s': 1.0})   # breaching: recorded
        assert first['evaluations'] == 1 and first['breached_evaluations'] == 1
        # a storm of passing probes inside the interval records NOTHING:
        # the breach sample cannot be diluted by probe frequency
        for _ in range(50):
            last = monitor.evaluate({'items_per_s': 500.0})
        assert last['evaluations'] == 1
        assert last['breached_evaluations'] == 1
        assert last['burn_rate'] >= 1.0
        # the fresh checks still reflect the CURRENT state
        assert not last['breached']

    def test_hard_breach_needs_warmup_grace(self):
        """A cold pipeline's first breaching evaluation (rates still
        ramping) must not read as a spent budget and 503 the pod."""
        monitor = SLOMonitor({'min_samples_per_s': 100.0,
                              'eval_interval_s': 0,
                              'min_evaluations': 5})
        verdict = monitor.evaluate({'items_per_s': 0.0})
        assert verdict['breached']
        assert verdict['burn_rate'] >= 1.0
        assert not verdict['hard_breach'], 'grace must hold off hard_breach'
        for _ in range(4):
            verdict = monitor.evaluate({'items_per_s': 0.0})
        assert verdict['evaluations'] == 5
        assert verdict['hard_breach'], 'sustained breach past grace asserts'

    def test_throughput_and_stall_targets(self):
        monitor = SLOMonitor({'min_samples_per_s': 100.0,
                              'max_stall_episodes': 0})
        good = monitor.evaluate({'items_per_s': 500.0})
        assert not good['breached']
        bad = monitor.evaluate({'items_per_s': 3.0})
        assert 'min_samples_per_s' in bad['breached_checks']
        monitor.record_stall_episode()
        stalled = monitor.evaluate({'items_per_s': 500.0})
        assert 'max_stall_episodes' in stalled['breached_checks']
        assert stalled['stall_episodes'] == 1


@pytest.fixture(scope='module')
def latency_dataset(tmp_path_factory):
    path = tmp_path_factory.mktemp('latency_ds')
    url = 'file://' + str(path / 'ds')
    create_test_dataset(url, range(64), num_files=2)
    return url


class TestReaderIntegration:
    def test_thread_pool_populates_histograms(self, latency_dataset):
        with make_reader(latency_dataset, reader_pool_type='thread',
                         workers_count=2, num_epochs=1,
                         shuffle_row_groups=False) as reader:
            rows = sum(1 for _ in reader)
            assert rows == 64
            summary = reader.latency.summary()
            for stage in ('io', 'decode', 'queue_wait', 'e2e_batch'):
                assert summary[stage]['count'] > 0, stage
            snap = reader.stats.snapshot()
            assert snap['queue_wait_p99_s'] > 0.0
            assert snap['queue_wait_p99_s'] >= snap['queue_wait_p50_s']
            assert snap['e2e_latency_p99_s'] > 0.0
            assert LATENCY_HISTOGRAMS_KEY in snap

    def test_process_pool_ships_bucket_deltas(self, latency_dataset):
        with make_reader(latency_dataset, reader_pool_type='process',
                         workers_count=2, num_epochs=1,
                         shuffle_row_groups=False) as reader:
            rows = sum(1 for _ in reader)
            assert rows == 64
            summary = reader.latency.summary()
            # io/decode are recorded INSIDE the worker interpreters and only
            # reach this process as shipped bucket-count deltas
            assert summary['io']['count'] > 0
            assert summary['decode']['count'] > 0
            assert summary['deserialize']['count'] > 0
            assert summary['queue_wait']['count'] > 0

    @pytest.mark.timeout(120)
    def test_killed_worker_loses_only_unshipped_deltas(self, latency_dataset):
        """A worker killed mid-epoch: every delta shipped before the kill
        survives in the consumer-side histograms (the merge_counts shipping
        contract), and the pool still dies loudly."""
        reader = make_reader(latency_dataset, reader_pool_type='process',
                             workers_count=1, num_epochs=1,
                             shuffle_row_groups=False, worker_recovery=False)
        try:
            iterator = iter(reader)
            # consume until at least one worker accounting message (which
            # carries the bucket deltas) has drained — the first payload
            # frame can arrive ahead of its accounting frame
            deadline = time.monotonic() + 60
            while 'io' not in reader.latency.summary():
                next(iterator)
                assert time.monotonic() < deadline, 'no delta shipped'
            before = reader.latency.summary()
            assert before['io']['count'] > 0
            reader._pool._processes[0].kill()
            with pytest.raises(RuntimeError):
                deadline = time.monotonic() + 60
                while time.monotonic() < deadline:
                    next(iterator)
            after = reader.latency.summary()
            # nothing already shipped is lost
            assert after['io']['count'] >= before['io']['count']
        finally:
            reader.stop()
            reader.join()

    def test_slo_breach_burns_budget_and_flips_healthz(self, latency_dataset,
                                                       tmp_path):
        """Inject a slow decode → p99 e2e breaches the target → /slo reports
        the burn → /healthz flips 503 under fail_healthz. The whole
        sensor-to-verdict path, end to end."""
        from petastorm_tpu.transform import TransformSpec

        def slow(row):
            time.sleep(0.003)
            return row

        with make_reader(latency_dataset, reader_pool_type='thread',
                         workers_count=1, num_epochs=1,
                         shuffle_row_groups=False,
                         transform_spec=TransformSpec(slow),
                         slo=dict(p99_e2e_ms=0.01, error_budget=0.5,
                                  fail_healthz=True, eval_interval_s=0,
                                  min_evaluations=1),
                         debug_port=0) as reader:
            sum(1 for _ in reader)
            port = reader.debug_port
            slo = json.load(urllib.request.urlopen(
                'http://127.0.0.1:%d/slo' % port))
            assert slo['breached']
            assert 'p99_e2e_ms' in slo['breached_checks']
            assert slo['checks']['p99_e2e_ms']['measured_ms'] > 0.01
            assert slo['burn_rate'] >= 1.0 and slo['hard_breach']
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen('http://127.0.0.1:%d/healthz' % port)
            assert err.value.code == 503
            body = json.loads(err.value.read())
            assert body['slo']['hard_breach']
            # the flight record carries the latency + slo evidence
            path = reader.dump_flight_record(str(tmp_path / 'flight.json'))
            blob = json.load(open(path))
            assert blob['slo']['hard_breach']
            assert 'p99_trend' in blob['latency']
            assert blob['latency']['stages']['e2e_batch']['count'] > 0

    def test_healthz_stays_200_without_fail_healthz(self, latency_dataset):
        with make_reader(latency_dataset, reader_pool_type='thread',
                         workers_count=1, num_epochs=1,
                         shuffle_row_groups=False,
                         slo=dict(p99_e2e_ms=1e-9, error_budget=0.01,
                                  eval_interval_s=0, min_evaluations=1),
                         debug_port=0) as reader:
            sum(1 for _ in reader)
            port = reader.debug_port
            slo = json.load(urllib.request.urlopen(
                'http://127.0.0.1:%d/slo' % port))
            assert slo['hard_breach']   # target is unmeetable on purpose
            response = urllib.request.urlopen(
                'http://127.0.0.1:%d/healthz' % port)
            assert response.status == 200   # contract breach != liveness

    def test_slo_route_404_without_targets(self, latency_dataset):
        with make_reader(latency_dataset, reader_pool_type='thread',
                         workers_count=1, num_epochs=1,
                         shuffle_row_groups=False, debug_port=0) as reader:
            sum(1 for _ in reader)
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(
                    'http://127.0.0.1:%d/slo' % reader.debug_port)
            assert err.value.code == 404

    def test_unknown_slo_target_fails_factory(self, latency_dataset):
        with pytest.raises(ValueError, match='unknown slo target'):
            make_reader(latency_dataset, slo=dict(p99_latency=5))

    def test_loader_records_e2e_once_per_batch(self, latency_dataset):
        from petastorm_tpu.jax_utils import JaxDataLoader
        with make_reader(latency_dataset, reader_pool_type='thread',
                         workers_count=2, num_epochs=1,
                         shuffle_row_groups=False) as reader:
            assert reader._e2e_live
            loader = JaxDataLoader(reader, batch_size=8)
            # the loader takes over the (later) batch-delivery point
            assert not reader._e2e_live
            batches = sum(1 for _ in loader)
            e2e = reader.latency.histograms['e2e_batch']
            assert e2e.count == batches
            infeed = reader.latency.histograms['infeed_wait']
            assert infeed.count == batches

    def test_kill_switch_creates_no_histogram_state(self, latency_dataset,
                                                    monkeypatch):
        monkeypatch.setenv(LATENCY_ENV_VAR, '0')
        assert not latency_enabled()
        assert ReaderStats().latency is None
        with make_reader(latency_dataset, reader_pool_type='thread',
                         workers_count=2, num_epochs=1,
                         shuffle_row_groups=False) as reader:
            rows = sum(1 for _ in reader)
            assert rows == 64
            assert reader.latency is None
            assert reader._worker_args['latency'] is False
            assert not reader._e2e_live
            for worker in reader._pool._workers:
                assert worker.latency is None
            snap = reader.stats.snapshot()
            assert LATENCY_HISTOGRAMS_KEY not in snap
            assert snap['queue_wait_p50_s'] == 0.0
            assert snap['queue_wait_p99_s'] == 0.0
            assert snap['e2e_latency_p99_s'] == 0.0

    def test_slo_monitor_works_under_kill_switch(self, latency_dataset,
                                                 monkeypatch):
        """Throughput targets still evaluate without the latency plane;
        latency targets skip loudly instead of silently passing."""
        monkeypatch.setenv(LATENCY_ENV_VAR, '0')
        with make_reader(latency_dataset, reader_pool_type='thread',
                         workers_count=2, num_epochs=1,
                         shuffle_row_groups=False,
                         slo=dict(p99_e2e_ms=5.0,
                                  min_samples_per_s=0.001)) as reader:
            sum(1 for _ in reader)
            verdict = reader.slo.evaluate()
            assert verdict['skipped_checks'] == ['p99_e2e_ms']
            assert verdict['checks']['min_samples_per_s']['ok']


class TestBottleneckTailStall:
    def test_tail_stall_discriminated_from_steady_backpressure(self):
        from petastorm_tpu.health import bottleneck_signals
        base = {'worker_io_s': 1.0, 'worker_decode_s': 1.0}
        steady = bottleneck_signals(dict(base, queue_wait_p50_s=0.2,
                                         queue_wait_p99_s=0.3))
        assert not steady['tail_stall']
        tail = bottleneck_signals(dict(base, queue_wait_p50_s=0.0005,
                                       queue_wait_p99_s=0.4))
        assert tail['tail_stall']
        assert tail['bottleneck'] == 'tail-stall'
        assert 'p99' in tail['hint']
        # no histogram keys at all (hand-built snapshot): never fires
        plain = bottleneck_signals(base)
        assert not plain['tail_stall']
