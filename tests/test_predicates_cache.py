"""Unit tests for predicates and the local disk cache
(reference ``tests/test_predicates.py``, ``tests/test_local_disk_cache.py``)."""

import numpy as np
import pytest

from petastorm_tpu.cache import LocalDiskCache, NullCache
from petastorm_tpu.predicates import (in_intersection, in_lambda, in_negate,
                                      in_pseudorandom_split, in_reduce, in_set)


class TestPredicates:
    def test_in_set(self):
        p = in_set({1, 2}, 'f')
        assert p.do_include({'f': 1}) and not p.do_include({'f': 3})
        assert p.get_fields() == ['f']

    def test_in_intersection(self):
        p = in_intersection({1, 2}, 'f')
        assert p.do_include({'f': [2, 9]}) and not p.do_include({'f': [5]})

    def test_in_lambda_with_state(self):
        state = {'count': 0}

        def count_and_pass(values, s):
            s['count'] += 1
            return True

        p = in_lambda(['f'], count_and_pass, state)
        assert p.do_include({'f': 1})
        assert state['count'] == 1

    def test_in_negate_and_reduce(self):
        p = in_reduce([in_set({1}, 'a'), in_negate(in_set({2}, 'b'))], all)
        assert sorted(p.get_fields()) == ['a', 'b']
        assert p.do_include({'a': 1, 'b': 3})
        assert not p.do_include({'a': 1, 'b': 2})

    def test_pseudorandom_split_deterministic(self):
        p0 = in_pseudorandom_split([0.3, 0.7], 0, 'f')
        results = [p0.do_include({'f': i}) for i in range(1000)]
        assert results == [p0.do_include({'f': i}) for i in range(1000)]
        frac = sum(results) / 1000
        assert 0.2 < frac < 0.4  # roughly 30%

    def test_pseudorandom_split_validation(self):
        with pytest.raises(ValueError):
            in_pseudorandom_split([0.5, 0.5], 2, 'f')
        with pytest.raises(ValueError):
            in_pseudorandom_split([0.8, 0.8], 0, 'f')


class TestLocalDiskCache:
    def test_miss_then_hit(self, tmp_path):
        cache = LocalDiskCache(str(tmp_path), 1 << 20)
        calls = {'n': 0}

        def fill():
            calls['n'] += 1
            return np.arange(10)

        v1 = cache.get('k1', fill)
        v2 = cache.get('k1', fill)
        assert calls['n'] == 1
        np.testing.assert_array_equal(v1, v2)

    def test_eviction_under_size_limit(self, tmp_path):
        cache = LocalDiskCache(str(tmp_path), size_limit_bytes=50_000)
        for i in range(20):
            cache.get('key_{}'.format(i), lambda i=i: np.full(1000, i))
        assert cache.size_bytes() <= 60_000  # approximately bounded

    def test_overwrite_does_not_double_count(self, tmp_path):
        # Overwriting a key must account only the size delta, not re-add the
        # full payload (advisor finding: premature eviction scans).
        cache = LocalDiskCache(str(tmp_path), size_limit_bytes=1 << 20)
        value = np.arange(1000)
        path = cache._key_path('k')
        cache._store(path, value)
        total_after_first = cache._approx_total
        for _ in range(10):
            cache._store(path, value)
        assert cache._approx_total == total_after_first

    def test_corrupt_entry_refilled(self, tmp_path):
        cache = LocalDiskCache(str(tmp_path), 1 << 20)
        cache.get('k', lambda: 42)
        path = cache._key_path('k')
        with open(path, 'wb') as f:
            f.write(b'garbage')
        assert cache.get('k', lambda: 43) == 43

    def test_cleanup(self, tmp_path):
        cache = LocalDiskCache(str(tmp_path / 'c'), 1 << 20, cleanup=True)
        cache.get('k', lambda: 1)
        cache.cleanup()
        import os
        assert not os.path.exists(str(tmp_path / 'c'))

    def test_null_cache(self):
        assert NullCache().get('k', lambda: 7) == 7
