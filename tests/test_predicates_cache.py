"""Unit tests for predicates and the local disk cache
(reference ``tests/test_predicates.py``, ``tests/test_local_disk_cache.py``)."""

import numpy as np
import pytest

from petastorm_tpu.cache import LocalDiskCache, NullCache
from petastorm_tpu.predicates import (in_intersection, in_lambda, in_negate,
                                      in_pseudorandom_split, in_reduce, in_set)


class TestPredicates:
    def test_in_set(self):
        p = in_set({1, 2}, 'f')
        assert p.do_include({'f': 1}) and not p.do_include({'f': 3})
        assert p.get_fields() == ['f']

    def test_in_intersection(self):
        p = in_intersection({1, 2}, 'f')
        assert p.do_include({'f': [2, 9]}) and not p.do_include({'f': [5]})

    def test_in_lambda_with_state(self):
        state = {'count': 0}

        def count_and_pass(values, s):
            s['count'] += 1
            return True

        p = in_lambda(['f'], count_and_pass, state)
        assert p.do_include({'f': 1})
        assert state['count'] == 1

    def test_in_negate_and_reduce(self):
        p = in_reduce([in_set({1}, 'a'), in_negate(in_set({2}, 'b'))], all)
        assert sorted(p.get_fields()) == ['a', 'b']
        assert p.do_include({'a': 1, 'b': 3})
        assert not p.do_include({'a': 1, 'b': 2})

    def test_pseudorandom_split_deterministic(self):
        p0 = in_pseudorandom_split([0.3, 0.7], 0, 'f')
        results = [p0.do_include({'f': i}) for i in range(1000)]
        assert results == [p0.do_include({'f': i}) for i in range(1000)]
        frac = sum(results) / 1000
        assert 0.2 < frac < 0.4  # roughly 30%

    def test_pseudorandom_split_validation(self):
        with pytest.raises(ValueError):
            in_pseudorandom_split([0.5, 0.5], 2, 'f')
        with pytest.raises(ValueError):
            in_pseudorandom_split([0.8, 0.8], 0, 'f')


class TestLocalDiskCache:
    def test_miss_then_hit(self, tmp_path):
        cache = LocalDiskCache(str(tmp_path), 1 << 20)
        calls = {'n': 0}

        def fill():
            calls['n'] += 1
            return np.arange(10)

        v1 = cache.get('k1', fill)
        v2 = cache.get('k1', fill)
        assert calls['n'] == 1
        np.testing.assert_array_equal(v1, v2)

    def test_eviction_under_size_limit(self, tmp_path):
        cache = LocalDiskCache(str(tmp_path), size_limit_bytes=50_000)
        for i in range(20):
            cache.get('key_{}'.format(i), lambda i=i: np.full(1000, i))
        assert cache.size_bytes() <= 60_000  # approximately bounded

    def test_overwrite_does_not_double_count(self, tmp_path):
        # Overwriting a key must account only the size delta, not re-add the
        # full payload (advisor finding: premature eviction scans).
        cache = LocalDiskCache(str(tmp_path), size_limit_bytes=1 << 20)
        value = np.arange(1000)
        path = cache._key_path('k')
        cache._store(path, value)
        total_after_first = cache._approx_total
        for _ in range(10):
            cache._store(path, value)
        assert cache._approx_total == total_after_first

    def test_corrupt_entry_refilled(self, tmp_path):
        cache = LocalDiskCache(str(tmp_path), 1 << 20)
        cache.get('k', lambda: 42)
        path = cache._key_path('k')
        with open(path, 'wb') as f:
            f.write(b'garbage')
        assert cache.get('k', lambda: 43) == 43

    def test_cleanup(self, tmp_path):
        cache = LocalDiskCache(str(tmp_path / 'c'), 1 << 20, cleanup=True)
        cache.get('k', lambda: 1)
        cache.cleanup()
        import os
        assert not os.path.exists(str(tmp_path / 'c'))

    def test_cleanup_leaves_no_renamed_residue(self, tmp_path):
        # shard dirs are removed rename-first (atomic disappearance); the
        # renamed '.removing' intermediates must not outlive cleanup()
        import os
        cache = LocalDiskCache(str(tmp_path / 'c'), 1 << 20, cleanup=True)
        for i in range(20):
            cache.get('k{}'.format(i), lambda i=i: i)
        cache.cleanup()
        assert not os.path.exists(str(tmp_path / 'c'))
        assert not [n for n in os.listdir(str(tmp_path))
                    if '.removing.' in n]

    def test_negative_drift_reseeds_from_scan(self, tmp_path):
        # Multi-process writers drift the per-process running total; a
        # concurrent overwrite can even drive it NEGATIVE (the other
        # process's bytes were never added here but the replaced-size
        # subtraction still applies). The next store must re-seed from a
        # directory scan instead of comparing garbage against the limit.
        cache = LocalDiskCache(str(tmp_path), size_limit_bytes=1 << 20)
        cache.get('seed', lambda: np.arange(100))
        cache._approx_total = -12345          # simulated cross-process drift
        cache.get('k2', lambda: np.arange(100))
        assert cache._approx_total >= 0
        assert abs(cache._approx_total - cache.size_bytes()) < 1024

    def test_stale_total_reseeds_periodically(self, tmp_path):
        from petastorm_tpu.cache import RESEED_SCAN_EVERY
        cache = LocalDiskCache(str(tmp_path), size_limit_bytes=1 << 20)
        cache.get('seed', lambda: np.arange(100))
        cache._approx_total = 10 ** 12        # wildly stale but positive
        cache._stores_since_scan = RESEED_SCAN_EVERY
        # a stale-but-positive total would otherwise trigger a pointless
        # full eviction scan on every store once it exceeds the limit
        cache.get('k2', lambda: np.arange(100))
        assert cache._approx_total < 10 ** 9
        assert abs(cache._approx_total - cache.size_bytes()) < 1024

    def test_null_cache(self):
        assert NullCache().get('k', lambda: 7) == 7


class TestPostTransformCaching:
    """The columnar worker caches POST-transform columns (the reference's
    cache-wraps-transform batch semantics, ``arrow_reader_worker.py:195-227``):
    epochs 2+ must skip decode AND transform, value-exactly."""

    @staticmethod
    def _store(tmp_path):
        import numpy as np

        from petastorm_tpu.codecs import CompressedImageCodec, ScalarCodec
        from petastorm_tpu.etl.dataset_metadata import materialize_dataset
        from petastorm_tpu.unischema import Unischema, UnischemaField
        schema = Unischema('Img', [
            UnischemaField('idx', np.int64, (), ScalarCodec(), False),
            UnischemaField('image', np.uint8, (16, 16), CompressedImageCodec('png'), False)])
        url = 'file://' + str(tmp_path / 'ds')
        rng = np.random.default_rng(0)
        with materialize_dataset(url, schema, rows_per_file=8) as w:
            w.write_rows({'idx': np.int64(i),
                          'image': rng.integers(0, 255, (16, 16), dtype=np.uint8)}
                         for i in range(32))
        return url

    @staticmethod
    def _collect(url, spec, cache_dir):
        import numpy as np

        from petastorm_tpu import make_columnar_reader
        kwargs = {}
        if cache_dir is not None:
            kwargs = dict(cache_type='local-disk',
                          cache_location=str(cache_dir),
                          cache_size_limit=2**30)
        out = {}
        with make_columnar_reader(url, num_epochs=1, reader_pool_type='dummy',
                                  shuffle_row_groups=False,
                                  transform_spec=spec, **kwargs) as r:
            for batch in r:
                for i, idx in enumerate(batch.idx):
                    out[int(idx)] = np.asarray(batch.image[i]).copy()
        return out

    def _spec(self, scale):
        import numpy as np

        from petastorm_tpu.transform import TransformSpec

        def f(cols, _scale=scale):
            cols = dict(cols)
            cols['image'] = (cols['image'].astype(np.int32) * _scale
                             ).clip(0, 255).astype(np.uint8)
            return cols
        return TransformSpec(f)

    def test_cached_epoch_equals_decoded_epoch(self, tmp_path):
        import numpy as np
        url = self._store(tmp_path)
        spec = self._spec(1)
        fresh = self._collect(url, spec, None)
        cache = tmp_path / 'cache'
        first = self._collect(url, spec, cache)          # fills the cache
        replay = self._collect(url, spec, cache)         # served from cache
        assert set(fresh) == set(first) == set(replay) == set(range(32))
        for k in fresh:
            np.testing.assert_array_equal(fresh[k], first[k])
            np.testing.assert_array_equal(fresh[k], replay[k])

    def test_cache_replay_skips_decode(self, tmp_path, monkeypatch):
        url = self._store(tmp_path)
        spec = self._spec(1)
        cache = tmp_path / 'cache'
        self._collect(url, spec, cache)                  # fill
        import petastorm_tpu.codecs as codecs

        def boom(*a, **k):
            raise AssertionError('decode ran on a cached epoch')
        monkeypatch.setattr(codecs.CompressedImageCodec, 'make_cell_decoder',
                            boom)
        self._collect(url, spec, cache)                  # must not decode

    def test_editing_transform_invalidates_cache(self, tmp_path):
        import numpy as np
        url = self._store(tmp_path)
        cache = tmp_path / 'cache'
        base = self._collect(url, self._spec(1), cache)
        # a DIFFERENT transform func must not be served the old entries
        doubled = self._collect(url, self._doubling_spec(), cache)
        changed = sum(not np.array_equal(base[k], doubled[k]) for k in base)
        assert changed > 0

    def test_same_func_different_parameter_invalidates_cache(self, tmp_path):
        """The sharp edge: same qualname, same bytecode, only the captured
        parameter differs (co_code is IDENTICAL for x*2 vs x*3 — constants
        live outside it). The fingerprint must still split the entries."""
        import numpy as np
        url = self._store(tmp_path)
        cache = tmp_path / 'cache'
        base = self._collect(url, self._spec(1), cache)
        tripled = self._collect(url, self._spec(3), cache)
        changed = sum(not np.array_equal(base[k], tripled[k]) for k in base)
        assert changed > 0

    def test_fingerprint_splits_defaults_and_closures(self):
        from petastorm_tpu.readers.columnar_worker import transform_fingerprint
        from petastorm_tpu.transform import TransformSpec

        def by_default(scale):
            def f(cols, _scale=scale):
                return cols
            return TransformSpec(f)

        def by_closure(scale):
            def f(cols):
                return {k: v * scale for k, v in cols.items()}
            return TransformSpec(f)

        assert (transform_fingerprint(by_default(2))
                != transform_fingerprint(by_default(3)))
        assert (transform_fingerprint(by_closure(2))
                != transform_fingerprint(by_closure(3)))
        # constant edits inside the body (repr of co_consts)
        assert (transform_fingerprint(TransformSpec(lambda c: {k: v * 2 for k, v in c.items()}))
                != transform_fingerprint(TransformSpec(lambda c: {k: v * 3 for k, v in c.items()})))

    @staticmethod
    def _doubling_spec():
        import numpy as np

        from petastorm_tpu.transform import TransformSpec

        def g(cols):
            cols = dict(cols)
            cols['image'] = (cols['image'].astype(np.int32) * 2
                             ).clip(0, 255).astype(np.uint8)
            return cols
        return TransformSpec(g)
