"""Edge scalar types end-to-end: datetime64 and Decimal through the writer,
both readers, and the adapter sanitizers (reference TestSchema carries
decimal/date fields; its adapters promote Decimal→string and
datetime→int64 ns — ``tf_utils.py:27-44``, ``pytorch.py:41-71``)."""

import datetime
from decimal import Decimal

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from petastorm_tpu import make_batch_reader, make_reader, materialize_dataset
from petastorm_tpu.codecs import ScalarCodec
from petastorm_tpu.unischema import Unischema, UnischemaField

EdgeSchema = Unischema('Edge', [
    UnischemaField('id', np.int64, (), ScalarCodec(), False),
    UnischemaField('ts', np.datetime64, (), ScalarCodec(), False),
    UnischemaField('price', Decimal, (), ScalarCodec(), False),
])


@pytest.fixture(scope='module')
def edge_dataset(tmp_path_factory):
    url = 'file://' + str(tmp_path_factory.mktemp('edge') / 'ds')
    rows = [{'id': np.int64(i),
             'ts': np.datetime64('2024-01-01T00:00:00') + np.timedelta64(i, 'h'),
             'price': Decimal('19.99') + Decimal(i)}
            for i in range(20)]
    with materialize_dataset(url, EdgeSchema) as w:
        w.write_rows(rows)
    return url, rows


class TestRowReader:
    def test_datetime_value_exact(self, edge_dataset):
        url, rows = edge_dataset
        with make_reader(url, shuffle_row_groups=False,
                         reader_pool_type='dummy') as reader:
            got = {int(r.id): r for r in reader}
        for expected in rows:
            out = got[int(expected['id'])].ts
            assert np.datetime64(out, 'ns') == np.datetime64(expected['ts'], 'ns')

    def test_decimal_round_trips_exactly(self, edge_dataset):
        url, rows = edge_dataset
        with make_reader(url, shuffle_row_groups=False,
                         reader_pool_type='dummy') as reader:
            got = {int(r.id): r for r in reader}
        for expected in rows:
            # stored as a string: exact decimal text survives
            assert Decimal(got[int(expected['id'])].price) == expected['price']


class TestForeignStore:
    @pytest.fixture(scope='class')
    def foreign_url(self, tmp_path_factory):
        path = tmp_path_factory.mktemp('edge_foreign') / 'ds'
        path.mkdir()
        table = pa.table({
            'id': pa.array(range(10), pa.int64()),
            'when': pa.array([datetime.datetime(2024, 3, 1, i) for i in range(10)],
                             pa.timestamp('us')),
            'amount': pa.array([Decimal('1.50') * i for i in range(10)],
                               pa.decimal128(10, 2)),
        })
        pq.write_table(table, str(path / 'part_0.parquet'))
        return 'file://' + str(path)

    def test_inferred_schema_and_values(self, foreign_url):
        with make_batch_reader(foreign_url, reader_pool_type='dummy') as reader:
            assert np.dtype(reader.schema.fields['when'].numpy_dtype).kind == 'M'
            batch = next(reader)
        whens = np.asarray(batch.when, dtype='datetime64[us]')
        assert whens[3] == np.datetime64('2024-03-01T03:00:00')
        assert Decimal(str(batch.amount[4])) == Decimal('6.00')


class TestAdapterSanitizers:
    def test_jax_loader_sanitizes(self, edge_dataset):
        from petastorm_tpu.jax_utils import JaxDataLoader
        url, rows = edge_dataset
        with make_reader(url, shuffle_row_groups=False,
                         reader_pool_type='dummy') as reader:
            loader = JaxDataLoader(reader, batch_size=5)
            batch = next(iter(loader))
        # datetime64 -> int64 ns; row order within a group is unspecified,
        # so match per-position via the id column
        assert batch['ts'].dtype == np.int64
        by_id = {int(r['id']): r for r in rows}
        for rid, ts_ns in zip(batch['id'], batch['ts']):
            expected = np.datetime64(by_id[int(rid)]['ts'], 'ns').astype(np.int64)
            assert ts_ns == expected

    def test_tf_dataset_sanitizes(self, edge_dataset):
        tf = pytest.importorskip('tensorflow')
        from petastorm_tpu.tf_utils import make_petastorm_dataset
        url, rows = edge_dataset
        with make_reader(url, shuffle_row_groups=False,
                         reader_pool_type='dummy') as reader:
            ds = make_petastorm_dataset(reader)
            row = next(iter(ds))
        assert row.ts.dtype == tf.int64
        assert row.price.dtype == tf.string
        by_id = {int(r['id']): r for r in rows}
        expected = by_id[int(row.id.numpy())]['price']
        assert Decimal(row.price.numpy().decode()) == expected


def test_nullable_scalar_cells_stay_none_in_row_reader(tmp_path):
    """Null scalar cells must surface as None through make_reader — the
    columnar row load must not hole nullable ints into NaN floats and then
    astype them into plausible-looking garbage (r05 review finding)."""
    import numpy as np

    from petastorm_tpu import make_batch_reader, make_reader
    from petastorm_tpu.codecs import ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import materialize_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField

    schema = Unischema('Nulls', [
        UnischemaField('id', np.int64, (), ScalarCodec(), False),
        UnischemaField('maybe_int', np.int64, (), ScalarCodec(), True),
        UnischemaField('maybe_float', np.float64, (), ScalarCodec(), True)])
    url = 'file://' + str(tmp_path / 'nulls')
    ints = [7, None, 9, None]
    floats = [1.5, None, 2.5, 3.5]
    with materialize_dataset(url, schema) as w:
        w.write_rows({'id': np.int64(i), 'maybe_int': ints[i],
                      'maybe_float': floats[i]} for i in range(4))

    with make_reader(url, reader_pool_type='dummy', num_epochs=1,
                     shuffle_row_groups=False) as r:
        rows = {int(row.id): row for row in r}
    assert rows[1].maybe_int is None and rows[3].maybe_int is None
    assert int(rows[0].maybe_int) == 7 and int(rows[2].maybe_int) == 9
    # non-null cells keep the declared numpy type even in null-bearing
    # groups (decode_row's cast semantics, not plain to_pylist ints)
    assert isinstance(rows[0].maybe_int, np.int64)
    assert rows[1].maybe_float is None
    assert float(rows[3].maybe_float) == 3.5

    # The BATCHED arrow path intentionally differs: nullable ints hole to
    # NaN (reference parity — the reference's arrow worker converts through
    # pandas, `arrow_reader_worker.py:38-87`, which has no int-with-null
    # representation). Row-granular readers are the None-preserving path.
    with make_batch_reader(url, reader_pool_type='dummy', num_epochs=1,
                           shuffle_row_groups=False) as r:
        batch = next(iter(r))
    by_id = dict(zip([int(i) for i in batch.id], batch.maybe_int))
    assert np.isnan(float(by_id[1])) and float(by_id[0]) == 7.0
