"""Row-group readahead tests: the prefetch unit (sync/take/cancel/errors),
the bounded file-handle LRU, reader integration across pool types and worker
paths (row/columnar/batch/ngram), order preservation, the stats-driven auto
depth, and the quick benchmark smoke."""

import threading
import time

import numpy as np
import pytest

from petastorm_tpu.readers.piece_worker import FileHandleCache
from petastorm_tpu.readers.readahead import (AUTO_MAX_DEPTH,
                                             RowGroupReadahead)
from petastorm_tpu.reader import (make_batch_reader, make_columnar_reader,
                                  make_reader)


class _FakeHandle:
    def __init__(self, path):
        self.path = path
        self.closed = False

    def close(self):
        self.closed = True


class TestFileHandleCache:
    def test_caches_and_reuses(self):
        opened = []

        def open_fn(path):
            handle = _FakeHandle(path)
            opened.append(handle)
            return handle

        cache = FileHandleCache(open_fn, max_size=4)
        a1 = cache.get('a')
        a2 = cache.get('a')
        assert a1 is a2
        assert len(opened) == 1

    def test_evicts_lru_and_closes(self):
        cache = FileHandleCache(_FakeHandle, max_size=2)
        a = cache.get('a')
        b = cache.get('b')
        cache.get('a')             # refresh 'a': 'b' is now the LRU entry
        c = cache.get('c')         # evicts 'b'
        assert b.closed
        assert not a.closed and not c.closed
        assert len(cache) == 2
        assert 'b' not in cache and 'a' in cache and 'c' in cache

    def test_close_all(self):
        cache = FileHandleCache(_FakeHandle, max_size=4)
        handles = [cache.get(p) for p in 'abc']
        cache.close_all()
        assert all(h.closed for h in handles)
        assert len(cache) == 0

    def test_rejects_zero_size(self):
        with pytest.raises(ValueError):
            FileHandleCache(_FakeHandle, max_size=0)


class _Recorder:
    """Minimal WorkerBase-shaped stats sink for drain_stats_into."""

    def __init__(self):
        self.times = {}
        self.counts = {}
        self.gauges = {}

    def record_time(self, stage, seconds):
        self.times[stage] = self.times.get(stage, 0.0) + seconds

    def record_count(self, name, n=1):
        self.counts[name] = self.counts.get(name, 0) + n

    def record_gauge(self, name, value):
        self.gauges[name] = value


def _plan(key):
    return (key, 'piece-' + str(key), ['col'])


class TestRowGroupReadahead:
    def test_prefetched_read_hits(self):
        reads = []

        def read_fn(piece, columns):
            reads.append(piece)
            return ('table', piece)

        ra = RowGroupReadahead(read_fn, depth=2)
        try:
            ra.sync([_plan(1), _plan(2), _plan(3)])   # depth 2: schedules 1, 2
            assert ra.take(1) == ('table', 'piece-1')
            ra.sync([_plan(2), _plan(3)])
            assert ra.take(2) == ('table', 'piece-2')
            assert ra.take(3) == ('table', 'piece-3')
            recorder = _Recorder()
            ra.drain_stats_into(recorder)
            assert recorder.counts['readahead_hits'] == 3
            assert 'readahead_misses' not in recorder.counts
            assert recorder.times['readahead_io_s'] > 0
        finally:
            ra.stop()

    def test_unplanned_key_is_a_miss(self):
        ra = RowGroupReadahead(lambda piece, columns: 'x', depth=2)
        try:
            assert ra.take(99) is None
            recorder = _Recorder()
            ra.drain_stats_into(recorder)
            assert recorder.counts['readahead_misses'] == 1
        finally:
            ra.stop()

    def test_desync_cancels_and_self_heals(self):
        ra = RowGroupReadahead(lambda piece, columns: piece, depth=2)
        try:
            ra.sync([_plan(1), _plan(2)])
            # the pool re-ordered work: outstanding [1, 2] is not a prefix
            ra.sync([_plan(5), _plan(6)])
            assert ra.take(5) == 'piece-5'
            assert ra.take(1) is None     # cancelled, falls back inline
        finally:
            ra.stop()

    def test_read_errors_surface_on_take(self):
        def read_fn(piece, columns):
            raise OSError('storage gone')

        ra = RowGroupReadahead(read_fn, depth=1)
        try:
            ra.sync([_plan(1)])
            with pytest.raises(OSError, match='storage gone'):
                ra.take(1)
        finally:
            ra.stop()

    def test_duplicate_keys_fifo(self):
        # shuffle_row_drop_partitions ventilates the same piece repeatedly:
        # duplicate keys must serve FIFO, one entry per occurrence
        served = []
        ra = RowGroupReadahead(lambda piece, columns: served.append(piece) or len(served),
                               depth=3)
        try:
            plans = [_plan(7), _plan(7), _plan(7)]
            ra.sync(plans)
            assert ra.take(7) == 1
            assert ra.take(7) == 2
            assert ra.take(7) == 3
        finally:
            ra.stop()

    def test_auto_depth_tracks_io_decode_ratio(self):
        # reads take ~4x the inter-take gap: auto depth should rise above its
        # initial value (and stay bounded)
        def slow_read(piece, columns):
            time.sleep(0.02)
            return piece

        ra = RowGroupReadahead(slow_read, depth='auto')
        try:
            keys = list(range(12))
            for i in keys:
                ra.sync([_plan(k) for k in keys[i:i + AUTO_MAX_DEPTH]])
                ra.take(i)
                time.sleep(0.005)   # "decode"
            assert 1 <= ra.depth <= AUTO_MAX_DEPTH
            assert ra.depth >= 3
        finally:
            ra.stop()

    def test_validates_depth(self):
        with pytest.raises(ValueError):
            RowGroupReadahead(lambda p, c: None, depth=-1)
        with pytest.raises(ValueError):
            RowGroupReadahead(lambda p, c: None, depth='warp')
        # 0 is legal since the autotune controller: dormant machinery that
        # set_depth() can activate live (docs/autotune.md)
        dormant = RowGroupReadahead(lambda p, c: None, depth=0)
        assert dormant.depth == 0
        dormant.stop()


def _reader_ids(url, **kwargs):
    with make_reader(url, shuffle_row_groups=False, num_epochs=1,
                     **kwargs) as reader:
        ids = [row.id for row in reader]
        diag = reader.diagnostics
    return ids, diag


class TestReaderIntegration:
    def test_results_and_order_match_serial(self, synthetic_dataset):
        """With one worker and shuffle off, readahead must preserve the exact
        ventilated-piece order the serial reader produces."""
        base_ids, _ = _reader_ids(synthetic_dataset.url,
                                  reader_pool_type='thread', workers_count=1)
        ra_ids, diag = _reader_ids(synthetic_dataset.url,
                                   reader_pool_type='thread', workers_count=1,
                                   io_readahead=3)
        assert ra_ids == base_ids
        assert diag['readahead_hits'] > 0
        assert diag['readahead_misses'] == 0
        assert diag['readahead_io_s'] > 0

    def test_thread_pool_multiworker_same_rows(self, synthetic_dataset):
        base_ids, _ = _reader_ids(synthetic_dataset.url,
                                  reader_pool_type='thread', workers_count=3)
        ra_ids, diag = _reader_ids(synthetic_dataset.url,
                                   reader_pool_type='thread', workers_count=3,
                                   io_readahead=2)
        assert sorted(ra_ids) == sorted(base_ids)
        assert diag['readahead_hits'] > 0

    def test_auto_depth_reader(self, synthetic_dataset):
        ra_ids, diag = _reader_ids(synthetic_dataset.url,
                                   reader_pool_type='thread', workers_count=2,
                                   io_readahead='auto')
        assert len(ra_ids) == len(synthetic_dataset.data)
        assert diag['readahead_hits'] > 0
        assert 0.0 <= diag['io_overlap_fraction'] <= 1.0

    def test_process_pool_counters_ship_back(self, synthetic_dataset):
        with make_columnar_reader(synthetic_dataset.url,
                                  reader_pool_type='process', workers_count=2,
                                  num_epochs=1, io_readahead=2) as reader:
            count = sum(1 for _ in reader)
            diag = reader.diagnostics
        assert count > 0
        # the counters were accumulated in worker interpreters and shipped
        # back via the accounting control messages
        assert diag['readahead_hits'] > 0
        assert diag['readahead_io_s'] > 0

    def test_batch_reader_readahead(self, scalar_dataset):
        with make_batch_reader(scalar_dataset.url, reader_pool_type='thread',
                               workers_count=1, shuffle_row_groups=False,
                               num_epochs=1, io_readahead=2) as reader:
            ids = np.concatenate([batch.id for batch in reader])
            diag = reader.diagnostics
        assert len(ids) == len(scalar_dataset.data)
        assert diag['readahead_hits'] > 0

    def test_predicate_items_bypass_prefetch(self, synthetic_dataset):
        from petastorm_tpu.predicates import in_lambda
        predicate = in_lambda(['id'], lambda v: v['id'] % 2 == 0)
        with make_reader(synthetic_dataset.url, reader_pool_type='thread',
                         workers_count=1, shuffle_row_groups=False,
                         num_epochs=1, predicate=predicate,
                         io_readahead=2) as reader:
            ids = sorted(row.id for row in reader)
            diag = reader.diagnostics
        expected = sorted(r['id'] for r in synthetic_dataset.data
                          if r['id'] % 2 == 0)
        assert ids == expected
        # predicate reads are multi-phase and unplannable: nothing prefetched
        assert diag['readahead_hits'] == 0

    def test_ngram_chunk_path_readahead(self, tmp_path):
        # the synthetic fixture's row groups hold ~1 row (no windows fit);
        # write a store with multi-row groups so the chunk path emits windows
        from petastorm_tpu.ngram import NGram
        from petastorm_tpu.test_util.dataset_gen import create_test_dataset
        url = 'file://' + str(tmp_path / 'ngram_ra')
        create_test_dataset(url, range(24), num_files=2,
                            row_group_size_mb=0.5)
        fields = {
            0: ['id', 'id2'],
            1: ['id'],
        }
        ngram = NGram(fields, delta_threshold=10, timestamp_field='id')
        with make_reader(url, schema_fields=ngram,
                         reader_pool_type='thread', workers_count=1,
                         shuffle_row_groups=False, num_epochs=1,
                         io_readahead=2) as reader:
            windows = list(reader)
            diag = reader.diagnostics
        assert windows
        assert diag['readahead_hits'] > 0
        assert diag['readahead_misses'] == 0

    def test_shuffle_row_drop_partitions_readahead(self, synthetic_dataset):
        base_ids, _ = _reader_ids(synthetic_dataset.url,
                                  reader_pool_type='thread', workers_count=1,
                                  shuffle_row_drop_partitions=2)
        ra_ids, diag = _reader_ids(synthetic_dataset.url,
                                   reader_pool_type='thread', workers_count=1,
                                   shuffle_row_drop_partitions=2,
                                   io_readahead=2)
        assert sorted(ra_ids) == sorted(base_ids)
        assert diag['readahead_hits'] > 0

    def test_dummy_pool_disables_readahead(self, synthetic_dataset):
        """DummyPool never hints workers: the reader must force readahead off
        rather than record every read as a misleading miss."""
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                         num_epochs=1, io_readahead=4) as reader:
            count = sum(1 for _ in reader)
            diag = reader.diagnostics
        assert count == len(synthetic_dataset.data)
        assert diag['readahead_hits'] == 0
        assert diag['readahead_misses'] == 0
        assert diag['readahead_io_s'] == 0.0

    def test_readahead_rejects_bad_values(self, synthetic_dataset):
        with pytest.raises(ValueError, match='io_readahead'):
            make_reader(synthetic_dataset.url, io_readahead=-1)
        with pytest.raises(ValueError, match='io_readahead'):
            make_reader(synthetic_dataset.url, io_readahead='fast')


class TestCacheKeyMemoization:
    def test_cache_key_format_and_reuse(self, synthetic_dataset, monkeypatch):
        """The dataset-path md5 and decode-hints hash are per-worker
        constants: computed in __init__, never per piece."""
        import hashlib

        from petastorm_tpu.cache import NullCache
        from petastorm_tpu.etl.dataset_metadata import (infer_or_load_unischema,
                                                        load_row_groups)
        from petastorm_tpu.fs import get_filesystem_and_path_or_paths
        from petastorm_tpu.readers.columnar_worker import ColumnarWorker

        fs, path, factory = get_filesystem_and_path_or_paths(
            synthetic_dataset.url)
        schema, _ = infer_or_load_unischema(fs, path)
        pieces = load_row_groups(fs, path)
        worker_args = {
            'filesystem_factory': factory, 'dataset_path': path,
            'schema': schema, 'full_schema': schema, 'ngram': None,
            'split_pieces': pieces, 'local_cache': NullCache(),
            'transform_spec': None, 'transformed_schema': schema,
            'decode_hints': {'image_png': {'scale': 2}},
        }
        worker = ColumnarWorker(0, lambda item: None, worker_args)
        try:
            expected_path_digest = hashlib.md5(str(path).encode()).hexdigest()
            key = worker._cache_key('columnar', pieces[0])
            assert key.startswith('columnar:' + expected_path_digest + ':')
            assert key == worker._cache_key('columnar', pieces[0])
            assert worker._decode_hints_digest in key

            # per-piece keying must not re-hash: md5 is forbidden after init
            def boom(*a, **k):
                raise AssertionError('md5 recomputed per piece')
            monkeypatch.setattr(hashlib, 'md5', boom)
            worker._cache_key('columnar', pieces[-1])
        finally:
            worker.shutdown()


class TestInfeedDiagnosis:
    def test_io_bound_signature(self):
        from petastorm_tpu.jax_utils import infeed_diagnosis
        diag = infeed_diagnosis({'worker_io_s': 9.0, 'worker_decode_s': 3.0})
        assert diag['bottleneck'] == 'io'
        assert diag['recommended_io_readahead'] == 3

    def test_decode_bound_signature(self):
        from petastorm_tpu.jax_utils import infeed_diagnosis
        diag = infeed_diagnosis({'worker_io_s': 1.0, 'worker_decode_s': 8.0})
        assert diag['bottleneck'] == 'decode'
        assert diag['recommended_io_readahead'] == 1

    def test_readahead_aware_io_accounting(self):
        from petastorm_tpu.jax_utils import infeed_diagnosis
        # hidden background reads count as io; the double-counted blocked
        # wait is removed from the stall side
        diag = infeed_diagnosis({'worker_io_s': 2.0, 'readahead_io_s': 6.0,
                                 'readahead_wait_s': 2.0,
                                 'worker_decode_s': 6.0})
        assert diag['io_s'] == pytest.approx(6.0)
        assert diag['bottleneck'] == 'balanced'

    def test_consumer_bound_signature(self):
        from petastorm_tpu.jax_utils import infeed_diagnosis
        diag = infeed_diagnosis({'worker_io_s': 0.5, 'worker_decode_s': 0.5,
                                 'worker_publish_wait_s': 9.0})
        assert diag['bottleneck'] == 'consumer'


def test_recommend_io_readahead_bounds():
    from petastorm_tpu.workers.stats import recommend_io_readahead
    assert recommend_io_readahead({}) == 1
    assert recommend_io_readahead({'worker_io_s': 100.0,
                                   'worker_decode_s': 1.0}) == 8
    assert recommend_io_readahead(
        {'worker_io_s': 3.1, 'worker_decode_s': 1.0}) == 4


def test_readahead_quick_benchmark_smoke():
    """The tier-1 gate on the tentpole: the slow-IO shim must show a real
    speedup with prefetch hits and a positive overlap fraction."""
    from petastorm_tpu.benchmark.readahead import run_readahead_bench
    result = run_readahead_bench(quick=True)   # asserts internally
    assert result['readahead']['readahead_hits'] > 0
    assert result['speedup_items_per_s'] >= 1.15
