"""Tests for the host-wide tiered shared row-group cache
(``petastorm_tpu/sharedcache.py``; see docs/cache.md).

Covers the concurrency/crash contracts the subsystem promises: concurrent
attach across threads and processes, single-flight fills, size-bounded
eviction that spills to the disk tier and respects live pins, dead-reader
pin expiry (the killed-process pattern from tests/test_health.py /
test_lineage.py applied to cache attachment), truncated-segment rejection,
the ``PETASTORM_TPU_SHARED_CACHE=0`` kill switch, and the uniform
``cache_type='shared'`` knob on every reader factory.
"""

import hashlib
import multiprocessing
import os
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from petastorm_tpu.cache import NullCache
from petastorm_tpu.sharedcache import (KIND_PICKLE5, CorruptSegmentError,
                                       SharedRowGroupCache, _PinRegistry,
                                       read_segment, shared_cache_enabled,
                                       write_segment)


def _mk(tmp_path, name='root', **kwargs):
    kwargs.setdefault('mem_dir', str(tmp_path / (name + '_mem')))
    return SharedRowGroupCache(str(tmp_path / name), 1 << 24, **kwargs)


def _digest(key):
    return hashlib.md5(key.encode()).hexdigest()


def _blob(i, n=20_000):
    return {'a': np.full(n, i, dtype=np.int64),
            'meta': {'i': i, 's': 'label_{}'.format(i)}}


# -- segment format ------------------------------------------------------------

class TestSegmentFormat:
    def test_roundtrip(self, tmp_path):
        path = str(tmp_path / 's.seg')
        frames = [b'meta', np.arange(1000, dtype=np.int64).tobytes()]
        write_segment(path, KIND_PICKLE5, frames)
        kind, views, _m = read_segment(path)
        assert kind == KIND_PICKLE5
        assert bytes(views[0]) == b'meta'
        np.testing.assert_array_equal(
            np.frombuffer(views[1], dtype=np.int64), np.arange(1000))

    @pytest.mark.parametrize('cut', [0, 3, 40, -3])
    def test_truncated_rejected(self, tmp_path, cut):
        path = str(tmp_path / 's.seg')
        write_segment(path, KIND_PICKLE5, [b'meta', b'x' * 4096])
        data = open(path, 'rb').read()
        with open(path, 'wb') as f:
            f.write(data[:cut])
        with pytest.raises(CorruptSegmentError):
            read_segment(path)

    def test_garbage_rejected(self, tmp_path):
        path = str(tmp_path / 's.seg')
        with open(path, 'wb') as f:
            f.write(b'not a segment at all' * 10)
        with pytest.raises(CorruptSegmentError):
            read_segment(path)


# -- basic cache behavior ------------------------------------------------------

class TestSharedCache:
    def test_miss_then_hit_and_zero_copy_readonly(self, tmp_path):
        cache = _mk(tmp_path)
        calls = {'n': 0}

        def fill():
            calls['n'] += 1
            return _blob(7)

        v1 = cache.get('k', fill)
        v2 = cache.get('k', fill)
        assert calls['n'] == 1
        np.testing.assert_array_equal(v1['a'], v2['a'])
        assert v2['meta'] == {'i': 7, 's': 'label_7'}
        # attached large arrays are read-only views over the mapping
        assert not v2['a'].flags.writeable

    def test_cross_instance_attach(self, tmp_path):
        a = _mk(tmp_path)
        b = _mk(tmp_path)
        a.get('k', lambda: _blob(1))
        v = b.get('k', lambda: pytest.fail('second instance must attach'))
        np.testing.assert_array_equal(v['a'], _blob(1)['a'])

    def test_arrow_table_segments(self, tmp_path):
        table = pa.table({'x': np.arange(500), 'y': ['s%d' % i
                                                     for i in range(500)]})
        a = _mk(tmp_path)
        b = _mk(tmp_path)
        a.get('t', lambda: table)
        got = b.get('t', lambda: pytest.fail('must attach'))
        assert got.equals(table)

    def test_contains_and_events(self, tmp_path):
        cache = _mk(tmp_path)
        assert not cache.contains('k')
        cache.get('k', lambda: _blob(0))
        assert cache.contains('k')
        cache.get('k', lambda: pytest.fail('hit expected'))
        events = cache.take_events()
        assert events['shared_misses'] == 1 and events['shared_hits'] == 1
        assert cache.take_events()['shared_hits'] == 0   # drained
        assert cache.occupancy_bytes() > 0

    def test_truncated_segment_refilled_not_served(self, tmp_path):
        cache = _mk(tmp_path)
        cache.get('k', lambda: _blob(3))
        seg = os.path.join(str(tmp_path / 'root_mem'),
                           _digest('k') + '.seg')
        data = open(seg, 'rb').read()
        with open(seg, 'wb') as f:
            f.write(data[:len(data) // 2])
        fresh = _mk(tmp_path)
        value = fresh.get('k', lambda: {'refilled': True})
        assert value == {'refilled': True}
        assert fresh.counters()['corrupt_dropped'] == 1

    def test_pickles_to_worker_processes(self, tmp_path):
        import pickle
        cache = _mk(tmp_path)
        cache.get('k', lambda: _blob(5))
        clone = pickle.loads(pickle.dumps(cache))
        v = clone.get('k', lambda: pytest.fail('clone must attach'))
        np.testing.assert_array_equal(v['a'], _blob(5)['a'])
        clone.close()

    def test_close_is_idempotent_and_releases_pins(self, tmp_path):
        cache = _mk(tmp_path)
        cache.get('k', lambda: _blob(1))
        cache.get('k', lambda: None)          # attach -> pin
        pins_dir = str(tmp_path / 'root' / 'pins')
        assert any(n.endswith('.pin') for n in os.listdir(pins_dir))
        cache.close()
        cache.close()
        assert not any(n.endswith('.pin') for n in os.listdir(pins_dir))


# -- eviction / pins -----------------------------------------------------------

class TestEvictionAndPins:
    def test_eviction_spills_to_disk_tier_and_promotes_back(self, tmp_path):
        cache = _mk(tmp_path, mem_size_limit_bytes=400_000)
        for i in range(8):
            cache.get('k%d' % i, lambda i=i: _blob(i))
        disk_dir = str(tmp_path / 'root' / 'disk')
        spilled = [n for n in os.listdir(disk_dir) if n.endswith('.seg')]
        assert spilled, 'mem-tier eviction must spill segments to disk'
        # every key still served (disk tier), value-exact
        for i in range(8):
            v = cache.get('k%d' % i,
                          lambda: pytest.fail('tiered lookup must hit'))
            assert v['a'][0] == i

    def test_eviction_under_pressure_skips_pinned_segment(self, tmp_path):
        cache = _mk(tmp_path, mem_size_limit_bytes=400_000)
        cache.get('pinned', lambda: _blob(0))
        held = cache.get('pinned', lambda: None)   # attach -> live pin
        for i in range(10):
            cache.get('k%d' % i, lambda i=i: _blob(i))
        mem_dir = str(tmp_path / 'root_mem')
        assert os.path.exists(os.path.join(
            mem_dir, _digest('pinned') + '.seg')), \
            'a live-pinned segment must survive memory pressure'
        assert cache.counters()['evictions'] > 0
        assert held['a'][0] == 0   # the mapping stayed valid throughout

    def test_dead_reader_pin_expires(self, tmp_path):
        pins = _PinRegistry(str(tmp_path / 'pins'))
        digest = _digest('k')
        # a pid that is certainly dead: a spawned child that already exited
        ctx = multiprocessing.get_context('spawn')
        child = ctx.Process(target=_exit_immediately)
        child.start()
        dead_pid = child.pid
        child.join()
        marker = os.path.join(str(tmp_path / 'pins'),
                              '{}.{}.deadbeef.pin'.format(digest, dead_pid))
        with open(marker, 'w'):
            pass
        assert not pins.is_pinned(digest)
        assert not os.path.exists(marker), 'dead pins are reclaimed on sight'

    def test_killed_reader_process_pins_expire(self, tmp_path):
        """The killed-worker pattern: a reader process attaches (pins) and
        dies without cleanup; its pins must not block eviction."""
        ctx = multiprocessing.get_context('spawn')
        child = ctx.Process(target=_attach_and_die,
                            args=(str(tmp_path),), daemon=True)
        child.start()
        child.join(timeout=120)
        assert child.exitcode == 17   # os._exit(17): no cleanup ran
        pins_dir = str(tmp_path / 'root' / 'pins')
        leaked = [n for n in os.listdir(pins_dir) if n.endswith('.pin')]
        assert leaked, 'the dead child must have leaked a pin file'
        pins = _PinRegistry(pins_dir)
        assert not pins.is_pinned(_digest('k'))

    def test_eviction_counts_surface_in_events(self, tmp_path):
        cache = _mk(tmp_path, mem_size_limit_bytes=300_000)
        for i in range(8):
            cache.get('k%d' % i, lambda i=i: _blob(i))
        events = cache.take_events()
        assert events['shared_evictions'] > 0


# -- single-flight -------------------------------------------------------------

class TestSingleFlight:
    def test_concurrent_fill_decodes_once(self, tmp_path):
        a = _mk(tmp_path)
        b = _mk(tmp_path)
        calls = {'n': 0}
        lock = threading.Lock()

        def slow_fill():
            with lock:
                calls['n'] += 1
            time.sleep(0.25)
            return _blob(9)

        results = [None, None]

        def run(i, inst):
            results[i] = inst.get('k', slow_fill)

        t1 = threading.Thread(target=run, args=(0, a))
        t2 = threading.Thread(target=run, args=(1, b))
        t1.start()
        time.sleep(0.05)
        t2.start()
        t1.join()
        t2.join()
        assert calls['n'] == 1
        np.testing.assert_array_equal(results[0]['a'], results[1]['a'])
        assert b.counters()['lock_waits'] + a.counters()['lock_waits'] == 1

    def test_same_instance_concurrent_misses_one_fill_no_error(self,
                                                               tmp_path):
        """Thread-pool workers share ONE cache instance: N concurrent
        same-key misses must produce one fill and zero escaping errors
        (an instance-scoped lock temp name would let one thread's cleanup
        break another's acquisition)."""
        cache = _mk(tmp_path)
        calls = {'n': 0}
        lock = threading.Lock()
        errors = []

        def slow_fill():
            with lock:
                calls['n'] += 1
            time.sleep(0.15)
            return _blob(4)

        def run():
            try:
                cache.get('k', slow_fill)
            except BaseException as e:  # noqa: BLE001 - recorded for assert
                errors.append(e)

        threads = [threading.Thread(target=run) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        assert calls['n'] == 1

    def test_promotion_drops_disk_copy(self, tmp_path):
        cache = _mk(tmp_path, mem_size_limit_bytes=400_000)
        for i in range(8):
            cache.get('k%d' % i, lambda i=i: _blob(i))
        disk_dir = str(tmp_path / 'root' / 'disk')
        spilled = {n for n in os.listdir(disk_dir) if n.endswith('.seg')}
        assert spilled
        digest = next(iter(spilled))[:-len('.seg')]
        key = next('k%d' % i for i in range(8)
                   if _digest('k%d' % i) == digest)
        cache.get(key, lambda: pytest.fail('disk-tier hit expected'))
        # promoted back to tier 0: the disk copy must not stay resident
        # against the disk budget too
        assert not os.path.exists(os.path.join(disk_dir, digest + '.seg'))
        mem_dir = str(tmp_path / 'root_mem')
        assert os.path.exists(os.path.join(mem_dir, digest + '.seg'))

    def test_stale_lock_from_dead_process_is_stolen(self, tmp_path):
        cache = _mk(tmp_path)
        ctx = multiprocessing.get_context('spawn')
        child = ctx.Process(target=_exit_immediately)
        child.start()
        dead_pid = child.pid
        child.join()
        lock_path = os.path.join(str(tmp_path / 'root'), 'locks',
                                 _digest('k') + '.lock')
        with open(lock_path, 'w') as f:
            f.write(str(dead_pid))
        start = time.perf_counter()
        value = cache.get('k', lambda: _blob(2))
        assert time.perf_counter() - start < 5.0
        assert value['a'][0] == 2
        assert cache.counters()['lock_steals'] == 1


# -- reader integration --------------------------------------------------------

def _image_store(tmp_path, rows=32, rows_per_file=8):
    from petastorm_tpu.codecs import CompressedImageCodec, ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import materialize_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField
    schema = Unischema('Img', [
        UnischemaField('idx', np.int64, (), ScalarCodec(), False),
        UnischemaField('image', np.uint8, (16, 16),
                       CompressedImageCodec('png'), False)])
    url = 'file://' + str(tmp_path / 'ds')
    rng = np.random.default_rng(0)
    with materialize_dataset(url, schema, rows_per_file=rows_per_file) as w:
        w.write_rows({'idx': np.int64(i),
                      'image': rng.integers(0, 255, (16, 16), dtype=np.uint8)}
                     for i in range(rows))
    return url


def _shared_kwargs(tmp_path):
    return dict(cache_type='shared',
                cache_location=str(tmp_path / 'cache'),
                cache_size_limit=1 << 26,
                cache_extra_settings={'mem_dir': str(tmp_path / 'mem')})


class TestReaderIntegration:
    def test_all_three_factories_accept_shared(self, tmp_path):
        from petastorm_tpu import (make_batch_reader, make_columnar_reader,
                                   make_reader)
        url = _image_store(tmp_path)
        kwargs = dict(shuffle_row_groups=False, **_shared_kwargs(tmp_path))
        with make_columnar_reader(url, num_epochs=2,
                                  reader_pool_type='dummy', **kwargs) as r:
            assert sum(len(b.idx) for b in r) == 64
            diag = r.diagnostics
        assert diag['shared_hits'] > 0 and diag['shared_misses'] > 0
        assert diag['shared_cache_bytes'] > 0
        with make_reader(url, num_epochs=1, reader_pool_type='dummy',
                         **kwargs) as r:
            assert len(list(r)) == 32
        with make_batch_reader(url, num_epochs=1, reader_pool_type='dummy',
                               **kwargs) as r:
            assert sum(len(b.idx) for b in r) == 32

    def test_hit_skips_io_and_decode_entirely(self, tmp_path, monkeypatch):
        from petastorm_tpu import make_columnar_reader
        url = _image_store(tmp_path)
        kwargs = dict(shuffle_row_groups=False, reader_pool_type='dummy',
                      **_shared_kwargs(tmp_path))
        with make_columnar_reader(url, num_epochs=1, **kwargs) as r:
            first = {int(i): img.copy()
                     for b in r for i, img in zip(b.idx, b.image)}
        import petastorm_tpu.codecs as codecs
        from petastorm_tpu.readers import piece_worker

        def boom(*a, **k):
            raise AssertionError('decode/read ran on a fully cached epoch')
        monkeypatch.setattr(codecs.CompressedImageCodec, 'make_cell_decoder',
                            boom)
        monkeypatch.setattr(piece_worker.ParquetPieceWorker,
                            '_read_row_group', boom)
        with make_columnar_reader(url, num_epochs=1, **kwargs) as r:
            replay = {int(i): img.copy()
                      for b in r for i, img in zip(b.idx, b.image)}
        assert set(replay) == set(first)
        for k in first:
            np.testing.assert_array_equal(first[k], replay[k])

    def test_process_pool_workers_attach(self, tmp_path):
        from petastorm_tpu import make_columnar_reader
        url = _image_store(tmp_path)
        kwargs = dict(shuffle_row_groups=False, reader_pool_type='process',
                      workers_count=2, **_shared_kwargs(tmp_path))
        with make_columnar_reader(url, num_epochs=2, **kwargs) as r:
            assert sum(len(b.idx) for b in r) == 64
            diag = r.diagnostics
        assert diag['shared_hits'] > 0

    def test_multiprocess_readers_decode_once(self, tmp_path):
        """Two concurrent reader PROCESSES over one store and one shared
        tier: the host-wide counters must show each row group filled
        exactly once."""
        url = _image_store(tmp_path)
        cache_root = str(tmp_path / 'cache')
        ctx = multiprocessing.get_context('spawn')
        queue = ctx.Queue()
        procs = [ctx.Process(target=_read_all_child,
                             args=(url, str(tmp_path), seed, queue),
                             daemon=True) for seed in (1, 2)]
        for p in procs:
            p.start()
        results = [queue.get(timeout=300) for _ in procs]
        for p in procs:
            p.join(timeout=60)
        assert all(r == 32 for r in results), results
        totals = SharedRowGroupCache.global_counters(cache_root)
        n_groups = 4   # 32 rows, 8 per file/group
        assert totals['fills'] == n_groups, totals
        assert totals['hits'] == n_groups, totals   # second reader all-hits

    def test_predicate_with_shared_cache_rejected(self, tmp_path):
        from petastorm_tpu import make_reader
        from petastorm_tpu.predicates import in_lambda
        url = _image_store(tmp_path)
        with pytest.raises(RuntimeError, match='cache'):
            make_reader(url, predicate=in_lambda(['idx'], lambda v: True),
                        **_shared_kwargs(tmp_path))

    def test_readahead_plans_only_shared_misses(self, tmp_path):
        """Tier-2: with the shared cache attached, the readahead planner
        prefetches cold keys (epoch 1) and plans nothing once the tier
        holds them (epoch 2 = pure hits, no background reads)."""
        from petastorm_tpu import make_columnar_reader
        url = _image_store(tmp_path)
        kwargs = dict(shuffle_row_groups=False, reader_pool_type='thread',
                      workers_count=1, io_readahead=2,
                      **_shared_kwargs(tmp_path))
        with make_columnar_reader(url, num_epochs=1, **kwargs) as r:
            assert sum(len(b.idx) for b in r) == 32
            cold = r.diagnostics
        assert cold['readahead_hits'] > 0
        with make_columnar_reader(url, num_epochs=1, **kwargs) as r:
            assert sum(len(b.idx) for b in r) == 32
            warm = r.diagnostics
        assert warm['shared_misses'] == 0
        assert warm['readahead_hits'] == 0 and warm['readahead_misses'] == 0


# -- knobs / kill switch -------------------------------------------------------

class TestKnobs:
    def test_make_cache_error_enumerates_types(self):
        from petastorm_tpu.reader import _make_cache
        with pytest.raises(ValueError) as e:
            _make_cache('bogus', None, None, None, None)
        for name in ('null', 'local-disk', 'shared'):
            assert name in str(e.value)

    def test_shared_needs_location_and_limit(self):
        from petastorm_tpu.reader import _make_cache
        with pytest.raises(ValueError, match='cache_location'):
            _make_cache('shared', None, None, None, None)

    def test_kill_switch_disables_attachment_entirely(self, tmp_path,
                                                      monkeypatch):
        from petastorm_tpu import make_columnar_reader
        from petastorm_tpu.reader import _make_cache
        monkeypatch.setenv('PETASTORM_TPU_SHARED_CACHE', '0')
        assert not shared_cache_enabled()
        assert isinstance(
            _make_cache('shared', str(tmp_path / 'c'), 1 << 20, None, None),
            NullCache)
        url = _image_store(tmp_path)
        loc = tmp_path / 'killed_cache'
        with make_columnar_reader(url, num_epochs=1,
                                  reader_pool_type='dummy',
                                  shuffle_row_groups=False,
                                  cache_type='shared',
                                  cache_location=str(loc),
                                  cache_size_limit=1 << 26) as r:
            assert sum(len(b.idx) for b in r) == 32
        assert not loc.exists(), \
            'kill switch must prevent any file/attachment at the location'

    def test_cli_accepts_cache_knobs(self):
        from petastorm_tpu.benchmark.cli import build_parser
        args = build_parser().parse_args(
            ['file:///tmp/x', '--cache-type', 'shared', '--cache-location',
             '/tmp/c', '--cache-size-limit', '1000000'])
        assert args.cache_type == 'shared'
        assert args.cache_location == '/tmp/c'
        assert args.cache_size_limit == 1000000


# -- spawn helpers (module-level: picklable) -----------------------------------

def _exit_immediately():
    os._exit(0)


def _attach_and_die(tmp_path):
    cache = SharedRowGroupCache(os.path.join(tmp_path, 'root'), 1 << 24,
                                mem_dir=os.path.join(tmp_path, 'root_mem'))
    cache.get('k', lambda: {'a': np.arange(1000)})
    cache.get('k', lambda: None)       # attach -> pin
    os._exit(17)                       # die WITHOUT close(): pins leak


def _read_all_child(url, tmp_path, seed, queue):
    try:
        from petastorm_tpu import make_columnar_reader
        kwargs = dict(cache_type='shared',
                      cache_location=os.path.join(tmp_path, 'cache'),
                      cache_size_limit=1 << 26,
                      cache_extra_settings={
                          'mem_dir': os.path.join(tmp_path, 'mem')})
        with make_columnar_reader(url, num_epochs=1, seed=seed,
                                  reader_pool_type='thread', workers_count=1,
                                  **kwargs) as reader:
            queue.put(sum(len(b.idx) for b in reader))
    except BaseException as e:  # noqa: BLE001 - surfaced in the parent
        queue.put(repr(e))
