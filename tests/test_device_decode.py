"""Device-side decode (bytes-through) tests: plan eligibility and the
decline matrix, raw-view/repack layout proofs, bit-identity of the jitted
decoder against the numpy reference against the host codec across dtypes x
chunkings (multi-chunk, empty chunk, zero-size cells), the
``PETASTORM_TPU_DEVICE_DECODE`` kill switch, fused device ``TransformSpec``
equality, end-to-end bytes-through epochs with the
``rows_decoded_device``/``bytes_shipped_raw`` observability split and the
lineage coverage audit on thread AND process pools, the ETL repack of
``CompressedNdarrayCodec`` stores, the device-staging ``prefetch_depth``
knob, and ``_contiguous_rows_view`` edge cases."""

import numpy as np
import pyarrow as pa
import pytest

from petastorm_tpu.codecs import (CompressedNdarrayCodec, NdarrayCodec,
                                  batched_decode_enabled)
from petastorm_tpu.jax_utils import (DEFAULT_PREFETCH_DEPTH,
                                     PREFETCH_DEPTH_ENV_VAR, JaxDataLoader,
                                     _contiguous_rows_view, infeed_diagnosis,
                                     make_jax_loader, resolve_prefetch_depth)
from petastorm_tpu.ops.decode import (DEVICE_DECODE_ENV_VAR, DeviceColumnPlan,
                                      build_fused_infeed, decode_raw_host,
                                      decode_raw_jax, device_decode_enabled,
                                      npy_header_bytes, plan_device_decode,
                                      plan_for_field, raw_column_view,
                                      repack_to_raw, split_device_columns)
from petastorm_tpu.reader import make_columnar_reader, make_reader
from petastorm_tpu.transform import TransformSpec
from petastorm_tpu.unischema import Unischema, UnischemaField
from petastorm_tpu.workers.stats import device_decode_fraction

RNG = np.random.default_rng(11)

jax = pytest.importorskip('jax')


def _field(name='x', dtype=np.float32, shape=(4, 3), codec=None,
           nullable=False):
    return UnischemaField(name, dtype, shape,
                          codec if codec is not None else NdarrayCodec(),
                          nullable)


def _cells(field, values):
    return [field.codec.encode(field, v) for v in values]


def _chunked(cells, chunk_sizes=None):
    """A binary ChunkedArray from encoded cells, optionally split into the
    given chunk sizes (0 = an empty chunk in the middle)."""
    if chunk_sizes is None:
        return pa.chunked_array([pa.array(cells, type=pa.binary())])
    chunks, at = [], 0
    for size in chunk_sizes:
        chunks.append(pa.array(cells[at:at + size], type=pa.binary()))
        at += size
    assert at == len(cells), 'chunk_sizes must cover every cell'
    return pa.chunked_array(chunks, type=pa.binary())


def _values(dtype, shape, n):
    dtype = np.dtype(dtype)
    if dtype.kind == 'b':
        return [RNG.integers(0, 2, size=shape).astype(dtype)
                for _ in range(n)]
    if dtype.kind in 'iu':
        info = np.iinfo(dtype)
        return [RNG.integers(info.min, info.max, size=shape,
                             endpoint=True).astype(dtype) for _ in range(n)]
    return [RNG.standard_normal(shape).astype(dtype) for _ in range(n)]


class TestPlanning:
    def test_npy_header_bytes_pins_the_writer_prefix(self):
        import io
        header = npy_header_bytes(np.float32, (4, 3))
        buf = io.BytesIO()
        np.save(buf, np.zeros((4, 3), dtype=np.float32))
        assert header is not None
        assert buf.getvalue().startswith(header)

    def test_npy_header_bytes_declines_object_dtype(self):
        assert npy_header_bytes(np.dtype(object), (2,)) is None

    @pytest.mark.parametrize('dtype,shape', [
        (np.float32, (4, 3)), (np.int16, (7,)), (np.uint8, (2, 2, 3)),
        (np.bool_, (5,)), (np.float16, (3,)), (np.int32, (0,)),
    ])
    def test_eligible_fields_plan(self, dtype, shape):
        plan, reason = plan_for_field(_field(dtype=dtype, shape=shape))
        assert reason is None
        assert plan.dtype == np.dtype(dtype)
        assert plan.shape == shape
        assert plan.stride == plan.header_len + plan.cell_nbytes

    @pytest.mark.parametrize('field,why', [
        (_field(codec=CompressedNdarrayCodec()), 'zlib'),
        (_field(shape=(None, 3)), 'shape'),
        (_field(nullable=True), 'nullable'),
        (_field(dtype=np.str_, shape=()), ''),
    ])
    def test_ineligible_fields_decline_with_a_reason(self, field, why):
        plan, reason = plan_for_field(field)
        assert plan is None
        assert isinstance(reason, str) and reason

    def test_big_endian_declines(self):
        plan, reason = plan_for_field(_field(dtype=np.dtype('>f4')))
        assert plan is None and reason

    def test_8_byte_dtypes_need_x64(self):
        """Without jax x64, i8/f8 arrays canonicalize to 32-bit — a bitcast
        decode could not be bit-identical, so planning must decline."""
        plan, reason = plan_for_field(_field(dtype=np.int64, shape=(7,)))
        if jax.config.jax_enable_x64:
            assert reason is None
        else:
            assert plan is None and 'x64' in reason


class TestPlanDecliners:
    """The whole-reader decline matrix of docs/decode.md: every feature
    that needs decoded host values turns planning off wholesale, with the
    reason recorded under '*'; nothing ever raises."""

    SCHEMA = Unischema('S', [_field('tokens', np.int32, (8,))])

    def _declines(self, **kwargs):
        plans, declined = plan_device_decode(self.SCHEMA, enabled=True,
                                             **kwargs)
        assert plans == {}
        assert '*' in declined
        return declined['*']

    def test_kill_switch(self, monkeypatch):
        monkeypatch.setenv(DEVICE_DECODE_ENV_VAR, 'off')
        assert not device_decode_enabled()
        plans, declined = plan_device_decode(self.SCHEMA)
        assert plans == {}
        assert DEVICE_DECODE_ENV_VAR in declined['*']

    def test_kill_switch_default_on(self, monkeypatch):
        monkeypatch.delenv(DEVICE_DECODE_ENV_VAR, raising=False)
        assert device_decode_enabled()

    def test_percell_ab_switch_wins(self, monkeypatch):
        """PETASTORM_TPU_BATCHED_DECODE=0 demands the host per-cell loop;
        bytes-through would silently bypass it, so planning declines."""
        from petastorm_tpu.codecs import BATCHED_DECODE_ENV_VAR
        monkeypatch.setenv(BATCHED_DECODE_ENV_VAR, '0')
        assert not batched_decode_enabled()
        plans, declined = plan_device_decode(self.SCHEMA, enabled=True)
        assert plans == {}
        assert BATCHED_DECODE_ENV_VAR in declined['*']

    def test_row_granular_reader(self):
        assert 'row-granular' in self._declines(batched_output=False)

    def test_unsupported_worker(self):
        assert 'worker' in self._declines(worker_supported=False)

    def test_predicate(self):
        assert 'predicate' in self._declines(has_predicate=True)

    def test_ngram(self):
        assert 'NGram' in self._declines(has_ngram=True)

    def test_tolerant_decode(self):
        assert 'on_decode_error' in self._declines(tolerant_decode=True)

    def test_host_transform_spec(self):
        spec = TransformSpec(lambda c: c)
        assert 'device=True' in self._declines(transform_spec=spec)

    def test_device_spec_changing_field_set(self):
        spec = TransformSpec(lambda c: c, device=True,
                             removed_fields=['tokens'])
        transformed = Unischema('T', [_field('other', np.int32, (8,))])
        reason = self._declines(transform_spec=spec,
                                transformed_schema=transformed)
        assert 'field set' in reason

    def test_device_spec_in_place_plans(self):
        spec = TransformSpec(lambda c: c, device=True)
        plans, declined = plan_device_decode(self.SCHEMA, enabled=True,
                                             transform_spec=spec,
                                             transformed_schema=self.SCHEMA)
        assert set(plans) == {'tokens'}

    def test_decode_hints_decline_per_column(self):
        schema = Unischema('S2', [_field('a', np.int32, (4,)),
                                  _field('b', np.float32, (2,))])
        plans, declined = plan_device_decode(
            schema, enabled=True, decode_hints={'a': {'scale': 2}})
        assert set(plans) == {'b'}
        assert 'hint' in declined['a']


class TestRawViewAndBitIdentity:
    """The core property: for every eligible dtype and chunking, the raw
    grid decodes bit-identically through the numpy reference, the jitted
    device path, and the host codec itself."""

    CASES = [
        (np.float32, (4, 3), None),
        (np.float32, (4, 3), [3, 0, 5]),       # empty chunk mid-column
        (np.int16, (7,), [2, 6]),
        (np.uint8, (2, 2), None),              # itemsize-1 bitcast
        (np.bool_, (5,), [4, 4]),
        (np.float16, (3,), None),
        (np.int32, (0,), [3, 5]),              # zero-size cells
    ]

    @pytest.mark.parametrize('dtype,shape,chunks', CASES)
    def test_three_way_bit_identity(self, dtype, shape, chunks):
        field = _field(dtype=dtype, shape=shape)
        plan, reason = plan_for_field(field)
        assert reason is None
        values = _values(dtype, shape, 8)
        column = _chunked(_cells(field, values), chunks)
        raw = raw_column_view(column, plan)
        assert raw is not None
        assert raw.shape == (8, plan.stride) and raw.dtype == np.uint8
        host = decode_raw_host(plan, raw)
        device = np.asarray(decode_raw_jax(plan, raw))
        codec_ref = np.stack([field.codec.decode(field, c)
                              for c in _cells(field, values)])
        assert host.dtype == device.dtype == codec_ref.dtype
        assert host.shape == device.shape == codec_ref.shape
        assert bool(np.array_equal(host, codec_ref))
        assert bool(np.array_equal(device, codec_ref))

    def test_decode_under_jit_matches_eager(self):
        field = _field(dtype=np.int32, shape=(6,))
        plan, _ = plan_for_field(field)
        values = _values(np.int32, (6,), 5)
        raw = raw_column_view(_chunked(_cells(field, values)), plan)
        jitted = jax.jit(lambda r: decode_raw_jax(plan, r))
        assert bool(np.array_equal(np.asarray(jitted(raw)),
                                   decode_raw_host(plan, raw)))

    def test_raw_view_is_zero_copy_single_chunk(self):
        field = _field(dtype=np.float32, shape=(4,))
        plan, _ = plan_for_field(field)
        column = _chunked(_cells(field, _values(np.float32, (4,), 6)))
        raw = raw_column_view(column, plan)
        assert raw.base is not None   # a view over the arrow buffer

    def test_nulls_decline_to_repack(self):
        field = _field(dtype=np.float32, shape=(2,))
        plan, _ = plan_for_field(field)
        cells = _cells(field, _values(np.float32, (2,), 3))
        column = pa.chunked_array([pa.array(cells[:2] + [None],
                                            type=pa.binary())])
        assert raw_column_view(column, plan) is None

    def test_foreign_header_declines(self):
        field = _field(dtype=np.float32, shape=(2,))
        other = _field(dtype=np.int64, shape=(1,))
        plan, _ = plan_for_field(field)
        cells = _cells(other, _values(np.int64, (1,), 3))
        assert raw_column_view(_chunked(cells), plan) is None

    def test_stride_drift_declines(self):
        field = _field(dtype=np.float32, shape=(2,))
        plan, _ = plan_for_field(field)
        cells = _cells(field, _values(np.float32, (2,), 3))
        cells[1] += b'\x00'   # one cell longer than the pinned stride
        assert raw_column_view(_chunked(cells), plan) is None

    def test_repack_round_trips(self):
        field = _field(dtype=np.int16, shape=(3, 2))
        plan, _ = plan_for_field(field)
        decoded = np.stack(_values(np.int16, (3, 2), 5))
        raw = repack_to_raw(plan, decoded)
        assert raw.shape == (5, plan.stride)
        assert bool(np.array_equal(decode_raw_host(plan, raw), decoded))
        assert bool(np.array_equal(np.asarray(decode_raw_jax(plan, raw)),
                                   decoded))

    def test_repack_shape_mismatch_raises(self):
        plan, _ = plan_for_field(_field(dtype=np.int16, shape=(3, 2)))
        with pytest.raises(ValueError):
            repack_to_raw(plan, np.zeros((5, 2, 3), dtype=np.int16))

    def test_host_decode_is_writable(self):
        field = _field(dtype=np.float32, shape=(4,))
        plan, _ = plan_for_field(field)
        raw = raw_column_view(
            _chunked(_cells(field, _values(np.float32, (4,), 3))), plan)
        out = decode_raw_host(plan, raw)
        out[0, 0] = 1.5   # the per-cell contract: callers may mutate


class TestFusedInfeed:
    def test_fused_decode_plus_device_transform(self):
        field = _field('tokens', np.int32, (4,))
        plan, _ = plan_for_field(field)
        values = _values(np.int32, (4,), 6)
        raw = raw_column_view(_chunked(_cells(field, values)), plan)
        spec = TransformSpec(
            lambda cols: dict(cols, tokens=cols['tokens'] * 2), device=True)
        fused = build_fused_infeed({'tokens': plan}, spec)
        out = fused({'tokens': raw})
        expect = np.stack(values) * 2
        assert bool(np.array_equal(np.asarray(out['tokens']), expect))

    def test_split_routes_only_planned_columns_by_default(self):
        """Unplanned columns must stay host numpy: silently returning them
        as immutable jax.Arrays breaks consumers that mutate in place."""
        plan, _ = plan_for_field(_field('tokens', np.int32, (4,)))
        batch = {'tokens': np.zeros((2, plan.stride), dtype=np.uint8),
                 'idx': np.arange(2),
                 'name': np.array(['a', 'b'], dtype=object)}
        device_cols, host_cols = split_device_columns(batch,
                                                      {'tokens': plan})
        assert set(device_cols) == {'tokens'}
        assert set(host_cols) == {'idx', 'name'}

    def test_split_includes_unplanned_numerics_for_fused_transform(self):
        """A fused device TransformSpec receives the full column dict, so
        unplanned numeric ndarrays ride the jit with it; object/str columns
        stay host either way."""
        plan, _ = plan_for_field(_field('tokens', np.int32, (4,)))
        batch = {'tokens': np.zeros((2, plan.stride), dtype=np.uint8),
                 'idx': np.arange(2),
                 'name': np.array(['a', 'b'], dtype=object)}
        device_cols, host_cols = split_device_columns(
            batch, {'tokens': plan}, include_unplanned=True)
        assert set(device_cols) == {'tokens', 'idx'}
        assert set(host_cols) == {'name'}


@pytest.fixture(scope='module')
def token_store(tmp_path_factory):
    from petastorm_tpu.benchmark.northstar import generate_token_dataset
    url = 'file://' + str(tmp_path_factory.mktemp('device_decode') / 'tok')
    generate_token_dataset(url, rows=64, seq_len=16, vocab=64, seed=3,
                           row_group_size_mb=0.01, ndarray_codec=True)
    return url


@pytest.fixture(scope='module')
def mixed_store(tmp_path_factory):
    """Two device-planned ndarray columns plus an UNPLANNED scalar column."""
    from petastorm_tpu.codecs import ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import materialize_dataset
    url = 'file://' + str(tmp_path_factory.mktemp('device_decode_mixed')
                          / 'mix')
    schema = Unischema('M', [
        _field('vec', np.float32, (3,)),
        _field('aux', np.int16, (2,)),
        UnischemaField('idx', np.int32, (), ScalarCodec(), False),
    ])
    with materialize_dataset(url, schema, row_group_size_mb=0.01) as writer:
        for i in range(24):
            writer.write_row({'vec': np.full((3,), i, dtype=np.float32),
                              'aux': np.array([i, -i], dtype=np.int16),
                              'idx': np.int32(i)})
    return url


def _epoch_tokens(url, monkeypatch, device, pool='thread', loader=True,
                  **loader_kwargs):
    """One epoch's tokens (row-order stable: shuffle off) plus the stats
    snapshot, through the reader alone or reader + JaxDataLoader."""
    monkeypatch.setenv(DEVICE_DECODE_ENV_VAR, 'on' if device else 'off')
    collected = []
    with make_columnar_reader(url, num_epochs=1, reader_pool_type=pool,
                              workers_count=1,
                              shuffle_row_groups=False) as reader:
        declined = dict(reader.device_decode_declined)
        if loader:
            with JaxDataLoader(reader, batch_size=16,
                               **loader_kwargs) as jax_loader:
                for batch in jax_loader:
                    collected.append(np.asarray(batch['tokens']))
        else:
            for batch in reader:
                collected.append(np.asarray(batch.tokens))
        snapshot = reader._stats_snapshot()
        report = reader.audit().assert_complete()
    tokens = np.concatenate(collected) if collected else np.empty((0,))
    return tokens, snapshot, declined, report


class TestEndToEnd:
    """Bytes-through epochs vs the kill-switch-off baseline: bit-identical
    output, the counters prove which path decoded, and the lineage audit
    stays green on both pool types."""

    @pytest.mark.parametrize('pool', ['thread', 'process'])
    def test_loader_epoch_identical_and_audited(self, token_store,
                                                monkeypatch, pool):
        device, snap_dev, declined, _ = _epoch_tokens(
            token_store, monkeypatch, True, pool=pool)
        host, snap_host, _, _ = _epoch_tokens(
            token_store, monkeypatch, False, pool=pool)
        assert declined.get('*') is None
        assert device.dtype == host.dtype == np.int32
        assert bool(np.array_equal(device, host))
        assert snap_dev['rows_decoded_device'] == len(device)
        assert snap_dev['rows_decoded_batched'] == 0
        assert snap_dev['bytes_shipped_raw'] > 0
        assert snap_dev['device_decode_fraction'] == 1.0
        assert snap_host['rows_decoded_device'] == 0
        assert snap_host['rows_decoded_batched'] == len(host)
        assert snap_host['bytes_shipped_raw'] == 0
        assert snap_host['device_decode_fraction'] == 0.0

    def test_reader_without_loader_host_decodes(self, token_store,
                                                monkeypatch):
        """No loader claims the plans: __next__ host-decodes the raw grids
        so plain reader consumers see decoded columns, bit-identical."""
        raw_path, snap, _, _ = _epoch_tokens(token_store, monkeypatch, True,
                                             loader=False)
        host, _, _, _ = _epoch_tokens(token_store, monkeypatch, False,
                                      loader=False)
        assert bool(np.array_equal(raw_path, host))
        assert snap['bytes_shipped_raw'] > 0          # workers shipped raw
        assert snap['rows_decoded_batched'] == len(raw_path)  # host fallback

    def test_loader_device_decode_off_knob(self, token_store, monkeypatch):
        """device_decode=False on the loader: the reader keeps host-decoding
        even though it planned bytes-through."""
        tokens, snap, _, _ = _epoch_tokens(token_store, monkeypatch, True,
                                           device_decode=False)
        assert snap['rows_decoded_device'] == 0
        assert snap['rows_decoded_batched'] == len(tokens)

    def test_fused_device_transform_spec(self, token_store, monkeypatch):
        baseline, _, _, _ = _epoch_tokens(token_store, monkeypatch, False)
        monkeypatch.setenv(DEVICE_DECODE_ENV_VAR, 'on')
        spec = TransformSpec(
            lambda cols: dict(cols, tokens=cols['tokens'] * 2), device=True)
        collected = []
        with make_columnar_reader(token_store, num_epochs=1,
                                  workers_count=1, shuffle_row_groups=False,
                                  transform_spec=spec) as reader:
            assert reader.device_decode_plans
            with JaxDataLoader(reader, batch_size=16) as loader:
                for batch in loader:
                    collected.append(np.asarray(batch['tokens']))
            snapshot = reader._stats_snapshot()
        assert bool(np.array_equal(np.concatenate(collected), baseline * 2))
        assert snapshot['device_decode_fraction'] == 1.0

    def test_unplanned_columns_stay_numpy(self, mixed_store, monkeypatch):
        """REVIEW fix: only PLANNED columns come back as jax.Arrays; the
        unplanned scalar column stays an np.ndarray (zero-copy collated
        batches are read-only per docs/decode.md, but the TYPE contract —
        numpy in, numpy out for unplanned columns — must hold with device
        decode on)."""
        monkeypatch.setenv(DEVICE_DECODE_ENV_VAR, 'on')
        seen_types = []

        def bump_idx(batch):
            seen_types.append(type(batch['idx']))
            return dict(batch, idx=batch['idx'] + 1)

        collected = []
        with make_columnar_reader(mixed_store, num_epochs=1, workers_count=1,
                                  shuffle_row_groups=False) as reader:
            assert set(reader.device_decode_plans) == {'vec', 'aux'}
            with JaxDataLoader(reader, batch_size=8,
                               transform_fn=bump_idx) as loader:
                for batch in loader:
                    assert isinstance(batch['idx'], np.ndarray)
                    collected.append((np.asarray(batch['idx']),
                                      np.asarray(batch['vec'])))
        assert seen_types and all(t is np.ndarray for t in seen_types)
        idx = np.concatenate([i for i, _ in collected])
        vec = np.concatenate([v for _, v in collected])
        assert bool(np.array_equal(np.sort(idx), np.arange(1, 25)))
        assert vec.dtype == np.float32 and vec.shape == (24, 3)
        assert bool(np.array_equal(vec[:, 0].astype(np.int64), idx - 1))

    def test_host_fallback_counts_rows_per_column(self, mixed_store,
                                                  monkeypatch):
        """REVIEW fix: the reader's no-loader host fallback accumulates
        rows per decoded COLUMN (2 planned columns here), matching the
        worker batched path's semantics so the derived fractions divide
        like-for-like."""
        def epoch(device):
            monkeypatch.setenv(DEVICE_DECODE_ENV_VAR,
                               'on' if device else 'off')
            with make_columnar_reader(mixed_store, num_epochs=1,
                                      workers_count=1,
                                      shuffle_row_groups=False) as reader:
                rows = sum(len(batch.idx) for batch in reader)
                return rows, reader._stats_snapshot()

        rows, snap_fallback = epoch(True)
        _, snap_host = epoch(False)
        assert rows == 24
        assert snap_fallback['rows_decoded_batched'] == 2 * rows
        assert (snap_fallback['rows_decoded_batched']
                == snap_host['rows_decoded_batched'])

    def test_row_reader_declines_wholesale(self, token_store, monkeypatch):
        monkeypatch.setenv(DEVICE_DECODE_ENV_VAR, 'on')
        with make_reader(token_store, num_epochs=1,
                         shuffle_row_groups=False) as reader:
            assert reader.device_decode_plans == {}
            assert '*' in reader.device_decode_declined
            next(iter(reader))

    def test_infeed_diagnosis_carries_split(self, token_store, monkeypatch):
        _, snapshot, _, _ = _epoch_tokens(token_store, monkeypatch, True)
        diag = infeed_diagnosis(snapshot)
        device = diag['device']
        assert device['rows_decoded_device'] == snapshot['rows_decoded_device']
        assert device['bytes_shipped_raw'] == snapshot['bytes_shipped_raw']
        assert device['device_decode_fraction'] == 1.0
        assert 'goodput_fraction' in device
        assert 'data_stall_fraction' in device
        assert 'prefetch_occupancy' in device

    def test_fraction_derivation(self):
        assert device_decode_fraction({'rows_decoded_device': 3,
                                       'rows_decoded_batched': 1}) == 0.75
        assert device_decode_fraction({}) is None


class TestShardedLoader:
    def test_sharded_decode_post_staging(self, token_store, monkeypatch):
        from petastorm_tpu.jax_utils import ShardedJaxLoader
        from jax.sharding import Mesh
        monkeypatch.setenv(DEVICE_DECODE_ENV_VAR, 'on')
        mesh = Mesh(np.array(jax.devices()[:1]), ('data',))
        baseline, _, _, _ = _epoch_tokens(token_store, monkeypatch, False)
        monkeypatch.setenv(DEVICE_DECODE_ENV_VAR, 'on')
        collected = []
        with make_columnar_reader(token_store, num_epochs=1,
                                  workers_count=1,
                                  shuffle_row_groups=False) as reader:
            with ShardedJaxLoader(reader, mesh,
                                  local_batch_size=16) as loader:
                for batch in loader:
                    collected.append(np.asarray(batch['tokens']))
            snapshot = reader._stats_snapshot()
        got = np.concatenate(collected)
        assert got.dtype == np.int32
        assert bool(np.array_equal(got, baseline))
        assert snapshot['rows_decoded_device'] == len(got)
        assert snapshot['device_decode_fraction'] == 1.0

    def test_transform_fn_declines_claim_and_sees_decoded_numpy(
            self, token_store, monkeypatch):
        """REVIEW fix: a host transform_fn runs pre-staging in the inner
        loader, where post-staging device decode has not happened yet — so
        the sharded loader must decline the bytes-through claim and let the
        reader host-decode. The transform must see decoded int32 numpy,
        never the raw (n, stride) uint8 grid."""
        from jax.sharding import Mesh
        from petastorm_tpu.jax_utils import ShardedJaxLoader
        baseline, _, _, _ = _epoch_tokens(token_store, monkeypatch, False)
        monkeypatch.setenv(DEVICE_DECODE_ENV_VAR, 'on')
        mesh = Mesh(np.array(jax.devices()[:1]), ('data',))
        seen = []

        def double(batch):
            seen.append((batch['tokens'].dtype, batch['tokens'].shape))
            return dict(batch, tokens=np.asarray(batch['tokens']) * 2)

        collected = []
        with make_columnar_reader(token_store, num_epochs=1,
                                  workers_count=1,
                                  shuffle_row_groups=False) as reader:
            assert reader.device_decode_plans   # the reader DID plan
            with ShardedJaxLoader(reader, mesh, local_batch_size=16,
                                  transform_fn=double) as loader:
                for batch in loader:
                    collected.append(np.asarray(batch['tokens']))
            snapshot = reader._stats_snapshot()
        got = np.concatenate(collected)
        assert seen and all(dt == np.int32 for dt, _ in seen)
        assert all(shape[1:] == baseline.shape[1:] for _, shape in seen)
        assert bool(np.array_equal(got, baseline * 2))
        # nothing decoded on device: the claim was declined, the reader
        # host-decoded and the host counters carry the whole epoch
        assert snapshot['rows_decoded_device'] == 0
        assert snapshot['rows_decoded_batched'] == len(got)


class TestEtlRepack:
    @pytest.fixture(scope='class')
    def compressed_store(self, tmp_path_factory):
        from petastorm_tpu.etl.dataset_metadata import materialize_dataset
        url = 'file://' + str(tmp_path_factory.mktemp('repack') / 'zlib')
        schema = Unischema('Z', [
            _field('emb', np.float32, (4, 3), CompressedNdarrayCodec()),
            _field('tag', np.int32, (2,), NdarrayCodec()),
        ])
        rows = [{'emb': RNG.standard_normal((4, 3)).astype(np.float32),
                 'tag': np.array([i, i + 1], dtype=np.int32)}
                for i in range(12)]
        with materialize_dataset(url, schema,
                                 row_group_size_mb=0.01) as writer:
            for row in rows:
                writer.write_row(row)
        return url, rows

    def test_repack_schema_swaps_codecs(self, compressed_store):
        from petastorm_tpu.etl.dataset_metadata import \
            get_schema_from_dataset_url
        from petastorm_tpu.etl.repack import repack_schema
        schema = get_schema_from_dataset_url(compressed_store[0])
        out, repacked = repack_schema(schema)
        assert repacked == ['emb']
        assert isinstance(out.fields['emb'].codec, NdarrayCodec)
        assert isinstance(out.fields['tag'].codec, NdarrayCodec)

    def test_repack_nullable_field_warns_still_ineligible(self, caplog):
        """REVIEW fix: the codec swap cannot fix static decliners like
        nullable=True — the repack must say so instead of silently
        producing a store that still declines device decode."""
        import logging
        from petastorm_tpu.etl.repack import (repack_schema,
                                              still_ineligible_after_repack)
        schema = Unischema('N', [
            _field('emb', np.float32, (2,), CompressedNdarrayCodec(),
                   nullable=True),
            _field('ok', np.float32, (2,), CompressedNdarrayCodec()),
        ])
        with caplog.at_level(logging.WARNING,
                             logger='petastorm_tpu.etl.repack'):
            out, repacked = repack_schema(schema)
        assert sorted(repacked) == ['emb', 'ok']
        reasons = still_ineligible_after_repack(out, repacked)
        assert set(reasons) == {'emb'}
        assert 'nullable' in reasons['emb']
        assert any('emb' in r.message and 'INELIGIBLE' in r.message
                   for r in caplog.records)

    def test_repack_schema_rejects_bad_field_names(self, compressed_store):
        from petastorm_tpu.etl.dataset_metadata import \
            get_schema_from_dataset_url
        from petastorm_tpu.etl.repack import repack_schema
        schema = get_schema_from_dataset_url(compressed_store[0])
        with pytest.raises(ValueError):
            repack_schema(schema, fields=['nope'])
        with pytest.raises(ValueError):
            repack_schema(schema, fields=['tag'])   # already NdarrayCodec

    def test_repacked_store_is_device_eligible_and_identical(
            self, compressed_store, tmp_path, monkeypatch):
        from petastorm_tpu.etl.repack import repack_to_ndarray_codec
        source_url, rows = compressed_store
        out_url = 'file://' + str(tmp_path / 'repacked')
        summary = repack_to_ndarray_codec(source_url, out_url)
        assert summary['rows'] == len(rows)
        assert summary['repacked_fields'] == ['emb']
        assert summary['still_ineligible'] == {}

        monkeypatch.setenv(DEVICE_DECODE_ENV_VAR, 'on')
        with make_columnar_reader(source_url, num_epochs=1,
                                  shuffle_row_groups=False) as reader:
            assert 'emb' in reader.device_decode_declined
        got = {}
        with make_columnar_reader(out_url, num_epochs=1,
                                  shuffle_row_groups=False) as reader:
            assert 'emb' in reader.device_decode_plans
            with JaxDataLoader(reader, batch_size=4) as loader:
                for batch in loader:
                    tags = np.asarray(batch['tag'])
                    embs = np.asarray(batch['emb'])
                    for i in range(len(tags)):
                        got[int(tags[i][0])] = embs[i]
            assert reader._stats_snapshot()['device_decode_fraction'] == 1.0
        assert len(got) == len(rows)
        for i, row in enumerate(rows):
            assert bool(np.array_equal(got[i], row['emb']))


class TestContiguousRowsViewEdges:
    """ISSUE-16 satellite: the zero-copy collate's edge cases."""

    def _col(self, n=10, shape=(4, 3)):
        # .copy() so the column OWNS its buffer (reshape alone returns a
        # view of the flat arange, collapsing row .base to the 1-D owner)
        return np.arange(n * int(np.prod(shape)),
                         dtype=np.float32).reshape((n,) + shape).copy()

    def test_empty_batch_declines(self):
        assert _contiguous_rows_view([]) is None

    def test_single_row_is_a_one_row_slice(self):
        col = self._col()
        out = _contiguous_rows_view([col[3]])
        assert out is not None and out.shape == (1, 4, 3)
        assert out.base is col
        assert bool(np.array_equal(out, col[3:4]))

    def test_non_owned_base_resolves_to_the_owner(self):
        """Rows sliced from a view: numpy collapses .base to the owning
        array, and the collate must still find the right range in it."""
        owner = self._col(12)
        col = owner[2:10]       # non-owning
        rows = [col[i] for i in range(3, 6)]
        out = _contiguous_rows_view(rows)
        assert out is not None
        assert out.base is owner
        assert bool(np.array_equal(out, owner[5:8]))

    def test_read_only_views_share_writability(self):
        col = self._col()
        col.setflags(write=False)
        out = _contiguous_rows_view([col[i] for i in range(2, 5)])
        assert out is not None
        assert not out.flags.writeable   # the slice shares the column's
        assert bool(np.array_equal(out, col[2:5]))

    def test_shuffled_rows_decline(self):
        col = self._col()
        assert _contiguous_rows_view([col[4], col[2], col[3]]) is None


class TestPrefetchDepthKnob:
    def test_default(self, monkeypatch):
        monkeypatch.delenv(PREFETCH_DEPTH_ENV_VAR, raising=False)
        assert resolve_prefetch_depth(None) == DEFAULT_PREFETCH_DEPTH

    def test_env_var(self, monkeypatch):
        monkeypatch.setenv(PREFETCH_DEPTH_ENV_VAR, '5')
        assert resolve_prefetch_depth(None) == 5

    def test_explicit_beats_env(self, monkeypatch):
        monkeypatch.setenv(PREFETCH_DEPTH_ENV_VAR, '5')
        assert resolve_prefetch_depth(3) == 3

    @pytest.mark.parametrize('bad', [0, -1, 'junk', 2.5])
    def test_invalid_depths_raise(self, bad):
        with pytest.raises(ValueError):
            resolve_prefetch_depth(bad)

    def test_invalid_env_raises_at_construction(self, monkeypatch):
        monkeypatch.setenv(PREFETCH_DEPTH_ENV_VAR, 'zero')
        with pytest.raises(ValueError):
            resolve_prefetch_depth(None)

    def test_loader_and_factory_thread_the_knob(self, token_store,
                                                monkeypatch):
        monkeypatch.setenv(PREFETCH_DEPTH_ENV_VAR, '4')
        with make_columnar_reader(token_store, num_epochs=1,
                                  shuffle_row_groups=False) as reader:
            with JaxDataLoader(reader, batch_size=16) as loader:
                assert loader.prefetch_depth == 4
                for _ in loader:
                    pass
        with make_columnar_reader(token_store, num_epochs=1,
                                  shuffle_row_groups=False) as reader:
            with make_jax_loader(reader, batch_size=16,
                                 prefetch_depth=3) as loader:
                assert loader.prefetch_depth == 3
                for _ in loader:
                    pass
