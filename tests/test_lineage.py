"""Sample-lineage tests: provenance threading across the three pools, epoch
coverage auditing (sharded runs, worker death, reset), shuffle-quality
metrics, bad-sample quarantine under all three ``on_decode_error`` policies,
bit-exact replay, the ``/coverage`` endpoint, flight-record lineage, and the
``PETASTORM_TPU_LINEAGE=0`` kill switch."""

import collections
import json
import os
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from petastorm_tpu.jax_utils import JaxDataLoader
from petastorm_tpu.lineage import (LINEAGE_COLUMN, PROVENANCE_KEY,
                                   BatchProvenance, CoverageAuditor,
                                   LineageTracker, Provenance,
                                   lineage_enabled, pack_rows, pack_source,
                                   unpack_source)
from petastorm_tpu.reader import (make_batch_reader, make_columnar_reader,
                                  make_reader)
from petastorm_tpu.test_util.dataset_gen import (create_non_petastorm_dataset,
                                                 create_test_dataset)
from petastorm_tpu.transform import TransformSpec


def _http_get(port, route):
    from http.client import HTTPConnection
    conn = HTTPConnection('127.0.0.1', port, timeout=10)
    try:
        conn.request('GET', route)
        response = conn.getresponse()
        return response.status, response.read().decode('utf-8')
    finally:
        conn.close()


@pytest.fixture()
def corrupt_dataset(tmp_path):
    """TestSchema store where ONE encoded 'matrix' cell is garbage bytes —
    the exact "a single corrupt sample kills the reader" scenario. The
    rewrite preserves the 1-row row-group layout so the petastorm metadata
    stays truthful."""
    url = 'file://' + str(tmp_path / 'corrupt')
    create_test_dataset(url, range(24), num_files=2)
    path = str(tmp_path / 'corrupt')
    files = sorted(os.path.join(path, f) for f in os.listdir(path)
                   if f.endswith('.parquet'))
    table = pq.read_table(files[0])
    cells = table.column('matrix').to_pylist()
    poison_row = 2
    cells[poison_row] = b'garbage-not-an-encoded-ndarray'
    idx = table.column_names.index('matrix')
    table = table.set_column(idx, 'matrix', pa.array(
        cells, type=table.schema.field('matrix').type))
    pq.write_table(table, files[0], row_group_size=1)
    return url


# -- packing / unit pieces ----------------------------------------------------

class TestPacking:
    def test_pack_roundtrip(self):
        packed = pack_source(1234, 567)
        assert unpack_source(packed) == (1234, 567)

    def test_pack_rows_vectorized(self):
        arr = pack_rows(7, 4)
        assert arr.dtype == np.int64
        assert [unpack_source(p) for p in arr] == [(7, i) for i in range(4)]

    def test_batch_provenance_shuffle_quality(self):
        sources = np.asarray([pack_source(s, i) for s, i in
                              [(1, 0), (1, 1), (2, 0), (1, 2), (2, 1)]])
        bp = BatchProvenance(sources, None)
        quality = bp.shuffle_quality()
        assert quality['rows'] == 5
        assert quality['sources'] == 2
        assert quality['adjacent_source_runs'] == 4
        assert quality['run_length_max'] == 2

    def test_tracker_ring_bounds(self):
        tracker = LineageTracker(enabled=True, record_capacity=4)
        record = Provenance('d', 0, '/p', 0, 1, ('all', 1), 0, -1, 0,
                            (0, 1), 0)
        seqs = [tracker.register(record) for _ in range(10)]
        assert tracker.resolve(seqs[0]) is None      # evicted
        assert tracker.resolve(seqs[-1]) is not None
        assert tracker.records_registered == 10

    def test_epoch_ledger_eviction(self):
        tracker = LineageTracker(enabled=True, epoch_capacity=2)
        for epoch in range(5):
            tracker.record_ventilated(epoch, 0, (0, 1))
        assert tracker.epochs() == [3, 4]


# -- provenance threading -----------------------------------------------------

class TestProvenanceThreading:
    @pytest.mark.parametrize('pool', ['thread', 'process', 'dummy'])
    def test_row_reader_provenance_all_pools(self, synthetic_dataset, pool):
        with make_reader(synthetic_dataset.url, reader_pool_type=pool,
                         workers_count=2, num_epochs=1,
                         shuffle_row_groups=False) as reader:
            rows = sum(1 for _ in reader)
            assert rows == len(synthetic_dataset.data)
            record = reader.last_provenance
            assert isinstance(record, Provenance)
            assert record.path.endswith('.parquet')
            assert record.selection[0] in ('all', 'slice', 'index')
            assert record.epoch == 0
            report = reader.audit().assert_complete()
            assert report['epochs'][0]['items_delivered'] > 0
            assert report['epochs'][0]['row_exact']

    def test_batch_reader_provenance(self, non_petastorm_dataset):
        with make_batch_reader(non_petastorm_dataset.url,
                               reader_pool_type='thread', workers_count=2,
                               num_epochs=1) as reader:
            total = sum(len(batch.id) for batch in reader)
            assert total == len(non_petastorm_dataset.data)
            # batched output: the last yielded batch IS one row group
            explained = reader.explain_batch()
            assert explained['enabled']
            assert explained['sources'][0]['row_group'] >= 0
            reader.audit().assert_complete()

    def test_columnar_reader_provenance(self, synthetic_dataset):
        with make_columnar_reader(synthetic_dataset.url,
                                  reader_pool_type='thread', workers_count=2,
                                  num_epochs=1) as reader:
            for _ in reader:
                pass
            assert reader.last_provenance is not None
            reader.audit().assert_complete()

    def test_drop_partitions_audit_row_exact(self, synthetic_dataset):
        with make_reader(synthetic_dataset.url, reader_pool_type='thread',
                         workers_count=2, num_epochs=1,
                         shuffle_row_drop_partitions=2) as reader:
            rows = sum(1 for _ in reader)
            assert rows == len(synthetic_dataset.data)
            report = reader.audit().assert_complete()
            verdict = report['epochs'][0]
            # every row group was split into 2 slice-selections whose union
            # must cover it exactly once
            assert verdict['row_exact']
            assert verdict['row_dups'] == 0 and verdict['row_missing'] == 0

    def test_predicate_reader_audits_without_missing(self, synthetic_dataset):
        from petastorm_tpu.predicates import in_lambda
        predicate = in_lambda(['id'], lambda values: values['id'] % 2 == 0)
        with make_reader(synthetic_dataset.url, reader_pool_type='thread',
                         workers_count=2, num_epochs=1,
                         predicate=predicate) as reader:
            rows = sum(1 for _ in reader)
            assert rows == sum(1 for r in synthetic_dataset.data
                               if r['id'] % 2 == 0)
            report = reader.audit().assert_complete()
            # filtered readers are item-exact, never row-missing-audited
            assert report['epochs'][0]['complete']

    def test_sharded_loader_keeps_top_level_jit_clean(self, synthetic_dataset):
        """ShardedJaxLoader batches stay `jax.jit`-able whole: provenance
        rides under '_host' with the other non-HBM values, and
        `batch_provenance_of` / `explain_batch` find it there."""
        import jax
        from jax.sharding import Mesh

        from petastorm_tpu.jax_utils import make_jax_loader
        from petastorm_tpu.lineage import batch_provenance_of
        devices = np.array(jax.devices())
        mesh = Mesh(devices.reshape(len(devices),), ('data',))
        with make_reader(synthetic_dataset.url, reader_pool_type='thread',
                         workers_count=2, num_epochs=1,
                         schema_fields=['id', 'matrix']) as reader:
            loader = make_jax_loader(reader, batch_size=8, mesh=mesh)
            batch = next(iter(loader))
            assert isinstance(batch['id'], jax.Array)
            assert PROVENANCE_KEY not in batch
            bp = batch['_host'][PROVENANCE_KEY]
            assert isinstance(bp, BatchProvenance) and len(bp) == 8
            assert batch_provenance_of(batch) is bp
            assert reader.explain_batch(batch)['rows'] == 8

    def test_loader_batch_provenance_and_explain(self, synthetic_dataset):
        with make_reader(synthetic_dataset.url, reader_pool_type='thread',
                         workers_count=2, num_epochs=1, seed=11) as reader:
            loader = JaxDataLoader(reader, batch_size=16,
                                   shuffling_queue_capacity=64, seed=5)
            batches = list(loader)
            assert all(PROVENANCE_KEY in b for b in batches)
            assert all(LINEAGE_COLUMN not in b for b in batches)
            bp = batches[0][PROVENANCE_KEY]
            assert isinstance(bp, BatchProvenance)
            assert len(bp) == len(batches[0]['id'])
            explained = reader.explain_batch(batches[0])
            assert explained['rows'] == len(bp)
            assert all('row_group' in s or s.get('evicted')
                       for s in explained['sources'])
            # the shuffle buffer mixes sources within a batch
            assert explained['shuffle']['rows'] == len(bp)


# -- coverage auditing --------------------------------------------------------

class TestCoverageAudit:
    def test_sharded_two_epochs_exactly_once(self, tmp_path):
        """The acceptance scenario: 2 shards x 2 epochs, shuffle on, audits
        as complete — every row exactly once per epoch per shard."""
        url = 'file://' + str(tmp_path / 'sharded')
        data = create_test_dataset(url, range(40), num_files=4)
        reports = []
        for shard in (0, 1):
            seen = collections.Counter()
            with make_reader(url, reader_pool_type='thread', workers_count=2,
                             num_epochs=2, shuffle_row_groups=True, seed=17,
                             cur_shard=shard, shard_count=2) as reader:
                for row in reader:
                    seen[int(row.id)] += 1
                report = reader.audit().assert_complete()
            assert report['complete'] is True
            for epoch, verdict in report['epochs'].items():
                assert verdict['dup_items'] == []
                assert verdict['dropped_items'] == []
                assert verdict['row_exact']
                assert verdict['row_dups'] == 0
                assert verdict['row_missing'] == 0
            # every id this shard owns was seen exactly twice (2 epochs)
            assert set(seen.values()) == {2}
            reports.append(report)
        # the two shards are disjoint and together cover the dataset
        shard_rows = [r['epochs'][0]['rows_delivered'] for r in reports]
        assert sum(shard_rows) == len(data)
        skew = CoverageAuditor.shard_skew(reports)
        assert sorted(skew['shards']) == [0, 1]
        for verdict in skew['epochs'].values():
            assert verdict['skew_ratio'] is not None
            assert verdict['skew_ratio'] < 2.0

    @pytest.mark.timeout(120)
    def test_killed_process_worker_reports_drops(self, tmp_path):
        """With worker auto-recovery OFF, a worker killed mid-epoch yields
        REPORTED drops with their source row groups — never a silent gap.
        (With recovery on — the default — the same kill becomes a respawn +
        exactly-once redispatch instead: tests/test_chaos.py.)"""
        url = 'file://' + str(tmp_path / 'droppy')
        create_test_dataset(url, range(32), num_files=2)
        reader = make_reader(url, reader_pool_type='process', workers_count=1,
                             num_epochs=1, shuffle_row_groups=False,
                             worker_recovery=False)
        try:
            iterator = iter(reader)
            next(iterator)   # at least one delivery before the kill
            reader._pool._processes[0].kill()
            with pytest.raises(RuntimeError):
                # the dead pool is detected within a few polls
                deadline = time.monotonic() + 60
                while time.monotonic() < deadline:
                    next(iterator)
            report = reader.audit().report()
            verdict = report['epochs'][0]
            assert verdict['items_delivered'] >= 1
            assert verdict['dropped_items'], 'the kill must surface as drops'
            for dropped in verdict['dropped_items']:
                assert dropped['path'].endswith('.parquet')
                assert dropped['row_group'] >= 0
            assert not verdict['complete']
            with pytest.raises(AssertionError, match='dropped'):
                reader.audit().assert_complete()
        finally:
            reader.stop()
            reader.join()

    def test_reset_starts_fresh_epoch_ledger(self, synthetic_dataset):
        with make_reader(synthetic_dataset.url, reader_pool_type='thread',
                         workers_count=2, num_epochs=1,
                         shuffle_row_groups=False) as reader:
            sum(1 for _ in reader)
            first = reader.audit().assert_complete()
            assert list(first['epochs']) == [0]
            reader.reset()
            sum(1 for _ in reader)
            second = reader.audit().assert_complete()
            # epoch numbers are globally monotone: the new pass audits in
            # its own ledger, the finished epoch 0 verdict is untouched
            assert sorted(second['epochs']) == [0, 1]
            assert second['passes'] == 1
            assert second['epochs'][0]['complete']
            assert second['epochs'][1]['complete']

    def test_shuffle_metrics_reported(self, synthetic_dataset):
        with make_reader(synthetic_dataset.url, reader_pool_type='thread',
                         workers_count=2, num_epochs=1, seed=3,
                         shuffle_row_groups=True) as reader:
            sum(1 for _ in reader)
            shuffle = reader.audit().report()['epochs'][0]['shuffle']
            assert shuffle['items'] > 0
            for key in ('lag_mean', 'lag_p50', 'lag_max',
                        'adjacent_source_runs', 'run_length_mean',
                        'run_length_max'):
                assert key in shuffle

    def test_drain_keeps_audit_complete(self, synthetic_dataset):
        with make_reader(synthetic_dataset.url, reader_pool_type='thread',
                         workers_count=2, num_epochs=1,
                         shuffle_row_groups=False) as reader:
            next(iter(reader))
            reader.drain()
            # discarded-on-purpose items registered as delivered: no phantom
            # drops in the audit
            reader.audit().assert_complete()


# -- quarantine ---------------------------------------------------------------

class TestQuarantine:
    def test_raise_policy_propagates(self, corrupt_dataset):
        with pytest.raises(Exception):
            with make_reader(corrupt_dataset, reader_pool_type='thread',
                             workers_count=1, num_epochs=1,
                             shuffle_row_groups=False) as reader:
                list(reader)

    def test_quarantine_policy_completes_epoch(self, corrupt_dataset):
        with make_reader(corrupt_dataset, reader_pool_type='thread',
                         workers_count=1, num_epochs=1,
                         shuffle_row_groups=False,
                         on_decode_error='quarantine') as reader:
            rows = sum(1 for _ in reader)
            assert rows == 23           # 24 minus the poisoned sample
            records = reader.lineage.quarantines()
            assert len(records) == 1
            record = records[0]
            assert record['stage'] == 'decode'
            assert record['field'] == 'matrix'
            assert record['path'].endswith('.parquet')
            assert record['rows'] == 1
            assert record['row_offsets'] == [0]   # 1-row groups
            assert reader.diagnostics['rows_quarantined'] == 1
            assert reader.diagnostics['items_quarantined'] == 1
            report = reader.audit().assert_complete()
            verdict = report['epochs'][0]
            assert verdict['rows_quarantined'] == 1
            # the poisoned item still DELIVERED (zero rows, cell-level
            # quarantine): every ventilated item is accounted for
            assert verdict['items_delivered'] == verdict['items_ventilated']
            assert verdict['complete']

    def test_skip_policy_counts_without_records(self, corrupt_dataset):
        with make_reader(corrupt_dataset, reader_pool_type='thread',
                         workers_count=1, num_epochs=1,
                         shuffle_row_groups=False,
                         on_decode_error='skip') as reader:
            rows = sum(1 for _ in reader)
            assert rows == 23
            assert reader.lineage.quarantines() == []
            assert reader.diagnostics['rows_quarantined'] == 1

    @pytest.mark.timeout(180)
    def test_quarantine_process_pool(self, corrupt_dataset):
        """The quarantine record and counters cross the process boundary in
        the accounting message."""
        with make_reader(corrupt_dataset, reader_pool_type='process',
                         workers_count=1, num_epochs=1,
                         shuffle_row_groups=False,
                         on_decode_error='quarantine') as reader:
            rows = sum(1 for _ in reader)
            assert rows == 23
            records = reader.lineage.quarantines()
            assert len(records) == 1 and records[0]['field'] == 'matrix'
            assert reader.diagnostics['rows_quarantined'] == 1
            reader.audit().assert_complete()

    def test_transform_error_quarantines_exact_row(self, synthetic_dataset):
        def poison(row):
            if row['id'] == 7:
                raise ValueError('poisoned id 7')
            return row

        spec = TransformSpec(poison)
        with make_reader(synthetic_dataset.url, reader_pool_type='thread',
                         workers_count=2, num_epochs=1,
                         shuffle_row_groups=False, transform_spec=spec,
                         on_decode_error='quarantine') as reader:
            rows = sum(1 for _ in reader)
            assert rows == len(synthetic_dataset.data) - 1
            records = reader.lineage.quarantines()
            assert len(records) == 1
            assert records[0]['stage'] == 'transform'
            assert 'poisoned id 7' in records[0]['error']
            assert records[0]['row_offsets'] is not None
            reader.audit().assert_complete()

    def test_invalid_policy_rejected(self, synthetic_dataset):
        with pytest.raises(ValueError, match='on_decode_error'):
            make_reader(synthetic_dataset.url, on_decode_error='explode')


# -- replay -------------------------------------------------------------------

class TestReplay:
    def test_replay_single_record(self, non_petastorm_dataset):
        with make_batch_reader(non_petastorm_dataset.url,
                               reader_pool_type='thread', workers_count=1,
                               num_epochs=1,
                               shuffle_row_groups=False) as reader:
            first = next(iter(reader))
            record = reader.last_provenance
            for _ in reader:
                pass
            replayed = reader.replay(record)
            np.testing.assert_array_equal(replayed['id'], first.id)
            np.testing.assert_array_equal(replayed['value'], first.value)

    def test_replay_shuffled_loader_batch_bit_exact(self, synthetic_dataset):
        """The acceptance criterion: replay() of a recorded batch provenance
        returns bit-identical rows, in batch order, across row groups."""
        with make_reader(synthetic_dataset.url, reader_pool_type='thread',
                         workers_count=2, num_epochs=1, seed=23) as reader:
            loader = JaxDataLoader(reader, batch_size=16,
                                   shuffling_queue_capacity=48, seed=29)
            batches = list(loader)
            batch = batches[1]
            replayed = reader.replay(batch)
            np.testing.assert_array_equal(replayed['id'], batch['id'])
            np.testing.assert_array_equal(replayed['matrix'], batch['matrix'])
            np.testing.assert_array_equal(replayed['image_png'],
                                          batch['image_png'])

    def test_replay_seq_handle(self, synthetic_dataset):
        with make_reader(synthetic_dataset.url, reader_pool_type='thread',
                         workers_count=1, num_epochs=1,
                         shuffle_row_groups=False) as reader:
            next(iter(reader))
            seq = reader.last_seq
            for _ in reader:
                pass
            replayed = reader.replay(seq)
            assert len(replayed['id']) == reader.lineage.resolve(seq).rows


# -- endpoint / flight record -------------------------------------------------

class TestSurfaces:
    def test_coverage_endpoint(self, synthetic_dataset):
        with make_reader(synthetic_dataset.url, reader_pool_type='thread',
                         workers_count=2, num_epochs=1, debug_port=0,
                         shuffle_row_groups=False) as reader:
            sum(1 for _ in reader)
            status, body = _http_get(reader.debug_port, '/coverage')
            assert status == 200
            report = json.loads(body)
            assert report['enabled'] is True
            assert report['epochs']['0']['complete'] is True
            # /diagnostics folds the coverage audit in
            status, body = _http_get(reader.debug_port, '/diagnostics')
            assert status == 200
            assert 'coverage' in json.loads(body)

    def test_flight_record_carries_lineage(self, synthetic_dataset, tmp_path):
        with make_reader(synthetic_dataset.url, reader_pool_type='thread',
                         workers_count=2, num_epochs=1,
                         shuffle_row_groups=False) as reader:
            sum(1 for _ in reader)
            path = reader.dump_flight_record(
                path=str(tmp_path / 'flight.json'))
            with open(path) as f:
                record = json.load(f)
            assert record['lineage']['enabled'] is True
            assert record['lineage']['epochs']['0']['complete'] is True
            assert 'recent_quarantines' in record['lineage']


# -- kill switch --------------------------------------------------------------

class TestKillSwitch:
    def test_lineage_env_gate(self, monkeypatch):
        monkeypatch.delenv('PETASTORM_TPU_LINEAGE', raising=False)
        assert lineage_enabled()
        monkeypatch.setenv('PETASTORM_TPU_LINEAGE', '0')
        assert not lineage_enabled()

    def test_disabled_publishes_nothing(self, synthetic_dataset, monkeypatch):
        monkeypatch.setenv('PETASTORM_TPU_LINEAGE', '0')
        with make_reader(synthetic_dataset.url, reader_pool_type='thread',
                         workers_count=2, num_epochs=1,
                         shuffle_row_groups=False) as reader:
            loader = JaxDataLoader(reader, batch_size=16)
            batches = list(loader)
            assert all(PROVENANCE_KEY not in b for b in batches)
            assert reader.last_provenance is None
            report = reader.lineage.coverage_report()
            assert report['enabled'] is False
            assert report['records_registered'] == 0
