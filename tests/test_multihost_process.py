"""REAL multi-process multi-host tests: N OS processes join a
``jax.distributed`` cluster (CPU backend) and drive ``ShardedIndexedLoader``
in lockstep — the demonstrated (not merely argued) version of the framework's
flagship claim: identical global batch streams on every host, and byte-exact
O(1) resume after a mid-epoch kill.

The reference's strongest analogue constructs several shard readers inside
ONE process and asserts their union is disjoint
(``/root/reference/petastorm/tests/test_end_to_end.py:446``); here the
processes are real, the cluster is real, and the assertion is global-value
exact. No TPU needed: each child forces 2 virtual CPU devices, so 2 processes
form a 4-device global mesh.
"""

import hashlib
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

CHILD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     'multihost_child.py')
STREAM_CHILD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            'multihost_stream_child.py')

pytestmark = pytest.mark.slow      # real clusters: tens of seconds each

BATCH = 8
EPOCHS = 2
SEED = 7
ROWS = 64


def _free_port():
    s = socket.socket()
    s.bind(('localhost', 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture(scope='module')
def indexed_url(tmp_path_factory):
    from petastorm_tpu.codecs import ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import materialize_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField
    schema = Unischema('Ids', [
        UnischemaField('id', np.int64, (), ScalarCodec(), False)])
    url = 'file://' + str(tmp_path_factory.mktemp('multihost') / 'ds')
    with materialize_dataset(url, schema, row_group_size_mb=0.01) as w:
        w.write_rows({'id': np.int64(i)} for i in range(ROWS))
    return url


def _expected_stream(url, start=(0, 0)):
    """Ground truth from the SINGLE-process IndexedBatchLoader: the sharded
    loader must reproduce exactly this global stream."""
    from petastorm_tpu.indexed import IndexedBatchLoader, IndexedDatasetReader
    loader = IndexedBatchLoader(IndexedDatasetReader(url), BATCH,
                                num_epochs=EPOCHS, seed=SEED, workers_count=1)
    loader.load_state_dict({'epoch': start[0], 'batch': start[1],
                            'version': 1})
    out = []
    for batch in loader:
        ids = np.ascontiguousarray(batch['id'].astype(np.int64))
        digest = hashlib.sha256(ids.tobytes()).hexdigest()[:24]
        out.append((digest, '{}:{}'.format(loader.epoch, loader.batch)))
    loader.close()
    return out


def _launch(nproc, url, start, max_steps, timeout=420):
    port = _free_port()
    env = dict(os.environ)
    # A TPU-tunnel site hook (keyed on this env var) preloads jax and
    # initializes backends at interpreter startup, which would make the
    # children's platform/device-count env and jax.distributed.initialize
    # come too late — scrub it so children start with a clean interpreter.
    env.pop('PALLAS_AXON_POOL_IPS', None)
    env['JAX_PLATFORMS'] = 'cpu'
    env['XLA_FLAGS'] = '--xla_force_host_platform_device_count=2'
    procs = []
    for pid in range(nproc):
        procs.append(subprocess.Popen(
            [sys.executable, CHILD, 'localhost:{}'.format(port), str(nproc),
             str(pid), url, str(BATCH), str(EPOCHS), str(SEED),
             str(start[0]), str(start[1]), str(max_steps)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env))
    streams = []
    for p in procs:
        out, err = p.communicate(timeout=timeout)
        assert p.returncode == 0, 'child failed:\n{}'.format(err.decode())
        lines = out.decode().splitlines()
        steps = [tuple(l.split()[1:3]) for l in lines if l.startswith('STEP ')]
        assert any(l.startswith('DONE') for l in lines), out.decode()
        streams.append(steps)
    return streams


@pytest.mark.timeout(600)
def test_global_batches_identical_across_processes(indexed_url):
    streams = _launch(2, indexed_url, start=(0, 0), max_steps=1000)
    # (a) every process observed the IDENTICAL global stream...
    assert streams[0] == streams[1]
    assert len(streams[0]) == EPOCHS * (ROWS // BATCH)
    # ...and (b) it is exactly the single-process loader's stream
    assert streams[0] == _expected_stream(indexed_url)


ROWS_4P = 72    # non-power-of-two: 9 batches of 8 over an 8-device mesh


@pytest.fixture(scope='module')
def indexed_url_4p(tmp_path_factory):
    from petastorm_tpu.codecs import ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import materialize_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField
    schema = Unischema('Ids', [
        UnischemaField('id', np.int64, (), ScalarCodec(), False)])
    url = 'file://' + str(tmp_path_factory.mktemp('multihost4') / 'ds')
    with materialize_dataset(url, schema, row_group_size_mb=0.01) as w:
        w.write_rows({'id': np.int64(i)} for i in range(ROWS_4P))
    return url


@pytest.mark.timeout(900)
def test_four_processes_non_power_of_two_rows(indexed_url_4p):
    """4 real processes (8-device global mesh) over a 72-row store: the
    global stream is identical on every host and equals the single-process
    loader's (catches divisibility/remainder bugs invisible at 2 procs)."""
    streams = _launch(4, indexed_url_4p, start=(0, 0), max_steps=1000)
    assert streams[0] == streams[1] == streams[2] == streams[3]
    assert len(streams[0]) == EPOCHS * (ROWS_4P // BATCH)
    assert streams[0] == _expected_stream(indexed_url_4p)


# ---------------------------------------------------------------------------
# streaming path: make_reader(shard_by_jax_process=True) + ShardedJaxLoader
# ---------------------------------------------------------------------------

STREAM_GROUP_ROWS = 4
STREAM_GROUPS = 9      # odd: 2 hosts get 5 vs 4 row groups (unbalanced)


@pytest.fixture(scope='module')
def stream_url(tmp_path_factory):
    """36 rows in 9 single-group files: row-group sharding over 2 hosts is
    UNBALANCED (20 vs 16 rows) — exercising the lockstep-stop protocol."""
    from petastorm_tpu.codecs import ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import materialize_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField
    schema = Unischema('Ids', [
        UnischemaField('id', np.int64, (), ScalarCodec(), False)])
    url = 'file://' + str(tmp_path_factory.mktemp('multihost_stream') / 'ds')
    with materialize_dataset(url, schema, row_group_size_mb=100,
                             rows_per_file=STREAM_GROUP_ROWS) as w:
        w.write_rows({'id': np.int64(i)}
                     for i in range(STREAM_GROUP_ROWS * STREAM_GROUPS))
    return url


def _launch_stream(nproc, url, local_batch, epochs=1, timeout=420):
    port = _free_port()
    env = dict(os.environ)
    env.pop('PALLAS_AXON_POOL_IPS', None)
    env['JAX_PLATFORMS'] = 'cpu'
    env['XLA_FLAGS'] = '--xla_force_host_platform_device_count=2'
    procs = [subprocess.Popen(
        [sys.executable, STREAM_CHILD, 'localhost:{}'.format(port),
         str(nproc), str(pid), url, str(local_batch), str(epochs)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env)
        for pid in range(nproc)]
    results = []
    for p in procs:
        out, err = p.communicate(timeout=timeout)
        assert p.returncode == 0, 'child failed:\n{}'.format(err.decode())
        lines = out.decode().splitlines()
        steps = []
        for line in lines:
            if line.startswith('STEP '):
                parts = line.split()
                pass_idx, digest = int(parts[1]), parts[2]
                local = [int(x) for x in parts[4].split(',')] if parts[4] else []
                steps.append((pass_idx, digest, local))
        assert any(l.startswith('DONE') for l in lines), out.decode()
        results.append(steps)
    return results


@pytest.mark.timeout(600)
def test_streaming_sharded_loader_two_processes(stream_url):
    """The streaming multi-host path with real processes (the round-3
    verdict's missing run): equal step counts on every host despite
    unbalanced shards, disjoint local shards, and identical assembled
    global arrays."""
    local_batch = STREAM_GROUP_ROWS
    streams = _launch_stream(2, stream_url, local_batch)
    for pass_idx in range(2):
        p0 = [s for s in streams[0] if s[0] == pass_idx]
        p1 = [s for s in streams[1] if s[0] == pass_idx]
        # (a) equal step counts — the deadlock invariant: the 20-row host
        # drops its surplus 5th batch and stops with the 16-row host; pass 2
        # additionally proves the surplus host drained + reset cleanly
        assert len(p0) == len(p1) == 4, (pass_idx, len(p0), len(p1))
        # (b) identical global arrays on both hosts, step by step
        assert [d for _, d, _ in p0] == [d for _, d, _ in p1]
    # (c) local shards are disjoint and correctly sized
    seen = [set(), set()]
    for proc, steps in enumerate(streams):
        for _, _, local in steps:
            assert len(local) == local_batch
            seen[proc].update(local)
    assert not seen[0] & seen[1]
    all_ids = set(range(STREAM_GROUP_ROWS * STREAM_GROUPS))
    assert seen[0] | seen[1] <= all_ids
    assert len(seen[0] | seen[1]) == 2 * local_batch * 4
    # (d) shard_by_jax_process: host0 reads even row groups, host1 odd ones
    host0_groups = {i // STREAM_GROUP_ROWS for i in seen[0]}
    host1_groups = {i // STREAM_GROUP_ROWS for i in seen[1]}
    assert all(g % 2 == 0 for g in host0_groups)
    assert all(g % 2 == 1 for g in host1_groups)


NGRAM_GROUP_ROWS = 6
NGRAM_GROUPS = 5       # odd: 2 hosts get 3 vs 2 row groups (unbalanced)
NGRAM_SPAN = 2         # 5 windows per 6-row group


@pytest.fixture(scope='module')
def ngram_stream_url(tmp_path_factory):
    """Timestamped token rows in 5 single-group files: window universes are
    per-group (windows never cross groups), and row-group sharding over 2
    hosts is unbalanced — 15 vs 10 windows."""
    from petastorm_tpu.codecs import ArrowListCodec, ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import materialize_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField
    schema = Unischema('TsTokens', [
        UnischemaField('ts', np.int64, (), ScalarCodec(), False),
        UnischemaField('tokens', np.int32, (4,), ArrowListCodec(), False)])
    url = 'file://' + str(tmp_path_factory.mktemp('multihost_ngram') / 'ds')
    rng = np.random.default_rng(5)
    with materialize_dataset(url, schema, row_group_size_mb=100,
                             rows_per_file=NGRAM_GROUP_ROWS) as w:
        w.write_rows({'ts': np.int64(i),
                      'tokens': rng.integers(0, 100, size=4, dtype=np.int32)}
                     for i in range(NGRAM_GROUP_ROWS * NGRAM_GROUPS))
    return url


NGRAM_CHILD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           'multihost_ngram_child.py')


@pytest.mark.timeout(600)
def test_streaming_sharded_ngram_two_processes(ngram_stream_url):
    """Multi-host streaming NGram (the round-4 verdict's silent
    NotImplementedError frontier): nested {offset: {field: global jax.Array}}
    batches on a real 2-process cluster — equal step counts under unbalanced
    window shards, identical global batches, disjoint local window shards."""
    local_batch = 4      # global 8 windows over the 4-device mesh
    port = _free_port()
    env = dict(os.environ)
    env.pop('PALLAS_AXON_POOL_IPS', None)
    env['JAX_PLATFORMS'] = 'cpu'
    env['XLA_FLAGS'] = '--xla_force_host_platform_device_count=2'
    procs = [subprocess.Popen(
        [sys.executable, NGRAM_CHILD, 'localhost:{}'.format(port),
         '2', str(pid), ngram_stream_url, str(local_batch), '1'],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env)
        for pid in range(2)]
    results = []
    for p in procs:
        out, err = p.communicate(timeout=420)
        assert p.returncode == 0, 'child failed:\n{}'.format(err.decode())
        lines = out.decode().splitlines()
        steps = []
        for line in lines:
            if line.startswith('STEP '):
                parts = line.split()
                steps.append((int(parts[1]), parts[2],
                              [int(x) for x in parts[4].split(',')]))
        assert any(l.startswith('DONE') for l in lines), out.decode()
        results.append(steps)
    # host0 owns groups 0,2,4 (15 windows = 3 local batches of 4 + surplus);
    # host1 owns 1,3 (10 windows = 2): lockstep stops both after TWO global
    # steps per pass, and the surplus host must drain + reset for pass 2
    for pass_idx in range(2):
        p0 = [s for s in results[0] if s[0] == pass_idx]
        p1 = [s for s in results[1] if s[0] == pass_idx]
        assert len(p0) == len(p1) == 2, (pass_idx, len(p0), len(p1))
        assert [d for _, d, _ in p0] == [d for _, d, _ in p1]
    seen = [set(), set()]
    for proc, steps in enumerate(results):
        for _, _, local in steps:
            assert len(local) == local_batch
            seen[proc].update(local)
    assert not seen[0] & seen[1]
    # local window-start ts values come from groups the host owns
    host0_groups = {t // NGRAM_GROUP_ROWS for t in seen[0]}
    host1_groups = {t // NGRAM_GROUP_ROWS for t in seen[1]}
    assert all(g % 2 == 0 for g in host0_groups)
    assert all(g % 2 == 1 for g in host1_groups)


@pytest.mark.timeout(900)
def test_kill_and_restore_mid_epoch_continues_byte_exact(indexed_url):
    # First incarnation dies after 5 batches (mid-epoch: 8 batches/epoch).
    first = _launch(2, indexed_url, start=(0, 0), max_steps=5)
    assert first[0] == first[1] and len(first[0]) == 5
    # The cursor that a checkpoint would have saved is the printed
    # next-cursor of the last consumed batch.
    resume_epoch, resume_batch = map(int, first[0][-1][1].split(':'))
    assert (resume_epoch, resume_batch) == (0, 5)
    # Second incarnation restores the cursor and must continue the global
    # stream byte-for-byte where the first left off.
    second = _launch(2, indexed_url, start=(resume_epoch, resume_batch),
                     max_steps=1000)
    assert second[0] == second[1]
    expected = _expected_stream(indexed_url)
    assert first[0] == expected[:5]
    assert second[0] == expected[5:]
