"""Worker-pool matrix tests (reference ``tests/test_workers_pool.py``,
``tests/test_ventilator.py``)."""

import numpy as np
import pytest

from petastorm_tpu.test_util.pool_workers import (ArrayWorker, FailingWorker, MultiEmitWorker,
                                                  SquareWorker)
from petastorm_tpu.workers import EmptyResultError, TimeoutWaitingForResultError
from petastorm_tpu.workers.dummy_pool import DummyPool
from petastorm_tpu.workers.process_pool import ProcessPool
from petastorm_tpu.workers.serializers import ArrowTableSerializer, PickleSerializer
from petastorm_tpu.workers.thread_pool import ThreadPool
from petastorm_tpu.workers.ventilator import ConcurrentVentilator

ALL_POOLS = [lambda: DummyPool(), lambda: ThreadPool(4), lambda: ProcessPool(2)]
POOL_IDS = ['dummy', 'thread', 'process']


def drain(pool):
    results = []
    while True:
        try:
            results.append(pool.get_results(timeout=30))
        except EmptyResultError:
            return results


@pytest.mark.parametrize('pool_factory', ALL_POOLS, ids=POOL_IDS)
def test_square_with_ventilator(pool_factory):
    pool = pool_factory()
    items = [{'x': i} for i in range(20)]
    vent = ConcurrentVentilator(pool.ventilate, items, iterations=1)
    pool.start(SquareWorker, ventilator=vent)
    results = drain(pool)
    assert sorted(results) == sorted(i * i for i in range(20))
    pool.stop()
    pool.join()


@pytest.mark.parametrize('pool_factory', ALL_POOLS, ids=POOL_IDS)
def test_manual_ventilation(pool_factory):
    pool = pool_factory()
    pool.start(SquareWorker)
    for i in range(5):
        pool.ventilate(i)
    assert sorted(drain(pool)) == [0, 1, 4, 9, 16]
    pool.stop()
    pool.join()


@pytest.mark.parametrize('pool_factory', ALL_POOLS, ids=POOL_IDS)
def test_multiple_epochs(pool_factory):
    pool = pool_factory()
    items = [{'x': i} for i in range(5)]
    vent = ConcurrentVentilator(pool.ventilate, items, iterations=3)
    pool.start(SquareWorker, ventilator=vent)
    results = drain(pool)
    assert len(results) == 15
    pool.stop()
    pool.join()


@pytest.mark.parametrize('pool_factory', ALL_POOLS, ids=POOL_IDS)
def test_zero_or_many_results_per_item(pool_factory):
    pool = pool_factory()
    vent = ConcurrentVentilator(pool.ventilate,
                                [{'x': 1, 'count': 0}, {'x': 2, 'count': 3}], iterations=1)
    pool.start(MultiEmitWorker, ventilator=vent)
    assert sorted(drain(pool)) == [2, 2, 2]
    pool.stop()
    pool.join()


@pytest.mark.parametrize('pool_factory', [lambda: DummyPool(), lambda: ThreadPool(2),
                                          lambda: ProcessPool(1)], ids=POOL_IDS)
def test_worker_exception_propagates(pool_factory):
    pool = pool_factory()
    pool.start(FailingWorker, worker_args={'poison': 3})
    for i in range(5):
        pool.ventilate(i)
    with pytest.raises(ValueError, match='poisoned item 3'):
        drain(pool)
    pool.stop()
    pool.join()


def test_process_pool_array_payloads():
    pool = ProcessPool(2)
    vent = ConcurrentVentilator(pool.ventilate, [{'n': i} for i in range(1, 8)], iterations=1)
    pool.start(ArrayWorker, ventilator=vent)
    results = drain(pool)
    assert sorted(len(r) for r in results) == list(range(1, 8))
    for r in results:
        np.testing.assert_array_equal(r, np.full((len(r),), len(r)))
    pool.stop()
    pool.join()


def test_thread_pool_timeout():
    pool = ThreadPool(1)
    vent = ConcurrentVentilator(pool.ventilate, [{'x': 1}], iterations=None)  # infinite
    pool.start(SquareWorker, ventilator=vent)
    assert pool.get_results(timeout=10) == 1
    pool.stop()
    pool.join()


def test_ventilator_backpressure_bound():
    ventilated = []
    vent = ConcurrentVentilator(lambda **kw: ventilated.append(kw), [{'x': i} for i in range(100)],
                                iterations=1, max_ventilation_queue_size=10)
    vent.start()
    import time
    time.sleep(0.3)
    assert len(ventilated) == 10  # blocked until items are marked processed
    for _ in range(90):
        vent.processed_item()
    time.sleep(0.5)
    assert len(ventilated) == 100
    vent.stop()


def test_ventilator_seeded_shuffle_is_reproducible():
    orders = []
    for _ in range(2):
        seen = []
        vent = ConcurrentVentilator(lambda **kw: seen.append(kw['x']),
                                    [{'x': i} for i in range(50)], iterations=1,
                                    randomize_item_order=True, random_seed=123)
        vent.start()
        while not vent.fully_ventilated():
            for _ in range(len(seen)):
                pass
            import time
            time.sleep(0.01)
        # mark all processed so completed() is reachable
        for _ in range(len(seen)):
            vent.processed_item()
        orders.append(seen)
        vent.stop()
    assert orders[0] == orders[1]
    assert orders[0] != list(range(50))  # actually shuffled


def test_ventilator_reset_after_completion():
    pool = ThreadPool(2)
    vent = ConcurrentVentilator(pool.ventilate, [{'x': i} for i in range(5)], iterations=1)
    pool.start(SquareWorker, ventilator=vent)
    assert len(drain(pool)) == 5
    vent.reset(iterations=1)
    assert len(drain(pool)) == 5
    pool.stop()
    pool.join()


def test_diagnostics_surface():
    pool = ThreadPool(2)
    pool.start(SquareWorker)
    assert 'output_queue_size' in pool.diagnostics
    pool.stop()
    pool.join()

    pool = ProcessPool(1)
    pool.start(SquareWorker)
    d = pool.diagnostics
    assert {'items_consumed', 'items_produced', 'items_inprocess'} <= set(d)
    pool.stop()
    pool.join()


def test_process_pool_backpressure_with_stalled_consumer():
    """With a stalled consumer, in-flight work stays bounded by the
    ventilation queue size instead of racing through the whole item list
    (reference back-pressure behavior, ``tests/test_reader.py:58-70``)."""
    import time
    pool = ProcessPool(2)
    vent = ConcurrentVentilator(pool.ventilate,
                                [{'x': i} for i in range(200)],
                                iterations=1, max_ventilation_queue_size=5)
    pool.start(SquareWorker, ventilator=vent)
    try:
        # consume nothing; give workers ample time to run ahead if they could.
        # items_inprocess counts VENTILATED-not-yet-acknowledged items and
        # moves without any get_results call (items_produced does not), so the
        # bound is falsifiable: a ventilator ignoring the queue size would
        # push it toward 200 here.
        deadline = time.monotonic() + 2.0
        seen = 0
        while time.monotonic() < deadline:
            seen = max(seen, pool.diagnostics['items_inprocess'])
            assert seen <= 5, seen
            time.sleep(0.1)
        assert seen > 0          # ventilation did start
        # draining releases slots and the remaining items flow
        results = drain(pool)
        assert sorted(results) == sorted(i * i for i in range(200))
    finally:
        pool.stop()
        pool.join()


def test_process_pool_get_results_timeout():
    pool = ProcessPool(1)
    vent = ConcurrentVentilator(pool.ventilate, [{'x': 1, 'count': 0}],
                                iterations=None)   # worker never publishes
    pool.start(MultiEmitWorker, ventilator=vent)
    try:
        with pytest.raises(TimeoutWaitingForResultError):
            pool.get_results(timeout=1.0)
    finally:
        pool.stop()
        pool.join()


class TestExecInNewProcess:
    """Direct coverage of the spawn-clean-interpreter launcher (reference
    ``tests/test_run_in_subprocess.py``); the process pool exercises it
    implicitly, these assert its contract directly."""

    def test_function_runs_in_fresh_interpreter(self, tmp_path, monkeypatch):
        import os
        from petastorm_tpu.workers.exec_in_new_process import exec_in_new_process

        # conftest pins JAX_PLATFORMS=cpu in THIS process; set a sentinel so
        # the child's 'cpu' can only come from the launcher's own pin (the
        # workers-never-grab-the-TPU invariant), not from inheritance
        monkeypatch.setenv('JAX_PLATFORMS', 'tpu')
        marker = str(tmp_path / 'out.txt')

        def write_pid_and_platform(path):
            import os
            with open(path, 'w') as f:
                f.write('{}:{}'.format(os.getpid(),
                                       os.environ.get('JAX_PLATFORMS', '')))

        proc = exec_in_new_process(write_pid_and_platform, args=(marker,))
        assert proc.wait(timeout=60) == 0
        pid_str, platform = open(marker).read().split(':')
        assert int(pid_str) != os.getpid()      # genuinely a new interpreter
        assert platform == 'cpu'                # workers never grab the TPU

    def test_nonzero_exit_on_worker_exception(self):
        from petastorm_tpu.workers.exec_in_new_process import exec_in_new_process

        def boom():
            raise RuntimeError('worker failed')

        proc = exec_in_new_process(boom)
        assert proc.wait(timeout=60) != 0
