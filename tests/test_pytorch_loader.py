"""PyTorch adapter tests (reference ``tests/test_pytorch_dataloader.py``)."""

import numpy as np
import pytest

torch = pytest.importorskip('torch')

from petastorm_tpu.pytorch import (BatchedDataLoader, DataLoader,  # noqa: E402
                                   _sanitize_pytorch_types,
                                   decimal_friendly_collate)
from petastorm_tpu.reader import make_batch_reader, make_reader  # noqa: E402


def _all_ids(batches, key='id'):
    out = []
    for b in batches:
        out.extend(np.asarray(b[key]).ravel().tolist())
    return out


class TestSanitize:
    def test_promotions(self):
        from decimal import Decimal
        row = {'b': np.array([True, False]),
               'u16': np.array([1, 2], np.uint16),
               'u32': np.array([1, 2], np.uint32),
               'd': Decimal('2.5')}
        out = _sanitize_pytorch_types(row)
        assert out['b'].dtype == np.uint8
        assert out['u16'].dtype == np.int32
        assert out['u32'].dtype == np.int64
        assert out['d'] == 2.5

    def test_none_rejected(self):
        with pytest.raises(TypeError, match='None'):
            _sanitize_pytorch_types({'x': None})


class TestDataLoader:
    def test_row_reader(self, synthetic_dataset):
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                         num_epochs=1, schema_fields=['id', 'matrix']) as reader:
            loader = DataLoader(reader, batch_size=10)
            batches = list(loader)
        assert sorted(_all_ids(batches)) == sorted(
            r['id'] for r in synthetic_dataset.data)
        assert isinstance(batches[0]['matrix'], torch.Tensor)
        assert batches[0]['matrix'].shape == (10, 8, 4, 3)

    def test_batched_reader_transposed(self, scalar_dataset):
        with make_batch_reader(scalar_dataset.url, reader_pool_type='dummy',
                               num_epochs=1,
                               schema_fields=['^id$', 'float64']) as reader:
            loader = DataLoader(reader, batch_size=16)
            batches = list(loader)
        assert sorted(_all_ids(batches)) == sorted(
            r['id'] for r in scalar_dataset.data)

    def test_shuffling(self, synthetic_dataset):
        def ids(capacity, seed=3):
            with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                             num_epochs=1, shuffle_row_groups=False,
                             schema_fields=['id']) as reader:
                return _all_ids(list(DataLoader(
                    reader, batch_size=10,
                    shuffling_queue_capacity=capacity, seed=seed)))

        plain, shuffled = ids(0), ids(50)
        assert sorted(plain) == sorted(shuffled)
        assert plain != shuffled


class TestBatchedDataLoader:
    def test_vectorized_batches(self, scalar_dataset):
        with make_batch_reader(scalar_dataset.url, reader_pool_type='dummy',
                               num_epochs=1) as reader:
            loader = BatchedDataLoader(reader, batch_size=16)
            batches = list(loader)
        assert sorted(_all_ids(batches)) == sorted(
            r['id'] for r in scalar_dataset.data)
        assert isinstance(batches[0]['id'], torch.Tensor)

    def test_requires_batched_reader(self, synthetic_dataset):
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy') as reader:
            with pytest.raises(ValueError, match='batched reader'):
                BatchedDataLoader(reader)

    def test_inmemory_cache(self, scalar_dataset):
        with make_batch_reader(scalar_dataset.url, reader_pool_type='dummy',
                               num_epochs=1) as reader:
            loader = BatchedDataLoader(reader, batch_size=16,
                                       inmemory_cache_all=True)
            first = _all_ids(list(loader))
            second = _all_ids(list(loader))
        assert first == second

    def test_shuffled_batches(self, scalar_dataset):
        with make_batch_reader(scalar_dataset.url, reader_pool_type='dummy',
                               num_epochs=1, shuffle_row_groups=False) as reader:
            loader = BatchedDataLoader(reader, batch_size=10,
                                       shuffling_queue_capacity=40, seed=0)
            ids = _all_ids(list(loader))
        assert sorted(ids) == sorted(r['id'] for r in scalar_dataset.data)
        assert ids != sorted(ids)

    def test_transform_fn(self, scalar_dataset):
        with make_batch_reader(scalar_dataset.url, reader_pool_type='dummy',
                               num_epochs=1) as reader:
            loader = BatchedDataLoader(
                reader, batch_size=8,
                transform_fn=lambda b: {'double': b['id'] * 2})
            batch = next(iter(loader))
        assert set(batch.keys()) == {'double'}


class TestCollate:
    def test_mixed_fields(self):
        rows = [{'x': np.float32(1.0), 's': 'a'},
                {'x': np.float32(2.0), 's': 'bb'}]
        out = decimal_friendly_collate(rows)
        assert isinstance(out['x'], torch.Tensor)
        assert out['s'] == ['a', 'bb']
