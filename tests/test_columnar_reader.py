"""Tests for the vectorized columnar decode path (``make_columnar_reader``),
the ``ArrowListCodec``, and the device-side epoch cache."""

import numpy as np
import pytest

from petastorm_tpu import make_columnar_reader, make_reader
from petastorm_tpu.codecs import ArrowListCodec, CompressedImageCodec, ScalarCodec
from petastorm_tpu.etl.dataset_metadata import materialize_dataset
from petastorm_tpu.predicates import in_lambda
from petastorm_tpu.transform import TransformSpec
from petastorm_tpu.unischema import Unischema, UnischemaField

ColumnarSchema = Unischema('ColumnarSchema', [
    UnischemaField('idx', np.int64, (), ScalarCodec(), False),
    UnischemaField('image', np.uint8, (12, 16), CompressedImageCodec('png'), False),
    UnischemaField('vec', np.int32, (9,), ArrowListCodec(), False),
    UnischemaField('mat', np.float32, (3, 4), ArrowListCodec(), False),
    UnischemaField('rag', np.int16, (None,), ArrowListCodec(), False),
    UnischemaField('label', np.int64, (), ScalarCodec(), False),
])


def _make_rows(n):
    rng = np.random.default_rng(7)
    return [{'idx': np.int64(i),
             'image': rng.integers(0, 255, size=(12, 16), dtype=np.uint8),
             'vec': rng.integers(0, 100, size=9).astype(np.int32),
             'mat': rng.standard_normal((3, 4)).astype(np.float32),
             'rag': np.arange(i % 5 + 1, dtype=np.int16),
             'label': np.int64(i % 10)} for i in range(n)]


@pytest.fixture(scope='module')
def columnar_dataset(tmp_path_factory):
    path = tmp_path_factory.mktemp('columnar_ds')
    url = 'file://' + str(path)
    rows = _make_rows(120)
    with materialize_dataset(url, ColumnarSchema, row_group_size_mb=0.05) as w:
        w.write_rows(rows)
    return url, rows


def _collect_columnar(reader):
    got = {}
    for batch in reader:
        for j in range(len(batch.idx)):
            got[int(batch.idx[j])] = {f: getattr(batch, f)[j]
                                      for f in batch._fields}
    return got


class TestColumnarReader:
    def test_matches_row_path_value_exact(self, columnar_dataset):
        url, rows = columnar_dataset
        with make_reader(url, num_epochs=1, shuffle_row_groups=False) as r:
            row_path = {int(row.idx): row for row in r}
        with make_columnar_reader(url, num_epochs=1,
                                  shuffle_row_groups=False) as r:
            assert r.batched_output
            col_path = _collect_columnar(r)
        assert set(row_path) == set(col_path) == set(range(120))
        for i in range(120):
            for f in ('image', 'vec', 'mat', 'rag'):
                np.testing.assert_array_equal(getattr(row_path[i], f),
                                              col_path[i][f])
            assert int(row_path[i].label) == int(col_path[i]['label'])

    def test_dtypes_and_shapes(self, columnar_dataset):
        url, _ = columnar_dataset
        with make_columnar_reader(url, num_epochs=1) as r:
            batch = next(iter(r))
        assert batch.image.dtype == np.uint8 and batch.image.shape[1:] == (12, 16)
        assert batch.vec.dtype == np.int32 and batch.vec.shape[1:] == (9,)
        assert batch.mat.dtype == np.float32 and batch.mat.shape[1:] == (3, 4)
        assert batch.rag.dtype == object           # wildcard shape stays ragged
        assert isinstance(batch.rag[0], np.ndarray)

    @pytest.mark.parametrize('pool', ['dummy', 'thread', 'process'])
    def test_pool_matrix(self, columnar_dataset, pool):
        url, _ = columnar_dataset
        with make_columnar_reader(url, num_epochs=1, reader_pool_type=pool,
                                  workers_count=2) as r:
            got = _collect_columnar(r)
        assert set(got) == set(range(120))

    def test_worker_predicate(self, columnar_dataset):
        url, _ = columnar_dataset
        pred = in_lambda(['label'], lambda v: v['label'] == 3)
        with make_columnar_reader(url, num_epochs=1, predicate=pred) as r:
            got = _collect_columnar(r)
        assert len(got) == 12
        assert all(int(v['label']) == 3 for v in got.values())
        assert all(i % 10 == 3 for i in got)

    def test_schema_view_fields(self, columnar_dataset):
        url, _ = columnar_dataset
        with make_columnar_reader(url, num_epochs=1,
                                  schema_fields=['idx', 'vec']) as r:
            batch = next(iter(r))
        assert set(batch._fields) == {'idx', 'vec'}

    def test_transform_spec_columnar_contract(self, columnar_dataset):
        url, _ = columnar_dataset

        def double_vec(columns):
            columns['vec'] = columns['vec'] * 2
            return columns

        spec = TransformSpec(double_vec)
        with make_columnar_reader(url, num_epochs=1, shuffle_row_groups=False,
                                  transform_spec=spec) as r:
            got = _collect_columnar(r)
        with make_columnar_reader(url, num_epochs=1,
                                  shuffle_row_groups=False) as r:
            plain = _collect_columnar(r)
        for i in range(120):
            np.testing.assert_array_equal(got[i]['vec'], plain[i]['vec'] * 2)

    def test_shuffle_row_drop_partitions(self, columnar_dataset):
        url, _ = columnar_dataset
        with make_columnar_reader(url, num_epochs=1,
                                  shuffle_row_drop_partitions=2) as r:
            got = _collect_columnar(r)
        assert set(got) == set(range(120))   # all partitions together = all rows

    def test_ngram_rejected(self, columnar_dataset):
        url, _ = columnar_dataset
        from petastorm_tpu.ngram import NGram
        fields = {0: ['idx'], 1: ['idx']}
        ngram = NGram(fields=fields, delta_threshold=1, timestamp_field='idx')
        with pytest.raises(ValueError, match='NGram'):
            make_columnar_reader(url, schema_fields=ngram)


class TestColumnarNullsAndBytes:
    def test_nullable_codec_field_and_bytes_scalar(self, tmp_path):
        from petastorm_tpu.codecs import NdarrayCodec
        schema = Unischema('NullSchema', [
            UnischemaField('idx', np.int64, (), ScalarCodec(), False),
            UnischemaField('arr', np.float32, (3,), NdarrayCodec(), True),
            UnischemaField('blob', bytes, (), ScalarCodec(), False),
        ])
        url = 'file://' + str(tmp_path)
        rows = [{'idx': np.int64(i),
                 'arr': None if i % 3 == 0 else np.full(3, i, np.float32),
                 'blob': b'x' * (i + 1)} for i in range(30)]
        with materialize_dataset(url, schema, row_group_size_mb=0.05) as w:
            w.write_rows(rows)
        with make_columnar_reader(url, num_epochs=1,
                                  shuffle_row_groups=False) as r:
            got = _collect_columnar(r)
        assert set(got) == set(range(30))
        for i in range(30):
            if i % 3 == 0:
                assert got[i]['arr'] is None
            else:
                np.testing.assert_array_equal(got[i]['arr'],
                                              np.full(3, i, np.float32))
            assert got[i]['blob'] == b'x' * (i + 1)


class TestArrowListCodec:
    def test_rejects_non_numeric(self):
        field = UnischemaField('s', str, (3,), ArrowListCodec(), False)
        with pytest.raises(ValueError, match='numeric'):
            ArrowListCodec().arrow_type(field)

    def test_rejects_multidim_wildcard(self):
        field = UnischemaField('x', np.int32, (None, 4), ArrowListCodec(), False)
        with pytest.raises(ValueError, match='1-D'):
            ArrowListCodec().arrow_type(field)

    def test_scalar_roundtrip(self):
        field = UnischemaField('m', np.float32, (2, 3), ArrowListCodec(), False)
        value = np.arange(6, dtype=np.float32).reshape(2, 3)
        codec = ArrowListCodec()
        encoded = codec.encode(field, value)
        decoded = codec.decode(field, list(encoded))
        np.testing.assert_array_equal(decoded, value)
        assert decoded.dtype == np.float32


class TestEpochCacheOnDevice:
    def test_replays_identical_epochs(self, columnar_dataset):
        url, _ = columnar_dataset
        from petastorm_tpu.jax_utils import JaxDataLoader, epoch_cache_on_device
        with make_columnar_reader(url, num_epochs=1,
                                  shuffle_row_groups=False) as r:
            loader = JaxDataLoader(r, batch_size=40, drop_last=True)
            gen = epoch_cache_on_device(loader)
            epoch1 = [next(gen) for _ in range(3)]
            epoch2 = [next(gen) for _ in range(3)]
        for b1, b2 in zip(epoch1, epoch2):
            np.testing.assert_array_equal(np.asarray(b1['idx']),
                                          np.asarray(b2['idx']))
            np.testing.assert_array_equal(np.asarray(b1['vec']),
                                          np.asarray(b2['vec']))

    def test_empty_loader_terminates(self):
        from petastorm_tpu.jax_utils import epoch_cache_on_device
        assert list(epoch_cache_on_device([])) == []


class TestDecodeHints:
    @pytest.fixture(scope='class')
    def image_url(self, tmp_path_factory):
        from petastorm_tpu.codecs import CompressedImageCodec, ScalarCodec
        from petastorm_tpu.etl.dataset_metadata import materialize_dataset
        from petastorm_tpu.unischema import Unischema, UnischemaField
        schema = Unischema('Img', [
            UnischemaField('id', np.int64, (), ScalarCodec(), False),
            UnischemaField('image', np.uint8, (376, 500, 3),
                           CompressedImageCodec('jpeg'), False)])
        url = 'file://' + str(tmp_path_factory.mktemp('hints') / 'ds')
        rng = np.random.default_rng(0)
        with materialize_dataset(url, schema, rows_per_file=8) as w:
            w.write_rows({'id': np.int64(i),
                          'image': rng.integers(0, 255, (376, 500, 3)).astype(np.uint8)}
                         for i in range(16))
        return url

    def test_columnar_reader_scaled_decode(self, image_url):
        from petastorm_tpu.reader import make_columnar_reader
        with make_columnar_reader(image_url, shuffle_row_groups=False,
                                  decode_hints={'image': {'min_shape': (112, 112)}}) as r:
            batch = next(r)
        assert batch.image.shape[1:] == (188, 250, 3)    # jpeg DCT denom 2

    @pytest.mark.parametrize('pool', ['dummy', 'process'])
    def test_row_reader_scaled_decode(self, image_url, pool):
        from petastorm_tpu import make_reader
        with make_reader(image_url, shuffle_row_groups=False,
                         reader_pool_type=pool, workers_count=2,
                         decode_hints={'image': {'min_shape': (40, 40)}}) as r:
            row = next(r)
        assert row.image.shape == (47, 63, 3)            # denom 8

    def test_bad_hint_fails_at_construction(self, image_url):
        from petastorm_tpu import make_reader
        with pytest.raises(ValueError, match='unknown field'):
            make_reader(image_url, decode_hints={'nope': {'min_shape': (8, 8)}})
        with pytest.raises(ValueError, match='decode_scaled'):
            make_reader(image_url, decode_hints={'id': {'min_shape': (8, 8)}})

    def test_typoed_hint_kwarg_fails_at_construction(self, image_url):
        from petastorm_tpu import make_reader
        with pytest.raises(ValueError, match='decode_scaled'):
            make_reader(image_url, decode_hints={'image': {'min_shap': (8, 8)}})

    def test_hints_partition_the_disk_cache(self, image_url, tmp_path):
        """Two readers sharing one cache dir but using different decode hints
        must not serve each other's decoded row groups."""
        from petastorm_tpu import make_reader
        kwargs = dict(shuffle_row_groups=False, reader_pool_type='dummy',
                      cache_type='local-disk', cache_location=str(tmp_path),
                      cache_size_limit=1 << 30)
        with make_reader(image_url,
                         decode_hints={'image': {'min_shape': (40, 40)}},
                         **kwargs) as r:
            assert next(r).image.shape == (47, 63, 3)
        with make_reader(image_url, **kwargs) as r:      # no hints
            assert next(r).image.shape == (376, 500, 3)  # not the cached 1/8

    def test_hinted_reader_schema_relaxes_spatial_dims(self, image_url):
        from petastorm_tpu import make_reader
        with make_reader(image_url,
                         decode_hints={'image': {'min_shape': (40, 40)}}) as r:
            assert r.schema.fields['image'].shape == (None, None, 3)
        with make_reader(image_url) as r:      # no hints: full static shape
            assert r.schema.fields['image'].shape == (376, 500, 3)

    def test_unscalable_field_keeps_static_shape(self, tmp_path):
        # png can never scale (REDUCED rounds), so a hint on it must not
        # relax the advertised static shape either
        from petastorm_tpu import make_reader
        from petastorm_tpu.codecs import CompressedImageCodec, ScalarCodec
        from petastorm_tpu.etl.dataset_metadata import materialize_dataset
        from petastorm_tpu.unischema import Unischema, UnischemaField
        schema = Unischema('Png', [
            UnischemaField('id', np.int64, (), ScalarCodec(), False),
            UnischemaField('image', np.uint8, (32, 32, 3),
                           CompressedImageCodec('png'), False)])
        url = 'file://' + str(tmp_path / 'png_ds')
        rng = np.random.default_rng(0)
        with materialize_dataset(url, schema) as w:
            w.write_rows({'id': np.int64(i),
                          'image': rng.integers(0, 255, (32, 32, 3)).astype(np.uint8)}
                         for i in range(4))
        with make_reader(url, shuffle_row_groups=False,
                         decode_hints={'image': {'min_shape': (8, 8)}}) as r:
            assert r.schema.fields['image'].shape == (32, 32, 3)
            assert next(r).image.shape == (32, 32, 3)


class TestScaleHintEndToEnd:
    """decode_hints={'image': {'scale': N}} — the variable-shape jpeg path."""

    @pytest.fixture(scope='class')
    def jpeg_url(self, tmp_path_factory):
        from petastorm_tpu.codecs import CompressedImageCodec, ScalarCodec
        from petastorm_tpu.etl.dataset_metadata import materialize_dataset
        from petastorm_tpu.unischema import Unischema, UnischemaField
        schema = Unischema('VarImg', [
            UnischemaField('id', np.int64, (), ScalarCodec(), False),
            UnischemaField('image', np.uint8, (None, None, 3),
                           CompressedImageCodec('jpeg'), False)])
        url = 'file://' + str(tmp_path_factory.mktemp('scale') / 'ds')
        rng = np.random.default_rng(0)
        with materialize_dataset(url, schema, rows_per_file=8) as w:
            w.write_rows({'id': np.int64(i),
                          'image': rng.integers(0, 255, (100 + i, 60, 3)).astype(np.uint8)}
                         for i in range(16))
        return url

    def test_columnar_scale_hint_halves_dims(self, jpeg_url):
        from petastorm_tpu.reader import make_columnar_reader
        with make_columnar_reader(jpeg_url, shuffle_row_groups=False,
                                  decode_hints={'image': {'scale': 2}}) as r:
            batch = next(r)
        # variable-shape: object column of per-row arrays at ceil(h/2)
        for i, img in enumerate(batch.image):
            assert img.shape == (-(-(100 + int(batch.id[i])) // 2), 30, 3)

    def test_row_reader_scale_hint(self, jpeg_url):
        from petastorm_tpu import make_reader
        with make_reader(jpeg_url, shuffle_row_groups=False,
                         reader_pool_type='dummy',
                         decode_hints={'image': {'scale': 4}}) as r:
            row = next(r)
        assert row.image.shape == (-(-(100 + int(row.id)) // 4), 15, 3)

    def test_bad_scale_fails_at_construction(self, jpeg_url):
        from petastorm_tpu import make_reader
        with pytest.raises(ValueError, match='scale'):
            make_reader(jpeg_url, decode_hints={'image': {'scale': 3}})


class TestBinaryCellViews:
    """_binary_cell_views must match to_pylist cell-for-cell for every arrow
    layout the reader can see: plain, chunked, sliced, nulls, large_binary."""

    def _check(self, arr):
        import pyarrow as pa
        from petastorm_tpu.readers.columnar_worker import _binary_cell_views
        chunked = arr if isinstance(arr, pa.ChunkedArray) else pa.chunked_array([arr])
        views = _binary_cell_views(chunked)
        expected = chunked.to_pylist()
        assert len(views) == len(expected)
        for v, e in zip(views, expected):
            if e is None:
                assert v is None
            else:
                assert v.tobytes() == e

    def test_plain_binary(self):
        import pyarrow as pa
        self._check(pa.array([b'a', b'bb', b'', b'cccc'], type=pa.binary()))

    def test_large_binary(self):
        import pyarrow as pa
        self._check(pa.array([b'xy', b'z', b'12345'], type=pa.large_binary()))

    def test_nulls(self):
        import pyarrow as pa
        self._check(pa.array([b'a', None, b'cc', None], type=pa.binary()))

    def test_sliced_array(self):
        import pyarrow as pa
        arr = pa.array([b'skip', b'a', b'bb', b'ccc'], type=pa.binary())
        self._check(pa.chunked_array([arr.slice(1, 3)]))

    def test_multiple_chunks(self):
        import pyarrow as pa
        chunked = pa.chunked_array([
            pa.array([b'one', b'two'], type=pa.binary()),
            pa.array([], type=pa.binary()),
            pa.array([b'three'], type=pa.binary()),
        ])
        self._check(chunked)

    def test_empty_column(self):
        import pyarrow as pa
        self._check(pa.chunked_array([pa.array([], type=pa.binary())]))

    def test_nullable_codec_column_end_to_end(self, tmp_path):
        # null cells must come back as None through the decode path
        from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
        from petastorm_tpu.etl.dataset_metadata import materialize_dataset
        from petastorm_tpu.reader import make_columnar_reader
        from petastorm_tpu.unischema import Unischema, UnischemaField
        schema = Unischema('Nullable', [
            UnischemaField('id', np.int64, (), ScalarCodec(), False),
            UnischemaField('vec', np.float32, (3,), NdarrayCodec(), True)])
        url = 'file://' + str(tmp_path / 'nulls')
        with materialize_dataset(url, schema) as w:
            w.write_rows({'id': np.int64(i),
                          'vec': (None if i % 2 else
                                  np.full(3, i, dtype=np.float32))}
                         for i in range(8))
        with make_columnar_reader(url, shuffle_row_groups=False) as r:
            batch = next(r)
        for i, vec in zip(batch.id, batch.vec):
            if i % 2:
                assert vec is None
            else:
                np.testing.assert_array_equal(vec, np.full(3, i, np.float32))
