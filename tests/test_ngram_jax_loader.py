"""NGram → JAX loop: per-timestep collation in ``JaxDataLoader`` (the
round-3 verdict gap — the TF adapter handled NGram, the JAX loader refused
it; reference ngram batching: ``tf_utils.py:141-183``) and the full
parquet → NGram windows → device batches → LM train step path."""

import numpy as np
import pytest

from petastorm_tpu import make_reader
from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
from petastorm_tpu.etl.dataset_metadata import materialize_dataset
from petastorm_tpu.jax_utils import JaxDataLoader, prefetch_to_device
from petastorm_tpu.ngram import NGram
from petastorm_tpu.unischema import Unischema, UnischemaField

SeqSchema = Unischema('SeqSchema', [
    UnischemaField('ts', np.int64, (), ScalarCodec(), False),
    UnischemaField('value', np.float32, (3,), NdarrayCodec(), False),
    UnischemaField('label', np.int32, (), ScalarCodec(), False),
])


@pytest.fixture(scope='module')
def seq_dataset(tmp_path_factory):
    """Timestamps 0..39 in 4 files (windows never cross row groups)."""
    path = tmp_path_factory.mktemp('ngram_jax') / 'ds'
    url = 'file://' + str(path)
    ts = list(range(40))
    rows = [{'ts': np.int64(t),
             'value': np.full(3, t, dtype=np.float32),
             'label': np.int32(t % 7)} for t in ts]
    with materialize_dataset(url, SeqSchema, row_group_size_mb=100,
                             rows_per_file=10) as w:
        w.write_rows(rows)
    return url, ts


def _ngram(length=3, fields=None):
    fields = fields or {i: ['ts', 'value', 'label'] for i in range(length)}
    return NGram(fields, delta_threshold=1, timestamp_field='ts')


POOLS = [('dummy', 1), ('thread', 2), ('process', 2)]


@pytest.mark.parametrize('pool_type,workers', POOLS, ids=[p[0] for p in POOLS])
def test_collation_value_exact(seq_dataset, pool_type, workers):
    """Batches are {offset: {field: (B, ...)}} with every timestep slice
    matching the generator's row for that timestamp."""
    url, _ = seq_dataset
    length = 3
    with make_reader(url, schema_fields=_ngram(length),
                     reader_pool_type=pool_type, workers_count=workers,
                     shuffle_row_groups=False, num_epochs=1) as reader:
        loader = JaxDataLoader(reader, batch_size=4)
        batches = list(loader)
    assert batches
    seen_ts0 = []
    for batch in batches:
        assert sorted(batch.keys()) == list(range(length))
        b = len(batch[0]['ts'])
        for off in range(length):
            step = batch[off]
            assert set(step.keys()) == {'ts', 'value', 'label'}
            assert step['value'].shape == (b, 3)
            np.testing.assert_array_equal(step['ts'], batch[0]['ts'] + off)
            np.testing.assert_array_equal(step['label'],
                                          (step['ts'] % 7).astype(np.int32))
            np.testing.assert_array_equal(
                step['value'], np.repeat(step['ts'][:, None], 3,
                                         axis=1).astype(np.float32))
        seen_ts0.extend(batch[0]['ts'].tolist())
    # 4 row groups x 10 rows, windows of 3 within each group -> 8 per group
    assert sorted(seen_ts0) == sorted(
        t for g in range(4) for t in range(g * 10, g * 10 + 8))


def test_gapped_offsets_and_subset_fields(seq_dataset):
    """Per-timestep field subsets and gapped offsets collate per declared
    offset only."""
    url, _ = seq_dataset
    ngram = _ngram(fields={0: ['ts', 'value'], 2: ['label']})
    with make_reader(url, schema_fields=ngram, reader_pool_type='dummy',
                     shuffle_row_groups=False, num_epochs=1) as reader:
        loader = JaxDataLoader(reader, batch_size=5)
        batches = list(loader)
    for batch in batches:
        assert sorted(batch.keys()) == [0, 2]
        assert set(batch[0].keys()) == {'ts', 'value'}
        assert set(batch[2].keys()) == {'label'}
        np.testing.assert_array_equal(
            batch[2]['label'], ((batch[0]['ts'] + 2) % 7).astype(np.int32))


def test_window_shuffle_keeps_alignment(seq_dataset):
    """Windows shuffle as whole units: timestep deltas stay exact under a
    shuffling buffer, while window order changes."""
    url, _ = seq_dataset

    def read(capacity):
        with make_reader(url, schema_fields=_ngram(2),
                         reader_pool_type='dummy', shuffle_row_groups=False,
                         num_epochs=1) as reader:
            loader = JaxDataLoader(reader, batch_size=4,
                                   shuffling_queue_capacity=capacity, seed=11)
            out = []
            for batch in loader:
                np.testing.assert_array_equal(batch[1]['ts'],
                                              batch[0]['ts'] + 1)
                out.extend(batch[0]['ts'].tolist())
            return out

    plain, shuffled = read(0), read(16)
    assert sorted(plain) == sorted(shuffled)
    assert plain != shuffled


def test_drop_last_and_batch_sizes(seq_dataset):
    url, _ = seq_dataset
    with make_reader(url, schema_fields=_ngram(3), reader_pool_type='dummy',
                     shuffle_row_groups=False, num_epochs=1) as reader:
        loader = JaxDataLoader(reader, batch_size=5, drop_last=True)
        batches = list(loader)
    assert batches and all(len(b[0]['ts']) == 5 for b in batches)
    # 32 windows total -> 6 full batches of 5
    assert len(batches) == 6


def test_pad_spec_rejected_for_ngram(seq_dataset):
    url, _ = seq_dataset
    with make_reader(url, schema_fields=_ngram(2),
                     reader_pool_type='dummy', num_epochs=1) as reader:
        with pytest.raises(ValueError, match='pad_spec'):
            JaxDataLoader(reader, batch_size=2,
                          pad_spec={'value': {'max_len': 3}})
        reader.stop()
        reader.join()


def test_inmemory_cache_replays_windows(seq_dataset):
    url, _ = seq_dataset
    with make_reader(url, schema_fields=_ngram(2), reader_pool_type='dummy',
                     shuffle_row_groups=False, num_epochs=1) as reader:
        loader = JaxDataLoader(reader, batch_size=4, inmemory_cache_all=True)
        first = [b[0]['ts'].copy() for b in loader]
        second = [b[0]['ts'].copy() for b in loader]
    assert len(first) == len(second)
    for a, b in zip(first, second):
        np.testing.assert_array_equal(a, b)


@pytest.mark.slow
def test_ngram_to_lm_train_step(tmp_path):
    """The end-to-end loop the verdict asked for: timestamped token chunks →
    NGram windows → device batches → one LM train step (loss finite,
    params update)."""
    import jax

    from petastorm_tpu.benchmark.northstar import (
        generate_timeseries_token_dataset, run_ngram_transformer_train_bench)

    url = 'file://' + str(tmp_path / 'tokens_ts')
    generate_timeseries_token_dataset(url, rows=96, chunk=16, vocab=256)
    report = run_ngram_transformer_train_bench(
        url, window=2, chunk=16, batch_size=4, num_steps=3, warmup_steps=1,
        workers_count=2, d_model=32, n_layers=1, n_heads=2, d_ff=64,
        vocab=256)
    assert report.steps == 3
    assert report.samples == 12


def test_sharded_loader_stages_ngram_batches(seq_dataset):
    """ShardedJaxLoader on an NGram reader yields nested {offset: {field:
    global jax.Array}} batches sharded at window granularity over the mesh
    (single process here; the real 2-process run lives in
    ``test_multihost_process.py::test_streaming_sharded_ngram_two_processes``)."""
    import jax
    from jax.sharding import Mesh

    from petastorm_tpu.jax_utils import ShardedJaxLoader

    url, _ = seq_dataset
    mesh = Mesh(np.array(jax.devices()[:2]), ('data',))
    with make_reader(url, schema_fields=_ngram(2), shuffle_row_groups=False,
                     reader_pool_type='dummy', num_epochs=1) as reader:
        loader = ShardedJaxLoader(reader, mesh, local_batch_size=4)
        seen_windows = 0
        for batch in loader:
            assert sorted(batch.keys()) == [0, 1]
            for off in (0, 1):
                arr = batch[off]['ts']
                assert isinstance(arr, jax.Array)
                assert arr.shape[0] == 4
                assert batch[off]['value'].shape == (4, 3)
            ts0 = np.asarray(batch[0]['ts'])
            # window alignment survives sharded staging: offset-1 rows are
            # the offset-0 rows' successors, value columns match their ts
            np.testing.assert_array_equal(np.asarray(batch[1]['ts']), ts0 + 1)
            np.testing.assert_array_equal(
                np.asarray(batch[0]['value']),
                np.repeat(ts0[:, None], 3, axis=1).astype(np.float32))
            seen_windows += 4
        # 4 groups x 9 windows = 36 windows; drop_last trims to 36
        assert seen_windows == 36


def test_prefetch_stages_ngram_batches(seq_dataset):
    """prefetch_to_device handles {offset: {field: array}} pytrees."""
    import jax

    url, _ = seq_dataset
    with make_reader(url, schema_fields=_ngram(2), reader_pool_type='dummy',
                     shuffle_row_groups=False, num_epochs=1) as reader:
        loader = JaxDataLoader(reader, batch_size=4, drop_last=True)
        staged = list(prefetch_to_device(iter(loader), size=2))
    assert staged
    for batch in staged:
        assert isinstance(batch[0]['ts'], jax.Array)
        np.testing.assert_array_equal(np.asarray(batch[1]['ts']),
                                      np.asarray(batch[0]['ts']) + 1)
