"""WeightedSamplingReader unit + integration suite.

Reference parity: ``petastorm/tests/test_weighted_sampling_reader.py`` —
select-one, non-normalized probabilities, statistical mixing, real readers,
bad arguments, schema/ngram compatibility, and framework-adapter integration.
"""

import collections

import numpy as np
import pytest

from petastorm_tpu.reader import make_reader
from petastorm_tpu.test_util.reader_mock import ReaderMock
from petastorm_tpu.unischema import Unischema, UnischemaField
from petastorm_tpu.weighted_sampling_reader import WeightedSamplingReader

_SCHEMA = Unischema('mock', [
    UnischemaField('id', np.int64, (), None, False),
])


class _StubReader:
    """Infinite reader yielding a constant tag — lets tests count exactly
    which underlying reader served each row."""

    def __init__(self, tag, schema=_SCHEMA, batched_output=False, ngram=None):
        self.tag = tag
        self.schema = schema
        self.batched_output = batched_output
        self.ngram = ngram
        self.last_row_consumed = False
        self.stopped = False
        self.joined = False

    def __iter__(self):
        return self

    def __next__(self):
        return self.tag

    def stop(self):
        self.stopped = True

    def join(self):
        self.joined = True


class TestSelection:
    def test_select_only_one_of_readers(self):
        mixed = WeightedSamplingReader(
            [_StubReader('a'), _StubReader('b')], [0.0, 1.0], seed=0)
        assert [next(mixed) for _ in range(100)] == ['b'] * 100

    def test_not_normalized_probabilities(self):
        """[2, 6] must behave exactly like [0.25, 0.75]."""
        counts = collections.Counter()
        mixed = WeightedSamplingReader(
            [_StubReader('a'), _StubReader('b')], [2, 6], seed=7)
        for _ in range(4000):
            counts[next(mixed)] += 1
        assert abs(counts['b'] / 4000 - 0.75) < 0.05

    def test_mixing_ratios(self):
        counts = collections.Counter()
        mixed = WeightedSamplingReader(
            [_StubReader(t) for t in 'abc'], [0.5, 0.3, 0.2], seed=3)
        for _ in range(6000):
            counts[next(mixed)] += 1
        assert abs(counts['a'] / 6000 - 0.5) < 0.05
        assert abs(counts['b'] / 6000 - 0.3) < 0.05
        assert abs(counts['c'] / 6000 - 0.2) < 0.05

    def test_seed_reproducible(self):
        def stream(seed):
            mixed = WeightedSamplingReader(
                [_StubReader('a'), _StubReader('b')], [0.5, 0.5], seed=seed)
            return [next(mixed) for _ in range(200)]

        assert stream(11) == stream(11)
        assert stream(11) != stream(12)

    def test_stops_when_any_reader_exhausted(self):
        finite = ReaderMock(_SCHEMA, num_rows=5)
        mixed = WeightedSamplingReader(
            [finite, _StubReader('b')], [1.0, 0.0], seed=0)
        rows = list(mixed)
        assert len(rows) == 5
        assert mixed.last_row_consumed


class TestValidation:
    def test_bad_arguments(self):
        with pytest.raises(ValueError, match='equal length'):
            WeightedSamplingReader([_StubReader('a')], [0.5, 0.5])
        with pytest.raises(ValueError, match='At least one'):
            WeightedSamplingReader([], [])
        with pytest.raises(ValueError, match='positive'):
            WeightedSamplingReader([_StubReader('a')], [0.0])
        # negative weights fail fast on their own (r05: previously they were
        # only caught when the TOTAL went non-positive, so [-1, 1] slipped
        # into a nonsense cumulative)
        with pytest.raises(ValueError, match='non-negative'):
            WeightedSamplingReader([_StubReader('a'), _StubReader('b')],
                                   [-1.0, 1.0])

    def test_schema_mismatch(self):
        other_schema = Unischema('other', [
            UnischemaField('other_field', np.int64, (), None, False),
        ])
        with pytest.raises(ValueError, match='same schema'):
            WeightedSamplingReader(
                [_StubReader('a'), _StubReader('b', schema=other_schema)],
                [0.5, 0.5])

    def test_batched_output_mismatch(self):
        with pytest.raises(ValueError, match='batched_output'):
            WeightedSamplingReader(
                [_StubReader('a'), _StubReader('b', batched_output=True)],
                [0.5, 0.5])

    def test_ngram_mismatch(self):
        with pytest.raises(ValueError, match='ngram'):
            WeightedSamplingReader(
                [_StubReader('a', ngram=object()), _StubReader('b')],
                [0.5, 0.5])

    def test_ngram_pair_allowed(self):
        mixed = WeightedSamplingReader(
            [_StubReader('a', ngram=object()),
             _StubReader('b', ngram=object())], [0.5, 0.5], seed=0)
        assert mixed.ngram is not None

    def test_context_manager_stops_all(self):
        readers = [_StubReader('a'), _StubReader('b')]
        with WeightedSamplingReader(readers, [0.5, 0.5], seed=0) as mixed:
            next(mixed)
        assert all(r.stopped and r.joined for r in readers)


class TestRealReaders:
    def test_mix_two_real_readers(self, synthetic_dataset):
        """Reference ``test_real_reader``: two live readers over the same
        store mix without losing schema-compliance of the rows."""
        r1 = make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                         schema_fields=['^id$'], num_epochs=None,
                         shuffle_row_groups=False)
        r2 = make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                         schema_fields=['^id$'], num_epochs=None,
                         shuffle_row_groups=False)
        expected_ids = {d['id'] for d in synthetic_dataset.data}
        with WeightedSamplingReader([r1, r2], [0.5, 0.5], seed=0) as mixed:
            got = [mixed.next().id for _ in range(50)]
        assert set(got) <= expected_ids
        assert len(got) == 50

    def test_mix_through_torch_loader(self):
        """Reference ``test_with_torch_api``: the mixed reader feeds the
        row-granular DataLoader."""
        torch = pytest.importorskip('torch')
        from petastorm_tpu.pytorch import DataLoader
        readers = [ReaderMock(_SCHEMA, num_rows=40),
                   ReaderMock(_SCHEMA, num_rows=40)]
        mixed = WeightedSamplingReader(readers, [0.5, 0.5], seed=0)
        with DataLoader(mixed, batch_size=10) as loader:
            batches = list(loader)
        assert batches, 'mixed reader produced no batches'
        assert all(isinstance(b['id'], torch.Tensor) for b in batches)
        assert all(len(b['id']) == 10 for b in batches[:-1])

    def test_mix_through_jax_loader(self):
        """The JAX per-row loader accepts the mixed reader surface too."""
        from petastorm_tpu.jax_utils import JaxDataLoader
        readers = [ReaderMock(_SCHEMA, num_rows=30),
                   ReaderMock(_SCHEMA, num_rows=30)]
        mixed = WeightedSamplingReader(readers, [0.5, 0.5], seed=0)
        with JaxDataLoader(mixed, batch_size=10) as loader:
            batches = list(loader)
        assert batches
        assert all(b['id'].shape[0] == 10 for b in batches[:-1])
