"""CLI tools + test-util components: copy_dataset, metadata_util, generate
metadata, ReaderMock, shuffling analysis, dummy-reader microbench."""

import numpy as np
import pytest

from petastorm_tpu.reader import make_reader


class TestThroughputCli:
    def test_single_run(self, scalar_dataset, capsys):
        from petastorm_tpu.benchmark.cli import main
        assert main([scalar_dataset.url, '-m', '20', '-n', '50',
                     '-w', '2']) == 0
        out = capsys.readouterr().out
        assert 'samples/sec' in out
        assert 'Dispersion' not in out

    def test_runs_dispersion(self, scalar_dataset, capsys):
        from petastorm_tpu.benchmark.cli import main
        assert main([scalar_dataset.url, '-m', '20', '-n', '50', '-w', '2',
                     '--runs', '3']) == 0
        out = capsys.readouterr().out
        assert 'Dispersion over 3 runs' in out
        assert 'spread' in out


class TestCopyDataset:
    def test_full_copy(self, synthetic_dataset, tmp_path):
        from petastorm_tpu.tools.copy_dataset import copy_dataset
        target = 'file://' + str(tmp_path / 'copy')
        copied = copy_dataset(synthetic_dataset.url, target)
        assert copied == len(synthetic_dataset.data)
        with make_reader(target, reader_pool_type='dummy', num_epochs=1) as r:
            ids = sorted(row.id for row in r)
        assert ids == sorted(r_['id'] for r_ in synthetic_dataset.data)

    def test_field_subset(self, synthetic_dataset, tmp_path):
        from petastorm_tpu.tools.copy_dataset import copy_dataset
        target = 'file://' + str(tmp_path / 'subset')
        copy_dataset(synthetic_dataset.url, target, field_regex=['^id.*'])
        with make_reader(target, reader_pool_type='dummy', num_epochs=1) as r:
            row = next(iter(r))
        assert set(row._fields) == {'id', 'id2', 'id_float', 'id_odd'}

    def test_not_null_filter(self, synthetic_dataset, tmp_path):
        from petastorm_tpu.tools.copy_dataset import copy_dataset
        target = 'file://' + str(tmp_path / 'notnull')
        copied = copy_dataset(synthetic_dataset.url, target,
                              field_regex=['id', 'matrix_nullable'],
                              not_null_fields=['matrix_nullable'])
        expected = [r for r in synthetic_dataset.data
                    if r['matrix_nullable'] is not None]
        assert copied == len(expected)

    def test_cli_main(self, synthetic_dataset, tmp_path):
        from petastorm_tpu.tools.copy_dataset import main
        target = 'file://' + str(tmp_path / 'cli_copy')
        assert main([synthetic_dataset.url, target, '--field-regex', '^id$']) == 0
        with make_reader(target, reader_pool_type='dummy', num_epochs=1) as r:
            assert sorted(row.id for row in r) == sorted(
                r_['id'] for r_ in synthetic_dataset.data)


class TestMetadataUtil:
    def test_prints_schema_and_rowgroups(self, synthetic_dataset, capsys):
        from petastorm_tpu.etl.metadata_util import main
        assert main([synthetic_dataset.url, '--schema', '--row-groups']) == 0
        out = capsys.readouterr().out
        assert 'Schema (stored)' in out
        assert 'row groups' in out
        assert 'matrix' in out

    def test_prints_index(self, tmp_path, capsys):
        from petastorm_tpu.etl.metadata_util import main
        from petastorm_tpu.etl.rowgroup_indexers import SingleFieldIndexer
        from petastorm_tpu.etl.rowgroup_indexing import build_rowgroup_index
        from petastorm_tpu.test_util.dataset_gen import create_test_dataset
        url = 'file://' + str(tmp_path / 'indexed_meta')
        create_test_dataset(url, range(20))
        build_rowgroup_index(url, [SingleFieldIndexer('by_pk', 'partition_key')])
        assert main([url, '--index']) == 0
        out = capsys.readouterr().out
        assert 'by_pk' in out


class TestReaderMock:
    def test_yields_schema_rows(self):
        from petastorm_tpu.test_util.dataset_gen import TestSchema
        from petastorm_tpu.test_util.reader_mock import ReaderMock
        mock = ReaderMock(TestSchema, num_rows=10)
        rows = list(mock)
        assert len(rows) == 10
        assert rows[0].matrix.shape == (8, 4, 3)
        assert isinstance(rows[0].partition_key, str)

    def test_reset(self):
        from petastorm_tpu.test_util.dataset_gen import TestSchema
        from petastorm_tpu.test_util.reader_mock import ReaderMock
        mock = ReaderMock(TestSchema, num_rows=5)
        first = [r.id for r in mock]
        mock.reset()
        second = [r.id for r in mock]
        assert first == second

    def test_feeds_jax_loader(self):
        from petastorm_tpu.jax_utils import JaxDataLoader
        from petastorm_tpu.test_util.dataset_gen import TestSchema
        from petastorm_tpu.test_util.reader_mock import ReaderMock
        mock = ReaderMock(TestSchema.create_schema_view(
            [TestSchema.fields['id'], TestSchema.fields['matrix']]), num_rows=20)
        loader = JaxDataLoader(mock, batch_size=5)
        batches = list(loader)
        assert len(batches) == 4
        assert batches[0]['matrix'].shape == (5, 8, 4, 3)


class TestShufflingAnalysis:
    def test_identical_stream_correlates(self):
        from petastorm_tpu.test_util.shuffling_analysis import \
            compute_correlation_distance
        ids = list(range(100))
        assert compute_correlation_distance(ids, ids) == pytest.approx(1.0)

    def test_shuffled_stream_decorrelates(self):
        from petastorm_tpu.test_util.shuffling_analysis import \
            compute_correlation_distance
        rng = np.random.default_rng(0)
        ids = list(range(1000))
        shuffled = list(rng.permutation(ids))
        assert compute_correlation_distance(shuffled, ids) < 0.2

    def test_mismatched_streams_rejected(self):
        from petastorm_tpu.test_util.shuffling_analysis import \
            compute_correlation_distance
        with pytest.raises(ValueError):
            compute_correlation_distance([1, 2], [1, 3])

    def test_reader_shuffling_quality(self, synthetic_dataset):
        from petastorm_tpu.test_util.shuffling_analysis import \
            analyze_shuffling_quality

        def factory(shuffle):
            return make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                               num_epochs=1, shuffle_row_groups=shuffle,
                               schema_fields=['id'])

        distance = analyze_shuffling_quality(factory, num_reads=2)
        assert distance < 0.9   # row-group shuffle: coarse but present


class TestDummyReaderBench:
    def test_runs(self, capsys):
        from petastorm_tpu.benchmark.dummy_reader import (DummyBatchReader,
                                                          _measure)
        from petastorm_tpu.jax_utils import JaxDataLoader
        reader = DummyBatchReader(chunk_size=100, num_chunks=5)
        rate = _measure(lambda: JaxDataLoader(reader, batch_size=50),
                        'test', 500)
        assert rate > 0


class TestInfeedOverlap:
    def test_report_math(self):
        from petastorm_tpu.benchmark.infeed import InfeedReport
        r = InfeedReport(steps=10, samples=100, total_time_s=2.0,
                         stall_time_s=0.2, compute_time_s=1.8)
        assert r.overlap == pytest.approx(0.9)
        assert r.stall_fraction == pytest.approx(0.1)
        assert r.samples_per_sec == pytest.approx(50.0)
        assert r.as_dict()['infeed_stall_pct'] == 10.0

    def test_measures_loader_pipeline(self, scalar_dataset):
        import jax.numpy as jnp
        from petastorm_tpu.benchmark.infeed import measure_infeed_overlap
        from petastorm_tpu.jax_utils import JaxDataLoader
        from petastorm_tpu.reader import make_batch_reader

        with make_batch_reader(scalar_dataset.url, reader_pool_type='thread',
                               workers_count=2, num_epochs=None) as reader:
            loader = JaxDataLoader(reader, batch_size=10)

            def step(batch):
                return jnp.sum(jnp.asarray(batch['id']))

            report = measure_infeed_overlap(iter(loader), step, num_steps=20,
                                            warmup_steps=2)
        assert report.steps == 20
        assert report.samples == 200
        assert 0.0 <= report.overlap <= 1.0
